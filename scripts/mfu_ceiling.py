"""MFU ceiling calibration (round-5 VERDICT item 5).

The bench reports ACHIEVED MFU for the full alternating iteration (optimizer
steps, weight syncs, BN state carry, loss plumbing included). Whether 26% at
batch 256 is "the ceiling" or leaves something on the table was, until this
script, an inference from the roofline model (PROFILE.md: arithmetic
intensity 15-17 vs ridge ~240 → bandwidth-bound). This measures it instead,
at three tiers on the same device:

1. ``gemm``: a large square bf16 matmul in a scan loop — what the MXU
   delivers at its friendliest shape; sanity-pins the peak-FLOPS constant
   the MFU denominator uses (PEAK_FLOPS_BY_KIND in bench.py).
2. ``bare:<config>``: the SAME conv/GEMM work as bench config 1/1b — the
   sampler forward plus fwd+bwd through dis(×2)/gan/cv at identical batch
   shapes — in a bare ``lax.scan`` with NO optimizer step, NO updater state,
   NO weight syncs, NO BN running-stats carry. Gradients stay live via an
   epsilon pseudo-update (XLA would dead-code-eliminate an unconsumed
   backward pass). This is the attainable MFU at these model shapes.
3. achieved: read from artifacts/benchmarks.json when present, so the
   report carries attainable-vs-achieved side by side.

Writes ``artifacts/mfu_ceiling.json``. Run on the real chip; ``--cpu``
exists only to smoke-test the code path.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import PEAK_FLOPS_BY_KIND, _peak_flops  # noqa: E402  (jax-free import)

SCAN_K = 128  # match the bench's device-loop window


def _timed_calls(fn, sync, *, min_s=3.0, max_calls=50) -> float:
    """Median seconds per call over enough calls to cover ``min_s``.

    ``sync`` receives EACH timed call's own return value and must fetch a
    scalar derived from it — fencing on anything bound before the loop (the
    pre-round-6 version synced a warmup output captured outside) measures
    dispatch latency, not execution, with unbounded error on the tunneled
    axon platform. jaxlint rule JG002 exists because of this function."""
    sync(fn())  # warmup/compile
    times = []
    while sum(times) < min_s and len(times) < max_calls:
        t0 = time.perf_counter()
        sync(fn())
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def bench_gemm(n: int, dtype, peak) -> dict:
    import jax
    import jax.numpy as jnp

    a = jnp.asarray(np.random.default_rng(0).standard_normal((n, n)), dtype)
    b = jnp.asarray(np.random.default_rng(1).standard_normal((n, n)), dtype)

    # anti-DCE constants pinned to the benchmark dtype OUTSIDE the scan body:
    # a bare float literal in carry arithmetic re-rounds to the compute dtype
    # per iteration (jaxlint JG008) — harmless to FLOPs here, but the ceiling
    # harness must model the hygiene the timed paths are held to
    scale = jnp.asarray(1e-3, dtype)
    eps = jnp.asarray(1e-6, dtype)

    @jax.jit
    def loop(a, b):
        def step(carry, _):
            # rebind so the K matmuls chain (no DCE, no hoisting)
            return jnp.tanh(carry @ b) * scale + a * eps, ()

        out, _ = jax.lax.scan(step, a, None, length=SCAN_K)
        return out

    sec_per_call = _timed_calls(
        lambda: loop(a, b), lambda out: np.asarray(out[0, 0]), min_s=2.0
    )
    # one n×n×n matmul = 2n³ FLOPs, K per call (tanh/scale are O(n²) noise)
    flops_per_call = 2.0 * n**3 * SCAN_K
    tflops = flops_per_call / sec_per_call / 1e12
    return {
        "n": n, "dtype": str(dtype.dtype if hasattr(dtype, "dtype") else dtype),
        "sec_per_matmul": sec_per_call / SCAN_K,
        "tflops": round(tflops, 2),
        "frac_of_peak": round(flops_per_call / (sec_per_call * peak), 4)
        if peak else None,
    }


def bench_bare(batch: int, peak) -> dict:
    """The fused iteration's compute core at config-1 shapes, bookkeeping
    stripped (see module docstring tier 2)."""
    import jax
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment

    cfg = ExperimentConfig(
        batch_size_train=batch, batch_size_pred=batch,
        num_iterations=2, save_models=False,
    )
    exp = GanExperiment(cfg)
    rng = np.random.default_rng(0)
    feats = jnp.asarray(rng.random((batch, 784), dtype=np.float32))
    labels = jnp.asarray(
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    )
    soft1 = jnp.ones((batch, 1), jnp.float32)
    soft0 = jnp.zeros((batch, 1), jnp.float32)
    ones = jnp.ones((batch, 1), jnp.float32)
    key = jax.random.PRNGKey(0)
    dis, gan, cv, gen = exp.dis, exp.gan, exp.cv, exp.gen
    z_size = cfg.z_size

    def grad_of(graph, params, f, l, k):
        def loss_fn(p):
            loss, _ = graph.loss(p, f, l, train=True, rng=k)
            return loss

        return jax.grad(loss_fn)(params)

    def pseudo(params, grads):
        # epsilon update: keeps every gradient live at O(bytes) cost —
        # the optimizer's memory traffic without its update math
        return jax.tree_util.tree_map(lambda p, g: p - 1e-12 * g, params, grads)

    @jax.jit
    def loop(dis_p, gan_p, cv_p, gen_p):
        def step(carry, t):
            dis_p, gan_p, cv_p, gen_p = carry
            # per-step key + a per-step gen_p nudge: both are required to
            # keep the sampler forward INSIDE the scan — with loop-invariant
            # gen_p and key, XLA hoists the whole generator out and the
            # "ceiling" silently drops a model's worth of FLOPs
            ks = jax.random.split(jax.random.fold_in(key, t), 6)
            z = jax.random.uniform(ks[0], (batch, z_size), jnp.float32, -1.0, 1.0)
            fake = gen.output(gen_p, z, train=False).reshape(feats.shape)
            dis_p = pseudo(dis_p, grad_of(dis, dis_p, feats, soft1, ks[1]))
            dis_p = pseudo(dis_p, grad_of(dis, dis_p, fake, soft0, ks[2]))
            z2 = jax.random.uniform(ks[3], (batch, z_size), jnp.float32, -1.0, 1.0)
            g_gan = grad_of(gan, gan_p, z2, ones, ks[4])
            gan_p = pseudo(gan_p, g_gan)
            nudge = 1e-12 * jnp.sum(jax.tree_util.tree_leaves(g_gan)[0])
            gen_p = jax.tree_util.tree_map(lambda p: p - nudge, gen_p)
            cv_p = pseudo(cv_p, grad_of(cv, cv_p, feats, labels, ks[5]))
            return (dis_p, gan_p, cv_p, gen_p), ()

        carry, _ = jax.lax.scan(
            step,
            (dis_p, gan_p, cv_p, gen_p),
            jnp.arange(SCAN_K),
        )
        return carry

    args = (exp.dis_state.params, exp.gan_state.params,
            exp.cv_state.params, exp.gen_params)
    from gan_deeplearning4j_tpu.harness.experiment import cost_analysis_dict

    cost = cost_analysis_dict(loop.lower(*args).compile().cost_analysis())
    flops_per_call = float(cost["flops"]) if cost and "flops" in cost else None
    sec_per_call = _timed_calls(
        lambda: loop(*args),
        lambda out: np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1],
        min_s=3.0,
    )
    sec_per_iter = sec_per_call / SCAN_K
    mfu = None
    if peak and flops_per_call:
        mfu = flops_per_call / (sec_per_call * peak)
    return {
        "batch": batch,
        "sec_per_iter": round(sec_per_iter, 6),
        "flops_per_iter": (
            flops_per_call / SCAN_K if flops_per_call else None
        ),
        "images_per_sec": round(batch / sec_per_iter, 2),
        "bare_mfu": round(mfu, 4) if mfu is not None else None,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="artifacts/mfu_ceiling.json")
    ap.add_argument("--gemm-n", type=int, default=4096)
    ap.add_argument("--batches", default="64,256")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    kind = jax.devices()[0].device_kind
    peak = _peak_flops(kind)
    report = {
        "platform": jax.default_backend(),
        "device_kind": kind,
        "peak_flops_assumed": peak,
        "scan_window": SCAN_K,
        "gemm": bench_gemm(args.gemm_n, jnp.bfloat16, peak),
        "bare": {},
    }
    print(json.dumps({"gemm": report["gemm"]}), flush=True)
    for b in [int(x) for x in args.batches.split(",")]:
        report["bare"][str(b)] = bench_bare(b, peak)
        print(json.dumps({f"bare_b{b}": report["bare"][str(b)]}), flush=True)

    # achieved (full-iteration) MFU from the bench artifact, when present
    try:
        with open("artifacts/benchmarks.json") as fh:
            rs = {r.get("config"): r for r in json.load(fh)["results"]}
        report["achieved"] = {
            "1": rs.get("1", {}).get("mfu"),
            "1b": rs.get("1b", {}).get("mfu"),
        }
    except (OSError, ValueError, KeyError):
        report["achieved"] = None

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2)
    print(json.dumps(report), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
