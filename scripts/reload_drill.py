#!/usr/bin/env python
"""Reload drill — a live server under load tracks a training run, swap by
swap, and a poisoned generation is quarantined by the canary gate.

The drill is the executable form of docs/DEPLOY.md's invariants, against
real subprocesses:

1. **seed bundle** — an untrained experiment publishes serving generation
   0 into a fresh serve store (in-process; publishing is cheap).
2. **live server** — ``python -m gan_deeplearning4j_tpu.serving
   --reload-store`` boots from that generation, warms synchronously, and
   starts its reload plane (watcher poll + canary gate on the workload's
   own data). Closed-loop client threads then hammer ``/v1/sample`` for
   the rest of the drill.
3. **supervisor segment** — ``python -m gan_deeplearning4j_tpu.resilience
   --serve-store`` trains the toy workload, publishing serving bundles on
   cadence. The drill watches ``/healthz`` and requires the server to
   swap to ≥ 2 newer generations and to converge on the trainer's FINAL
   generation — with **zero** requests lost and **zero** shed across every
   swap (the zero-downtime invariant).
4. **poison** — the drill republishes the newest bundle with a saturated
   (all-weights-large) generator: digest-VALID, quality-garbage. A forced
   ``POST /admin/reload?block=1`` must reject it at the canary gate,
   quarantine it through the store, and keep serving the good generation.
5. **evidence** — the server's span trace (``GET /debug/spans``) must
   contain a ``deploy.swap`` span, and the Prometheus
   ``serving_generation`` gauge must equal the final good generation.

Results land as a BENCH-style JSON (``--output``; ``--record TAG`` also
writes ``BENCH_reload_<TAG>.json`` at the repo root). Exit status is
nonzero on any invariant breach, so CI gates on the drill directly
(``scripts/tpu_campaign.sh`` runs ``--smoke`` CPU-pinned after the
resilience drill).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from resilience_drill import make_workload  # noqa: E402 (scripts/ sibling)

SERVER = [sys.executable, "-m", "gan_deeplearning4j_tpu.serving"]
WORKER = [sys.executable, "-m", "gan_deeplearning4j_tpu.resilience"]

# Subprocesses run with the persistent XLA compilation cache OFF for the
# same reason the resilience drill's workers do (XLA:CPU AOT loader
# hazard — see resilience_drill.run_worker): a cache-poisoned segfault
# must not masquerade as a reload failure.
_ENV = {**os.environ, "GDT_COMPILATION_CACHE": "off"}


def log(msg: str) -> None:
    print(f"[reload-drill] {msg}", flush=True)


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def http_json(method: str, url: str, payload=None, timeout: float = 10.0,
              headers=None):
    """(status, decoded JSON body) — None body on connection failure.
    ``headers`` adds/overrides request headers (the fleet drill's
    X-Trace-Id propagation probe)."""
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json",
                                          **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        try:
            return exc.code, json.loads(exc.read())
        except (ValueError, OSError):
            return exc.code, None
    except (urllib.error.URLError, OSError, TimeoutError):
        return None, None


def seed_bundle(workload: dict, serve_store_root: str, keep_last: int) -> int:
    """Publish generation 0 (the untrained model) so the server has an
    initial bundle to boot from; returns the generation number."""
    from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment
    from gan_deeplearning4j_tpu.resilience import CheckpointStore

    cfg = ExperimentConfig.from_json(workload["config"])
    exp = GanExperiment(cfg)
    store = CheckpointStore(serve_store_root, keep_last=keep_last)
    info = exp.publish_for_serving(store=store)
    return info["generation"]


def poison_newest(serve_store_root: str, keep_last: int) -> int:
    """Republish the newest bundle with a saturated generator — every
    weight pushed far positive, so the sigmoid output pins at 1.0:
    digest-valid bytes, collapsed model. Returns the poisoned generation
    number."""
    from gan_deeplearning4j_tpu.resilience import CheckpointStore
    from gan_deeplearning4j_tpu.utils.serializer import read_model, write_model

    store = CheckpointStore(serve_store_root, keep_last=keep_last)
    newest = store.latest_valid()
    number = store.next_number()

    def writer(staging: str) -> None:
        with open(os.path.join(newest.path, "serving.json")) as fh:
            manifest = json.load(fh)
        for name in os.listdir(newest.path):
            if name == "MANIFEST.json":
                continue
            shutil.copy2(os.path.join(newest.path, name),
                         os.path.join(staging, name))
        gen_zip = os.path.join(staging, manifest["generator"])
        graph, params, _, _ = read_model(gen_zip, load_updater=False)
        import jax

        poisoned = jax.tree_util.tree_map(
            lambda a: np.full_like(np.asarray(a), 25.0), params)
        write_model(gen_zip, graph, poisoned, save_updater=False)
        manifest["generation"] = number
        with open(os.path.join(staging, "serving.json"), "w") as fh:
            json.dump(manifest, fh, indent=2)

    generation = store.publish(writer, step=newest.step,
                               extra={"kind": "serving"})
    if generation.number != number:
        raise RuntimeError(
            f"poisoned bundle labeled generation {number} but the store "
            f"assigned {generation.number} — concurrent writer?")
    return generation.number


class LoadGenerator:
    """Closed-loop /v1/sample clients. Every attempt is accounted: ok,
    shed (overloaded/deadline), error, or lost (no HTTP answer) — the
    zero-lost / zero-shed ledger the swap invariant reads."""

    def __init__(self, base: str, z_size: int, threads: int = 2):
        self.base = base
        self.z_size = z_size
        self.stop = threading.Event()
        self.counts = {"sent": 0, "ok": 0, "shed": 0, "error": 0, "lost": 0}
        self._lock = threading.Lock()
        self._threads = [
            threading.Thread(target=self._run, args=(i,), daemon=True)
            for i in range(threads)
        ]

    def _run(self, tid: int) -> None:
        rng = np.random.default_rng(1000 + tid)
        while not self.stop.is_set():
            rows = (rng.random((int(rng.integers(1, 4)), self.z_size),
                               dtype=np.float32) * 2.0 - 1.0)
            with self._lock:
                self.counts["sent"] += 1
            status, body = http_json(
                "POST", f"{self.base}/v1/sample", {"data": rows.tolist()})
            with self._lock:
                if status is None:
                    self.counts["lost"] += 1
                elif status == 200:
                    self.counts["ok"] += 1
                elif status == 503:
                    self.counts["shed"] += 1
                else:
                    self.counts["error"] += 1
            time.sleep(0.005)  # keep 2 shared cores breathable

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def finish(self) -> dict:
        self.stop.set()
        for t in self._threads:
            t.join(timeout=10.0)
        # the joins are bounded — a straggler stuck in a slow request may
        # still be incrementing, so read under the same lock the workers
        # write under
        with self._lock:
            return dict(self.counts)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="campaign/CI shape: 24 steps, serve-publish every 2")
    p.add_argument("--total-steps", type=int, default=None)
    p.add_argument("--serve-every", type=int, default=None)
    p.add_argument("--publish-every", type=int, default=None)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--keep-last", type=int, default=10,
                   help="serve-store retention (roomy: the server may read "
                        "an older generation while the trainer publishes)")
    p.add_argument("--poll", type=float, default=0.3,
                   help="server reload-plane poll interval")
    p.add_argument("--workdir", default=None,
                   help="keep work files here instead of a temp dir")
    p.add_argument("--output", default=None, metavar="PATH",
                   help="write the drill JSON here")
    p.add_argument("--record", default=None, metavar="TAG",
                   help="also write BENCH_reload_<TAG>.json at the repo root")
    args = p.parse_args(argv)

    total = args.total_steps or (24 if args.smoke else 60)
    serve_every = args.serve_every or (2 if args.smoke else 3)
    publish_every = args.publish_every or (6 if args.smoke else 10)
    workdir = args.workdir or tempfile.mkdtemp(prefix="reload_drill_")
    cleanup = args.workdir is None
    os.makedirs(workdir, exist_ok=True)
    serve_store = os.path.join(workdir, "store_serve")
    train_store = os.path.join(workdir, "store_train")

    workload = make_workload(workdir, args.seed)
    results: dict = {}
    invariants: dict = {}
    server = worker = None
    load = None
    port = free_port()
    base = f"http://127.0.0.1:{port}"

    try:
        # -- phase 1: seed bundle + live server -------------------------
        gen0 = seed_bundle(workload, serve_store, args.keep_last)
        log(f"seeded serving generation {gen0}")
        server_log = open(os.path.join(workdir, "server.log"), "w")
        server = subprocess.Popen(
            SERVER + [
                "--reload-store", serve_store,
                "--reload-poll", str(args.poll),
                "--canary-data", workload["data"],
                "--canary-samples", "48",
                "--canary-fid-ratio", "1.1",
                "--canary-fid-slack", "0.5",
                "--host", "127.0.0.1", "--port", str(port),
                "--replicas", "1", "--buckets", "1,8",
                "--max-latency", "0.002",
                "--warmup", "sync", "--telemetry",
            ],
            cwd=_REPO, env=_ENV, stdout=server_log, stderr=server_log,
        )
        deadline = time.monotonic() + 240.0
        health = None
        while time.monotonic() < deadline:
            if server.poll() is not None:
                log(f"server exited rc={server.returncode} during startup")
                return 2
            status, health = http_json("GET", f"{base}/healthz", timeout=5.0)
            if status == 200 and health and health.get("status") == "ok":
                break
            time.sleep(0.25)
        else:
            log("server never became healthy — cannot drill")
            return 2
        if health["generation"] != gen0:
            raise RuntimeError(
                f"server booted from generation {health['generation']}, "
                f"expected the seeded {gen0}")
        z_size = 4  # the drill workload's latent width (make_workload)
        log(f"server healthy on {base}, serving generation {gen0}")

        # -- phase 2: load + supervisor segment -------------------------
        load = LoadGenerator(base, z_size)
        load.start()
        worker_log = open(os.path.join(workdir, "worker.log"), "w")
        worker = subprocess.Popen(
            WORKER + [
                "--config", workload["config"], "--data", workload["data"],
                "--store", train_store,
                "--serve-store", serve_store,
                "--total-steps", str(total),
                "--publish-every", str(publish_every),
                "--serve-publish-every", str(serve_every),
                "--keep-last", str(args.keep_last),
                "--summary", os.path.join(workdir, "worker_summary.json"),
            ],
            cwd=_REPO, env=_ENV, stdout=worker_log, stderr=worker_log,
        )
        generations_seen = [gen0]
        t_worker = time.monotonic()
        while worker.poll() is None:
            if time.monotonic() - t_worker > 600.0:
                worker.kill()
                log("worker hung — killed")
                break
            status, body = http_json("GET", f"{base}/healthz", timeout=5.0)
            if status == 200 and body:
                g = body.get("generation")
                if g is not None and g != generations_seen[-1]:
                    generations_seen.append(g)
                    log(f"server swapped to generation {g} "
                        f"(reload: {body.get('reload')})")
            time.sleep(0.1)
        worker_rc = worker.returncode
        try:
            with open(os.path.join(workdir, "worker_summary.json")) as fh:
                worker_summary = json.load(fh)
        except (OSError, json.JSONDecodeError):
            worker_summary = {}  # a dead worker breaches the invariants below
        final_gen = worker_summary.get("final_serve_generation")
        log(f"worker done rc={worker_rc}, final serve generation {final_gen}")

        # convergence: the server must reach the trainer's final generation
        deadline = time.monotonic() + 60.0
        converged = False
        while time.monotonic() < deadline:
            status, body = http_json("GET", f"{base}/healthz", timeout=5.0)
            if status == 200 and body:
                g = body.get("generation")
                if g != generations_seen[-1] and g is not None:
                    generations_seen.append(g)
                    log(f"server swapped to generation {g}")
                if g == final_gen:
                    converged = True
                    break
            time.sleep(0.1)
        swaps = len(generations_seen) - 1
        _, metrics = http_json("GET", f"{base}/metrics", timeout=5.0)
        results["swap_phase"] = {
            "worker_rc": worker_rc,
            "serve_publishes": worker_summary.get("serve_publish_count"),
            "final_serve_generation": final_gen,
            "generations_seen": generations_seen,
            "swaps_observed": swaps,
            "engine_swaps_metric": (metrics or {}).get("engine_swaps"),
            "converged_to_final": converged,
        }
        invariants["swaps_ge_2"] = swaps >= 2
        invariants["converged_to_final_generation"] = converged

        # the span trace must show the swap (fetched before poison-phase
        # traffic can age it out of the ring)
        trace_path = os.path.join(workdir, "reload_trace.json")
        _, trace = http_json("GET", f"{base}/debug/spans", timeout=10.0)
        span_names = {e.get("name") for e in (trace or {}).get(
            "traceEvents", [])}
        with open(trace_path, "w") as fh:
            json.dump(trace or {}, fh)
        invariants["trace_has_swap_span"] = "deploy.swap" in span_names
        results["trace"] = {"path": trace_path,
                            "events": len((trace or {}).get("traceEvents",
                                                            []))}

        # -- phase 3: poison + canary quarantine ------------------------
        poison = poison_newest(serve_store, args.keep_last)
        log(f"published poisoned generation {poison}")
        # force the poll — but the periodic watcher may already be
        # mid-cycle on the poison (409 is then the CORRECT busy answer),
        # so drive to the OUTCOME: the reload plane reports a rejection
        deadline = time.monotonic() + 120.0
        rejected = False
        while time.monotonic() < deadline and not rejected:
            status, body = http_json(
                "POST", f"{base}/admin/reload?block=1", {}, timeout=120.0)
            log(f"forced reload: {status} "
                f"{(body or {}).get('reload') or (body or {}).get('error')}")
            _, h = http_json("GET", f"{base}/healthz", timeout=5.0)
            rejected = ((h or {}).get("reload", {}).get("rejected", 0) >= 1)
            if not rejected:
                time.sleep(0.5)
        # with the poison quarantined, a forced blocking poll finds
        # nothing newer and answers 200 — the admin route's happy path
        status, body = http_json(
            "POST", f"{base}/admin/reload?block=1", {}, timeout=120.0)
        _, after = http_json("GET", f"{base}/healthz", timeout=5.0)
        from gan_deeplearning4j_tpu.resilience import CheckpointStore

        entry = CheckpointStore(serve_store,
                                keep_last=args.keep_last).entry(poison)
        reload_state = (after or {}).get("reload", {})
        results["poison_phase"] = {
            "poisoned_generation": poison,
            "admin_reload_status": status,
            "ledger_status": entry.get("status"),
            "quarantine_reason": entry.get("reason"),
            "served_generation_after": (after or {}).get("generation"),
            "reload": reload_state,
        }
        invariants["poison_quarantined"] = (
            entry.get("status") == "quarantined"
            and "canary" in (entry.get("reason") or ""))
        invariants["poison_never_served"] = (
            poison not in generations_seen
            and (after or {}).get("generation") == final_gen)
        invariants["rejection_surfaced"] = (
            status == 200 and (reload_state.get("rejected") or 0) >= 1)

        # -- phase 4: ledgers + the gauge -------------------------------
        counts = load.finish()
        load = None
        results["requests"] = counts
        invariants["zero_lost"] = (
            counts["lost"] == 0 and counts["error"] == 0
            and counts["ok"] == counts["sent"])
        invariants["zero_shed_during_swaps"] = counts["shed"] == 0
        prom_gauge = None
        try:
            with urllib.request.urlopen(
                    f"{base}/metrics?format=prom", timeout=5.0) as resp:
                for line in resp.read().decode().splitlines():
                    if line.startswith("serving_generation "):
                        prom_gauge = float(line.split()[-1])
        except (urllib.error.URLError, OSError):
            pass
        results["serving_generation_gauge"] = prom_gauge
        invariants["gauge_tracks_served_generation"] = (
            prom_gauge is not None and final_gen is not None
            and int(prom_gauge) == int(final_gen))
    finally:
        if load is not None:
            load.finish()
        for proc in (worker, server):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    proc.kill()

    # -- verdict ---------------------------------------------------------
    ok = bool(invariants) and all(invariants.values())
    payload = {
        "bench": "reload_drill",
        "config": {
            "total_steps": total,
            "publish_every": publish_every,
            "serve_publish_every": serve_every,
            "poll_interval": args.poll,
            "seed": args.seed,
            "smoke": bool(args.smoke),
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "results": results,
        "invariants": invariants,
        "ok": ok,
    }
    text = json.dumps(payload, indent=2)
    print(text)
    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                    exist_ok=True)
        with open(args.output, "w") as fh:
            fh.write(text + "\n")
    if args.record:
        with open(os.path.join(_REPO, f"BENCH_reload_{args.record}.json"),
                  "w") as fh:
            fh.write(text + "\n")
    if cleanup and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        log(f"INVARIANT BREACH — work files kept at {workdir}")
    for name, good in sorted(invariants.items()):
        log(f"invariant {name}: {'ok' if good else 'BREACH'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
