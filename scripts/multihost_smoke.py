"""Multi-host smoke: real ``jax.distributed`` coordination across processes.

The reference's distribution layer is built for genuine multi-JVM clusters
(Spark driver + Kryo-serialized task shipping,
dl4jGANComputerVision.java:317-330) even though it runs ``local[4]`` in-tree.
Our analog: each host process runs this script with a process id; they meet
at a gRPC coordinator (``runtime.environment.initialize_distributed`` — the
Spark-driver analog), form ONE global device mesh spanning both processes,
and run

1. one ``GraphTrainer`` pmean step (per-step gradient all-reduce), and
2. one ``ParameterAveragingTrainer`` round (k local steps then cross-worker
   parameter+updater averaging),

on globally-sharded batches built with ``jax.make_array_from_process_local_data``
(each process contributes only its local rows — nothing is gathered on a
"driver"). Every process asserts its local replicas are bit-identical and
prints a params checksum; the caller (tests/test_multihost.py or
``__graft_entry__.dryrun_multihost``) asserts the checksums agree ACROSS
processes — the cross-host equivalent of the reference's broadcast-back
invariant (SURVEY §3.3).

Rendezvous discipline: with ``--barrier-root DIR`` (a directory every
process can reach — the launcher's scratch dir locally, the shared store
root on a real cluster) the processes meet at ``resilience/mesh.py`` file
barriers instead of ad-hoc trust: a ``boot`` barrier BEFORE
``jax.distributed`` init (so a never-launched peer surfaces as a bounded
timeout and exit 3, not a gRPC dial that blocks forever) and a ``done``
barrier after the last mode (so a peer that died mid-run fails THIS
process loudly too, instead of leaving the parent to diff checksums
against a ghost). Barrier timeout = exit 3, always nonzero.

Run one process per host:
    python scripts/multihost_smoke.py --coordinator HOST:PORT \
        --num-processes N --process-id I [--local-devices 2] \
        [--barrier-root DIR] [--barrier-timeout S]
"""

from __future__ import annotations

import argparse
import os
import sys


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--coordinator", required=True, help="host:port of process 0")
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--local-devices", type=int, default=2,
                    help="virtual CPU devices per process (TPU: real chips)")
    ap.add_argument("--platform", default="cpu",
                    help="cpu (virtual mesh) or tpu (real pod slice)")
    ap.add_argument("--barrier-root", default=None, metavar="DIR",
                    help="shared dir for resilience.mesh file barriers — "
                         "use a FRESH dir per launch (stale arrival "
                         "markers from a previous run would satisfy the "
                         "boot barrier instantly); omit to skip the "
                         "barrier discipline (hosts without a shared "
                         "filesystem)")
    ap.add_argument("--barrier-timeout", type=float, default=240.0,
                    help="bound on each rendezvous; expiry exits 3")
    args = ap.parse_args()

    if args.platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={args.local_devices}"
            ).strip()

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from gan_deeplearning4j_tpu.resilience.mesh import MeshCoordinator, MeshTimeout

    barrier = None
    if args.barrier_root:
        # sweep=False: the smoke only borrows the BARRIER primitive — a
        # shared barrier root may also be a live checkpoint gang's store
        # root, and the coordinator's stale-gang sweep would read that
        # gang's in-flight round as a corpse
        barrier = MeshCoordinator(
            args.barrier_root, worker=args.process_id,
            world_size=args.num_processes, token="smoke",
            timeout_s=args.barrier_timeout, sweep=False,
        )

    def rendezvous(name: str) -> None:
        """Meet the other processes at a bounded file barrier — a peer
        that never shows up becomes exit 3 here, not an unbounded gRPC
        dial or a parent-side checksum diff against a ghost."""
        if barrier is None:
            return
        try:
            barrier.barrier(name)
        except MeshTimeout as exc:
            print(f"[multihost] BARRIER TIMEOUT at {name!r}: {exc}",
                  flush=True)
            raise SystemExit(3)

    # boot rendezvous BEFORE jax.distributed: initialize_distributed's
    # coordinator dial blocks unboundedly when a peer was never launched —
    # the barrier turns that into a bounded, loud failure
    rendezvous("boot")

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from gan_deeplearning4j_tpu.models import mlp_gan
    from gan_deeplearning4j_tpu.parallel import GraphTrainer, ParameterAveragingTrainer
    from gan_deeplearning4j_tpu.runtime.environment import initialize_distributed

    initialize_distributed(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    # explicit check, not a bare assert (stripped under -O — jaxlint JG003):
    # a half-formed cluster must die loudly before any collective hangs
    if jax.process_count() != args.num_processes:
        raise SystemExit(
            f"[multihost] expected {args.num_processes} processes, backend "
            f"reports {jax.process_count()} — coordinator/process_id flags "
            f"disagree with the cluster that actually formed"
        )
    n_global = jax.device_count()
    n_local = jax.local_device_count()
    print(
        f"[multihost] process {args.process_id}/{args.num_processes} up: "
        f"{n_local} local / {n_global} global devices",
        flush=True,
    )

    mesh = jax.sharding.Mesh(np.asarray(jax.devices()).reshape(n_global), ("data",))
    data_sharding = NamedSharding(mesh, P("data"))

    cfg = mlp_gan.MlpGanConfig(num_features=8, z_size=2, hidden=(16,))
    graph = mlp_gan.build_discriminator(cfg)

    def global_batch(rows_global: int, seed: int):
        """Each process materializes ONLY its own rows of the global batch
        (deterministic per-row stream, so the global batch is well-defined
        regardless of process count)."""
        rng = np.random.default_rng(seed)
        feats = rng.random((rows_global, cfg.num_features), dtype=np.float32)
        labels = (rng.random((rows_global, 1)) > 0.5).astype(np.float32)
        rows_local = rows_global // jax.process_count()
        lo = args.process_id * rows_local
        local = slice(lo, lo + rows_local)
        return (
            jax.make_array_from_process_local_data(data_sharding, feats[local]),
            jax.make_array_from_process_local_data(data_sharding, labels[local]),
        )

    def checksum(tree) -> str:
        """Order-stable BYTE digest of a pytree: sha256 over each leaf's
        first addressable shard, leaves sorted by path. Two processes print
        the same digest iff their replicated states are BIT-identical —
        a %.f-rounded scalar sum could hide small or cancelling divergence."""
        import hashlib

        h = hashlib.sha256()
        for path, leaf in sorted(
            jax.tree_util.tree_flatten_with_path(tree)[0], key=lambda kv: str(kv[0])
        ):
            shards = getattr(leaf, "addressable_shards", None)
            data = shards[0].data if shards else leaf
            h.update(str(path).encode())
            h.update(np.ascontiguousarray(np.asarray(data)).tobytes())
        return h.hexdigest()[:16]

    # local-replica equality: the shared invariant checker from the driver
    # entry module (don't duplicate it here)
    from __graft_entry__ import _assert_replicated

    def assert_local_replicas_equal(tree, what: str) -> None:
        _assert_replicated(tree, what)

    # -- 1. per-step pmean over the cross-process mesh ----------------------
    trainer = GraphTrainer(graph, mesh=mesh)
    state = trainer.init_state()
    feats, labels = global_batch(2 * n_global, seed=1)
    state, loss = trainer.train_step(state, feats, labels)
    assert_local_replicas_equal(state.params, "pmean params")
    print(
        f"[multihost] mode=pmean loss={float(loss):.6f} "
        f"checksum={checksum(state.params)}",
        flush=True,
    )

    # -- 2. one parameter-averaging round (k local steps, then the mean) ----
    freq, per_worker = 2, 2
    pa = ParameterAveragingTrainer(
        graph, mesh, batch_size_per_worker=per_worker, averaging_frequency=freq
    )
    pa_state = pa.init_state()
    feats, labels = global_batch(n_global * freq * per_worker, seed=2)
    pa_state, losses = pa.fit_round(pa_state, feats, labels)
    assert_local_replicas_equal(pa_state.params, "averaged params")
    assert_local_replicas_equal(pa_state.opt_state, "averaged updater state")
    print(
        f"[multihost] mode=param_averaging mean_loss={float(jnp.mean(losses)):.6f} "
        f"checksum={checksum(pa_state.params)}",
        flush=True,
    )

    # -- 3. WGAN-GP round (scanned grad-of-grad critic + generator step) ----
    # (round-4 VERDICT item 8: the WGAN mode joins the cross-process smoke)
    from gan_deeplearning4j_tpu.models.wgan_gp import WganGpConfig, WganGpTrainer

    wcfg = WganGpConfig(
        height=8, width=8, channels=1, z_size=4, base_filters=8,
        dense_width=32, n_critic=2, seed=666,
    )
    wtr = WganGpTrainer(wcfg, mesh=mesh)
    critic_state, gen_state = wtr.init_states(seed=0)
    rngw = np.random.default_rng(3)
    rows_global = n_global  # one row per device per critic minibatch
    rows_local = rows_global // jax.process_count()
    lo = args.process_id * rows_local
    real_global = rngw.random(
        (wcfg.n_critic, rows_global, wcfg.num_features), dtype=np.float32
    )
    batches_sharding = NamedSharding(mesh, P(None, "data"))
    real_batches = jax.make_array_from_process_local_data(
        batches_sharding, real_global[:, lo : lo + rows_local]
    )
    critic_state, gen_state, c_loss, g_loss = wtr.train_round(
        critic_state, gen_state, real_batches, jax.random.PRNGKey(7)
    )
    assert_local_replicas_equal(critic_state.params, "wgan critic params")
    assert_local_replicas_equal(gen_state.params, "wgan gen params")
    print(
        f"[multihost] mode=wgan_gp c_loss={float(c_loss):.6f} "
        f"g_loss={float(g_loss):.6f} "
        f"checksum={checksum((critic_state.params, gen_state.params))}",
        flush=True,
    )
    # done rendezvous: a peer that died after its own modes must fail THIS
    # process too — the smoke's contract is all-N-or-nobody
    rendezvous("done")
    print(f"[multihost] process {args.process_id} OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
