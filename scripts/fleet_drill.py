#!/usr/bin/env python
"""Fleet drill — real faults against the multi-process serving plane.

The executable form of docs/FLEET.md's invariants, against real
subprocesses under closed-loop load through the router:

1. **boot** — an untrained experiment seeds serving generation 0 into a
   fresh store; ``python -m gan_deeplearning4j_tpu.fleet`` spawns N
   workers from it plus the router, and the drill waits until every
   worker is warm and routable. Closed-loop client threads then hammer
   the ROUTER's ``/v1/sample`` for the rest of the drill.
2. **SIGKILL** — one worker is hard-killed. The router ejects it (or the
   manager relaunches it first — whichever signal lands first), requests
   in flight there are retried on another worker under the budget, and
   the slot must come back routable with a fresh process.
3. **SIGSTOP** — one worker is hung, not killed. Per-request timeouts
   plus the passive breaker must eject it; after SIGCONT the half-open
   probe must RE-ADMIT it without a restart (the hang was transient).
4. **rolling upgrade** — a supervisor segment trains and publishes newer
   serving generations on cadence; the fleet must admit them through ONE
   sidecar canary decision each and roll workers one at a time, ending
   converged on the trainer's final generation.
5. **poison** — a digest-valid but quality-garbage generation is
   published. The fleet admission gate must reject it, quarantine it
   through the store (fleet-wide, once), and no worker may ever serve it.
6. **trace propagation + fleet aggregation** — a deliberately-retried
   request (a client deadline no worker can meet, so every attempt sheds
   and the router re-routes it) must leave spans carrying ONE trace id on
   the router and at least two distinct worker pids in the router's
   merged ``GET /debug/trace``; ``trace_report`` must fold that merged
   trace with rc 0. ``GET /metrics?scope=fleet`` (JSON and Prometheus)
   must sum per-worker request counters EXACTLY against simultaneous
   direct worker scrapes, and the router's own ok counter must equal the
   load ledger's ok count — the zero-lost ledger and the aggregated
   metrics are the same numbers or one of them is lying.
7. **ledger** — every submitted request got exactly one answer, zero
   lost, client-visible 503s bounded by the router's own honest-503
   counters (the retry-budget contract), zero 5xx, and every worker's
   ``serve_compile_counts`` stays 0 (re-routing cannot break the
   bounded-compile invariant).

Results land as a BENCH-style JSON (``--output``; ``--record TAG`` also
writes ``BENCH_fleet_<TAG>.json`` at the repo root). Exit status is
nonzero on any invariant breach, so CI gates on the drill directly
(``scripts/tpu_campaign.sh`` runs ``--smoke`` CPU-pinned after the
reload drill).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from resilience_drill import make_workload  # noqa: E402 (scripts/ sibling)
from reload_drill import (  # noqa: E402
    free_port,
    http_json,
    poison_newest,
    seed_bundle,
)

FLEET = [sys.executable, "-m", "gan_deeplearning4j_tpu.fleet"]
TRAINER = [sys.executable, "-m", "gan_deeplearning4j_tpu.resilience"]

# Subprocesses run with the persistent XLA compilation cache OFF for the
# same reason the resilience/reload drills' workers do (XLA:CPU AOT
# loader hazard): a cache-poisoned segfault must not masquerade as a
# fleet failure.
_ENV = {**os.environ, "GDT_COMPILATION_CACHE": "off"}


def log(msg: str) -> None:
    print(f"[fleet-drill] {msg}", flush=True)


class LoadGenerator:
    """Closed-loop /v1/sample clients against the ROUTER. Every attempt
    is accounted: ok (200), shed (503), error (other status), or lost
    (no HTTP answer at all) — the exactly-one-answer ledger. The client
    timeout leaves room for the router's full retry schedule, so a slow
    answer is never misread as a lost one."""

    def __init__(self, base: str, z_size: int, threads: int = 2,
                 timeout: float = 30.0):
        self.base = base
        self.z_size = z_size
        self.timeout = timeout
        self.stop = threading.Event()
        self.counts = {"sent": 0, "ok": 0, "shed": 0, "error": 0, "lost": 0}
        self._lock = threading.Lock()
        self._threads = [
            threading.Thread(target=self._run, args=(i,), daemon=True)
            for i in range(threads)
        ]

    def _run(self, tid: int) -> None:
        rng = np.random.default_rng(2000 + tid)
        while not self.stop.is_set():
            rows = (rng.random((int(rng.integers(1, 4)), self.z_size),
                               dtype=np.float32) * 2.0 - 1.0)
            with self._lock:
                self.counts["sent"] += 1
            status, _ = http_json(
                "POST", f"{self.base}/v1/sample", {"data": rows.tolist()},
                timeout=self.timeout)
            with self._lock:
                if status is None:
                    self.counts["lost"] += 1
                elif status == 200:
                    self.counts["ok"] += 1
                elif status == 503:
                    self.counts["shed"] += 1
                else:
                    self.counts["error"] += 1
            time.sleep(0.005)  # keep 2 shared cores breathable

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def finish(self) -> dict:
        self.stop.set()
        for t in self._threads:
            t.join(timeout=self.timeout + 5.0)
        return dict(self.counts)


class FleetMonitor:
    """Polls the router's /healthz continuously, recording every (worker,
    generation) pair observed and the routable-count envelope — the
    drill's ground truth for 'the poison was never served' and 'the
    ejection actually happened'."""

    def __init__(self, base: str):
        self.base = base
        self.stop = threading.Event()
        self.generations_served: set = set()
        self.min_routable: int = 10**9
        self.max_routable: int = 0
        self.last: dict = {}
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        while not self.stop.is_set():
            status, body = http_json("GET", f"{self.base}/healthz",
                                     timeout=5.0)
            if status == 200 and body:
                self.last = body
                self.min_routable = min(self.min_routable,
                                        body.get("routable", 0))
                self.max_routable = max(self.max_routable,
                                        body.get("routable", 0))
                for w in body.get("workers", []):
                    if w.get("routable") and w.get("generation") is not None:
                        self.generations_served.add(w["generation"])
            time.sleep(0.1)

    def start(self) -> None:
        self._thread.start()

    def finish(self) -> None:
        self.stop.set()
        self._thread.join(timeout=10.0)


def fleet_health(base: str):
    _, body = http_json("GET", f"{base}/healthz", timeout=5.0)
    return body or {}


def wait_for(predicate, deadline_s: float, what: str, interval: float = 0.2):
    """Poll until predicate() is truthy; returns its value (None on
    timeout, logged — the caller's invariant records the breach)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval)
    log(f"TIMEOUT waiting for {what} ({deadline_s:.0f}s)")
    return None


def worker_by_id(health: dict, worker_id: str) -> dict:
    for w in (health.get("fleet") or {}).get("workers", []):
        if w["id"] == worker_id:
            return w
    return {}


def router_worker(health: dict, worker_id: str) -> dict:
    for w in health.get("workers", []):
        if w["id"] == worker_id:
            return w
    return {}


def run_trace_phase(base: str, z_size: int, worker_pids: set,
                    trace_out: str, invariants: dict) -> dict:
    """Phase 5a — prove one trace id threads a retried request across the
    router and two distinct worker processes. The probe request carries a
    client deadline no worker can meet (1 µs), so every attempt sheds
    with a worker-side ``serve.request`` span and the router re-routes to
    a different worker; the router's merged ``GET /debug/trace`` must
    then show the id on ≥2 worker pids plus the router's own spans, and
    ``trace_report`` must fold the merged artifact with rc 0."""
    rows = [[0.0] * z_size]
    chosen = None
    observed: dict = {}
    for attempt in range(5):
        tid = f"drill-retry-{attempt}"
        status, _ = http_json(
            "POST", f"{base}/v1/sample", {"data": rows, "timeout": 1e-6},
            timeout=30.0, headers={"X-Trace-Id": tid})
        _, merged = http_json("GET", f"{base}/debug/trace", timeout=20.0)
        events = (merged or {}).get("traceEvents") or []
        pids = {e.get("pid") for e in events
                if (e.get("args") or {}).get("trace_id") == tid}
        observed = {
            "trace_id": tid, "probe_status": status,
            "pids_with_id": sorted(p for p in pids if p is not None),
            "worker_pids": sorted(worker_pids),
            "merged_events": len(events),
        }
        if len(pids & worker_pids) >= 2 and (pids - worker_pids):
            chosen = merged
            break
        time.sleep(0.3)
    invariants["trace_one_id_spans_router_and_two_workers"] = (
        chosen is not None)
    rc = None
    if chosen is not None:
        with open(trace_out, "w") as fh:
            json.dump(chosen, fh)
            fh.write("\n")
        report = subprocess.run(
            [sys.executable, os.path.join(_REPO, "scripts",
                                          "trace_report.py"), trace_out],
            capture_output=True, text=True, timeout=120.0)
        rc = report.returncode
        log(f"trace_report on merged fleet trace: rc={rc}")
        observed["trace_out"] = trace_out
    invariants["trace_report_folds_merged_trace"] = rc == 0
    observed["trace_report_rc"] = rc
    return observed


def _counter_total(snapshot: dict, family: str, match=None) -> float:
    total = 0.0
    for s in ((snapshot or {}).get(family) or {}).get("series", []):
        labels = s.get("labels") or {}
        if match and any(labels.get(k) != v for k, v in match.items()):
            continue
        total += float(s.get("value", 0.0))
    return total


def run_aggregation_phase(base: str, worker_ports: list, counts: dict,
                          invariants: dict) -> dict:
    """Phase 5b — the aggregation-exactness story, on frozen counters:
    the fleet-scope snapshot (JSON and Prometheus) must sum per-worker
    ``serve_requests_total`` EXACTLY against simultaneous direct worker
    scrapes, report zero scrape gaps, and the router's own ok counter
    must equal the load ledger's ok count. Also checks the satellite
    surfaces: SLO block and per-worker scrape staleness in /healthz."""
    import urllib.request

    direct_total = 0.0
    per_worker: dict = {}
    for port in worker_ports:
        _, snap = http_json(
            "GET", f"http://127.0.0.1:{port}/metrics?scope=registry",
            timeout=10.0)
        t = _counter_total(snap, "serve_requests_total")
        per_worker[str(port)] = t
        direct_total += t
    _, fleet_snap = http_json("GET", f"{base}/metrics?scope=fleet",
                              timeout=30.0)
    fleet_snap = fleet_snap or {}
    fleet_total = _counter_total(fleet_snap, "serve_requests_total")
    router_ok = _counter_total(fleet_snap, "fleet_requests_total",
                               match={"outcome": "ok"})
    gaps = (fleet_snap.get("_fleet") or {}).get("gaps")

    prom_total = None
    try:
        with urllib.request.urlopen(
                f"{base}/metrics?scope=fleet&format=prom",
                timeout=30.0) as resp:
            prom_text = resp.read().decode()
        prom_total = sum(
            float(line.rsplit(" ", 1)[1])
            for line in prom_text.splitlines()
            if line.startswith("serve_requests_total{"))
    except (OSError, ValueError):
        pass

    health = fleet_health(base)
    slo = health.get("slo") or {}
    ages = [w.get("last_scrape_age_s") for w in health.get("workers", [])]

    invariants["fleet_counter_sum_exact"] = (
        fleet_total == direct_total > 0)
    invariants["fleet_prom_matches_json"] = prom_total == fleet_total
    invariants["fleet_scrape_no_gaps"] = gaps == []
    invariants["router_ok_counter_matches_ledger"] = (
        router_ok == counts["ok"])
    invariants["slo_surfaced_with_traffic"] = (
        (slo.get("totals") or {}).get("requests", 0) >= counts["sent"])
    invariants["worker_scrape_age_surfaced"] = bool(ages) and all(
        isinstance(a, (int, float)) for a in ages)
    return {
        "per_worker_requests": per_worker,
        "direct_total": direct_total,
        "fleet_total": fleet_total,
        "prom_total": prom_total,
        "router_ok": router_ok,
        "ledger_ok": counts["ok"],
        "gaps": gaps,
        "slo": slo,
        "last_scrape_age_s": ages,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="campaign/CI shape: 2 workers, 12 trainer steps")
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--total-steps", type=int, default=None)
    p.add_argument("--serve-every", type=int, default=None)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--keep-last", type=int, default=10)
    p.add_argument("--workdir", default=None,
                   help="keep work files here instead of a temp dir")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="where the merged fleet Chrome trace lands "
                        "(default: <workdir>/fleet_trace.json); "
                        "tpu_campaign.sh gates trace_report on it")
    p.add_argument("--output", default=None, metavar="PATH")
    p.add_argument("--record", default=None, metavar="TAG",
                   help="also write BENCH_fleet_<TAG>.json at the repo root")
    args = p.parse_args(argv)

    n_workers = args.workers or (2 if args.smoke else 3)
    total = args.total_steps or (12 if args.smoke else 24)
    serve_every = args.serve_every or 6
    workdir = args.workdir or tempfile.mkdtemp(prefix="fleet_drill_")
    cleanup = args.workdir is None
    os.makedirs(workdir, exist_ok=True)
    serve_store = os.path.join(workdir, "store_serve")
    train_store = os.path.join(workdir, "store_train")

    workload = make_workload(workdir, args.seed)
    results: dict = {}
    invariants: dict = {}
    fleet = trainer = None
    load = monitor = None
    router_port = free_port()
    worker_ports = [free_port() for _ in range(n_workers)]
    base = f"http://127.0.0.1:{router_port}"

    try:
        # -- phase 0: seed + boot the fleet -----------------------------
        gen0 = seed_bundle(workload, serve_store, args.keep_last)
        log(f"seeded serving generation {gen0}")
        fleet_log = open(os.path.join(workdir, "fleet.log"), "w")
        fleet = subprocess.Popen(
            FLEET + [
                "--store", serve_store,
                "--workers", str(n_workers),
                "--port", str(router_port),
                "--worker-ports", ",".join(str(x) for x in worker_ports),
                "--log-dir", workdir,
                "--poll", "0.5", "--probe-interval", "0.15",
                "--request-timeout", "3.0",
                "--retry-ratio", "0.5", "--retry-burst", "10",
                "--eject-failures", "3", "--reopen-after", "0.5",
                "--drain-timeout", "15", "--warm-timeout", "240",
                "--hang-restart", "30",
                "--buckets", "1,8", "--replicas", "1",
                "--max-latency", "0.002",
                "--canary-data", workload["data"],
                "--canary-samples", "32",
                "--canary-fid-ratio", "1.1", "--canary-fid-slack", "0.5",
                "--boot-wait", "60", "--telemetry",
            ],
            cwd=_REPO, env=_ENV, stdout=fleet_log, stderr=fleet_log,
        )
        health = wait_for(
            lambda: (fleet.poll() is None
                     and (h := fleet_health(base)).get("routable")
                     == n_workers and h.get("generation") == gen0 and h),
            420.0, "fleet healthy on the seed generation")
        if not health:
            log(f"fleet never became healthy (rc={fleet.poll()})")
            return 2
        z_size = 4  # the drill workload's latent width (make_workload)
        log(f"fleet healthy on {base}: {n_workers} workers, "
            f"generation {gen0}")
        monitor = FleetMonitor(base)
        monitor.start()
        load = LoadGenerator(base, z_size)
        load.start()
        time.sleep(1.0)  # let traffic establish before the first fault

        # -- phase 1: SIGKILL a worker ----------------------------------
        victim = worker_by_id(health, "w0")
        log(f"SIGKILL worker w0 (pid {victim.get('pid')})")
        os.kill(victim["pid"], signal.SIGKILL)
        recovered = wait_for(
            lambda: ((h := fleet_health(base)).get("routable") == n_workers
                     and worker_by_id(h, "w0").get("restarts", 0) >= 1
                     and worker_by_id(h, "w0").get("pid")
                     not in (None, victim["pid"]) and h),
            300.0, "SIGKILLed worker relaunched and re-admitted")
        results["sigkill"] = {
            "old_pid": victim.get("pid"),
            "new_pid": worker_by_id(recovered or {}, "w0").get("pid"),
            "restarts": worker_by_id(recovered or {}, "w0").get("restarts"),
            "counts_at_recovery": dict(load.counts),
        }
        invariants["sigkill_worker_relaunched"] = bool(recovered)

        # -- phase 2: SIGSTOP (hang) + half-open re-admission -----------
        health = fleet_health(base)
        hung = worker_by_id(health, "w1")
        restarts_before = hung.get("restarts", 0)
        log(f"SIGSTOP worker w1 (pid {hung.get('pid')})")
        os.kill(hung["pid"], signal.SIGSTOP)
        try:
            ejected = wait_for(
                lambda: not router_worker(fleet_health(base),
                                          "w1").get("routable", True),
                120.0, "hung worker ejected")
        finally:
            os.kill(hung["pid"], signal.SIGCONT)
        log("SIGCONT sent — waiting for half-open re-admission")
        readmitted = wait_for(
            lambda: ((h := fleet_health(base)).get("routable") == n_workers
                     and router_worker(h, "w1").get("routable") and h),
            120.0, "hung worker re-admitted")
        restarts_after = worker_by_id(readmitted or {}, "w1").get(
            "restarts", -1)
        results["sigstop"] = {
            "pid": hung.get("pid"),
            "ejected": bool(ejected),
            "readmitted": bool(readmitted),
            "restarts_before": restarts_before,
            "restarts_after": restarts_after,
            "counts_at_recovery": dict(load.counts),
        }
        invariants["hung_worker_ejected"] = bool(ejected)
        invariants["hung_worker_readmitted_without_restart"] = (
            bool(readmitted) and restarts_after == restarts_before)

        # -- phase 3: rolling generation upgrades -----------------------
        trainer_log = open(os.path.join(workdir, "trainer.log"), "w")
        trainer = subprocess.Popen(
            TRAINER + [
                "--config", workload["config"], "--data", workload["data"],
                "--store", train_store,
                "--serve-store", serve_store,
                "--total-steps", str(total),
                "--publish-every", str(serve_every),
                "--serve-publish-every", str(serve_every),
                "--keep-last", str(args.keep_last),
                "--summary", os.path.join(workdir, "trainer_summary.json"),
            ],
            cwd=_REPO, env=_ENV, stdout=trainer_log, stderr=trainer_log,
        )
        try:
            trainer.wait(timeout=600.0)
        except subprocess.TimeoutExpired:
            trainer.kill()
            log("trainer hung — killed")
        try:
            with open(os.path.join(workdir, "trainer_summary.json")) as fh:
                trainer_summary = json.load(fh)
        except (OSError, json.JSONDecodeError):
            trainer_summary = {}
        final_gen = trainer_summary.get("final_serve_generation")
        log(f"trainer done rc={trainer.returncode}, "
            f"final serve generation {final_gen}")
        converged = wait_for(
            lambda: ((h := fleet_health(base)).get("generation") == final_gen
                     and h.get("routable") == n_workers
                     and (h.get("fleet") or {}).get("state") == "idle"
                     and h),
            600.0, "fleet converged on the trainer's final generation")
        fleet_state = (converged or fleet_health(base)).get("fleet") or {}
        results["rolling_upgrade"] = {
            "trainer_rc": trainer.returncode,
            "final_serve_generation": final_gen,
            "fleet_generation": (converged or {}).get("generation"),
            "rolls": fleet_state.get("rolls"),
            "rejected": fleet_state.get("rejected"),
            "counts_at_convergence": dict(load.counts),
        }
        invariants["fleet_converged_to_final_generation"] = bool(converged)
        invariants["rolling_upgrades_ge_1"] = (
            (fleet_state.get("rolls") or 0) >= 1)

        # -- phase 4: poisoned generation, one fleet-wide decision ------
        rejected_before = fleet_state.get("rejected", 0)
        poison = poison_newest(serve_store, args.keep_last)
        log(f"published poisoned generation {poison}")
        rejected = wait_for(
            lambda: (((h := fleet_health(base)).get("fleet") or {})
                     .get("rejected", 0) > rejected_before and h),
            420.0, "fleet rejected the poisoned generation")
        from gan_deeplearning4j_tpu.resilience import CheckpointStore

        entry = CheckpointStore(serve_store,
                                keep_last=args.keep_last).entry(poison)
        after = fleet_health(base)
        results["poison"] = {
            "generation": poison,
            "ledger_status": entry.get("status"),
            "quarantine_reason": entry.get("reason"),
            "fleet_generation_after": after.get("generation"),
            "rejected": (after.get("fleet") or {}).get("rejected"),
        }
        invariants["poison_rejected_once_fleet_wide"] = bool(rejected)
        invariants["poison_quarantined_in_store"] = (
            entry.get("status") == "quarantined"
            and "canary" in (entry.get("reason") or ""))
        invariants["poison_never_served"] = (
            poison not in monitor.generations_served
            and after.get("generation") == final_gen)

        # -- phase 5: trace propagation + fleet aggregation -------------
        # quiesce first: with the load generator stopped, per-worker
        # counters are frozen, so the exactness assertions below compare
        # stable numbers instead of racing live traffic
        counts = load.finish()
        load = None
        monitor.finish()
        trace_out = args.trace_out or os.path.join(workdir,
                                                   "fleet_trace.json")
        health_now = fleet_health(base)
        worker_pids = {w.get("pid")
                       for w in (health_now.get("fleet") or {})
                       .get("workers", []) if w.get("pid")}
        results["trace"] = run_trace_phase(base, z_size, worker_pids,
                                           trace_out, invariants)
        results["fleet_metrics"] = run_aggregation_phase(
            base, worker_ports, counts, invariants)

        # -- phase 6: ledgers -------------------------------------------
        _, router_metrics = http_json("GET", f"{base}/metrics", timeout=5.0)
        router_metrics = router_metrics or {}
        results["requests"] = counts
        results["router"] = {
            k: router_metrics.get(k)
            for k in ("proxied", "ok", "error", "retries",
                      "budget_exhausted", "no_worker", "attempts_exhausted",
                      "ejections", "retry_budget_tokens")
        }
        results["generations_served"] = sorted(monitor.generations_served)
        results["routable_envelope"] = [monitor.min_routable,
                                        monitor.max_routable]
        invariants["exactly_one_answer_zero_lost"] = (
            counts["lost"] == 0
            and counts["ok"] + counts["shed"] + counts["error"]
            == counts["sent"])
        # the retry-budget contract: every client-visible 503 is one of
        # the router's honest-503 paths, and no request got a 5xx the
        # router could not account for
        honest_503s = ((router_metrics.get("budget_exhausted") or 0)
                       + (router_metrics.get("no_worker") or 0)
                       + (router_metrics.get("attempts_exhausted") or 0))
        invariants["errors_bounded_by_retry_budget"] = (
            counts["error"] == 0 and counts["shed"] <= honest_503s)
        # bounded-compile through re-routing: no worker ever paid a
        # serve-time compile (scraped directly, not via the router)
        serve_compiles = {}
        for port in worker_ports:
            _, m = http_json("GET", f"http://127.0.0.1:{port}/metrics",
                             timeout=5.0)
            if m:
                serve_compiles[str(port)] = (m.get("engine") or {}).get(
                    "serve_compile_counts", {})
        results["serve_compile_counts"] = serve_compiles
        invariants["no_serve_time_compiles"] = bool(serve_compiles) and all(
            all(v == 0 for v in counts_.values())
            for counts_ in serve_compiles.values())
    finally:
        if load is not None:
            load.finish()
        if monitor is not None and not monitor.stop.is_set():
            monitor.finish()
        for proc in (trainer, fleet):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=20.0)
                except subprocess.TimeoutExpired:
                    proc.kill()

    # -- verdict ---------------------------------------------------------
    ok = bool(invariants) and all(invariants.values())
    payload = {
        "bench": "fleet_drill",
        "config": {
            "workers": n_workers,
            "total_steps": total,
            "serve_publish_every": serve_every,
            "seed": args.seed,
            "smoke": bool(args.smoke),
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "results": results,
        "invariants": invariants,
        "ok": ok,
    }
    text = json.dumps(payload, indent=2)
    print(text)
    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                    exist_ok=True)
        with open(args.output, "w") as fh:
            fh.write(text + "\n")
    if args.record:
        with open(os.path.join(_REPO, f"BENCH_fleet_{args.record}.json"),
                  "w") as fh:
            fh.write(text + "\n")
    if cleanup and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        log(f"INVARIANT BREACH — work files kept at {workdir}")
    for name, good in sorted(invariants.items()):
        log(f"invariant {name}: {'ok' if good else 'BREACH'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
