#!/usr/bin/env python
"""Fleet drill — real faults against the multi-process serving plane.

The executable form of docs/FLEET.md's invariants, against real
subprocesses under closed-loop load through the router:

1. **boot** — an untrained experiment seeds serving generation 0 into a
   fresh store; ``python -m gan_deeplearning4j_tpu.fleet`` spawns N
   workers from it plus the router, and the drill waits until every
   worker is warm and routable. Closed-loop client threads then hammer
   the ROUTER's ``/v1/sample`` for the rest of the drill.
2. **SIGKILL** — one worker is hard-killed. The router ejects it (or the
   manager relaunches it first — whichever signal lands first), requests
   in flight there are retried on another worker under the budget, and
   the slot must come back routable with a fresh process.
3. **SIGSTOP** — one worker is hung, not killed. Per-request timeouts
   plus the passive breaker must eject it; after SIGCONT the half-open
   probe must RE-ADMIT it without a restart (the hang was transient).
4. **rolling upgrade** — a supervisor segment trains and publishes newer
   serving generations on cadence; the fleet must admit them through ONE
   sidecar canary decision each and roll workers one at a time, ending
   converged on the trainer's final generation.
5. **poison** — a digest-valid but quality-garbage generation is
   published. The fleet admission gate must reject it, quarantine it
   through the store (fleet-wide, once), and no worker may ever serve it.
6. **trace propagation + fleet aggregation** — a deliberately-retried
   request (a client deadline no worker can meet, so every attempt sheds
   and the router re-routes it) must leave spans carrying ONE trace id on
   the router and at least two distinct worker pids in the router's
   merged ``GET /debug/trace``; ``trace_report`` must fold that merged
   trace with rc 0. ``GET /metrics?scope=fleet`` (JSON and Prometheus)
   must sum per-worker request counters EXACTLY against simultaneous
   direct worker scrapes, and the router's own ok counter must equal the
   load ledger's ok count — the zero-lost ledger and the aggregated
   metrics are the same numbers or one of them is lying.
7. **ledger** — every submitted request got exactly one answer, zero
   lost, client-visible 503s bounded by the router's own honest-503
   counters (the retry-budget contract), zero 5xx, and every worker's
   ``serve_compile_counts`` stays 0 (re-routing cannot break the
   bounded-compile invariant).

Results land as a BENCH-style JSON (``--output``; ``--record TAG`` also
writes ``BENCH_fleet_<TAG>.json`` at the repo root). Exit status is
nonzero on any invariant breach, so CI gates on the drill directly
(``scripts/tpu_campaign.sh`` runs ``--smoke`` CPU-pinned after the
reload drill).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from resilience_drill import make_workload  # noqa: E402 (scripts/ sibling)
from reload_drill import (  # noqa: E402
    free_port,
    http_json,
    poison_newest,
    seed_bundle,
)

FLEET = [sys.executable, "-m", "gan_deeplearning4j_tpu.fleet"]
TRAINER = [sys.executable, "-m", "gan_deeplearning4j_tpu.resilience"]

# Subprocesses run with the persistent XLA compilation cache OFF for the
# same reason the resilience/reload drills' workers do (XLA:CPU AOT
# loader hazard): a cache-poisoned segfault must not masquerade as a
# fleet failure.
_ENV = {**os.environ, "GDT_COMPILATION_CACHE": "off"}


def log(msg: str) -> None:
    print(f"[fleet-drill] {msg}", flush=True)


class LoadGenerator:
    """Closed-loop /v1/sample clients against the ROUTER. Every attempt
    is accounted: ok (200), shed (503), error (other status), or lost
    (no HTTP answer at all) — the exactly-one-answer ledger — and
    admitted (200) latencies are captured for the autoscale phase's
    bounded-p99 invariant. The thread population can be ramped
    mid-drill (:meth:`add_threads` — the ~10x burst). The client
    timeout leaves room for the router's full retry schedule, so a slow
    answer is never misread as a lost one."""

    def __init__(self, base: str, z_size: int, threads: int = 2,
                 timeout: float = 30.0, pace: float = 0.005,
                 rows: tuple = (1, 4)):
        self.base = base
        self.z_size = z_size
        self.timeout = timeout
        self.rows = rows  # rng.integers(*rows) rows per request
        self.stop = threading.Event()
        self.counts = {"sent": 0, "ok": 0, "shed": 0, "error": 0, "lost": 0}
        self.ok_latencies: list = []
        self._lock = threading.Lock()
        self._threads: list = []
        self._boot = (threads, pace)

    def _run(self, tid: int, pace: float) -> None:
        rng = np.random.default_rng(2000 + tid)
        while not self.stop.is_set():
            rows = (rng.random((int(rng.integers(*self.rows)), self.z_size),
                               dtype=np.float32) * 2.0 - 1.0)
            with self._lock:
                self.counts["sent"] += 1
            t0 = time.monotonic()
            status, _ = http_json(
                "POST", f"{self.base}/v1/sample", {"data": rows.tolist()},
                timeout=self.timeout)
            latency = time.monotonic() - t0
            with self._lock:
                if status is None:
                    self.counts["lost"] += 1
                elif status == 200:
                    self.counts["ok"] += 1
                    self.ok_latencies.append(latency)
                elif status == 503:
                    self.counts["shed"] += 1
                else:
                    self.counts["error"] += 1
            time.sleep(pace)  # keep 2 shared cores breathable

    def start(self) -> None:
        self.add_threads(*self._boot)

    def add_threads(self, n: int, pace: float = 0.005) -> None:
        for _ in range(n):
            t = threading.Thread(
                target=self._run, args=(len(self._threads), pace),
                daemon=True)
            t.start()
            self._threads.append(t)

    def finish(self) -> dict:
        self.stop.set()
        for t in self._threads:
            t.join(timeout=self.timeout + 5.0)
        # the joins are bounded — a straggler stuck in a slow request may
        # still be incrementing, so read under the same lock the workers
        # write under
        with self._lock:
            return dict(self.counts)


class FleetMonitor:
    """Polls the router's /healthz continuously, recording every (worker,
    generation) pair observed and the routable-count envelope — the
    drill's ground truth for 'the poison was never served' and 'the
    ejection actually happened'."""

    def __init__(self, base: str):
        self.base = base
        self.stop = threading.Event()
        self.generations_served: set = set()
        self.min_routable: int = 10**9
        self.max_routable: int = 0
        self.last: dict = {}
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        while not self.stop.is_set():
            status, body = http_json("GET", f"{self.base}/healthz",
                                     timeout=5.0)
            if status == 200 and body:
                self.last = body
                self.min_routable = min(self.min_routable,
                                        body.get("routable", 0))
                self.max_routable = max(self.max_routable,
                                        body.get("routable", 0))
                for w in body.get("workers", []):
                    if w.get("routable") and w.get("generation") is not None:
                        self.generations_served.add(w["generation"])
            time.sleep(0.1)

    def start(self) -> None:
        self._thread.start()

    def finish(self) -> None:
        self.stop.set()
        self._thread.join(timeout=10.0)


def fleet_health(base: str):
    _, body = http_json("GET", f"{base}/healthz", timeout=5.0)
    return body or {}


def wait_for(predicate, deadline_s: float, what: str, interval: float = 0.2):
    """Poll until predicate() is truthy; returns its value (None on
    timeout, logged — the caller's invariant records the breach)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval)
    log(f"TIMEOUT waiting for {what} ({deadline_s:.0f}s)")
    return None


def worker_by_id(health: dict, worker_id: str) -> dict:
    for w in (health.get("fleet") or {}).get("workers", []):
        if w["id"] == worker_id:
            return w
    return {}


def router_worker(health: dict, worker_id: str) -> dict:
    for w in health.get("workers", []):
        if w["id"] == worker_id:
            return w
    return {}


def run_trace_phase(base: str, z_size: int, worker_pids: set,
                    trace_out: str, invariants: dict) -> dict:
    """Phase 5a — prove one trace id threads a retried request across the
    router and two distinct worker processes. The probe request carries a
    client deadline no worker can meet (1 µs), so every attempt sheds
    with a worker-side ``serve.request`` span and the router re-routes to
    a different worker; the router's merged ``GET /debug/trace`` must
    then show the id on ≥2 worker pids plus the router's own spans, and
    ``trace_report`` must fold the merged artifact with rc 0."""
    rows = [[0.0] * z_size]
    chosen = None
    observed: dict = {}
    for attempt in range(5):
        tid = f"drill-retry-{attempt}"
        status, _ = http_json(
            "POST", f"{base}/v1/sample", {"data": rows, "timeout": 1e-6},
            timeout=30.0, headers={"X-Trace-Id": tid})
        _, merged = http_json("GET", f"{base}/debug/trace", timeout=20.0)
        events = (merged or {}).get("traceEvents") or []
        pids = {e.get("pid") for e in events
                if (e.get("args") or {}).get("trace_id") == tid}
        observed = {
            "trace_id": tid, "probe_status": status,
            "pids_with_id": sorted(p for p in pids if p is not None),
            "worker_pids": sorted(worker_pids),
            "merged_events": len(events),
        }
        if len(pids & worker_pids) >= 2 and (pids - worker_pids):
            chosen = merged
            break
        time.sleep(0.3)
    invariants["trace_one_id_spans_router_and_two_workers"] = (
        chosen is not None)
    rc = None
    if chosen is not None:
        with open(trace_out, "w") as fh:
            json.dump(chosen, fh)
            fh.write("\n")
        report = subprocess.run(
            [sys.executable, os.path.join(_REPO, "scripts",
                                          "trace_report.py"), trace_out],
            capture_output=True, text=True, timeout=120.0)
        rc = report.returncode
        log(f"trace_report on merged fleet trace: rc={rc}")
        observed["trace_out"] = trace_out
    invariants["trace_report_folds_merged_trace"] = rc == 0
    observed["trace_report_rc"] = rc
    return observed


def _counter_total(snapshot: dict, family: str, match=None) -> float:
    total = 0.0
    for s in ((snapshot or {}).get(family) or {}).get("series", []):
        labels = s.get("labels") or {}
        if match and any(labels.get(k) != v for k, v in match.items()):
            continue
        total += float(s.get("value", 0.0))
    return total


def run_aggregation_phase(base: str, worker_ports: list, counts: dict,
                          invariants: dict) -> dict:
    """Phase 5b — the aggregation-exactness story, on frozen counters:
    the fleet-scope snapshot (JSON and Prometheus) must sum per-worker
    ``serve_requests_total`` EXACTLY against simultaneous direct worker
    scrapes, report zero scrape gaps, and the router's own ok counter
    must equal the load ledger's ok count. Also checks the satellite
    surfaces: SLO block and per-worker scrape staleness in /healthz."""
    import urllib.request

    direct_total = 0.0
    per_worker: dict = {}
    for port in worker_ports:
        _, snap = http_json(
            "GET", f"http://127.0.0.1:{port}/metrics?scope=registry",
            timeout=10.0)
        t = _counter_total(snap, "serve_requests_total")
        per_worker[str(port)] = t
        direct_total += t
    _, fleet_snap = http_json("GET", f"{base}/metrics?scope=fleet",
                              timeout=30.0)
    fleet_snap = fleet_snap or {}
    fleet_total = _counter_total(fleet_snap, "serve_requests_total")
    router_ok = _counter_total(fleet_snap, "fleet_requests_total",
                               match={"outcome": "ok"})
    gaps = (fleet_snap.get("_fleet") or {}).get("gaps")

    prom_total = None
    try:
        with urllib.request.urlopen(
                f"{base}/metrics?scope=fleet&format=prom",
                timeout=30.0) as resp:
            prom_text = resp.read().decode()
        prom_total = sum(
            float(line.rsplit(" ", 1)[1])
            for line in prom_text.splitlines()
            if line.startswith("serve_requests_total{"))
    except (OSError, ValueError):
        pass

    health = fleet_health(base)
    slo = health.get("slo") or {}
    ages = [w.get("last_scrape_age_s") for w in health.get("workers", [])]

    invariants["fleet_counter_sum_exact"] = (
        fleet_total == direct_total > 0)
    invariants["fleet_prom_matches_json"] = prom_total == fleet_total
    invariants["fleet_scrape_no_gaps"] = gaps == []
    invariants["router_ok_counter_matches_ledger"] = (
        router_ok == counts["ok"])
    invariants["slo_surfaced_with_traffic"] = (
        (slo.get("totals") or {}).get("requests", 0) >= counts["sent"])
    invariants["worker_scrape_age_surfaced"] = bool(ages) and all(
        isinstance(a, (int, float)) for a in ages)
    return {
        "per_worker_requests": per_worker,
        "direct_total": direct_total,
        "fleet_total": fleet_total,
        "prom_total": prom_total,
        "router_ok": router_ok,
        "ledger_ok": counts["ok"],
        "gaps": gaps,
        "slo": slo,
        "last_scrape_age_s": ages,
    }


# ===========================================================================
# the autoscale-under-burst phase (--autoscale)
# ===========================================================================

class AutoscaleMonitor:
    """Polls the router's /healthz, recording the (slot count, brownout)
    trajectory — the ground truth for 'the fleet grew, brownout engaged
    only at max, and it shrank back'."""

    def __init__(self, base: str):
        self.base = base
        self.stop = threading.Event()
        self.max_slots = 0
        self.min_slots = 10**9
        self.brownout_seen = False
        self.brownout_slot_counts: set = set()
        self.transitions: list = []  # (slots, brownout_level) changes
        self.last: dict = {}
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        prev = None
        while not self.stop.is_set():
            status, body = http_json("GET", f"{self.base}/healthz",
                                     timeout=5.0)
            if status == 200 and body:
                self.last = body
                slots = len((body.get("fleet") or {}).get("workers", []))
                brownout = (body.get("brownout") or {})
                level = int(brownout.get("level") or 0)
                self.max_slots = max(self.max_slots, slots)
                self.min_slots = min(self.min_slots, slots)
                if level > 0:
                    self.brownout_seen = True
                    self.brownout_slot_counts.add(slots)
                if (slots, level) != prev:
                    prev = (slots, level)
                    self.transitions.append(
                        {"t": round(time.monotonic(), 3),
                         "slots": slots, "brownout": level})
            time.sleep(0.1)

    def start(self) -> None:
        self._thread.start()

    def finish(self) -> None:
        self.stop.set()
        self._thread.join(timeout=10.0)


def _p99(samples: list) -> float:
    if not samples:
        return float("nan")
    ranked = sorted(samples)
    return ranked[min(len(ranked) - 1,
                      max(0, int(np.ceil(0.99 * len(ranked))) - 1))]


def run_autoscale(args) -> int:
    """The autoscale-under-burst drill (docs/FLEET.md "Autoscaling"):
    boot an elastic fleet at min size, ramp closed-loop load ~10x, and
    assert the whole elasticity story — grow to max, mid-resize SIGKILL
    recovered, brownout only at max, large slabs shed with honest 503s,
    zero lost, bounded p99 for admitted requests, shrink back to min
    after quiesce."""
    min_workers = args.workers or 1
    max_workers = args.max_workers or 3
    burst_threads = args.burst_threads or (12 if args.smoke else 16)
    workdir = args.workdir or tempfile.mkdtemp(prefix="fleet_autoscale_")
    cleanup = args.workdir is None
    os.makedirs(workdir, exist_ok=True)
    serve_store = os.path.join(workdir, "store_serve")
    workload = make_workload(workdir, args.seed)
    results: dict = {}
    invariants: dict = {}
    fleet = None
    load = monitor = None
    ok_latencies: list = []
    router_port = free_port()
    base = f"http://127.0.0.1:{router_port}"
    brownout_max_rows = 16
    z_size = 4  # the drill workload's latent width (make_workload)

    try:
        # -- phase 0: seed + boot the elastic fleet at min size ----------
        gen0 = seed_bundle(workload, serve_store, args.keep_last)
        log(f"seeded serving generation {gen0}")
        fleet_log = open(os.path.join(workdir, "fleet.log"), "w")
        fleet = subprocess.Popen(
            FLEET + [
                "--store", serve_store,
                "--workers", str(min_workers),
                "--port", str(router_port),
                "--log-dir", workdir,
                "--poll", "2.0", "--probe-interval", "0.15",
                "--request-timeout", "3.0",
                "--retry-ratio", "0.5", "--retry-burst", "10",
                "--eject-failures", "3", "--reopen-after", "0.5",
                "--drain-timeout", "15", "--warm-timeout", "240",
                "--hang-restart", "30",
                "--buckets", "1,8", "--replicas", "1",
                "--max-latency", "0.002",
                "--boot-wait", "60",
                "--autoscale", "--max-workers", str(max_workers),
                "--scale-interval", "0.5",
                "--scale-up-pressure", "3.0", "--scale-down-pressure", "1.0",
                "--scale-up-ticks", "2", "--scale-down-ticks", "6",
                "--scale-up-cooldown", "2.0", "--scale-down-cooldown", "2.0",
                "--brownout-exit-ticks", "4",
                "--brownout-max-rows", str(brownout_max_rows),
                "--brownout-deadline-ms", "1500",
                "--spawn-backoff", "0.5", "--spawn-backoff-max", "5.0",
                "--slo-fast-window", "5", "--slo-slow-window", "30",
            ],
            cwd=_REPO, env=_ENV, stdout=fleet_log, stderr=fleet_log,
        )
        health = wait_for(
            lambda: (fleet.poll() is None
                     and (h := fleet_health(base)).get("routable")
                     == min_workers and h.get("generation") == gen0 and h),
            420.0, "fleet healthy at min size")
        if not health:
            log(f"fleet never became healthy (rc={fleet.poll()})")
            return 2
        initial_ids = {w["id"] for w in (health.get("fleet") or {})
                       .get("workers", [])}
        invariants["boots_at_min_size"] = len(initial_ids) == min_workers
        monitor = AutoscaleMonitor(base)
        monitor.start()

        # -- phase 1: light load holds at min ----------------------------
        load = LoadGenerator(base, z_size, threads=0)
        load.add_threads(1, pace=0.05)
        time.sleep(6.0)
        slots_light = len((fleet_health(base).get("fleet") or {})
                          .get("workers", []))
        invariants["light_load_holds_at_min"] = slots_light == min_workers
        log(f"light load: {slots_light} slot(s) (min {min_workers})")

        # -- phase 2: ~10x burst -> scale-up, with a mid-resize SIGKILL --
        log(f"ramping to {burst_threads + 1} closed-loop threads")
        load.add_threads(burst_threads, pace=0.002)
        grown = wait_for(
            lambda: (len(((h := fleet_health(base)).get("fleet") or {})
                         .get("workers", [])) > min_workers and h),
            180.0, "first scale-up under burst")
        invariants["scales_up_under_burst"] = bool(grown)
        kill_result: dict = {"killed": None}
        if grown:
            # the first scaled-up worker is still warming (jax import +
            # AOT ladder — tens of seconds): SIGKILL it mid-resize. The
            # supervise loop must relaunch it (spawn-failure backoff, no
            # hot loop) and the fleet must still reach max.
            new_workers = [w for w in (grown.get("fleet") or {})
                           .get("workers", []) if w["id"] not in initial_ids]
            victim = new_workers[0]
            log(f"mid-resize SIGKILL: worker {victim['id']} "
                f"(pid {victim['pid']})")
            try:
                os.kill(victim["pid"], signal.SIGKILL)
                kill_result["killed"] = victim
            except (OSError, TypeError) as exc:
                log(f"SIGKILL failed ({exc}) — worker finished booting?")
            recovered = wait_for(
                lambda: ((w := worker_by_id(fleet_health(base),
                                            victim["id"])).get("alive")
                         and w.get("pid") not in (None, victim["pid"])
                         and w),
                120.0, "mid-resize-killed worker relaunched")
            kill_result["recovered"] = recovered or None
            invariants["mid_resize_sigkill_recovered"] = bool(recovered)
        results["mid_resize_kill"] = kill_result

        # -- phase 3: brownout at max size -------------------------------
        browned = wait_for(
            lambda: ((h := fleet_health(base)).get("brownout") or {})
                    .get("active") and h,
            240.0, "brownout under sustained overload at max size")
        at_brownout = browned or fleet_health(base)
        slots_at_brownout = len((at_brownout.get("fleet") or {})
                                .get("workers", []))
        invariants["brownout_engages"] = bool(browned)
        invariants["brownout_only_at_max"] = (
            bool(browned) and slots_at_brownout == max_workers
            and monitor.brownout_slot_counts <= {max_workers})
        results["brownout"] = {
            "slots_at_engage": slots_at_brownout,
            "healthz_status": (browned or {}).get("status"),
            "block": (browned or {}).get("brownout"),
        }
        # tier-1 admission: an oversized sample slab sheds with an honest
        # 503 naming the brownout, while the small-slab load keeps flowing
        big = [[0.0] * z_size for _ in range(brownout_max_rows + 8)]
        status, body = http_json("POST", f"{base}/v1/sample",
                                 {"data": big}, timeout=30.0)
        invariants["brownout_sheds_large_slabs"] = (
            status == 503 and "brownout" in json.dumps(body or {}))
        results["brownout"]["large_slab_probe"] = {
            "status": status, "body": body}
        _, rm = http_json("GET", f"{base}/metrics", timeout=10.0)
        results["brownout"]["router_level"] = (rm or {}).get("brownout_level")
        invariants["brownout_gauge_surfaced"] = (
            (rm or {}).get("brownout_level", 0) >= 1
            and (rm or {}).get("brownout_shed", 0) >= 1)

        # every scaled-up worker (the relaunched SIGKILL victim included)
        # must finish warming and re-earn router admission — "capacity"
        # means routable, not spawned
        full = wait_for(
            lambda: ((h := fleet_health(base)).get("routable")
                     == max_workers and h),
            240.0, "scaled-up workers admitted as routable capacity")
        invariants["scaled_up_workers_admitted"] = bool(full)

        # -- phase 4: quiesce -> drain back to min, brownout released ----
        counts = load.finish()
        ok_latencies = list(load.ok_latencies)
        load = None
        log("load stopped — waiting for scale-down to min")
        shrunk = wait_for(
            lambda: ((h := fleet_health(base)).get("routable") == min_workers
                     and len((h.get("fleet") or {}).get("workers", []))
                     == min_workers
                     and not (h.get("brownout") or {}).get("active") and h),
            300.0, "fleet drained back to min after quiesce")
        invariants["quiesce_shrinks_to_min"] = bool(shrunk)
        monitor.finish()

        # -- phase 5: ledgers --------------------------------------------
        _, router_metrics = http_json("GET", f"{base}/metrics", timeout=5.0)
        router_metrics = router_metrics or {}
        results["requests"] = counts
        results["router"] = {
            k: router_metrics.get(k)
            for k in ("proxied", "ok", "error", "retries",
                      "budget_exhausted", "no_worker", "attempts_exhausted",
                      "brownout_shed", "ejections", "retry_budget_tokens")
        }
        results["scaling"] = {
            "max_slots": monitor.max_slots,
            "min_slots": monitor.min_slots,
            "transitions": monitor.transitions,
            "autoscaler": ((shrunk or fleet_health(base)).get("fleet")
                           or {}).get("autoscaler"),
        }
        invariants["exactly_one_answer_zero_lost"] = (
            counts["lost"] == 0 and counts["error"] == 0
            and counts["ok"] + counts["shed"] + counts["error"]
            == counts["sent"])
        honest_503s = ((router_metrics.get("budget_exhausted") or 0)
                       + (router_metrics.get("no_worker") or 0)
                       + (router_metrics.get("attempts_exhausted") or 0)
                       + (router_metrics.get("brownout_shed") or 0))
        invariants["sheds_bounded_by_honest_503s"] = (
            counts["shed"] <= honest_503s)
    finally:
        if load is not None:
            load.finish()
            ok_latencies = list(load.ok_latencies)
        if monitor is not None and not monitor.stop.is_set():
            monitor.finish()
        if fleet is not None and fleet.poll() is None:
            fleet.terminate()
            try:
                fleet.wait(timeout=20.0)
            except subprocess.TimeoutExpired:
                fleet.kill()

    # p99 of admitted requests, bounded: an autoscaling fleet may queue,
    # but an admitted request must never hang toward its client timeout
    p99 = _p99(ok_latencies)
    results["latency"] = {
        "ok_requests": len(ok_latencies),
        "p99_s": None if not ok_latencies else round(p99, 4),
        "bound_s": args.p99_bound,
    }
    invariants["p99_of_admitted_bounded"] = (
        bool(ok_latencies) and p99 <= args.p99_bound)

    ok = bool(invariants) and all(invariants.values())
    payload = {
        "bench": "fleet_autoscale_drill",
        "config": {
            "min_workers": min_workers,
            "max_workers": max_workers,
            "burst_threads": burst_threads,
            "seed": args.seed,
            "smoke": bool(args.smoke),
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "results": results,
        "invariants": invariants,
        "ok": ok,
    }
    text = json.dumps(payload, indent=2)
    print(text)
    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                    exist_ok=True)
        with open(args.output, "w") as fh:
            fh.write(text + "\n")
    if args.record:
        with open(os.path.join(_REPO,
                               f"BENCH_autoscale_{args.record}.json"),
                  "w") as fh:
            fh.write(text + "\n")
    if cleanup and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        log(f"INVARIANT BREACH — work files kept at {workdir}")
    for name, good in sorted(invariants.items()):
        log(f"invariant {name}: {'ok' if good else 'BREACH'}")
    return 0 if ok else 1


# ===========================================================================
# the alerting drill (--alerts)
# ===========================================================================

class AlertsMonitor:
    """Polls the router's /alerts continuously: every alertname ever seen
    FIRING (with a sample payload), plus audit windows — during a window
    opened by :meth:`open_window`, any firing instance is recorded as a
    false fire. The drill's ground truth for 'fires on the fault, silent
    when calm'."""

    def __init__(self, base: str):
        self.base = base
        self.stop = threading.Event()
        self.fired: dict = {}          # alertname -> first firing entry
        self.false_fires: list = []    # firing entries seen inside windows
        self._window = None            # (name,) when an audit window is open
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        while not self.stop.is_set():
            status, body = http_json("GET", f"{self.base}/alerts",
                                     timeout=5.0)
            if status == 200 and body:
                firing = [e for e in body.get("alerts", [])
                          if e.get("state") == "firing"]
                with self._lock:
                    window = self._window
                    for entry in firing:
                        self.fired.setdefault(entry["alert"], entry)
                        if window is not None:
                            self.false_fires.append(
                                {"window": window, **entry})
            time.sleep(0.1)

    def open_window(self, name: str) -> None:
        with self._lock:
            self._window = name

    def close_window(self) -> None:
        with self._lock:
            self._window = None

    def start(self) -> None:
        self._thread.start()

    def finish(self) -> None:
        self.stop.set()
        self._thread.join(timeout=10.0)


def _firing_names(alerts_body: dict) -> set:
    return {e["alert"] for e in (alerts_body or {}).get("alerts", [])
            if e.get("state") == "firing"}


def run_alerts(args) -> int:
    """The fire-and-resolve drill (docs/OBSERVABILITY.md "Alerting"):
    boot a fleet with the alert plane on, prove the default rule pack
    end-to-end — calm phases stay silent, a SIGKILLed worker fires
    ``worker_down`` with the dead pid and an exemplar trace id that
    resolves into the merged ``GET /debug/trace``, an overload ramp
    fires ``latency_anomaly``, quiesce resolves both, and the
    exactly-one-answer ledger holds throughout."""
    n_workers = args.workers or 2
    burst_threads = args.burst_threads or 12
    workdir = args.workdir or tempfile.mkdtemp(prefix="fleet_alerts_")
    cleanup = args.workdir is None
    os.makedirs(workdir, exist_ok=True)
    serve_store = os.path.join(workdir, "store_serve")
    workload = make_workload(workdir, args.seed)
    z_size = 4  # the drill workload's latent width (make_workload)
    results: dict = {}
    invariants: dict = {}
    fleet = None
    load = burst = monitor = None
    router_port = free_port()
    base = f"http://127.0.0.1:{router_port}"
    calm_audit_s = 6.0 if args.smoke else 10.0

    try:
        # -- phase 0: seed + boot with the alert plane on ----------------
        gen0 = seed_bundle(workload, serve_store, args.keep_last)
        log(f"seeded serving generation {gen0}")
        fleet_log = open(os.path.join(workdir, "fleet.log"), "w")
        fleet = subprocess.Popen(
            FLEET + [
                "--store", serve_store,
                "--workers", str(n_workers),
                "--port", str(router_port),
                "--log-dir", workdir,
                "--poll", "2.0", "--probe-interval", "0.15",
                "--request-timeout", "10.0",
                "--retry-ratio", "0.5", "--retry-burst", "10",
                "--eject-failures", "3", "--reopen-after", "0.5",
                "--drain-timeout", "15", "--warm-timeout", "240",
                "--hang-restart", "30",
                "--buckets", "1,8", "--replicas", "1",
                "--max-latency", "0.002",
                "--boot-wait", "60",
                "--slo-fast-window", "5", "--slo-slow-window", "30",
                "--telemetry",
                "--alerts", "--alert-stale-after", "10",
                # drill scale: the toy workload's p99 drifts by hundreds
                # of ms, not the production default's ~0.6 s page bar
                "--alert-latency-drift", "0.02",
            ],
            cwd=_REPO, env=_ENV, stdout=fleet_log, stderr=fleet_log,
        )
        health = wait_for(
            lambda: (fleet.poll() is None
                     and (h := fleet_health(base)).get("routable")
                     == n_workers and h.get("generation") == gen0 and h),
            420.0, "fleet healthy with the alert plane on")
        if not health:
            log(f"fleet never became healthy (rc={fleet.poll()})")
            return 2
        _, alerts0 = http_json("GET", f"{base}/alerts", timeout=10.0)
        invariants["alert_surface_up"] = bool(
            alerts0 and alerts0.get("rules"))
        results["rules"] = [r.get("name")
                            for r in (alerts0 or {}).get("rules", [])]
        log(f"alert plane up: rules {results['rules']}")
        monitor = AlertsMonitor(base)
        monitor.start()

        # -- phase 1: calm — baselines build, nothing may fire -----------
        load = LoadGenerator(base, z_size, threads=2, timeout=30.0,
                             pace=0.02)
        load.start()
        time.sleep(4.0)  # settle: baselines arm, boot noise ages out
        monitor.open_window("calm_1")
        try:
            time.sleep(calm_audit_s)
        finally:
            monitor.close_window()
        invariants["calm1_zero_firing"] = not any(
            f["window"] == "calm_1" for f in monitor.false_fires)
        log(f"calm-1 audit done ({calm_audit_s:.0f}s, firing seen: "
            f"{sorted(_firing_names(http_json('GET', base + '/alerts', timeout=5.0)[1]))})")

        # -- phase 2: SIGKILL -> worker_down fires with evidence ---------
        victim = worker_by_id(fleet_health(base), "w0")
        log(f"SIGKILL worker w0 (pid {victim.get('pid')})")
        os.kill(victim["pid"], signal.SIGKILL)
        fired = wait_for(
            lambda: next(
                (e for e in (http_json("GET", f"{base}/alerts",
                                       timeout=5.0)[1] or {})
                 .get("alerts", [])
                 if e.get("alert") == "worker_down"
                 and e.get("state") == "firing"
                 and e.get("labels", {}).get("worker") == "w0"), None),
            90.0, "worker_down firing for w0")
        invariants["worker_down_fires"] = bool(fired)
        exemplars = (fired or {}).get("exemplars") or []
        exemplar_pids = {e.get("pid") for e in exemplars}
        exemplar_ids = [e.get("trace_id") for e in exemplars
                        if e.get("trace_id")]
        invariants["worker_down_labels_dead_pid"] = (
            victim.get("pid") in exemplar_pids)
        invariants["worker_down_has_exemplar"] = bool(exemplar_ids)
        results["worker_down"] = {
            "victim_pid": victim.get("pid"),
            "alert": fired,
        }
        # the exemplar ids must resolve into the merged fleet trace —
        # an alert is one click from the causal chain of a bad request
        _, merged = http_json("GET", f"{base}/debug/trace", timeout=20.0)
        trace_ids = {(e.get("args") or {}).get("trace_id")
                     for e in (merged or {}).get("traceEvents", [])}
        linked = sorted(set(exemplar_ids) & trace_ids)
        invariants["exemplar_trace_in_merged_trace"] = bool(linked)
        results["worker_down"]["exemplars_in_trace"] = linked
        # surfaces: prom ALERTS series, healthz block, transition counter
        import urllib.request
        with urllib.request.urlopen(f"{base}/alerts?format=prom",
                                    timeout=10.0) as resp:
            prom = resp.read().decode()
        invariants["prom_alerts_series"] = (
            'ALERTS{alertname="worker_down"' in prom
            and 'state="firing"' in prom)
        hz = fleet_health(base)
        invariants["healthz_alerts_block"] = any(
            f.get("alert") == "worker_down"
            for f in (hz.get("alerts") or {}).get("firing", []))
        _, fleet_snap = http_json("GET", f"{base}/metrics?scope=fleet",
                                  timeout=30.0)
        invariants["transition_counter_surfaced"] = _counter_total(
            fleet_snap, "fleet_alerts_total",
            match={"alertname": "worker_down", "state": "firing"}) >= 1

        # -- phase 3: relaunch + re-admission -> worker_down resolves ----
        recovered = wait_for(
            lambda: (h := fleet_health(base)).get("routable") == n_workers
            and h,
            300.0, "killed worker relaunched and re-admitted")
        invariants["worker_relaunched"] = bool(recovered)
        resolved = wait_for(
            lambda: "worker_down" not in _firing_names(
                http_json("GET", f"{base}/alerts", timeout=5.0)[1]),
            60.0, "worker_down resolves after re-admission")
        invariants["worker_down_resolves"] = bool(resolved)
        log("worker_down resolved")

        # -- phase 4: overload ramp -> latency_anomaly fires -------------
        # slab-shaped burst: 200-256-row samples chunk through the
        # 8-bucket ladder (25-32 flushes each), so the light load's small
        # requests queue behind real work and their router-measured
        # latency genuinely drifts — the signal the anomaly rule exists
        # for (the p99 must clear the rule's MAD floor, ~0.6 s over the
        # calm baseline)
        burst = LoadGenerator(base, z_size, threads=0, timeout=60.0,
                              rows=(200, 257))
        burst.add_threads(burst_threads + 4, pace=0.002)
        log(f"overload ramp: +{burst_threads} slab-slinging threads")
        anomaly = wait_for(
            lambda: "latency_anomaly" in _firing_names(
                http_json("GET", f"{base}/alerts", timeout=5.0)[1]),
            150.0, "latency_anomaly firing under overload")
        invariants["latency_anomaly_fires"] = bool(anomaly)
        _, mid_alerts = http_json("GET", f"{base}/alerts", timeout=5.0)
        results["overload_firing"] = sorted(_firing_names(mid_alerts))
        _, mid_snap = http_json("GET", f"{base}/metrics?scope=fleet",
                                timeout=30.0)
        lat = ((mid_snap or {}).get("fleet_request_latency_seconds")
               or {}).get("series") or [{}]
        results["overload_latency"] = {
            k: lat[0].get(k) for k in ("p50", "p99", "count")}

        # -- phase 5: quiesce -> everything resolves ---------------------
        burst_counts = burst.finish()
        burst = None
        log("burst stopped — waiting for every alert to resolve "
            "(the light load keeps the latency stream fresh)")
        quiet = wait_for(
            lambda: not _firing_names(
                http_json("GET", f"{base}/alerts", timeout=5.0)[1]),
            180.0, "all alerts resolve after quiesce")
        invariants["all_alerts_resolve"] = bool(quiet)

        # -- phase 6: calm again — still nothing may fire ----------------
        monitor.open_window("calm_2")
        try:
            time.sleep(calm_audit_s)
        finally:
            monitor.close_window()
        invariants["calm2_zero_firing"] = not any(
            f["window"] == "calm_2" for f in monitor.false_fires)
        monitor.finish()

        # -- phase 7: audits + ledger ------------------------------------
        # every alertname that ever fired must be explainable by the
        # faults this drill injected; anything else is a false fire
        expected = {"worker_down", "latency_anomaly"}
        allowed = expected | {"slo_availability_burn", "slo_latency_burn",
                              "queue_pressure_anomaly", "scrape_stale"}
        ever_fired = set(monitor.fired)
        invariants["expected_alerts_fired"] = expected <= ever_fired
        invariants["no_unexpected_alertnames"] = ever_fired <= allowed
        results["ever_fired"] = sorted(ever_fired)
        results["false_fires"] = len(monitor.false_fires)
        results["false_fire_entries"] = monitor.false_fires[:20]

        counts = load.finish()
        load = None
        for key, value in burst_counts.items():
            counts[key] = counts.get(key, 0) + value
        results["requests"] = counts
        _, router_metrics = http_json("GET", f"{base}/metrics",
                                      timeout=5.0)
        router_metrics = router_metrics or {}
        results["router"] = {
            k: router_metrics.get(k)
            for k in ("proxied", "ok", "error", "retries",
                      "budget_exhausted", "no_worker",
                      "attempts_exhausted", "ejections")
        }
        invariants["exactly_one_answer_zero_lost"] = (
            counts["lost"] == 0 and counts["error"] == 0
            and counts["ok"] + counts["shed"] + counts["error"]
            == counts["sent"])
        honest_503s = ((router_metrics.get("budget_exhausted") or 0)
                       + (router_metrics.get("no_worker") or 0)
                       + (router_metrics.get("attempts_exhausted") or 0))
        invariants["sheds_bounded_by_honest_503s"] = (
            counts["shed"] <= honest_503s)

        # -- phase 8: the incident as ONE timeline -----------------------
        # (scripts/trace_report.py --alerts: spans + alert transitions)
        _, final_alerts = http_json("GET", f"{base}/alerts", timeout=10.0)
        alerts_out = os.path.join(workdir, "alerts.json")
        with open(alerts_out, "w") as fh:
            json.dump(final_alerts or {}, fh)
            fh.write("\n")
        trace_out = args.trace_out or os.path.join(workdir,
                                                   "alerts_trace.json")
        _, merged = http_json("GET", f"{base}/debug/trace", timeout=30.0)
        with open(trace_out, "w") as fh:
            json.dump(merged or {}, fh)
            fh.write("\n")
        report = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "scripts", "trace_report.py"),
             trace_out, "--alerts", alerts_out],
            capture_output=True, text=True, timeout=120.0)
        invariants["trace_report_alert_overlay"] = report.returncode == 0
        results["incident_timeline"] = {
            "alerts_json": alerts_out, "trace": trace_out,
            "trace_report_rc": report.returncode,
            "incidents": len((final_alerts or {}).get("incidents", [])),
        }
        log(f"trace_report --alerts rc={report.returncode}")
    finally:
        for gen in (load, burst):
            if gen is not None:
                gen.finish()
        if monitor is not None and not monitor.stop.is_set():
            monitor.finish()
        if fleet is not None and fleet.poll() is None:
            fleet.terminate()
            try:
                fleet.wait(timeout=20.0)
            except subprocess.TimeoutExpired:
                fleet.kill()

    ok = bool(invariants) and all(invariants.values())
    payload = {
        "bench": "fleet_alerts_drill",
        "config": {
            "workers": n_workers,
            "burst_threads": burst_threads,
            "calm_audit_s": calm_audit_s,
            "seed": args.seed,
            "smoke": bool(args.smoke),
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "results": results,
        "invariants": invariants,
        "ok": ok,
    }
    text = json.dumps(payload, indent=2)
    print(text)
    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                    exist_ok=True)
        with open(args.output, "w") as fh:
            fh.write(text + "\n")
    if args.record:
        with open(os.path.join(_REPO,
                               f"BENCH_alerts_{args.record}.json"),
                  "w") as fh:
            fh.write(text + "\n")
    if cleanup and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        log(f"INVARIANT BREACH — work files kept at {workdir}")
    for name, good in sorted(invariants.items()):
        log(f"invariant {name}: {'ok' if good else 'BREACH'}")
    return 0 if ok else 1


# ===========================================================================
# the multi-model multiplexing drill (--mux)
# ===========================================================================

def _mux_counts(snapshot: dict) -> dict:
    """Per-model outcome totals off ``mux_requests_total`` (summed over
    kinds): {model: {status: count}}."""
    out: dict = {}
    for s in snapshot.get("mux_requests_total", {}).get("series", []):
        labels = s.get("labels", {})
        model, status = labels.get("model"), labels.get("status")
        per = out.setdefault(model, {})
        per[status] = per.get(status, 0.0) + float(s.get("value", 0.0))
    return out


def _brownout_sheds(snapshot: dict) -> dict:
    return {
        s["labels"]["model"]: float(s["value"])
        for s in snapshot.get("mux_brownout_sheds_total",
                              {}).get("series", [])
    }


def run_mux(args) -> int:
    """The multiplexing drill (docs/MULTIPLEX.md): real engines from
    three seeded store generations behind one MuxService, driven
    in-process over real HTTP:

    1. **split** — two variants (expensive "heavy" at 90%, cheap "lite"
       at 10%) under closed-loop load: zero lost, both served, observed
       split within tolerance of the weights.
    2. **ramp + injected burn** — a third generation is adopted and
       ramped 1% → 10% → 50% → 100% on its own per-variant SLO signal;
       a burst of injected failures into the candidate's tracker must
       AUTO-ROLLBACK the ramp (weights restored exactly), then a clean
       re-ramp must complete with the candidate elected primary.
    3. **brownout** — synthetic overload (big-slab closed-loop burst
       against a small queue) must walk the per-model brownout tier up:
       the expensive variant sheds with honest 503s while the cheap one
       keeps answering; quiesce releases the tier.

    The exactly-one-answer ledger holds across all phases."""
    import numpy as np  # noqa: F811 (drill-local import shape)

    from gan_deeplearning4j_tpu.resilience import CheckpointStore
    from gan_deeplearning4j_tpu.serving import make_server
    from gan_deeplearning4j_tpu.serving.mux import (
        BrownoutController,
        MuxRegistry,
        MuxService,
        health_from_tracker,
    )
    from gan_deeplearning4j_tpu.telemetry.registry import get_registry
    from gan_deeplearning4j_tpu.telemetry.slo import SLOConfig

    workdir = args.workdir or tempfile.mkdtemp(prefix="fleet_mux_")
    cleanup = args.workdir is None
    os.makedirs(workdir, exist_ok=True)
    serve_store = os.path.join(workdir, "store_serve")
    workload = make_workload(workdir, args.seed)
    z_size = 4  # the drill workload's latent width (make_workload)
    split_seconds = 5.0 if args.smoke else 8.0
    results: dict = {}
    invariants: dict = {}
    server = svc = load = None
    t_start = time.monotonic()

    try:
        # -- phase 0: seed three generations, boot the mux service -------
        bundles = []
        store = CheckpointStore(serve_store, keep_last=args.keep_last)
        for i in range(3):
            gen_number = seed_bundle(workload, serve_store, args.keep_last)
            bundles.append((gen_number, store.latest_valid().path))
        log(f"seeded serving generations "
            f"{[n for n, _ in bundles]} into {serve_store}")
        drill_buckets = (1, 8)  # the ladder every drill engine serves
        registry = MuxRegistry(
            buckets=drill_buckets, budget=3,
            batcher_kwargs={"max_latency": 0.002, "max_queue": 12,
                            "default_timeout": 5.0})
        # the cost gradient the brownout sheds by: "heavy" is the
        # expensive fp32 primary, "lite" a REAL bf16 sibling built by
        # the quant plane (docs/QUANT.md) — half the resident bytes, so
        # the MEASURED cost ordering is deterministic even on a noisy
        # CPU host. "lite" lands pre-measured through the manifest
        # adoption path: measure_bundle_cost writes the cost block and
        # registry.add() picks it up, flipping cost_source to
        # "measured" without any drill-side plumbing.
        from gan_deeplearning4j_tpu.quant import (
            build_bf16_variant,
            measure_bundle_cost,
            measure_engine_cost,
        )

        lite_dir = os.path.join(workdir, "variant_bf16")
        build_bf16_variant(bundles[1][1], lite_dir)
        # price the variant on the ladder the registry will serve it on
        # (a literal here would shadow a learned manifest ladder — JG031)
        measure_bundle_cost(lite_dir, buckets=drill_buckets, rounds=2)
        registry.add("heavy", bundle_path=bundles[0][1], cost=4.0,
                     weight=0.9, generation=bundles[0][0])
        registry.add("lite", bundle_path=lite_dir, cost=1.0,
                     weight=0.1, generation=bundles[1][0])
        # the manifest-adoption path worked before any drill-side
        # plumbing ran: lite entered already measured, heavy (a store
        # bundle, no cost block) on its declared bootstrap
        invariants["manifest_cost_block_adopted"] = (
            registry.cost_sources() == {"heavy": "declared",
                                        "lite": "measured"})
        svc = MuxService(
            registry,
            slo_config=SLOConfig(
                availability_target=0.9, latency_target=0.9,
                latency_threshold_s=2.0,
                fast_window_s=2.0, slow_window_s=8.0),
            brownout=BrownoutController(
                threshold=0.25, enter_ticks=2, exit_ticks=6))
        svc.start_control_loop(interval=0.2)
        server = make_server(svc, port=0)
        port = server.server_address[1]
        base = f"http://127.0.0.1:{port}"
        threading.Thread(target=server.serve_forever, daemon=True).start()
        health = fleet_health(base)
        invariants["boots_ok"] = health.get("status") == "ok"
        invariants["shared_pool_attached"] = (
            registry.engine_for("heavy")._shared_staging is registry.pool)
        # the measurement the ordering invariants run on is PAIRED:
        # both live engines profiled back to back, interleaved, and the
        # min-per_row block kept per engine — an unpaired measurement
        # (lite timed cold at build, heavy timed later under different
        # host load) lets one slow sample flip the ranking on a model
        # this small. This also exercises the second adoption route:
        # set_measured_cost landing a live profile on a registered
        # variant (heavy flips declared -> measured here).
        heavy_cost = lite_cost = None
        for _ in range(3):
            hb = measure_engine_cost(registry.engine_for("heavy"),
                                     rounds=2)
            lb = measure_engine_cost(registry.engine_for("lite"),
                                     rounds=2)
            if heavy_cost is None or hb["per_row_s"] < heavy_cost[
                    "per_row_s"]:
                heavy_cost = hb
            if lite_cost is None or lb["per_row_s"] < lite_cost[
                    "per_row_s"]:
                lite_cost = lb
        registry.set_measured_cost("heavy", heavy_cost)
        registry.set_measured_cost("lite", lite_cost)
        costs = registry.costs()
        sources = registry.cost_sources()
        results["measured_costs"] = {
            "scalars": costs,
            "sources": sources,
            "resident_param_bytes": {
                "heavy": heavy_cost["resident_param_bytes"],
                "lite": lite_cost["resident_param_bytes"],
            },
        }
        invariants["costs_measured_not_declared"] = (
            sources.get("heavy") == "measured"
            and sources.get("lite") == "measured")
        # the quant claim, measured on this host: the bf16 sibling pins
        # half the bytes and its residency-rent scalar ranks below fp32
        invariants["bf16_variant_genuinely_cheaper"] = (
            lite_cost["resident_param_bytes"]
            < heavy_cost["resident_param_bytes"]
            and costs["lite"] < costs["heavy"])
        health = fleet_health(base)
        invariants["status_reports_cost_source"] = all(
            health.get("costs", {}).get(n, {}).get("cost_source")
            == "measured" for n in ("heavy", "lite"))
        log(f"mux service up at {base}: "
            f"variants {sorted(registry.names())}, measured costs "
            f"{ {n: f'{c:.3g}' for n, c in costs.items()} }")

        # -- phase 1: 10/90 split under closed-loop load ------------------
        load = LoadGenerator(base, z_size, threads=4, pace=0.004)
        load.start()
        time.sleep(split_seconds)
        counts = _mux_counts(get_registry().snapshot())
        heavy_ok = counts.get("heavy", {}).get("ok", 0.0)
        lite_ok = counts.get("lite", {}).get("ok", 0.0)
        served = heavy_ok + lite_ok
        lite_share = (lite_ok / served) if served else float("nan")
        results["split"] = {
            "requests": served, "heavy_ok": heavy_ok, "lite_ok": lite_ok,
            "lite_share_observed": lite_share, "lite_share_expected": 0.1,
        }
        invariants["split_serves_both_variants"] = (
            heavy_ok > 0 and lite_ok > 0)
        # binomial tolerance, wide enough for a short smoke window
        invariants["split_matches_weights"] = (
            served >= 200 and 0.04 <= lite_share <= 0.20)
        log(f"split: {served:.0f} served, lite share "
            f"{lite_share:.3f} (want ~0.10)")

        # -- phase 2: ramp with one injected SLO burn → auto-rollback -----
        registry.add("cand", bundle_path=bundles[2][1], cost=1.0,
                     weight=0.0, generation=bundles[2][0])
        # the candidate's store bundle carries no cost block: it enters
        # on its declared bootstrap, coexisting with measured peers —
        # the bootstrap-default contract (docs/QUANT.md)
        invariants["declared_bootstrap_coexists"] = (
            registry.cost_sources().get("cand") == "declared")
        # generous holds: the injection below must land while the ramp
        # is still mid-ladder, not race a sprinting one
        ramp = svc.start_ramp("cand", stages=(0.01, 0.10, 0.50, 1.0),
                              hold_ticks=10)
        mid = wait_for(
            lambda: (ramp.snapshot().get("fraction") or 0.0) >= 0.10
            or ramp.state != "ramping",
            60.0, "ramp reaches the 10% stage")
        invariants["ramp_reaches_mid_stage"] = bool(
            mid and ramp.state == "ramping")
        # the injected burn: a failure burst into the candidate's OWN
        # SLI stream (the signal the rollback rule reads) — the mux
        # analogue of the resilience drill's fault injections
        tracker = svc.tracker_for("cand")
        for _ in range(200):
            tracker.record(False)
        rolled = wait_for(lambda: ramp.state == "rolled_back", 20.0,
                          "ramp auto-rollback on the injected burn")
        weights = registry.splitter.weights()
        invariants["ramp_rolls_back_on_burn"] = bool(rolled)
        invariants["rollback_restores_weights"] = (
            weights.get("cand") == 0.0
            and abs(weights.get("heavy", 0) - 0.9) < 1e-9
            and abs(weights.get("lite", 0) - 0.1) < 1e-9)
        results["ramp_rollback"] = {
            "rollbacks": ramp.rollbacks,
            "events": list(ramp.events),
            "weights_after": weights,
        }
        log(f"ramp rolled back (events: "
            f"{[e['event'] for e in ramp.events]})")

        # -- phase 3: clean re-ramp completes 1% → 100% -------------------
        # the injected failures must first age out of the candidate's
        # fast window, or the re-ramp reads yesterday's burn and rolls
        # back on stale evidence
        burn_gone = wait_for(
            lambda: health_from_tracker(tracker)() is not False,
            20.0, "injected burn ages out of the fast window",
            interval=0.5)
        invariants["injected_burn_ages_out"] = bool(burn_gone)
        ramp2 = svc.start_ramp("cand", stages=(0.01, 0.10, 0.50, 1.0),
                               hold_ticks=2)
        done = wait_for(
            lambda: ramp2.state in ("complete", "rolled_back"),
            120.0, "clean ramp completion")
        invariants["ramp_completes"] = (
            done is not None and ramp2.state == "complete")
        invariants["candidate_elected_primary"] = (
            registry.primary_name() == "cand"
            and registry.splitter.shares() == {"cand": 1.0})
        results["ramp_complete"] = {
            "state": ramp2.state,
            "events": list(ramp2.events),
            "shares": registry.splitter.shares(),
        }
        log(f"clean ramp: {ramp2.state}, primary "
            f"{registry.primary_name()}")

        # -- phase 4: synthetic overload → per-model brownout -------------
        # restore the two-variant split so the cost gradient is live
        # (cand keeps zero weight; level-2 would shed it before "lite")
        registry.set_weights({"heavy": 0.6, "lite": 0.4, "cand": 0.0})
        sheds_before = _brownout_sheds(get_registry().snapshot())
        counts_before = _mux_counts(get_registry().snapshot())
        # big-slab closed-loop burst: 256-row slabs chunk through the
        # 8-bucket ladder (32 real flushes each), backing the small
        # (max_queue=16) per-variant queues up — real queue pressure,
        # synthetic only in its shape
        slab_stop = threading.Event()

        def slab_client(tid: int) -> None:
            rng = np.random.default_rng(7000 + tid)
            while not slab_stop.is_set():
                rows = rng.random((256, z_size), dtype=np.float32)
                http_json("POST", f"{base}/v1/sample",
                          {"data": rows.tolist()}, timeout=30.0)

        slab_threads = [
            threading.Thread(target=slab_client, args=(i,), daemon=True)
            for i in range(16)
        ]
        for t in slab_threads:
            t.start()
        engaged = wait_for(lambda: svc.brownout_level >= 1, 30.0,
                           "brownout engages under the slab burst")
        level_seen = svc.brownout_level
        # hold the burst briefly so sheds accumulate while engaged
        time.sleep(2.0)
        sheds_mid = _brownout_sheds(get_registry().snapshot())
        slab_stop.set()
        for t in slab_threads:
            t.join(timeout=40.0)
        heavy_sheds = (sheds_mid.get("heavy", 0.0)
                       - sheds_before.get("heavy", 0.0))
        lite_sheds = (sheds_mid.get("lite", 0.0)
                      - sheds_before.get("lite", 0.0))
        counts_mid = _mux_counts(get_registry().snapshot())
        lite_ok_during = (counts_mid.get("lite", {}).get("ok", 0.0)
                          - counts_before.get("lite", {}).get("ok", 0.0))
        invariants["brownout_engages_under_overload"] = bool(engaged)
        invariants["brownout_sheds_expensive_first"] = (
            heavy_sheds > 0 and lite_sheds == 0)
        # and that order came from the MEASUREMENT: both shed-ranked
        # variants carry measured scalars and the one that shed ranks
        # above the one that served — not the 4.0-vs-1.0 declaration
        mid_costs = registry.costs()
        mid_sources = registry.cost_sources()
        invariants["shed_order_follows_measured_cost"] = (
            mid_sources.get("heavy") == "measured"
            and mid_sources.get("lite") == "measured"
            and mid_costs["heavy"] > mid_costs["lite"]
            and heavy_sheds > 0 and lite_sheds == 0)
        invariants["cheap_variant_serves_through_brownout"] = (
            lite_ok_during > 0)
        released = wait_for(lambda: svc.brownout_level == 0, 30.0,
                            "brownout releases after quiesce")
        invariants["brownout_releases_after_quiesce"] = bool(released)
        results["brownout"] = {
            "max_level_seen": level_seen,
            "heavy_sheds": heavy_sheds,
            "lite_sheds": lite_sheds,
            "lite_ok_during_brownout": lite_ok_during,
        }
        log(f"brownout: level {level_seen}, heavy sheds "
            f"{heavy_sheds:.0f}, lite sheds {lite_sheds:.0f}, "
            f"lite ok during {lite_ok_during:.0f}")

        # -- phase 5: manifest-built conditional variant ------------------
        # the zoo seam (docs/ZOO.md): a variant built FROM a scenario
        # manifest — conditional dcgan-mnist, a genuinely different
        # architecture (28×28 conv generator, latent+one-hot input) than
        # the tabular drill variants — joins the SAME registry. The
        # publish goes through the real experiment path so serving.json
        # carries the zoo block; the engine the registry builds from the
        # bundle is conditional end to end, and the mux width check
        # scopes to the ROUTED variant: full-width rows pinned to it
        # serve while latent-width rows 400 without touching the
        # tabular variants' contracts.
        from gan_deeplearning4j_tpu.harness import GanExperiment
        from gan_deeplearning4j_tpu.zoo.manifest import ScenarioManifest

        scn = ScenarioManifest(
            architecture="dcgan", conditioning="class", dataset="mnist",
            resolution=28, num_classes=10, z_size=z_size)
        cond_dir = os.path.join(workdir, "variant_cond")
        GanExperiment(scn.experiment_config(seed=args.seed + 41)
                      ).publish_for_serving(cond_dir)
        # price it on the ladder it will serve (a variable, not a
        # literal — JG031) so it enters the registry already measured
        measure_bundle_cost(cond_dir, buckets=drill_buckets, rounds=2)
        registry.add("cond", bundle_path=cond_dir, cost=2.0, weight=0.0)
        registry.ensure_resident("cond")
        cond_engine = registry.engine_for("cond")
        cond_width = cond_engine.input_width("sample")
        heavy_width = registry.engine_for("heavy").input_width("sample")
        rng = np.random.default_rng(args.seed + 42)
        zc = rng.random((5, cond_width - 10), dtype=np.float32) * 2.0 - 1.0
        onehot = np.eye(10, dtype=np.float32)[np.arange(5) % 10]
        full_rows = np.concatenate([zc, onehot], axis=1)
        st_full, body_full = http_json(
            "POST", f"{base}/v1/sample",
            {"data": full_rows.tolist(), "model": "cond"}, timeout=30.0)
        st_narrow, _ = http_json(
            "POST", f"{base}/v1/sample",
            {"data": zc.tolist(), "model": "cond"}, timeout=30.0)
        cond_costs = registry.costs()
        results["conditional_variant"] = {
            "scenario": dict(cond_engine.scenario or {}),
            "input_width": cond_width,
            "tabular_input_width": heavy_width,
            "pinned_full_width_status": st_full,
            "pinned_latent_width_status": st_narrow,
            "cost": cond_costs.get("cond"),
            "cost_source": registry.cost_sources().get("cond"),
        }
        invariants["conditional_variant_manifest_built"] = (
            bool(cond_engine.conditional)
            and cond_engine.class_count == 10
            and (cond_engine.scenario or {}).get("dataset") == "mnist")
        invariants["conditional_enters_measured"] = (
            registry.cost_sources().get("cond") == "measured")
        invariants["conditional_pinned_serves_full_width"] = (
            st_full == 200
            and len((body_full or {}).get("data", [])) == 5)
        invariants["conditional_width_guard_rejects_latent"] = (
            st_narrow == 400)
        invariants["conditional_architecture_distinct"] = (
            cond_width != heavy_width
            and cond_costs.get("cond") != cond_costs.get("heavy"))
        log(f"conditional variant: width {cond_width} (tabular "
            f"{heavy_width}), pinned full-width -> {st_full}, "
            f"latent-width -> {st_narrow}, cost "
            f"{cond_costs.get('cond'):.3g} ({results['conditional_variant']['cost_source']})")

        # -- ledger -------------------------------------------------------
        final = load.finish()
        results["ledger"] = final
        results["staging_pool"] = registry.pool.stats()
        results["registry"] = registry.snapshot()
        invariants["zero_lost"] = final["lost"] == 0
        invariants["zero_client_errors"] = final["error"] == 0
        log(f"ledger: {final}")
    finally:
        if load is not None and not load.stop.is_set():
            load.finish()
        if server is not None:
            server.shutdown()
            server.server_close()
        if svc is not None:
            svc.close()

    ok = all(invariants.values()) and bool(invariants)
    payload = {
        "benchmark": "fleet_mux_drill",
        "torn": False,
        "config": {
            "seed": args.seed,
            "smoke": bool(args.smoke),
            "split_seconds": split_seconds,
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "wall_seconds": time.monotonic() - t_start,
        "results": results,
        "invariants": invariants,
        "ok": ok,
    }
    text = json.dumps(payload, indent=2)
    print(text)
    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                    exist_ok=True)
        with open(args.output, "w") as fh:
            fh.write(text + "\n")
    if args.record:
        with open(os.path.join(_REPO, f"BENCH_mux_{args.record}.json"),
                  "w") as fh:
            fh.write(text + "\n")
    if cleanup and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        log(f"INVARIANT BREACH — work files kept at {workdir}")
    for name, good in sorted(invariants.items()):
        log(f"invariant {name}: {'ok' if good else 'BREACH'}")
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="campaign/CI shape: 2 workers, 12 trainer steps")
    p.add_argument("--workers", type=int, default=None)
    p.add_argument("--total-steps", type=int, default=None)
    p.add_argument("--serve-every", type=int, default=None)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--keep-last", type=int, default=10)
    p.add_argument("--workdir", default=None,
                   help="keep work files here instead of a temp dir")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="where the merged fleet Chrome trace lands "
                        "(default: <workdir>/fleet_trace.json); "
                        "tpu_campaign.sh gates trace_report on it")
    p.add_argument("--output", default=None, metavar="PATH")
    p.add_argument("--record", default=None, metavar="TAG",
                   help="also write BENCH_fleet_<TAG>.json at the repo root "
                        "(BENCH_autoscale_<TAG>.json with --autoscale)")
    p.add_argument("--autoscale", action="store_true",
                   help="run the autoscale-under-burst phase instead of "
                        "the fault drill: min-size boot, ~10x closed-loop "
                        "ramp, grow/brownout/shrink invariants "
                        "(docs/FLEET.md 'Autoscaling')")
    p.add_argument("--mux", action="store_true",
                   help="run the multi-model multiplexing drill instead: "
                        "weighted split, 1%%->100%% canary ramp with an "
                        "injected-burn auto-rollback, per-model brownout "
                        "shed order (docs/MULTIPLEX.md; --record writes "
                        "BENCH_mux_<TAG>.json)")
    p.add_argument("--alerts", action="store_true",
                   help="run the alerting fire-and-resolve drill instead: "
                        "SIGKILL -> worker_down with the dead pid + an "
                        "exemplar trace, overload -> latency anomaly, "
                        "quiesce -> both resolve, zero false fires in the "
                        "calm audits (docs/OBSERVABILITY.md 'Alerting'; "
                        "--record writes BENCH_alerts_<TAG>.json)")
    p.add_argument("--max-workers", type=int, default=None,
                   help="autoscale ceiling (default 3; --workers is the "
                        "min, default 1)")
    p.add_argument("--burst-threads", type=int, default=None,
                   help="closed-loop threads in the burst (default 12 "
                        "smoke / 16 full; the ~10x ramp over the single "
                        "light-phase thread)")
    p.add_argument("--p99-bound", type=float, default=15.0,
                   help="autoscale invariant: p99 seconds bound for "
                        "admitted (200) requests")
    args = p.parse_args(argv)

    if sum(map(bool, (args.autoscale, args.mux, args.alerts))) > 1:
        p.error("--autoscale, --mux, and --alerts are separate drills")
    if args.autoscale:
        return run_autoscale(args)
    if args.mux:
        return run_mux(args)
    if args.alerts:
        return run_alerts(args)

    n_workers = args.workers or (2 if args.smoke else 3)
    total = args.total_steps or (12 if args.smoke else 24)
    serve_every = args.serve_every or 6
    workdir = args.workdir or tempfile.mkdtemp(prefix="fleet_drill_")
    cleanup = args.workdir is None
    os.makedirs(workdir, exist_ok=True)
    serve_store = os.path.join(workdir, "store_serve")
    train_store = os.path.join(workdir, "store_train")

    workload = make_workload(workdir, args.seed)
    results: dict = {}
    invariants: dict = {}
    fleet = trainer = None
    load = monitor = None
    router_port = free_port()
    worker_ports = [free_port() for _ in range(n_workers)]
    base = f"http://127.0.0.1:{router_port}"

    try:
        # -- phase 0: seed + boot the fleet -----------------------------
        gen0 = seed_bundle(workload, serve_store, args.keep_last)
        log(f"seeded serving generation {gen0}")
        fleet_log = open(os.path.join(workdir, "fleet.log"), "w")
        fleet = subprocess.Popen(
            FLEET + [
                "--store", serve_store,
                "--workers", str(n_workers),
                "--port", str(router_port),
                "--worker-ports", ",".join(str(x) for x in worker_ports),
                "--log-dir", workdir,
                "--poll", "0.5", "--probe-interval", "0.15",
                "--request-timeout", "3.0",
                "--retry-ratio", "0.5", "--retry-burst", "10",
                "--eject-failures", "3", "--reopen-after", "0.5",
                "--drain-timeout", "15", "--warm-timeout", "240",
                "--hang-restart", "30",
                "--buckets", "1,8", "--replicas", "1",
                "--max-latency", "0.002",
                "--canary-data", workload["data"],
                "--canary-samples", "32",
                "--canary-fid-ratio", "1.1", "--canary-fid-slack", "0.5",
                "--boot-wait", "60", "--telemetry",
                # warm elasticity (ISSUE 19): every worker — including
                # re-spawns and scale-ups — shares one persistent XLA
                # cache, so restarts reuse AOT artifacts instead of
                # recompiling the ladder
                "--compilation-cache", os.path.join(workdir, "xla_cache"),
            ],
            cwd=_REPO, env=_ENV, stdout=fleet_log, stderr=fleet_log,
        )
        health = wait_for(
            lambda: (fleet.poll() is None
                     and (h := fleet_health(base)).get("routable")
                     == n_workers and h.get("generation") == gen0 and h),
            420.0, "fleet healthy on the seed generation")
        if not health:
            log(f"fleet never became healthy (rc={fleet.poll()})")
            return 2
        z_size = 4  # the drill workload's latent width (make_workload)
        log(f"fleet healthy on {base}: {n_workers} workers, "
            f"generation {gen0}")
        monitor = FleetMonitor(base)
        monitor.start()
        load = LoadGenerator(base, z_size)
        load.start()
        time.sleep(1.0)  # let traffic establish before the first fault

        # -- phase 1: SIGKILL a worker ----------------------------------
        victim = worker_by_id(health, "w0")
        log(f"SIGKILL worker w0 (pid {victim.get('pid')})")
        os.kill(victim["pid"], signal.SIGKILL)
        recovered = wait_for(
            lambda: ((h := fleet_health(base)).get("routable") == n_workers
                     and worker_by_id(h, "w0").get("restarts", 0) >= 1
                     and worker_by_id(h, "w0").get("pid")
                     not in (None, victim["pid"]) and h),
            300.0, "SIGKILLed worker relaunched and re-admitted")
        results["sigkill"] = {
            "old_pid": victim.get("pid"),
            "new_pid": worker_by_id(recovered or {}, "w0").get("pid"),
            "restarts": worker_by_id(recovered or {}, "w0").get("restarts"),
            "routable_s": worker_by_id(recovered or {}, "w0").get(
                "routable_s"),
            "counts_at_recovery": dict(load.counts),
        }
        invariants["sigkill_worker_relaunched"] = bool(recovered)
        # the re-spawned worker warmed its whole ladder before admission
        # (shared --compilation-cache makes that warmup AOT-reusable);
        # no request may ever pay a serve-time compile on the new pid
        _, w0_metrics = http_json(
            "GET", f"http://127.0.0.1:{worker_ports[0]}/metrics",
            timeout=5.0)
        respawn_compiles = ((w0_metrics or {}).get("engine") or {}).get(
            "serve_compile_counts", {})
        results["sigkill"]["serve_compile_counts"] = respawn_compiles
        invariants["respawned_worker_no_serve_compiles"] = bool(
            respawn_compiles) and all(
            v == 0 for v in respawn_compiles.values())

        # -- phase 2: SIGSTOP (hang) + half-open re-admission -----------
        health = fleet_health(base)
        hung = worker_by_id(health, "w1")
        restarts_before = hung.get("restarts", 0)
        log(f"SIGSTOP worker w1 (pid {hung.get('pid')})")
        os.kill(hung["pid"], signal.SIGSTOP)
        try:
            ejected = wait_for(
                lambda: not router_worker(fleet_health(base),
                                          "w1").get("routable", True),
                120.0, "hung worker ejected")
        finally:
            os.kill(hung["pid"], signal.SIGCONT)
        log("SIGCONT sent — waiting for half-open re-admission")
        readmitted = wait_for(
            lambda: ((h := fleet_health(base)).get("routable") == n_workers
                     and router_worker(h, "w1").get("routable") and h),
            120.0, "hung worker re-admitted")
        restarts_after = worker_by_id(readmitted or {}, "w1").get(
            "restarts", -1)
        results["sigstop"] = {
            "pid": hung.get("pid"),
            "ejected": bool(ejected),
            "readmitted": bool(readmitted),
            "restarts_before": restarts_before,
            "restarts_after": restarts_after,
            "counts_at_recovery": dict(load.counts),
        }
        invariants["hung_worker_ejected"] = bool(ejected)
        invariants["hung_worker_readmitted_without_restart"] = (
            bool(readmitted) and restarts_after == restarts_before)

        # -- phase 3: rolling generation upgrades -----------------------
        trainer_log = open(os.path.join(workdir, "trainer.log"), "w")
        trainer = subprocess.Popen(
            TRAINER + [
                "--config", workload["config"], "--data", workload["data"],
                "--store", train_store,
                "--serve-store", serve_store,
                "--total-steps", str(total),
                "--publish-every", str(serve_every),
                "--serve-publish-every", str(serve_every),
                "--keep-last", str(args.keep_last),
                "--summary", os.path.join(workdir, "trainer_summary.json"),
            ],
            cwd=_REPO, env=_ENV, stdout=trainer_log, stderr=trainer_log,
        )
        try:
            trainer.wait(timeout=600.0)
        except subprocess.TimeoutExpired:
            trainer.kill()
            log("trainer hung — killed")
        try:
            with open(os.path.join(workdir, "trainer_summary.json")) as fh:
                trainer_summary = json.load(fh)
        except (OSError, json.JSONDecodeError):
            trainer_summary = {}
        final_gen = trainer_summary.get("final_serve_generation")
        log(f"trainer done rc={trainer.returncode}, "
            f"final serve generation {final_gen}")
        converged = wait_for(
            lambda: ((h := fleet_health(base)).get("generation") == final_gen
                     and h.get("routable") == n_workers
                     and (h.get("fleet") or {}).get("state") == "idle"
                     and h),
            600.0, "fleet converged on the trainer's final generation")
        fleet_state = (converged or fleet_health(base)).get("fleet") or {}
        results["rolling_upgrade"] = {
            "trainer_rc": trainer.returncode,
            "final_serve_generation": final_gen,
            "fleet_generation": (converged or {}).get("generation"),
            "rolls": fleet_state.get("rolls"),
            "rejected": fleet_state.get("rejected"),
            "counts_at_convergence": dict(load.counts),
        }
        invariants["fleet_converged_to_final_generation"] = bool(converged)
        invariants["rolling_upgrades_ge_1"] = (
            (fleet_state.get("rolls") or 0) >= 1)

        # -- phase 4: poisoned generation, one fleet-wide decision ------
        rejected_before = fleet_state.get("rejected", 0)
        poison = poison_newest(serve_store, args.keep_last)
        log(f"published poisoned generation {poison}")
        rejected = wait_for(
            lambda: (((h := fleet_health(base)).get("fleet") or {})
                     .get("rejected", 0) > rejected_before and h),
            420.0, "fleet rejected the poisoned generation")
        from gan_deeplearning4j_tpu.resilience import CheckpointStore

        entry = CheckpointStore(serve_store,
                                keep_last=args.keep_last).entry(poison)
        after = fleet_health(base)
        results["poison"] = {
            "generation": poison,
            "ledger_status": entry.get("status"),
            "quarantine_reason": entry.get("reason"),
            "fleet_generation_after": after.get("generation"),
            "rejected": (after.get("fleet") or {}).get("rejected"),
        }
        invariants["poison_rejected_once_fleet_wide"] = bool(rejected)
        invariants["poison_quarantined_in_store"] = (
            entry.get("status") == "quarantined"
            and "canary" in (entry.get("reason") or ""))
        invariants["poison_never_served"] = (
            poison not in monitor.generations_served
            and after.get("generation") == final_gen)

        # -- phase 5: trace propagation + fleet aggregation -------------
        # quiesce first: with the load generator stopped, per-worker
        # counters are frozen, so the exactness assertions below compare
        # stable numbers instead of racing live traffic
        counts = load.finish()
        load = None
        monitor.finish()
        trace_out = args.trace_out or os.path.join(workdir,
                                                   "fleet_trace.json")
        health_now = fleet_health(base)
        worker_pids = {w.get("pid")
                       for w in (health_now.get("fleet") or {})
                       .get("workers", []) if w.get("pid")}
        results["trace"] = run_trace_phase(base, z_size, worker_pids,
                                           trace_out, invariants)
        results["fleet_metrics"] = run_aggregation_phase(
            base, worker_ports, counts, invariants)

        # -- phase 6: ledgers -------------------------------------------
        _, router_metrics = http_json("GET", f"{base}/metrics", timeout=5.0)
        router_metrics = router_metrics or {}
        results["requests"] = counts
        results["router"] = {
            k: router_metrics.get(k)
            for k in ("proxied", "ok", "error", "retries",
                      "budget_exhausted", "no_worker", "attempts_exhausted",
                      "ejections", "retry_budget_tokens")
        }
        results["generations_served"] = sorted(monitor.generations_served)
        results["routable_envelope"] = [monitor.min_routable,
                                        monitor.max_routable]
        # launch-to-routable per worker (fleet_scaleup_routable_seconds
        # feeds the same numbers to /metrics) — the elasticity surface
        # scale-ups and re-spawns are judged on
        final_health = fleet_health(base)
        results["scaleup_routable_s"] = {
            w["id"]: w.get("routable_s")
            for w in (final_health.get("fleet") or {}).get("workers", [])}
        invariants["exactly_one_answer_zero_lost"] = (
            counts["lost"] == 0
            and counts["ok"] + counts["shed"] + counts["error"]
            == counts["sent"])
        # the retry-budget contract: every client-visible 503 is one of
        # the router's honest-503 paths, and no request got a 5xx the
        # router could not account for
        honest_503s = ((router_metrics.get("budget_exhausted") or 0)
                       + (router_metrics.get("no_worker") or 0)
                       + (router_metrics.get("attempts_exhausted") or 0))
        invariants["errors_bounded_by_retry_budget"] = (
            counts["error"] == 0 and counts["shed"] <= honest_503s)
        # bounded-compile through re-routing: no worker ever paid a
        # serve-time compile (scraped directly, not via the router)
        serve_compiles = {}
        for port in worker_ports:
            _, m = http_json("GET", f"http://127.0.0.1:{port}/metrics",
                             timeout=5.0)
            if m:
                serve_compiles[str(port)] = (m.get("engine") or {}).get(
                    "serve_compile_counts", {})
        results["serve_compile_counts"] = serve_compiles
        invariants["no_serve_time_compiles"] = bool(serve_compiles) and all(
            all(v == 0 for v in counts_.values())
            for counts_ in serve_compiles.values())
    finally:
        if load is not None:
            load.finish()
        if monitor is not None and not monitor.stop.is_set():
            monitor.finish()
        for proc in (trainer, fleet):
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=20.0)
                except subprocess.TimeoutExpired:
                    proc.kill()

    # -- verdict ---------------------------------------------------------
    ok = bool(invariants) and all(invariants.values())
    payload = {
        "bench": "fleet_drill",
        "config": {
            "workers": n_workers,
            "total_steps": total,
            "serve_publish_every": serve_every,
            "seed": args.seed,
            "smoke": bool(args.smoke),
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "results": results,
        "invariants": invariants,
        "ok": ok,
    }
    text = json.dumps(payload, indent=2)
    print(text)
    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                    exist_ok=True)
        with open(args.output, "w") as fh:
            fh.write(text + "\n")
    if args.record:
        with open(os.path.join(_REPO, f"BENCH_fleet_{args.record}.json"),
                  "w") as fh:
            fh.write(text + "\n")
    if cleanup and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        log(f"INVARIANT BREACH — work files kept at {workdir}")
    for name, good in sorted(invariants.items()):
        log(f"invariant {name}: {'ok' if good else 'BREACH'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
