#!/usr/bin/env python
"""Resilience drill — kill training mid-run, prove recovery, measure cost.

The drill is the executable form of docs/RESILIENCE.md's invariants. It
launches the supervised worker (``python -m gan_deeplearning4j_tpu
.resilience``) as a real subprocess, murders it, relaunches it, corrupts
its checkpoints, and checks that the resilience layer keeps every promise:

1. **oracle** — an uninterrupted run of ``total_steps`` records the ground
   truth: final state digests, per-step train time, checkpoint-write
   overhead.
2. **kill/recover** — a fresh store; a deterministic (seeded) fault
   schedule SIGKILLs the worker at step N. The drill observes the death,
   relaunches (the schedule is handed only to the first launch — the
   preemption happened once), and measures recovery time, lost steps, and
   relaunch count.
3. **bit-exact resume** — the recovered run's final state digests must be
   IDENTICAL to the oracle's: interrupted-and-resumed == uninterrupted at
   equal total steps.
4. **corruption fallback** — the recovered store's newest generation gets
   its bytes flipped; a further run must quarantine it (ledger status
   ``quarantined``), restore from the prior generation, and complete.

Results land as a BENCH-style JSON (``--output``, and ``--record TAG``
additionally writes ``BENCH_resilience_<TAG>.json`` at the repo root).
Exit status is nonzero on any invariant breach — non-bit-exact resume, a
corrupt generation selected, the relaunch/retry budget exceeded without a
terminal error — so CI can gate on the drill directly
(``scripts/tpu_campaign.sh`` runs ``--smoke`` CPU-pinned as a preflight).

The workload is the tabular family at toy size: the drill proves the
*mechanism* (processes really die; stores really quarantine), not model
quality, and must be cheap enough to run as a tier-1 smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

WORKER = [sys.executable, "-m", "gan_deeplearning4j_tpu.resilience"]


def log(msg: str) -> None:
    print(f"[drill] {msg}", flush=True)


def make_workload(workdir: str, seed: int) -> dict:
    """Config + deterministic synthetic data for the drill's tiny tabular
    GAN. Returns the paths the worker CLI consumes."""
    from gan_deeplearning4j_tpu.harness import ExperimentConfig

    cfg = ExperimentConfig(
        model_family="tabular", num_features=16, z_size=4,
        batch_size_train=8, batch_size_pred=8,
        height=1, width=1, channels=1,
        save_models=False, seed=seed, file_prefix="tabular",
        output_dir=os.path.join(workdir, "out"),
    )
    config_path = os.path.join(workdir, "config.json")
    cfg.to_json(config_path)
    rng = np.random.default_rng(seed)
    features = rng.random((64, 16), dtype=np.float32)
    labels = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)]
    data_path = os.path.join(workdir, "data.npz")
    np.savez(data_path, features=features, labels=labels)
    return {"config": config_path, "data": data_path}


def run_worker(workload: dict, store: str, total_steps: int,
               publish_every: int, summary_path: str,
               schedule_path: str | None = None,
               timeout_s: float = 600.0) -> tuple:
    """One worker lifetime. Returns (returncode, summary_dict_or_None,
    wall_seconds). A negative returncode is death by signal."""
    cmd = WORKER + [
        "--config", workload["config"], "--data", workload["data"],
        "--store", store,
        "--total-steps", str(total_steps),
        "--publish-every", str(publish_every),
        "--summary", summary_path,
    ]
    if schedule_path:
        cmd += ["--fault-schedule", schedule_path]
    # Workers run with the persistent XLA compilation cache OFF: the
    # XLA:CPU AOT loader is unsafe (runtime/environment.py — cpu_aot_loader
    # errors, SIGILL/heap-corruption risk), and a worker segfaulting on a
    # poisoned cache entry is indistinguishable from the fault being
    # drilled — the one contamination this harness cannot tolerate. An
    # environment that exported GDT_COMPILATION_CACHE (the test suite
    # does) must not leak it into the workers.
    env = {**os.environ, "GDT_COMPILATION_CACHE": "off"}
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(cmd, cwd=_REPO, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        # a hung worker is an invariant failure to REPORT, not a drill
        # crash: rc=None flows through the phase logic as "unexpected exit"
        log(f"worker hung past {timeout_s:.0f}s — killed")
        return None, None, time.perf_counter() - t0
    wall = time.perf_counter() - t0
    summary = None
    if os.path.exists(summary_path):
        try:
            with open(summary_path) as fh:
                summary = json.load(fh)
        except json.JSONDecodeError:
            summary = None  # torn write from a killed worker — expected
    if proc.returncode not in (0, 75) and proc.returncode >= 0:
        log(f"worker rc={proc.returncode} stderr tail: "
            f"{proc.stderr[-500:]}")
    return proc.returncode, summary, wall  # negative rc = death by signal


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="tier-1 shape: 12 steps, publish every 3")
    p.add_argument("--total-steps", type=int, default=None)
    p.add_argument("--publish-every", type=int, default=None)
    p.add_argument("--kill-step", type=int, default=None,
                   help="override the seeded kill step")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--relaunch-budget", type=int, default=5)
    p.add_argument("--workdir", default=None,
                   help="keep work files here instead of a temp dir")
    p.add_argument("--output", default=None, metavar="PATH",
                   help="write the drill JSON here")
    p.add_argument("--record", default=None, metavar="TAG",
                   help="also write BENCH_resilience_<TAG>.json at the "
                        "repo root")
    args = p.parse_args(argv)

    total = args.total_steps or (12 if args.smoke else 40)
    publish_every = args.publish_every or (3 if args.smoke else 5)
    workdir = args.workdir or tempfile.mkdtemp(prefix="resilience_drill_")
    cleanup = args.workdir is None
    os.makedirs(workdir, exist_ok=True)

    from gan_deeplearning4j_tpu.resilience import (
        CheckpointStore,
        FaultSchedule,
        FaultSpec,
        corrupt_generation,
    )

    workload = make_workload(workdir, args.seed)

    # the seeded schedule: one SIGKILL somewhere in (0, total)
    if args.kill_step is not None:
        kill_step = args.kill_step
        schedule = FaultSchedule([FaultSpec(kind="kill", step=kill_step)])
    else:
        schedule = FaultSchedule.seeded(args.seed, total, kinds=("kill",))
        kill_step = schedule.specs[0].step
    schedule_path = os.path.join(workdir, "faults.json")
    schedule.to_json(schedule_path)

    results: dict = {}
    invariants: dict = {}

    # -- phase 1: oracle ------------------------------------------------
    log(f"oracle: {total} uninterrupted steps, publish every {publish_every}")
    rc, oracle, oracle_wall = run_worker(
        workload, os.path.join(workdir, "store_oracle"), total,
        publish_every, os.path.join(workdir, "summary_oracle.json"))
    if rc != 0 or oracle is None or oracle.get("status") != "completed":
        log(f"oracle run failed (rc={rc}) — cannot drill")
        return 2
    results["oracle"] = {
        "wall_s": oracle_wall,
        "train_s": oracle["train_s"],
        "publish_s": oracle["publish_s"],
        "publish_count": oracle["publish_count"],
        "steps": oracle["steps"],
        "checkpoint_overhead_frac": (
            oracle["publish_s"] / (oracle["train_s"] + oracle["publish_s"])
            if oracle["train_s"] + oracle["publish_s"] > 0 else 0.0
        ),
        "checkpoint_write_s_mean": (
            oracle["publish_s"] / oracle["publish_count"]
            if oracle["publish_count"] else 0.0
        ),
    }

    # -- phase 2: kill + relaunch ---------------------------------------
    fault_store = os.path.join(workdir, "store_fault")
    log(f"kill/recover: SIGKILL scheduled at step {kill_step}")
    relaunches = 0
    killed_observed = False
    recovery = None
    final = None
    while relaunches <= args.relaunch_budget:
        first = relaunches == 0
        rc, summary, wall = run_worker(
            workload, fault_store, total, publish_every,
            os.path.join(workdir, f"summary_fault_{relaunches}.json"),
            schedule_path=schedule_path if first else None)
        if rc == 0 and summary is not None:
            final = summary
            if not first and recovery is None:
                restores = [e for e in summary.get("events", [])
                            if e.get("event") == "restore"]
                restored_step = restores[0]["step"] if restores else 0
                recovery = {
                    "relaunch_wall_s": wall,
                    "restore_s": summary.get("restore_s"),
                    "time_to_first_step_s": summary.get(
                        "time_to_first_step_s"),
                    "restored_step": restored_step,
                    "lost_steps": kill_step - restored_step,
                }
            break
        if rc is not None and rc < 0:
            killed_observed = True
            log(f"worker died by signal (rc={rc}) — relaunching")
            relaunches += 1
            continue
        log(f"worker exited rc={rc} unexpectedly — drill failed")
        break
    results["kill_recover"] = {
        "kill_step": kill_step,
        "killed_observed": killed_observed,
        "relaunches": relaunches,
        "recovery": recovery,
        "completed": final is not None,
    }
    invariants["kill_observed"] = killed_observed
    invariants["recovered_within_budget"] = (
        final is not None and relaunches <= args.relaunch_budget)

    # -- phase 3: bit-exact resume --------------------------------------
    oracle_digests = oracle.get("state_digests")
    final_digests = (final or {}).get("state_digests")
    invariants["bit_exact_resume"] = (
        oracle_digests is not None and oracle_digests == final_digests)
    results["bit_exact"] = {
        "oracle_digests": oracle_digests,
        "recovered_digests": final_digests,
    }

    # -- phase 4: corruption fallback -----------------------------------
    corrupt_result: dict = {}
    if final is not None:
        store = CheckpointStore(fault_store)
        published = store.published()
        newest = published[-1]
        prior = published[-2] if len(published) > 1 else None
        member = corrupt_generation(store, newest, seed=args.seed)
        log(f"corrupted generation {newest} member {member!r}; "
            f"extending run to {total + publish_every} steps")
        rc, summary, wall = run_worker(
            workload, fault_store, total + publish_every, publish_every,
            os.path.join(workdir, "summary_corrupt.json"))
        restores = [e for e in (summary or {}).get("events", [])
                    if e.get("event") == "restore"]
        restored_gen = restores[0]["generation"] if restores else None
        entry = CheckpointStore(fault_store).entry(newest)
        corrupt_result = {
            "corrupted_generation": newest,
            "corrupted_member": member,
            "fallback_generation": restored_gen,
            "ledger_status": entry.get("status"),
            "quarantine_reason": entry.get("reason"),
            "completed": rc == 0 and (summary or {}).get("status")
            == "completed",
        }
        invariants["corrupt_never_selected"] = (
            entry.get("status") == "quarantined"
            and restored_gen is not None
            and restored_gen != newest
            and (prior is None or restored_gen == prior)
            and corrupt_result["completed"]
        )
    else:
        invariants["corrupt_never_selected"] = False
    results["corruption"] = corrupt_result

    # -- verdict ---------------------------------------------------------
    ok = all(invariants.values())
    payload = {
        "bench": "resilience_drill",
        "config": {
            "total_steps": total,
            "publish_every": publish_every,
            "kill_step": kill_step,
            "seed": args.seed,
            "smoke": bool(args.smoke),
            "relaunch_budget": args.relaunch_budget,
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "results": results,
        "invariants": invariants,
        # each worker's registry snapshot (same series a live scrape would
        # show) — bench artifacts and /metrics share one definition
        "telemetry": {
            "oracle": oracle.get("telemetry"),
            "recovered": (final or {}).get("telemetry"),
        },
        "ok": ok,
    }
    text = json.dumps(payload, indent=2)
    print(text)
    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                    exist_ok=True)
        with open(args.output, "w") as fh:
            fh.write(text + "\n")
    if args.record:
        with open(os.path.join(_REPO,
                               f"BENCH_resilience_{args.record}.json"),
                  "w") as fh:
            fh.write(text + "\n")
    if cleanup and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        log(f"INVARIANT BREACH — work files kept at {workdir}")
    for name, good in sorted(invariants.items()):
        log(f"invariant {name}: {'ok' if good else 'BREACH'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
