#!/usr/bin/env python
"""Resilience drill — kill training mid-run, prove recovery, measure cost.

The drill is the executable form of docs/RESILIENCE.md's invariants. It
launches the supervised worker (``python -m gan_deeplearning4j_tpu
.resilience``) as a real subprocess, murders it, relaunches it, corrupts
its checkpoints, and checks that the resilience layer keeps every promise:

1. **oracle** — an uninterrupted run of ``total_steps`` records the ground
   truth: final state digests, per-step train time, checkpoint-write
   overhead.
2. **kill/recover** — a fresh store; a deterministic (seeded) fault
   schedule SIGKILLs the worker at step N. The drill observes the death,
   relaunches (the schedule is handed only to the first launch — the
   preemption happened once), and measures recovery time, lost steps, and
   relaunch count.
3. **bit-exact resume** — the recovered run's final state digests must be
   IDENTICAL to the oracle's: interrupted-and-resumed == uninterrupted at
   equal total steps.
4. **corruption fallback** — the recovered store's newest generation gets
   its bytes flipped; a further run must quarantine it (ledger status
   ``quarantined``), restore from the prior generation, and complete.

``--multihost N`` drills the MESH plane instead (resilience/mesh.py): N
real worker processes share one store and two-phase-commit coordinated
sharded generations. Phases: single-worker oracles (ground truth at
``total`` and ``total + publish_every`` steps); a gang with a straggler
shard writer and a seeded worker kill (the survivor must gang-abort with
exit 76, the relaunched gang must finish digest-identical to the oracle);
a coordinator killed INSIDE the commit window (marker written, rename
never happens — the half-committed round must stay invisible to
``latest_valid()`` and the relaunch must recover); and elastic resume
(the 2-worker-written store extended on a 1-worker and a 2-worker mesh,
both digest-identical to the uninterrupted oracle). ``--record TAG``
writes ``BENCH_resilience_mh_<TAG>.json`` (recovery time, lost steps,
commit overhead vs the single-writer publish).

Results land as a BENCH-style JSON (``--output``, and ``--record TAG``
additionally writes ``BENCH_resilience_<TAG>.json`` at the repo root).
Exit status is nonzero on any invariant breach — non-bit-exact resume, a
corrupt generation selected, the relaunch/retry budget exceeded without a
terminal error — so CI can gate on the drill directly
(``scripts/tpu_campaign.sh`` runs ``--smoke`` CPU-pinned as a preflight).

The workload is the tabular family at toy size: the drill proves the
*mechanism* (processes really die; stores really quarantine), not model
quality, and must be cheap enough to run as a tier-1 smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

WORKER = [sys.executable, "-m", "gan_deeplearning4j_tpu.resilience"]


def log(msg: str) -> None:
    print(f"[drill] {msg}", flush=True)


def make_workload(workdir: str, seed: int) -> dict:
    """Config + deterministic synthetic data for the drill's tiny tabular
    GAN. Returns the paths the worker CLI consumes."""
    from gan_deeplearning4j_tpu.harness import ExperimentConfig

    cfg = ExperimentConfig(
        model_family="tabular", num_features=16, z_size=4,
        batch_size_train=8, batch_size_pred=8,
        height=1, width=1, channels=1,
        save_models=False, seed=seed, file_prefix="tabular",
        output_dir=os.path.join(workdir, "out"),
    )
    config_path = os.path.join(workdir, "config.json")
    cfg.to_json(config_path)
    rng = np.random.default_rng(seed)
    features = rng.random((64, 16), dtype=np.float32)
    labels = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)]
    data_path = os.path.join(workdir, "data.npz")
    np.savez(data_path, features=features, labels=labels)
    return {"config": config_path, "data": data_path}


def _load_summary(summary_path: str):
    """The worker's --summary JSON, or None when it never landed or was
    torn by a kill — one judgment shared by the single-worker and gang
    paths."""
    if not os.path.exists(summary_path):
        return None
    try:
        with open(summary_path) as fh:
            return json.load(fh)
    except json.JSONDecodeError:
        return None  # torn write from a killed worker — expected


def run_worker(workload: dict, store: str, total_steps: int,
               publish_every: int, summary_path: str,
               schedule_path: str | None = None,
               timeout_s: float = 600.0) -> tuple:
    """One worker lifetime. Returns (returncode, summary_dict_or_None,
    wall_seconds). A negative returncode is death by signal."""
    cmd = WORKER + [
        "--config", workload["config"], "--data", workload["data"],
        "--store", store,
        "--total-steps", str(total_steps),
        "--publish-every", str(publish_every),
        "--summary", summary_path,
    ]
    if schedule_path:
        cmd += ["--fault-schedule", schedule_path]
    # Workers run with the persistent XLA compilation cache OFF: the
    # XLA:CPU AOT loader is unsafe (runtime/environment.py — cpu_aot_loader
    # errors, SIGILL/heap-corruption risk), and a worker segfaulting on a
    # poisoned cache entry is indistinguishable from the fault being
    # drilled — the one contamination this harness cannot tolerate. An
    # environment that exported GDT_COMPILATION_CACHE (the test suite
    # does) must not leak it into the workers.
    env = {**os.environ, "GDT_COMPILATION_CACHE": "off"}
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(cmd, cwd=_REPO, capture_output=True, text=True,
                              timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        # a hung worker is an invariant failure to REPORT, not a drill
        # crash: rc=None flows through the phase logic as "unexpected exit"
        log(f"worker hung past {timeout_s:.0f}s — killed")
        return None, None, time.perf_counter() - t0
    wall = time.perf_counter() - t0
    summary = _load_summary(summary_path)
    if proc.returncode not in (0, 75) and proc.returncode >= 0:
        log(f"worker rc={proc.returncode} stderr tail: "
            f"{proc.stderr[-500:]}")
    return proc.returncode, summary, wall  # negative rc = death by signal


def run_gang(workload: dict, store: str, total_steps: int,
             publish_every: int, world_size: int, token: str,
             summary_dir: str, schedules: dict | None = None,
             mesh_timeout_s: float = 15.0, timeout_s: float = 600.0) -> list:
    """One gang lifetime: ``world_size`` concurrent worker processes
    against one store. ``schedules`` maps worker id -> fault schedule
    path (workers absent from the map run clean). Returns a list of
    (returncode, summary_or_None, wall_seconds) per worker; each worker's
    summary lands in ``summary_dir/summary_<token>_w<k>.json``. Drained
    concurrently — a sequential wait would deadlock against the mesh
    barriers."""
    from concurrent.futures import ThreadPoolExecutor

    os.makedirs(summary_dir, exist_ok=True)
    env = {**os.environ, "GDT_COMPILATION_CACHE": "off"}
    procs = []
    for k in range(world_size):
        cmd = WORKER + [
            "--config", workload["config"], "--data", workload["data"],
            "--store", store,
            "--total-steps", str(total_steps),
            "--publish-every", str(publish_every),
            "--mesh-size", str(world_size),
            "--mesh-worker", str(k),
            "--mesh-token", token,
            "--mesh-timeout", str(mesh_timeout_s),
            "--summary",
            os.path.join(summary_dir, f"summary_{token}_w{k}.json"),
        ]
        if schedules and k in schedules:
            cmd += ["--fault-schedule", schedules[k]]
        procs.append(subprocess.Popen(
            cmd, cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env))
    t0 = time.perf_counter()
    results = []
    with ThreadPoolExecutor(world_size) as pool:
        futures = [pool.submit(p.communicate, timeout=timeout_s)
                   for p in procs]
        for k, future in enumerate(futures):
            try:
                _, err = future.result()
            except subprocess.TimeoutExpired:
                log(f"gang {token} worker {k} hung past {timeout_s:.0f}s "
                    f"— killed")
                for q in procs:
                    q.kill()
                err = ""
            results.append(err)
    # reap anything killed after a hang: its communicate() thread bailed
    # on TimeoutExpired, so returncode would otherwise stay None and the
    # rc classification below would crash instead of reporting the breach
    for proc in procs:
        if proc.returncode is None:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass  # unkillable — rc stays None, reported as a hang
    wall = time.perf_counter() - t0
    out = []
    for k, (proc, err) in enumerate(zip(procs, results)):
        summary = _load_summary(
            os.path.join(summary_dir, f"summary_{token}_w{k}.json"))
        if proc.returncode is None:
            log(f"gang {token} worker {k} unreaped after kill — "
                f"treating as hung (rc=None)")
        elif proc.returncode not in (0, 75, 76) and proc.returncode >= 0:
            log(f"gang {token} worker {k} rc={proc.returncode} stderr "
                f"tail: {err[-500:]}")
        out.append((proc.returncode, summary, wall))
    return out


def _gang_digests(gang: list) -> list:
    """state_digests of every completed worker in a gang result."""
    return [s.get("state_digests") for rc, s, _ in gang
            if rc == 0 and s is not None]


def _gang_shards(gang: list) -> list:
    """Which updater shard each gang worker wrote (supervisor summary's
    ``updater_shard``: index, count, files) — so a shard mismatch names
    the owning worker instead of leaving it encoded in file names."""
    out = []
    for k, (rc, s, _) in enumerate(gang):
        shard = dict((s or {}).get("updater_shard") or {})
        # the summary's self-claimed rank must not mask which GANG SLOT
        # produced the record — a rank/slot disagreement is exactly what
        # a shard-mismatch log exists to expose
        shard.pop("worker", None)
        out.append({"worker": k, "rc": rc, **shard})
    return out


def _log_shard_owners(shards: list, what: str) -> None:
    for rec in shards:
        files = ", ".join(rec.get("files", [])) or "<none>"
        log(f"{what}: worker {rec['worker']} wrote updater shard "
            f"{rec.get('shard_index', '?')}/{rec.get('shard_count', '?')} "
            f"({files})")


def run_multihost_drill(args, workdir: str, total: int,
                        publish_every: int) -> dict:
    """The mesh-plane drill (see module docstring). Returns the BENCH
    payload; ``invariants`` within gate the exit code."""
    from gan_deeplearning4j_tpu.resilience import (
        CheckpointStore,
        FaultSchedule,
        FaultSpec,
    )
    from gan_deeplearning4j_tpu.resilience.mesh import MESH_STAGE_PREFIX

    n = args.multihost
    workload = make_workload(workdir, args.seed)
    results: dict = {}
    invariants: dict = {}
    total_ext = total + publish_every

    def stage_dirs(store_root: str) -> list:
        return sorted(d for d in os.listdir(store_root)
                      if d.startswith(MESH_STAGE_PREFIX))

    def all_published_verify(store_root: str) -> bool:
        store = CheckpointStore(store_root)
        return all(store.verify(g) is None for g in store.published())

    # -- phase 1: single-worker oracles (ground truth) -------------------
    log(f"oracle: single worker, {total} and {total_ext} uninterrupted "
        f"steps")
    rc, oracle, oracle_wall = run_worker(
        workload, os.path.join(workdir, "store_oracle"), total,
        publish_every, os.path.join(workdir, "summary_oracle.json"))
    rc2, oracle_ext, _ = run_worker(
        workload, os.path.join(workdir, "store_oracle_ext"), total_ext,
        publish_every, os.path.join(workdir, "summary_oracle_ext.json"))
    if (rc != 0 or oracle is None or oracle.get("status") != "completed"
            or rc2 != 0 or oracle_ext is None
            or oracle_ext.get("status") != "completed"):
        log(f"oracle runs failed (rc={rc}/{rc2}) — cannot drill")
        return {"ok": False, "invariants": {"oracle_completed": False}}
    results["oracle"] = {
        "wall_s": oracle_wall,
        "publish_count": oracle["publish_count"],
        "checkpoint_write_s_mean": (
            oracle["publish_s"] / oracle["publish_count"]
            if oracle["publish_count"] else 0.0),
    }

    # -- phase 2: worker kill + straggler under coordinated publish ------
    mesh_store = os.path.join(workdir, "store_mesh")
    if args.kill_step is not None:
        kill_step = args.kill_step
    else:
        seeded = FaultSchedule.seeded(args.seed, total, kinds=("kill",))
        kill_step = max(seeded.specs[0].step, publish_every + 1)
    victim = n - 1  # a non-coordinator writer; the coordinator dies in p3
    schedule = FaultSchedule([
        FaultSpec(kind="straggler", step=publish_every,
                  args={"seconds": 0.3}),
        FaultSpec(kind="kill", step=kill_step),
    ])
    schedule_path = os.path.join(workdir, "faults_mesh.json")
    schedule.to_json(schedule_path)
    log(f"mesh kill/recover: {n} workers, straggler at publish "
        f"{publish_every}, SIGKILL worker {victim} at step {kill_step}")
    gang1 = run_gang(workload, mesh_store, total, publish_every, n, "g1",
                     workdir, schedules={victim: schedule_path},
                     mesh_timeout_s=args.mesh_timeout)
    rcs = [rc for rc, _, _ in gang1]
    invariants["mh_kill_observed"] = rcs[victim] is not None and \
        rcs[victim] < 0
    invariants["mh_gang_aborted"] = any(rc == 76 for rc in rcs)
    # all-or-nothing: every generation the dead gang left behind is
    # complete and digest-clean, and nothing beyond the kill step surfaced
    store = CheckpointStore(mesh_store)
    latest = store.latest_valid()
    invariants["mh_no_partial_generation"] = (
        all_published_verify(mesh_store)
        and latest is not None and latest.step <= kill_step)
    log(f"gang g1 rcs={rcs}; latest valid step="
        f"{latest.step if latest else None}")

    t_recover = time.perf_counter()
    gang2 = run_gang(workload, mesh_store, total, publish_every, n, "g2",
                     workdir, mesh_timeout_s=args.mesh_timeout)
    recovery_wall = time.perf_counter() - t_recover
    digests2 = _gang_digests(gang2)
    shards2 = _gang_shards(gang2)
    invariants["mh_recovered"] = len(digests2) == n
    invariants["mh_workers_agree"] = (
        len(digests2) == n and all(d == digests2[0] for d in digests2))
    invariants["mh_bit_exact_resume"] = (
        bool(digests2) and digests2[0] == oracle.get("state_digests"))
    if not (invariants["mh_workers_agree"]
            and invariants["mh_bit_exact_resume"]):
        # name the owning worker per shard so a mismatch is attributable
        # without decoding shard file names by hand
        _log_shard_owners(shards2, "shard mismatch")
    coord_summary = gang2[0][1] or {}
    restores = [e for e in coord_summary.get("events", [])
                if e.get("event") == "restore"]
    restored_step = restores[0]["step"] if restores else 0
    mesh_publish_mean = (
        coord_summary.get("publish_s", 0.0)
        / coord_summary.get("publish_count", 1)
        if coord_summary.get("publish_count") else None)
    results["kill_recover"] = {
        "kill_step": kill_step,
        "victim": victim,
        "gang1_rcs": rcs,
        "worker_shards": shards2,
        "recovery_wall_s": recovery_wall,
        "restore_s": coord_summary.get("restore_s"),
        "time_to_first_step_s": coord_summary.get("time_to_first_step_s"),
        "restored_step": restored_step,
        "lost_steps": kill_step - restored_step,
        "mesh_publish_s_mean": mesh_publish_mean,
        "commit_overhead_vs_single": (
            mesh_publish_mean / results["oracle"]["checkpoint_write_s_mean"]
            if mesh_publish_mean
            and results["oracle"]["checkpoint_write_s_mean"] else None),
    }

    # -- phase 3: coordinator killed inside the commit window ------------
    commit_store = os.path.join(workdir, "store_mesh_commit")
    window_step = 2 * publish_every  # publish 1 must land, publish 2 dies
    schedule = FaultSchedule([
        FaultSpec(kind="kill_committed", step=window_step),
    ])
    commit_schedule_path = os.path.join(workdir, "faults_commit.json")
    schedule.to_json(commit_schedule_path)
    log(f"commit-window kill: coordinator dies after the commit marker "
        f"of the publish at step {window_step}, before the rename")
    gang3 = run_gang(workload, commit_store, total, publish_every, n,
                     "g3", workdir, schedules={0: commit_schedule_path},
                     mesh_timeout_s=args.mesh_timeout)
    rcs3 = [rc for rc, _, _ in gang3]
    store = CheckpointStore(commit_store)
    latest = store.latest_valid()
    leftovers = stage_dirs(commit_store)
    # the half-committed round: marker written, never renamed — it must be
    # invisible to latest_valid() (fall back to the previous generation)
    # and absent from the ledger
    attempted = (store.published()[-1] + 1) if store.published() else 0
    invariants["mh_commit_window_all_or_nothing"] = (
        rcs3[0] is not None and rcs3[0] < 0
        and any(rc == 76 for rc in rcs3[1:])
        and bool(leftovers)
        and any(os.path.exists(os.path.join(commit_store, d,
                                            "MANIFEST.json"))
                for d in leftovers)
        and latest is not None
        and latest.step == publish_every
        and store.entry(attempted) == {})
    log(f"gang g3 rcs={rcs3}; leftovers={leftovers}; latest="
        f"{latest.step if latest else None}")
    gang4 = run_gang(workload, commit_store, total, publish_every, n,
                     "g4", workdir, mesh_timeout_s=args.mesh_timeout)
    digests4 = _gang_digests(gang4)
    invariants["mh_commit_window_recovered"] = (
        len(digests4) == n
        and digests4[0] == oracle.get("state_digests")
        and not stage_dirs(commit_store))  # the corpse round was swept
    results["commit_window"] = {
        "window_step": window_step,
        "gang_rcs": rcs3,
        "stage_leftovers": leftovers,
        "fallback_step": latest.step if latest else None,
    }

    # -- phase 4: elastic resume — M=2-written store onto N∈{1,2} --------
    elastic: dict = {}
    for shape, label in ((1, "mesh_to_single"), (n, "mesh_to_mesh")):
        src = os.path.join(workdir, f"store_elastic_{shape}")
        shutil.copytree(mesh_store, src)
        log(f"elastic resume: {n}-written store extended to {total_ext} "
            f"steps on {shape} worker(s)")
        if shape == 1:
            rc, summary, wall = run_worker(
                workload, src, total_ext, publish_every,
                os.path.join(workdir, "summary_elastic1.json"))
            digests = [summary.get("state_digests")] if rc == 0 and summary \
                else []
        else:
            gang = run_gang(workload, src, total_ext, publish_every,
                            shape, f"g5-{shape}", workdir,
                            mesh_timeout_s=args.mesh_timeout)
            digests = _gang_digests(gang)
        ok = bool(digests) and all(
            d == oracle_ext.get("state_digests") for d in digests)
        invariants[f"mh_elastic_{label}"] = ok
        elastic[label] = {"workers": shape, "bit_exact": ok}
    results["elastic"] = elastic

    ok = all(invariants.values())
    return {
        "bench": "resilience_drill_multihost",
        "config": {
            "total_steps": total,
            "publish_every": publish_every,
            "world_size": n,
            "kill_step": kill_step,
            "seed": args.seed,
            "smoke": bool(args.smoke),
            "mesh_timeout_s": args.mesh_timeout,
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "results": results,
        "invariants": invariants,
        "telemetry": {
            "oracle": oracle.get("telemetry"),
            "recovered_coordinator": coord_summary.get("telemetry"),
        },
        "ok": ok,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="tier-1 shape: 12 steps, publish every 3")
    p.add_argument("--total-steps", type=int, default=None)
    p.add_argument("--publish-every", type=int, default=None)
    p.add_argument("--kill-step", type=int, default=None,
                   help="override the seeded kill step")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--relaunch-budget", type=int, default=5)
    p.add_argument("--multihost", type=int, default=0, metavar="N",
                   help="drill the MESH plane with N coordinated worker "
                        "processes sharing one store (0 = single-host "
                        "drill, the default)")
    p.add_argument("--mesh-timeout", type=float, default=15.0,
                   help="mesh in-round wait bound handed to the workers "
                        "(multihost mode); expiry = gang abort")
    p.add_argument("--workdir", default=None,
                   help="keep work files here instead of a temp dir")
    p.add_argument("--output", default=None, metavar="PATH",
                   help="write the drill JSON here")
    p.add_argument("--record", default=None, metavar="TAG",
                   help="also write BENCH_resilience_<TAG>.json at the "
                        "repo root")
    args = p.parse_args(argv)

    total = args.total_steps or (12 if args.smoke else 40)
    publish_every = args.publish_every or (3 if args.smoke else 5)
    workdir = args.workdir or tempfile.mkdtemp(prefix="resilience_drill_")
    cleanup = args.workdir is None
    os.makedirs(workdir, exist_ok=True)

    if args.multihost:
        if args.multihost < 2:
            p.error("--multihost needs N >= 2 (one coordinator plus at "
                    "least one peer writer)")
        payload = run_multihost_drill(args, workdir, total, publish_every)
        invariants = payload.get("invariants", {})
        ok = bool(payload.get("ok"))
        text = json.dumps(payload, indent=2)
        print(text)
        if args.output:
            os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                        exist_ok=True)
            with open(args.output, "w") as fh:
                fh.write(text + "\n")
        if args.record:
            with open(os.path.join(
                    _REPO, f"BENCH_resilience_mh_{args.record}.json"),
                    "w") as fh:
                fh.write(text + "\n")
        if cleanup and ok:
            shutil.rmtree(workdir, ignore_errors=True)
        elif not ok:
            log(f"INVARIANT BREACH — work files kept at {workdir}")
        for name, good in sorted(invariants.items()):
            log(f"invariant {name}: {'ok' if good else 'BREACH'}")
        return 0 if ok else 1

    from gan_deeplearning4j_tpu.resilience import (
        CheckpointStore,
        FaultSchedule,
        FaultSpec,
        corrupt_generation,
    )

    workload = make_workload(workdir, args.seed)

    # the seeded schedule: one SIGKILL somewhere in (0, total)
    if args.kill_step is not None:
        kill_step = args.kill_step
        schedule = FaultSchedule([FaultSpec(kind="kill", step=kill_step)])
    else:
        schedule = FaultSchedule.seeded(args.seed, total, kinds=("kill",))
        kill_step = schedule.specs[0].step
    schedule_path = os.path.join(workdir, "faults.json")
    schedule.to_json(schedule_path)

    results: dict = {}
    invariants: dict = {}

    # -- phase 1: oracle ------------------------------------------------
    log(f"oracle: {total} uninterrupted steps, publish every {publish_every}")
    rc, oracle, oracle_wall = run_worker(
        workload, os.path.join(workdir, "store_oracle"), total,
        publish_every, os.path.join(workdir, "summary_oracle.json"))
    if rc != 0 or oracle is None or oracle.get("status") != "completed":
        log(f"oracle run failed (rc={rc}) — cannot drill")
        return 2
    results["oracle"] = {
        "wall_s": oracle_wall,
        "train_s": oracle["train_s"],
        "publish_s": oracle["publish_s"],
        "publish_count": oracle["publish_count"],
        "steps": oracle["steps"],
        "checkpoint_overhead_frac": (
            oracle["publish_s"] / (oracle["train_s"] + oracle["publish_s"])
            if oracle["train_s"] + oracle["publish_s"] > 0 else 0.0
        ),
        "checkpoint_write_s_mean": (
            oracle["publish_s"] / oracle["publish_count"]
            if oracle["publish_count"] else 0.0
        ),
    }

    # -- phase 2: kill + relaunch ---------------------------------------
    fault_store = os.path.join(workdir, "store_fault")
    log(f"kill/recover: SIGKILL scheduled at step {kill_step}")
    relaunches = 0
    killed_observed = False
    recovery = None
    final = None
    while relaunches <= args.relaunch_budget:
        first = relaunches == 0
        rc, summary, wall = run_worker(
            workload, fault_store, total, publish_every,
            os.path.join(workdir, f"summary_fault_{relaunches}.json"),
            schedule_path=schedule_path if first else None)
        if rc == 0 and summary is not None:
            final = summary
            if not first and recovery is None:
                restores = [e for e in summary.get("events", [])
                            if e.get("event") == "restore"]
                restored_step = restores[0]["step"] if restores else 0
                recovery = {
                    "relaunch_wall_s": wall,
                    "restore_s": summary.get("restore_s"),
                    "time_to_first_step_s": summary.get(
                        "time_to_first_step_s"),
                    "restored_step": restored_step,
                    "lost_steps": kill_step - restored_step,
                }
            break
        if rc is not None and rc < 0:
            killed_observed = True
            log(f"worker died by signal (rc={rc}) — relaunching")
            relaunches += 1
            continue
        log(f"worker exited rc={rc} unexpectedly — drill failed")
        break
    results["kill_recover"] = {
        "kill_step": kill_step,
        "killed_observed": killed_observed,
        "relaunches": relaunches,
        "recovery": recovery,
        "completed": final is not None,
    }
    invariants["kill_observed"] = killed_observed
    invariants["recovered_within_budget"] = (
        final is not None and relaunches <= args.relaunch_budget)

    # -- phase 3: bit-exact resume --------------------------------------
    oracle_digests = oracle.get("state_digests")
    final_digests = (final or {}).get("state_digests")
    invariants["bit_exact_resume"] = (
        oracle_digests is not None and oracle_digests == final_digests)
    results["bit_exact"] = {
        "oracle_digests": oracle_digests,
        "recovered_digests": final_digests,
    }

    # -- phase 4: corruption fallback -----------------------------------
    corrupt_result: dict = {}
    if final is not None:
        store = CheckpointStore(fault_store)
        published = store.published()
        newest = published[-1]
        prior = published[-2] if len(published) > 1 else None
        member = corrupt_generation(store, newest, seed=args.seed)
        log(f"corrupted generation {newest} member {member!r}; "
            f"extending run to {total + publish_every} steps")
        rc, summary, wall = run_worker(
            workload, fault_store, total + publish_every, publish_every,
            os.path.join(workdir, "summary_corrupt.json"))
        restores = [e for e in (summary or {}).get("events", [])
                    if e.get("event") == "restore"]
        restored_gen = restores[0]["generation"] if restores else None
        entry = CheckpointStore(fault_store).entry(newest)
        corrupt_result = {
            "corrupted_generation": newest,
            "corrupted_member": member,
            "fallback_generation": restored_gen,
            "ledger_status": entry.get("status"),
            "quarantine_reason": entry.get("reason"),
            "completed": rc == 0 and (summary or {}).get("status")
            == "completed",
        }
        invariants["corrupt_never_selected"] = (
            entry.get("status") == "quarantined"
            and restored_gen is not None
            and restored_gen != newest
            and (prior is None or restored_gen == prior)
            and corrupt_result["completed"]
        )
    else:
        invariants["corrupt_never_selected"] = False
    results["corruption"] = corrupt_result

    # -- verdict ---------------------------------------------------------
    ok = all(invariants.values())
    payload = {
        "bench": "resilience_drill",
        "config": {
            "total_steps": total,
            "publish_every": publish_every,
            "kill_step": kill_step,
            "seed": args.seed,
            "smoke": bool(args.smoke),
            "relaunch_budget": args.relaunch_budget,
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "results": results,
        "invariants": invariants,
        # each worker's registry snapshot (same series a live scrape would
        # show) — bench artifacts and /metrics share one definition
        "telemetry": {
            "oracle": oracle.get("telemetry"),
            "recovered": (final or {}).get("telemetry"),
        },
        "ok": ok,
    }
    text = json.dumps(payload, indent=2)
    print(text)
    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                    exist_ok=True)
        with open(args.output, "w") as fh:
            fh.write(text + "\n")
    if args.record:
        with open(os.path.join(_REPO,
                               f"BENCH_resilience_{args.record}.json"),
                  "w") as fh:
            fh.write(text + "\n")
    if cleanup and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        log(f"INVARIANT BREACH — work files kept at {workdir}")
    for name, good in sorted(invariants.items()):
        log(f"invariant {name}: {'ok' if good else 'BREACH'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
