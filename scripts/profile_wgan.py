"""Decompose the WGAN-GP round on-chip (round-4 VERDICT item 4).

Round 3 measured config 5 (WGAN-GP CIFAR-10) at 3.2% MFU with 25.6%
cross-chunk jitter and left the round unexamined. This script answers the
open question — does the gradient-penalty double-grad recompute the critic
forward? — with XLA's own numbers, and captures the evidence the PROFILE.md
analysis needs:

1. cost analysis (FLOPs / bytes) of separately compiled subprograms at the
   bench shapes: critic forward, Wasserstein-term grad (no GP), GP-term
   grad, the full critic-loss grad, one fused critic round (n_critic scanned
   steps), and the generator step. The ratio
   ``full_grad / (w_grad + gp_grad)`` exposes cross-term sharing;
   ``gp_grad / forward`` against the analytic ~5x (fwd + bwd for the inner
   gradient, then a second backward through it) exposes rematerialization.
2. wall-clock of the scanned round window (the bench's scan-32 shape) with
   cross-chunk jitter, traced to ``--trace-dir`` for Perfetto.

Writes ``--out`` JSON. ``--cpu`` runs the plumbing on tiny shapes for CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--height", type=int, default=32)
    ap.add_argument("--width", type=int, default=32)
    ap.add_argument("--channels", type=int, default=3)
    ap.add_argument("--z-size", type=int, default=128)
    ap.add_argument("--n-critic", type=int, default=5)
    ap.add_argument("--batch", type=int, default=64, help="per-critic-step batch")
    ap.add_argument("--scan-window", type=int, default=32)
    ap.add_argument("--chunks", type=int, default=8)
    ap.add_argument("--trace-dir", default="artifacts/trace_wgan")
    ap.add_argument("--out", default="artifacts/profile_wgan.json")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from gan_deeplearning4j_tpu.harness.experiment import (
        cost_analysis_dict, shape_struct,
    )
    from gan_deeplearning4j_tpu.models.wgan_gp import WganGpConfig, WganGpTrainer
    from gan_deeplearning4j_tpu.ops import losses as loss_ops
    from gan_deeplearning4j_tpu.utils.profiling import device_trace

    cfg = WganGpConfig(
        height=args.height, width=args.width, channels=args.channels,
        z_size=args.z_size, n_critic=args.n_critic,
        **({"base_filters": 8, "dense_width": 32} if args.cpu else {}),
    )
    tr = WganGpTrainer(cfg)
    critic_state, gen_state = tr.init_states(seed=0)
    b = args.batch
    f = cfg.num_features
    rng = np.random.default_rng(0)
    real = jnp.asarray(rng.random((b, f), dtype=np.float32))
    key = jax.random.PRNGKey(1)
    k_z, k_gp = jax.random.split(key)

    def cost_of(fn, *fn_args):
        """(flops, bytes) of the compiled program for fn at these args."""
        c = cost_analysis_dict(
            jax.jit(fn).lower(*fn_args).compile().cost_analysis()) or {}
        return float(c.get("flops", 0.0)), float(c.get("bytes accessed", 0.0))

    def score(params, x):
        return tr.critic.output(params, x, train=False)[:, 0]

    def gen_fake():
        # every loss term below derives fakes the same way _critic_loss
        # does (generator forward in-graph, same key), so the term programs
        # are structurally comparable — using a precomputed host array for
        # some terms would bias full/sum upward by one generator forward
        z = jax.random.normal(k_z, (b, cfg.z_size), jnp.float32)
        return tr.generator.output(gen_state.params, z, train=False).reshape(b, -1)

    def w_loss(params):
        fk = gen_fake()
        return jnp.mean(score(params, fk)) - jnp.mean(score(params, real))

    def gp_loss(params):
        return loss_ops.gradient_penalty(
            lambda x: score(params, x), real, gen_fake(), k_gp
        )

    def full_loss(params):
        return tr._critic_loss(params, gen_state.params, real, key)

    costs = {}
    costs["critic_forward"] = cost_of(score, critic_state.params, real)
    costs["generator_forward"] = cost_of(gen_fake)
    costs["w_term_grad"] = cost_of(jax.grad(w_loss), critic_state.params)
    costs["gp_term_grad"] = cost_of(jax.grad(gp_loss), critic_state.params)
    costs["full_loss_grad"] = cost_of(jax.grad(full_loss), critic_state.params)
    costs["critic_round"] = tuple(
        float((cost_analysis_dict(tr._critic_round.lower(
            shape_struct(critic_state), shape_struct(gen_state.params),
            jax.ShapeDtypeStruct((cfg.n_critic, b, f), jnp.float32),
            jax.ShapeDtypeStruct((2,), jnp.uint32),
        ).compile().cost_analysis()) or {}).get(k, 0.0))
        for k in ("flops", "bytes accessed")
    )
    costs["gen_step"] = tuple(
        float((cost_analysis_dict(tr._gen_step.lower(
            shape_struct(gen_state), shape_struct(critic_state.params),
            jax.ShapeDtypeStruct((b, cfg.z_size), jnp.float32),
        ).compile().cost_analysis()) or {}).get(k, 0.0))
        for k in ("flops", "bytes accessed")
    )

    fwd_f = costs["critic_forward"][0]
    gen_f = costs["generator_forward"][0]
    w_f, gp_f, full_f = (costs[k][0] for k in
                         ("w_term_grad", "gp_term_grad", "full_loss_grad"))
    # each standalone term program embeds one generator forward; subtract it
    # so the sharing ratio compares CRITIC work only (the denominator would
    # otherwise double-count fake generation)
    w_c, gp_c, full_c = w_f - gen_f, gp_f - gen_f, full_f - gen_f
    analysis = {
        # ~5x fwd analytic floor for the GP term (inner fwd+bwd, then a
        # second backward through the inner gradient); materially above
        # that = XLA rematerializes the critic forward inside the double-grad
        "gp_grad_over_forward": round(gp_c / fwd_f, 2) if fwd_f else None,
        # ≈1.0 = no sharing between the Wasserstein and GP terms (each
        # compiled standalone); <1.0 = the fused program shares work
        "full_over_sum_of_terms": round(full_c / (w_c + gp_c), 3)
        if (w_c + gp_c) else None,
        # the scanned round vs n_critic standalone steps — scan overhead
        "round_over_ncritic_fullgrad": round(
            costs["critic_round"][0] / (cfg.n_critic * full_f), 3
        ) if full_f else None,
    }

    # -- wall clock: the bench's scan-window shape, traced ------------------
    k_iters = args.scan_window
    rounds = jnp.asarray(
        rng.random((k_iters, cfg.n_critic, b, f), dtype=np.float32)
    )
    cs, gs = critic_state, gen_state
    cs, gs, c_l, g_l = tr.train_rounds(cs, gs, rounds, jax.random.PRNGKey(2))
    np.asarray(c_l)  # compile + settle
    chunk_secs = []
    with device_trace(args.trace_dir):
        for _ in range(args.chunks):
            t0 = time.perf_counter()
            cs, gs, c_l, g_l = tr.train_rounds(cs, gs, rounds, jax.random.PRNGKey(3))
            np.asarray(c_l)  # value fetch = the only true fence on axon
            chunk_secs.append(time.perf_counter() - t0)
    per_round = np.asarray(chunk_secs) / k_iters
    wall = {
        "scan_window": k_iters,
        "sec_per_round": round(float(per_round.mean()), 6),
        "images_per_sec": round(cfg.n_critic * b / float(per_round.mean()), 2),
        "cross_chunk_jitter": round(
            float(per_round.std(ddof=1) / per_round.mean()), 4
        ),
        "chunk_seconds": [round(s, 4) for s in chunk_secs],
    }

    out = {
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "shapes": {"batch": b, "n_critic": cfg.n_critic, "features": f,
                   "z_size": cfg.z_size},
        "costs_flops_bytes": {k: list(v) for k, v in costs.items()},
        "analysis": analysis,
        "wall": wall,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(out, fh, indent=2)
    print(json.dumps({"analysis": analysis, "wall": wall}), flush=True)
    print(f"wrote {args.out}; trace under {args.trace_dir}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
