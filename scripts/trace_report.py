#!/usr/bin/env python
"""trace_report — fold a Chrome trace into occupancy + top-spans tables.

Consumes the Chrome trace-event JSON the telemetry span tracer exports
(``Tracer.dump``, ``serve_bench --trace``, the serving API's
``GET /debug/spans``, the resilience worker's ``--span-trace``), or any
file in the same format, and answers the two questions a wall of spans
hides:

1. **per-phase occupancy** — for each span name: total busy seconds, how
   much of the trace's wall span that is, call count, mean and max. The
   "phase" is the span name's dotted prefix family (``serve.batcher.*``,
   ``resilience.*``), so the report reads as a plane-by-plane budget.
2. **top spans** — the N longest individual spans with their timestamps
   and correlation args: the tail-latency forensics view.

Exit status is the campaign-gate contract: nonzero when the file is
missing, malformed, or contains no complete spans — an empty trace
artifact must FAIL the pipeline that was supposed to produce one, not
pass silently (``scripts/tpu_campaign.sh`` runs this over the serve-bench
smoke's trace).

Stdlib-only; works anywhere, including jax-free containers.

Usage::

    python scripts/trace_report.py artifacts/serve_trace.json
    python scripts/trace_report.py trace.json --top 20 --json report.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict


def load_events(path: str) -> list:
    """The trace's event list. Accepts both the object form
    (``{"traceEvents": [...]}``) and the bare-array form the Chrome
    format also allows. Raises ValueError on anything else."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict):
        events = doc.get("traceEvents")
    elif isinstance(doc, list):
        events = doc
    else:
        raise ValueError(f"not a Chrome trace: top-level {type(doc).__name__}")
    if not isinstance(events, list):
        raise ValueError("not a Chrome trace: no traceEvents array")
    return events


def validate(events: list) -> list:
    """Schema check — every event needs ph/ts/pid/tid and a name; returns
    the list of violations (empty = valid)."""
    problems = []
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        for field in ("name", "ph", "ts", "pid", "tid"):
            if field not in ev:
                problems.append(f"event {i} ({ev.get('name', '?')}): "
                                f"missing {field!r}")
        if ev.get("ph") == "X" and "dur" not in ev:
            problems.append(f"event {i} ({ev.get('name', '?')}): "
                            f"complete event without dur")
    return problems


def _pair_async(events: list) -> list:
    """Synthesize (name, ts, dur, args) rows for async b/e pairs keyed by
    (name, id) — the batcher's cross-thread flight spans."""
    open_by_key: dict = {}
    rows = []
    for ev in events:
        if ev.get("ph") == "b":
            open_by_key[(ev["name"], ev.get("id"))] = ev
        elif ev.get("ph") == "e":
            begin = open_by_key.pop((ev["name"], ev.get("id")), None)
            if begin is not None:
                rows.append({
                    "name": ev["name"],
                    "ts": begin["ts"],
                    "dur": max(0.0, ev["ts"] - begin["ts"]),
                    "args": {**(begin.get("args") or {}),
                             **(ev.get("args") or {})},
                })
    return rows


def fold(events: list, top_n: int = 10) -> dict:
    """The report payload: wall span, per-name occupancy, top spans."""
    spans = [
        {"name": ev["name"], "ts": ev["ts"], "dur": ev.get("dur", 0.0),
         "args": ev.get("args") or {}}
        for ev in events if ev.get("ph") == "X"
    ]
    spans += _pair_async(events)
    if not spans:
        raise ValueError("trace holds no complete spans (ph=X or b/e pairs)")
    all_ts = [ev["ts"] for ev in events if isinstance(ev.get("ts"), (int, float))]
    wall_us = max(
        max((s["ts"] + s["dur"]) for s in spans),
        max(all_ts),
    ) - min(all_ts)
    wall_us = max(wall_us, 1e-9)

    by_name: dict = defaultdict(lambda: {"busy_us": 0.0, "count": 0,
                                         "max_us": 0.0})
    for s in spans:
        agg = by_name[s["name"]]
        agg["busy_us"] += s["dur"]
        agg["count"] += 1
        agg["max_us"] = max(agg["max_us"], s["dur"])
    phases = {}
    for name, agg in by_name.items():
        phases[name] = {
            "busy_s": agg["busy_us"] / 1e6,
            "count": agg["count"],
            "mean_ms": agg["busy_us"] / agg["count"] / 1e3,
            "max_ms": agg["max_us"] / 1e3,
            "occupancy": agg["busy_us"] / wall_us,
        }

    top = sorted(spans, key=lambda s: -s["dur"])[:top_n]
    return {
        "wall_s": wall_us / 1e6,
        "events": len(events),
        "spans": len(spans),
        "phases": dict(sorted(phases.items(),
                              key=lambda kv: -kv[1]["busy_s"])),
        "top_spans": [
            {"name": s["name"], "start_us": s["ts"], "dur_ms": s["dur"] / 1e3,
             "args": s["args"]}
            for s in top
        ],
    }


def render(report: dict) -> str:
    out = [
        f"wall {report['wall_s']:.3f}s — {report['events']} events, "
        f"{report['spans']} spans",
        "",
        f"{'span name':>32s}  {'busy s':>9s}  {'occ':>6s}  {'n':>6s}  "
        f"{'mean ms':>9s}  {'max ms':>9s}",
    ]
    for name, p in report["phases"].items():
        out.append(
            f"{name:>32s}  {p['busy_s']:9.3f}  {p['occupancy']:6.1%}  "
            f"{p['count']:6d}  {p['mean_ms']:9.3f}  {p['max_ms']:9.3f}"
        )
    out.append("")
    out.append("top spans:")
    for s in report["top_spans"]:
        args = {k: v for k, v in s["args"].items() if k != "riders"}
        out.append(f"  {s['dur_ms']:9.3f}ms  {s['name']:<28s}  {args}")
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("trace", help="Chrome trace-event JSON file")
    p.add_argument("--top", type=int, default=10,
                   help="longest individual spans to list")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="also write the report as JSON")
    args = p.parse_args(argv)

    try:
        events = load_events(args.trace)
        problems = validate(events)
        if problems:
            for line in problems[:20]:
                sys.stderr.write(f"trace_report: {line}\n")
            sys.stderr.write(
                f"trace_report: {args.trace}: {len(problems)} schema "
                f"violation(s)\n")
            return 1
        report = fold(events, top_n=args.top)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        sys.stderr.write(f"trace_report: {args.trace}: {exc}\n")
        return 1
    print(render(report))
    if args.json:
        os.makedirs(os.path.dirname(os.path.abspath(args.json)),
                    exist_ok=True)
        with open(args.json, "w") as fh:
            json.dump(report, fh, indent=2)
            fh.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
