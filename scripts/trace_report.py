#!/usr/bin/env python
"""trace_report — fold Chrome trace(s) into occupancy + attribution tables.

Consumes the Chrome trace-event JSON the telemetry span tracer exports
(``Tracer.dump``, ``serve_bench --trace``, the serving API's
``GET /debug/spans``, the fleet router's merged ``GET /debug/trace``, the
resilience worker's ``--span-trace``), or any file in the same format,
and answers the questions a wall of spans hides:

1. **per-phase occupancy** — for each span name: total busy seconds, how
   much of the trace's wall span that is, call count, mean and max. The
   "phase" is the span name's dotted prefix family (``serve.batcher.*``,
   ``resilience.*``), so the report reads as a plane-by-plane budget.
2. **top spans** — the N longest individual spans with their timestamps
   and correlation args: the tail-latency forensics view.
3. **per-worker occupancy skew** — when the (merged) trace holds more
   than one pid: busy seconds and occupancy per pid, plus a per-span-name
   skew table (max/min busy across pids) naming where the mesh is
   unbalanced. Multiple trace files merge by concatenation — every
   process's tracer pins timestamps to the wall epoch and stamps its own
   pid, so N worker traces are ONE timeline (docs/OBSERVABILITY.md).
4. **barrier-wait attribution** — ``resilience.mesh_stage`` /
   ``resilience.mesh_commit_wait`` spans fold per (generation, worker)
   into a table that NAMES the straggler of each coordinated publish: the
   worker with the longest stage time is the one everyone else's
   commit-wait paid for.
5. **alert overlay** (``--alerts <alerts.json>``) — the fleet alert
   plane's incident ring (the ``GET /alerts`` payload, or a bare list of
   transition records) rendered as instant events on the same wall-epoch
   timeline, so an incident reads as ONE story: the spans that slowed
   down, the alert going pending → firing over them, and the resolve
   after the recovery (docs/OBSERVABILITY.md "Alerting").

Exit status is the campaign-gate contract: nonzero when a file is
missing, malformed, or the merged trace contains no complete spans — an
empty trace artifact must FAIL the pipeline that was supposed to produce
one, not pass silently (``scripts/tpu_campaign.sh`` runs this over the
serve-bench smoke's trace and the fleet drill's merged trace).

Stdlib-only; works anywhere, including jax-free containers.

Usage::

    python scripts/trace_report.py artifacts/serve_trace.json
    python scripts/trace_report.py w0_trace.json w1_trace.json \\
        --merge-out artifacts/mesh_trace.json --json report.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

#: span names whose (gen, worker) args drive the barrier table
STAGE_SPAN = "resilience.mesh_stage"
WAIT_SPAN = "resilience.mesh_commit_wait"


def alert_events(path: str) -> list:
    """Alert lifecycle transitions as Chrome instant events. Accepts the
    ``GET /alerts`` payload (reads its ``incidents`` ring) or a bare list
    of transition records; each record's wall-clock ``t`` lands on the
    same epoch the span tracers pin their timestamps to, so the overlay
    and the spans share one timeline. Raises ValueError on anything that
    is not alert-shaped — a wrong file must fail the report, not overlay
    nothing."""
    with open(path) as fh:
        doc = json.load(fh)
    records = doc.get("incidents") if isinstance(doc, dict) else doc
    if not isinstance(records, list):
        raise ValueError(f"{path}: not an /alerts payload "
                         f"(no incidents list)")
    events = []
    for record in records:
        if not (isinstance(record, dict) and "alert" in record
                and isinstance(record.get("t"), (int, float))):
            raise ValueError(f"{path}: malformed incident record "
                             f"{record!r}")
        to = record.get("to", "?")
        events.append({
            "name": f"alert:{record['alert']}:{to}",
            "ph": "i",
            "s": "g",  # global scope: the marker spans the whole track
            "ts": record["t"] * 1e6,
            "pid": "alerts",
            "tid": record.get("severity", "alert"),
            "args": {k: v for k, v in record.items() if k != "t"},
        })
    return events


def load_events(path: str) -> list:
    """The trace's event list. Accepts both the object form
    (``{"traceEvents": [...]}``) and the bare-array form the Chrome
    format also allows. Raises ValueError on anything else."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict):
        events = doc.get("traceEvents")
    elif isinstance(doc, list):
        events = doc
    else:
        raise ValueError(f"not a Chrome trace: top-level {type(doc).__name__}")
    if not isinstance(events, list):
        raise ValueError("not a Chrome trace: no traceEvents array")
    return events


def validate(events: list) -> list:
    """Schema check — every event needs ph/ts/pid/tid and a name; returns
    the list of violations (empty = valid)."""
    problems = []
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        for field in ("name", "ph", "ts", "pid", "tid"):
            if field not in ev:
                problems.append(f"event {i} ({ev.get('name', '?')}): "
                                f"missing {field!r}")
        if ev.get("ph") == "X" and "dur" not in ev:
            problems.append(f"event {i} ({ev.get('name', '?')}): "
                            f"complete event without dur")
    return problems


def _pair_async(events: list) -> list:
    """Synthesize (name, ts, dur, args, pid) rows for async b/e pairs
    keyed by (pid, name, id) — the batcher's cross-thread flight spans.
    The pid joins the key so two processes' flights never cross-pair in
    a merged trace."""
    open_by_key: dict = {}
    rows = []
    for ev in events:
        if ev.get("ph") == "b":
            open_by_key[(ev.get("pid"), ev["name"], ev.get("id"))] = ev
        elif ev.get("ph") == "e":
            begin = open_by_key.pop(
                (ev.get("pid"), ev["name"], ev.get("id")), None)
            if begin is not None:
                rows.append({
                    "name": ev["name"],
                    "ts": begin["ts"],
                    "dur": max(0.0, ev["ts"] - begin["ts"]),
                    "pid": begin.get("pid"),
                    "args": {**(begin.get("args") or {}),
                             **(ev.get("args") or {})},
                })
    return rows


def _worker_tables(spans: list, wall_us: float) -> dict:
    """Per-pid occupancy + per-span-name skew, for merged multi-process
    traces. Skew = max/min busy seconds across the pids that ran the
    span name — 1.0 is a perfectly balanced mesh; the table is sorted
    worst-first so the unbalanced phase tops the report."""
    by_pid: dict = defaultdict(lambda: {"busy_us": 0.0, "spans": 0})
    by_name_pid: dict = defaultdict(lambda: defaultdict(float))
    for s in spans:
        pid = s.get("pid")
        by_pid[pid]["busy_us"] += s["dur"]
        by_pid[pid]["spans"] += 1
        by_name_pid[s["name"]][pid] += s["dur"]
    workers = {
        str(pid): {
            "busy_s": agg["busy_us"] / 1e6,
            "spans": agg["spans"],
            "occupancy": agg["busy_us"] / wall_us,
        }
        for pid, agg in by_pid.items()
    }
    skew = {}
    for name, pids in by_name_pid.items():
        if len(pids) < 2:
            continue  # a single-pid span name has no skew to attribute
        values = sorted(pids.values())
        lo, hi = values[0], values[-1]
        skew[name] = {
            "pids": {str(p): v / 1e6 for p, v in sorted(pids.items())},
            "min_s": lo / 1e6,
            "max_s": hi / 1e6,
            "skew": (hi / lo) if lo > 0 else float("inf"),
        }
    return {
        "workers": dict(sorted(workers.items())),
        "skew": dict(sorted(skew.items(), key=lambda kv: -kv[1]["skew"])),
    }


def _barrier_table(spans: list) -> list:
    """Per coordinated publish (keyed by the ``gen`` span arg): each
    worker's stage vs commit-wait seconds, and THE NAMED STRAGGLER — the
    worker whose shard write took longest, i.e. what every other
    worker's barrier wait was spent on."""
    rounds: dict = defaultdict(lambda: defaultdict(
        lambda: {"stage_s": 0.0, "wait_s": 0.0, "pid": None}))
    for s in spans:
        if s["name"] not in (STAGE_SPAN, WAIT_SPAN):
            continue
        args = s.get("args") or {}
        gen, worker = args.get("gen"), args.get("worker")
        if gen is None or worker is None:
            continue
        slot = rounds[gen][worker]
        slot["pid"] = s.get("pid")
        key = "stage_s" if s["name"] == STAGE_SPAN else "wait_s"
        slot[key] += s["dur"] / 1e6
    table = []
    for gen in sorted(rounds):
        workers = rounds[gen]
        straggler = max(workers, key=lambda w: workers[w]["stage_s"])
        peers = [w for w in workers if w != straggler]
        table.append({
            "generation": gen,
            "workers": {
                str(w): {"pid": v["pid"],
                         "stage_s": round(v["stage_s"], 6),
                         "commit_wait_s": round(v["wait_s"], 6)}
                for w, v in sorted(workers.items())
            },
            "straggler": straggler,
            "straggler_stage_s": round(workers[straggler]["stage_s"], 6),
            "peer_max_wait_s": round(
                max((workers[w]["wait_s"] for w in peers), default=0.0), 6),
        })
    return table


def fold(events: list, top_n: int = 10) -> dict:
    """The report payload: wall span, per-name occupancy, top spans —
    plus per-worker and barrier attribution when the trace spans more
    than one process."""
    spans = [
        {"name": ev["name"], "ts": ev["ts"], "dur": ev.get("dur", 0.0),
         "pid": ev.get("pid"), "args": ev.get("args") or {}}
        for ev in events if ev.get("ph") == "X"
    ]
    spans += _pair_async(events)
    if not spans:
        raise ValueError("trace holds no complete spans (ph=X or b/e pairs)")
    all_ts = [ev["ts"] for ev in events if isinstance(ev.get("ts"), (int, float))]
    wall_us = max(
        max((s["ts"] + s["dur"]) for s in spans),
        max(all_ts),
    ) - min(all_ts)
    wall_us = max(wall_us, 1e-9)

    by_name: dict = defaultdict(lambda: {"busy_us": 0.0, "count": 0,
                                         "max_us": 0.0})
    for s in spans:
        agg = by_name[s["name"]]
        agg["busy_us"] += s["dur"]
        agg["count"] += 1
        agg["max_us"] = max(agg["max_us"], s["dur"])
    phases = {}
    for name, agg in by_name.items():
        phases[name] = {
            "busy_s": agg["busy_us"] / 1e6,
            "count": agg["count"],
            "mean_ms": agg["busy_us"] / agg["count"] / 1e3,
            "max_ms": agg["max_us"] / 1e3,
            "occupancy": agg["busy_us"] / wall_us,
        }

    top = sorted(spans, key=lambda s: -s["dur"])[:top_n]
    report = {
        "wall_s": wall_us / 1e6,
        "events": len(events),
        "spans": len(spans),
        "pids": sorted({str(s["pid"]) for s in spans}),
        "phases": dict(sorted(phases.items(),
                              key=lambda kv: -kv[1]["busy_s"])),
        "top_spans": [
            {"name": s["name"], "start_us": s["ts"], "dur_ms": s["dur"] / 1e3,
             "pid": s["pid"], "args": s["args"]}
            for s in top
        ],
    }
    if len(report["pids"]) > 1:
        report.update(_worker_tables(spans, wall_us))
    barriers = _barrier_table(spans)
    if barriers:
        report["barriers"] = barriers
    return report


def render(report: dict) -> str:
    out = [
        f"wall {report['wall_s']:.3f}s — {report['events']} events, "
        f"{report['spans']} spans, {len(report['pids'])} process(es)",
        "",
        f"{'span name':>32s}  {'busy s':>9s}  {'occ':>6s}  {'n':>6s}  "
        f"{'mean ms':>9s}  {'max ms':>9s}",
    ]
    for name, p in report["phases"].items():
        out.append(
            f"{name:>32s}  {p['busy_s']:9.3f}  {p['occupancy']:6.1%}  "
            f"{p['count']:6d}  {p['mean_ms']:9.3f}  {p['max_ms']:9.3f}"
        )
    if "workers" in report:
        out.append("")
        out.append("per-worker occupancy:")
        out.append(f"  {'pid':>10s}  {'busy s':>9s}  {'occ':>6s}  "
                   f"{'spans':>6s}")
        for pid, w in report["workers"].items():
            out.append(f"  {pid:>10s}  {w['busy_s']:9.3f}  "
                       f"{w['occupancy']:6.1%}  {w['spans']:6d}")
        if report.get("skew"):
            out.append("")
            out.append("occupancy skew (max/min busy across pids, "
                       "worst first):")
            for name, s in list(report["skew"].items())[:10]:
                skew = ("inf" if s["skew"] == float("inf")
                        else f"{s['skew']:.2f}x")
                out.append(f"  {name:<32s}  {skew:>8s}  "
                           f"(min {s['min_s']:.3f}s, max {s['max_s']:.3f}s)")
    for b in report.get("barriers", []):
        out.append("")
        out.append(
            f"mesh publish gen {b['generation']}: straggler worker "
            f"{b['straggler']} (stage {b['straggler_stage_s']:.3f}s; "
            f"peers waited up to {b['peer_max_wait_s']:.3f}s)")
        for w, v in b["workers"].items():
            out.append(f"  worker {w} (pid {v['pid']}): stage "
                       f"{v['stage_s']:.3f}s, commit wait "
                       f"{v['commit_wait_s']:.3f}s")
    out.append("")
    out.append("top spans:")
    for s in report["top_spans"]:
        args = {k: v for k, v in s["args"].items() if k != "riders"}
        out.append(f"  {s['dur_ms']:9.3f}ms  {s['name']:<28s}  {args}")
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("traces", nargs="+",
                   help="Chrome trace-event JSON file(s); several merge "
                        "into one timeline (wall-epoch timestamps)")
    p.add_argument("--top", type=int, default=10,
                   help="longest individual spans to list")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="also write the report as JSON")
    p.add_argument("--merge-out", default=None, metavar="PATH",
                   help="write the merged Chrome trace (Perfetto-loadable)")
    p.add_argument("--alerts", default=None, metavar="PATH",
                   help="overlay the alert plane's firing/resolved "
                        "transitions (a GET /alerts payload, or a bare "
                        "incident list) as instant events on the merged "
                        "timeline")
    args = p.parse_args(argv)

    events: list = []
    try:
        for path in args.traces:
            file_events = load_events(path)
            problems = validate(file_events)
            if problems:
                for line in problems[:20]:
                    sys.stderr.write(f"trace_report: {line}\n")
                sys.stderr.write(
                    f"trace_report: {path}: {len(problems)} schema "
                    f"violation(s)\n")
                return 1
            events.extend(file_events)
        overlay = alert_events(args.alerts) if args.alerts else []
        events.extend(overlay)
        report = fold(events, top_n=args.top)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        sys.stderr.write(f"trace_report: {exc}\n")
        return 1
    if overlay:
        by_state: dict = {}
        for ev in overlay:
            to = (ev.get("args") or {}).get("to", "?")
            by_state[to] = by_state.get(to, 0) + 1
        report["alerts"] = {"transitions": len(overlay),
                            "by_state": dict(sorted(by_state.items()))}
    print(render(report))
    if overlay:
        print("\nalert overlay:")
        for ev in overlay:
            arg = ev.get("args") or {}
            labels = arg.get("labels") or {}
            label_text = ",".join(f"{k}={v}" for k, v in
                                  sorted(labels.items()))
            print(f"  {ev['ts'] / 1e6:.3f}s  {arg.get('alert', '?'):<28s}"
                  f"  {arg.get('from', '?')} -> {arg.get('to', '?')}"
                  f"  {{{label_text}}}")
    if args.merge_out:
        os.makedirs(os.path.dirname(os.path.abspath(args.merge_out)),
                    exist_ok=True)
        with open(args.merge_out, "w") as fh:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms",
                       "metadata": {"sources": args.traces}}, fh)
            fh.write("\n")
    if args.json:
        os.makedirs(os.path.dirname(os.path.abspath(args.json)),
                    exist_ok=True)
        with open(args.json, "w") as fh:
            json.dump(report, fh, indent=2)
            fh.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
