"""Tuning sweep for the DCGAN-MNIST quality run (round-3 VERDICT weak #7,
round-5 VERDICT item 4: the discriminator overpowers the generator late in
training — final g_loss 11.9 vs d_loss 0.23).

Round 5 extends the LR grid with the two untried G/D-balance LEVERS the
round-4 verdict named: per-batch label-noise resampling
(``resample_label_noise=True``) and a dis-LR staircase decay
(``dis_lr_decay_every``/``dis_lr_decay_rate``), each as its own arm at the
reference LR point, plus a combined arm. ``--resume-from`` merges the
completed grid arms of a prior (partial) sweep so chip time goes to the
arms that have never run — the round-4 outage killed arm 7 of 9.

Each arm trains for ``--iterations`` with the in-training quick-FID tracker
(frozen features, paired z across arms AND boundaries) and records: the
best quick FID + where it happened, the FINAL quick FID (the round-5 target
is final-model quality, ≤0.4), final losses, and transfer accuracy. Writes
``artifacts/tuning_sweep.json``; the quality run's headline configuration
is chosen from this artifact by the campaign's selector — a recorded
experiment, not a silent retune.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REF_DIS_LR, REF_GEN_LR = 0.002, 0.004

# lever arms (round-5 VERDICT item 4), all at the reference LR point; decay
# cadences chosen so the 1200-iteration screen ends at a meaningfully
# decayed scale (0.7^6 ≈ 0.12, 0.5^3 = 0.125) without freezing D early
LEVER_ARMS = [
    {"label": "resample_noise", "resample_label_noise": True},
    {"label": "dis_decay_0.7@200", "dis_lr_decay_every": 200,
     "dis_lr_decay_rate": 0.7},
    {"label": "dis_decay_0.5@400", "dis_lr_decay_every": 400,
     "dis_lr_decay_rate": 0.5},
    {"label": "resample+dis_decay_0.7@200", "resample_label_noise": True,
     "dis_lr_decay_every": 200, "dis_lr_decay_rate": 0.7},
]


def _arm_key(a: dict) -> tuple:
    return (
        a.get("dis_lr", REF_DIS_LR), a.get("gen_lr", REF_GEN_LR),
        bool(a.get("resample_label_noise", False)),
        int(a.get("dis_lr_decay_every", 0)),
        float(a.get("dis_lr_decay_rate", 1.0)),
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--iterations", type=int, default=1200)
    ap.add_argument("--batch", type=int, default=200)
    ap.add_argument("--num-train", type=int, default=10000)
    ap.add_argument("--num-test", type=int, default=1000)
    ap.add_argument("--eval-every", type=int, default=100)
    ap.add_argument("--select-samples", type=int, default=2048)
    ap.add_argument("--dis-lrs", default="0.001,0.002,0.004")
    ap.add_argument("--gen-lrs", default="0.002,0.004,0.008")
    ap.add_argument("--no-levers", action="store_true",
                    help="grid arms only (round-4 behavior)")
    ap.add_argument("--resume-from", default="artifacts/tuning_sweep_partial.json",
                    help="merge completed arms from a prior partial sweep "
                         "(matched on the full arm signature) instead of "
                         "re-burning chip time on them; '' disables")
    ap.add_argument("--out", default="artifacts/tuning_sweep.json")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--seed", type=int, default=666)
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from gan_deeplearning4j_tpu.data import DeviceResidentIterator
    from gan_deeplearning4j_tpu.data.dataset import one_hot_np
    from gan_deeplearning4j_tpu.data.mnist import load_mnist
    from gan_deeplearning4j_tpu.eval.accuracy import accuracy_score
    from gan_deeplearning4j_tpu.eval.fid import (
        FeatureStats,
        frozen_feature_fn,
        quick_fid_scorer,
    )
    from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment

    t_start = time.time()
    tag, ((xtr, ytr), (xte, yte)) = load_mnist(
        num_train=args.num_train, num_test=args.num_test, seed=args.seed
    )
    print(f"data source: {tag}  train={xtr.shape}", flush=True)

    # one frozen feature space + one paired z seed shared by EVERY arm, so
    # quick-FID differences between arms are model differences, not
    # evaluation noise
    frozen_fn = frozen_feature_fn(28, 28, 1, seed=666, batch_size=2500)
    real_stats = FeatureStats.from_features(frozen_fn(xtr))

    specs = [
        {"label": f"lr_{dis_lr}x{gen_lr}", "dis_lr": dis_lr, "gen_lr": gen_lr}
        for dis_lr, gen_lr in itertools.product(
            [float(x) for x in args.dis_lrs.split(",")],
            [float(x) for x in args.gen_lrs.split(",")],
        )
    ]
    if not args.no_levers:
        specs += [dict(a) for a in LEVER_ARMS]

    # resume: completed arms from a prior partial sweep stand in verbatim —
    # same seed, same frozen feature space, same paired z, so the numbers
    # are directly comparable and the chip re-runs only what never ran
    resumed = {}
    if args.resume_from and os.path.exists(args.resume_from):
        try:
            with open(args.resume_from) as fh:
                for a in json.load(fh).get("arms", []):
                    resumed[_arm_key(a)] = a
        except (OSError, ValueError) as exc:
            print(f"resume-from unreadable ({exc}); running all arms", flush=True)
    arms = []
    for spec in specs:
        if _arm_key(spec) in resumed:
            arm = dict(resumed[_arm_key(spec)])
            arm.setdefault("label", spec["label"])
            arm["resumed"] = True
            arms.append(arm)
            print(json.dumps({"resumed": arm["label"]}), flush=True)
            continue
        cfg = ExperimentConfig(
            batch_size_train=args.batch, batch_size_pred=500,
            num_iterations=args.iterations,
            print_every=args.eval_every, save_every=10 ** 9,
            save_models=False, output_dir="output/tune",
            dis_learning_rate=spec.get("dis_lr", REF_DIS_LR),
            gen_learning_rate=spec.get("gen_lr", REF_GEN_LR),
            resample_label_noise=spec.get("resample_label_noise", False),
            dis_lr_decay_every=spec.get("dis_lr_decay_every", 0),
            dis_lr_decay_rate=spec.get("dis_lr_decay_rate", 1.0),
            seed=args.seed,
        )
        exp = GanExperiment(cfg)
        track = quick_fid_scorer(
            exp, frozen_fn, real_stats,
            num_samples=args.select_samples, seed=args.seed + 13,
        )
        curve = track.curve

        train_it = DeviceResidentIterator(
            xtr, one_hot_np(ytr, 10), batch_size=args.batch
        )
        test_it = DeviceResidentIterator(xte, one_hot_np(yte, 10), batch_size=500)
        t0 = time.time()
        result = exp.run(train_it, test_it, eval_callback=track)
        track(exp, result["iterations"])  # scorer dedups a cadence-landed final
        preds_csv = exp.export_predictions(test_it, result["iterations"])
        acc = accuracy_score(np.loadtxt(preds_csv, delimiter=",", ndmin=2), yte)
        best_i, best_fid = min(curve, key=lambda p: p[1])
        arm = {
            "label": spec["label"],
            "dis_lr": cfg.dis_learning_rate, "gen_lr": cfg.gen_learning_rate,
            "resample_label_noise": cfg.resample_label_noise,
            "dis_lr_decay_every": cfg.dis_lr_decay_every,
            "dis_lr_decay_rate": cfg.dis_lr_decay_rate,
            "best_quick_fid": best_fid, "best_at_iteration": best_i,
            "final_quick_fid": curve[-1][1],
            "accuracy": round(float(acc), 4),
            "d_loss_final": result["history"][-1]["d_loss"],
            "g_loss_final": result["history"][-1]["g_loss"],
            "quick_fid_curve": curve,
            "wall_seconds": round(time.time() - t0, 1),
        }
        arms.append(arm)
        print(json.dumps({k: v for k, v in arm.items() if k != "quick_fid_curve"}),
              flush=True)

    ranked = sorted(arms, key=lambda a: a["best_quick_fid"])
    by_final = sorted(arms, key=lambda a: a["final_quick_fid"])
    out = {
        "data_source": tag,
        "iterations": args.iterations,
        "batch_size": args.batch,
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "reference_point": {"dis_lr": REF_DIS_LR, "gen_lr": REF_GEN_LR},
        "arms": arms,
        "ranking_by_best_quick_fid": [
            [a.get("label"), a["best_quick_fid"]] for a in ranked
        ],
        "ranking_by_final_quick_fid": [
            [a.get("label"), a["final_quick_fid"]] for a in by_final
        ],
        "wall_seconds": round(time.time() - t_start, 1),
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(out, fh, indent=2)
    print(f"wrote {args.out}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
