#!/usr/bin/env python
"""Paired A/B bench for cross-replica weight-update sharding.

Runs the SAME fused training workload twice per mesh size — replicated
updates (``--update-sharding off``) vs sharded updates (``on``) — and
records, per mesh size N:

- step time (min-of-rounds, the noise-robust methodology serve_bench
  --compare established on the shared-core container);
- per-device RESIDENT trained-state bytes (updater state measured from
  the live arrays' addressable shards), with the invariant that sharded
  mode holds them to ~1/N of the replicated baseline;
- the compiled fused program's collective mix (all-reduce /
  reduce-scatter / all-gather instruction counts from the optimized
  HLO) — the observable trace of the paper's transformation;
- parity: the sharded run must match the replicated baseline within the
  documented tolerance after one fused iteration (see
  docs/RESILIENCE.md, update-sharding section: ulp-level reassociation
  in the fused program is amplified chaotically by GAN dynamics, so
  cross-mode parity is tolerance-based at one iteration while the
  single-model trainer step is digest-exact — asserted here too).

Exit status is nonzero on any invariant breach. ``--smoke`` is the
campaign gate shape (parity + residency only, small workload);
``--record TAG`` writes ``BENCH_update_sharding_<TAG>.json`` at the repo
root. CPU-container caveat: with mesh shards sharing two host cores,
every added collective (the param all-gather) is a device-thread sync
barrier, so sharded mode reads 1.1-1.4x step time HERE while on chip
the gathered bytes ride the ICI the replaced all-reduce already paid
for — the step-time gate therefore applies on non-CPU platforms only
(``--gate-step-time-on-cpu`` forces it; the ratio is always recorded),
and the campaign's chip arm is the record that matters (ROADMAP:
TPU-measured truth).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--update-sharding", choices=["off", "on", "both"],
                   default="both",
                   help="which arm(s) to run; 'both' is the paired A/B")
    p.add_argument("--mesh", default="2,4",
                   help="comma-separated mesh sizes to bench (forced host "
                        "devices on CPU; device subsets on a real mesh)")
    p.add_argument("--iterations", type=int, default=24,
                   help="timed iterations per round")
    p.add_argument("--rounds", type=int, default=3,
                   help="timed rounds per arm (min is reported)")
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--step-time-slack", type=float, default=1.05,
                   help="sharded/replicated min-round ratio gate")
    p.add_argument("--no-step-gate", action="store_true",
                   help="record step time but do not gate on it")
    p.add_argument("--gate-step-time-on-cpu", action="store_true",
                   help="apply the step-time gate on the CPU/host "
                        "platform too. Off by default: on forced host "
                        "devices every collective is a barrier across "
                        "device THREADS sharing the same cores, so the "
                        "param all-gather reads as +15-40%% step time — "
                        "a sync-count artifact of the substrate, not the "
                        "algorithm (on chip the gathered bytes ride the "
                        "ICI the replaced all-reduce already paid for). "
                        "The ratio is always recorded either way.")
    p.add_argument("--smoke", action="store_true",
                   help="campaign shape: tiny workload, parity + residency "
                        "invariants only")
    p.add_argument("--record", default=None, metavar="TAG",
                   help="write BENCH_update_sharding_<TAG>.json at the "
                        "repo root")
    p.add_argument("--output", default=None,
                   help="also write the summary JSON here")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    meshes = [int(x) for x in str(args.mesh).split(",") if x.strip()]
    if args.smoke:
        args.iterations = min(args.iterations, 6)
        args.rounds = min(args.rounds, 2)
        args.batch = min(args.batch, 32)
        args.no_step_gate = True

    # forced host devices must land before jax initializes (inert on TPU)
    need = max(meshes)
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={need}"
        ).strip()

    import jax
    import numpy as np

    from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment
    from gan_deeplearning4j_tpu.resilience.supervisor import TrainingSupervisor
    from gan_deeplearning4j_tpu.runtime import TpuEnvironment

    import tempfile
    workdir = tempfile.mkdtemp(prefix="update_sharding_bench_")

    rng = np.random.default_rng(666)
    feats = rng.random((args.batch, 784), dtype=np.float32)
    labels = np.zeros((args.batch, 10), np.float32)
    labels[np.arange(args.batch), rng.integers(0, 10, args.batch)] = 1.0

    def build(mesh_size: int, sharded: bool) -> GanExperiment:
        cfg = ExperimentConfig(
            batch_size_train=args.batch, batch_size_pred=args.batch,
            num_iterations=args.iterations, latent_grid=4,
            data_dir=os.path.join(workdir, "data"),
            output_dir=os.path.join(workdir, f"out-{mesh_size}-{sharded}"),
            save_models=False, distributed="pmean",
            update_sharding=sharded,
        )
        mesh = TpuEnvironment(device_limit=mesh_size).make_mesh()
        return GanExperiment(cfg, mesh=mesh)

    def updater_bytes(exp) -> dict:
        """{device id: resident trained-state (updater) bytes} from the
        live arrays' addressable shards — replicated leaves count their
        full copy on every device, sharded rows only their slice."""
        per_dev: dict = {}
        states = [exp.dis_state, exp.gan_state]
        if exp.cv_state is not None:
            states.append(exp.cv_state)
        for st in states:
            for leaf in jax.tree_util.tree_leaves(st.opt_state):
                for shard in leaf.addressable_shards:
                    per_dev[shard.device.id] = (
                        per_dev.get(shard.device.id, 0)
                        + shard.data.nbytes)
        return per_dev

    def collective_counts(exp) -> dict:
        """all-reduce / reduce-scatter / all-gather instruction counts in
        the optimized fused program (best effort — absent cost models or
        text export just yield {})."""
        try:
            import jax.numpy as jnp

            b = args.batch
            from gan_deeplearning4j_tpu.harness.experiment import shape_struct
            f32 = jnp.float32
            text = exp._fused.lower(
                shape_struct(exp.dis_state), shape_struct(exp.gan_state),
                shape_struct(exp.cv_state), shape_struct(exp.gen_params),
                jax.ShapeDtypeStruct((b, 784), f32),
                jax.ShapeDtypeStruct((b, 10), f32),
                jax.ShapeDtypeStruct((b, 1), f32),
                jax.ShapeDtypeStruct((b, 1), f32),
            ).compile().as_text()
        except Exception:
            return {}
        return {op: text.count(f" {op}")
                for op in ("all-reduce", "reduce-scatter", "all-gather")}

    def timed_arm(exp) -> dict:
        rounds = []
        for _ in range(args.rounds):
            t0 = time.perf_counter()
            losses = None
            for _ in range(args.iterations):
                losses = exp.train_iteration(feats, labels)
            # fence: losses are device scalars until read
            vals = [float(v) for v in losses.values()]
            rounds.append((time.perf_counter() - t0) / args.iterations)
            if not all(np.isfinite(vals)):
                raise RuntimeError(f"non-finite losses: {vals}")
        return {"step_s_min": min(rounds), "step_s_rounds": rounds}

    results = []
    invariants: dict = {}
    for n in meshes:
        entry: dict = {"mesh": n}
        arms = {}
        run_off = args.update_sharding in ("off", "both")
        run_on = args.update_sharding in ("on", "both")

        # parity probe first (fresh experiments, one fused iteration)
        if run_off and run_on:
            a = build(n, False)
            b = build(n, True)
            a.train_iteration(feats, labels)
            b.train_iteration(feats, labels)
            worst = 0.0
            da, db = a.digest_states(), b.digest_states()
            for name in da:
                for la, lb in zip(jax.tree_util.tree_leaves(da[name]),
                                  jax.tree_util.tree_leaves(db[name])):
                    la64 = np.asarray(la, np.float64)
                    lb64 = np.asarray(lb, np.float64)
                    denom = np.maximum(np.abs(la64), 1e-2)
                    worst = max(worst, float(
                        np.max(np.abs(la64 - lb64) / denom)))
            entry["parity_rel_max_iter1"] = worst
            invariants[f"parity_tolerance_mesh{n}"] = worst <= 5e-2
            digests_equal = (TrainingSupervisor.state_digests(a)
                             == TrainingSupervisor.state_digests(b))
            entry["parity_digest_exact_iter1"] = digests_equal

            # residency from the parity pair (post-step, steady state)
            rep_bytes = updater_bytes(a)
            sh_bytes = updater_bytes(b)
            rep_total = max(rep_bytes.values())
            sh_worst = max(sh_bytes.values())
            entry["replicated_updater_bytes_per_device"] = rep_total
            entry["sharded_updater_bytes_per_device"] = sh_bytes
            ratio = sh_worst / rep_total
            entry["resident_ratio"] = ratio
            entry["resident_ratio_ideal"] = 1.0 / n
            # ≈ 1/N: allow padding + the per-group widest-row excess
            invariants[f"resident_ratio_mesh{n}"] = ratio <= 1.35 / n
            entry["plan"] = {
                name: tr.plan.describe() for name, tr in (
                    ("dis", b.dis_trainer), ("gan", b.gan_trainer),
                    ("CV", b.cv_trainer)) if tr is not None
            }
            entry["collectives"] = {
                "replicated": collective_counts(a),
                "sharded": collective_counts(b),
            }
            del a, b

        if not args.smoke:
            if run_off:
                arms["replicated"] = timed_arm(build(n, False))
            if run_on:
                arms["sharded"] = timed_arm(build(n, True))
            if run_off and run_on and not args.no_step_gate:
                ratio = (arms["sharded"]["step_s_min"]
                         / arms["replicated"]["step_s_min"])
                entry["step_time_ratio"] = ratio
                on_cpu = jax.devices()[0].platform == "cpu"
                if on_cpu and not args.gate_step_time_on_cpu:
                    entry["step_time_note"] = (
                        "recorded, not gated: on forced host devices "
                        "each added collective is a sync barrier across "
                        "device threads sharing the host cores — the "
                        "chip gate runs in the campaign")
                else:
                    invariants[f"step_time_mesh{n}"] = \
                        ratio <= args.step_time_slack
        entry["arms"] = arms
        results.append(entry)
        print(f"mesh {n}: {json.dumps({k: v for k, v in entry.items() if k != 'plan'}, default=str)[:400]}")

    summary = {
        "bench": "update_sharding",
        "platform": jax.devices()[0].platform,
        "batch": args.batch,
        "iterations": args.iterations,
        "rounds": args.rounds,
        "smoke": bool(args.smoke),
        "results": results,
        "invariants": invariants,
    }
    text = json.dumps(summary, indent=2, default=str)
    if args.output:
        with open(args.output, "w") as fh:
            fh.write(text + "\n")
    if args.record:
        path = os.path.join(ROOT, f"BENCH_update_sharding_{args.record}.json")
        with open(path, "w") as fh:
            fh.write(text + "\n")
        print(f"recorded {path}")
    bad = [k for k, v in invariants.items() if not v]
    if bad:
        sys.stderr.write(f"update_sharding_bench: invariants violated: "
                         f"{bad}\n")
        return 1
    print("update_sharding_bench: all invariants hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
