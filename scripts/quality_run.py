"""Long training run + quality eval (round-1 VERDICT item 3).

Trains the MNIST-family DCGAN + transfer classifier on the best available
real data (see ``data/mnist.py::load_mnist`` — on this image: the bundled UCI
handwritten digits upsampled to 28×28), then records the quality artifacts
the reference implies (gan.ipynb cells 5–6 + ``DCGAN_Generated_Images.png``):

- the 10×10 latent-manifold PNG (committed into ``artifacts/``),
- transfer-classifier accuracy on the held-out test split,
- FID@50k: 50k generator samples vs the real set, features tapped from the
  trained discriminator's ``dis_dense_layer_6`` (the layer the reference's
  transfer classifier trusts; no Inception weights exist offline —
  BASELINE.md "Data provenance"),
- per-iteration throughput stats.

Writes ``artifacts/quality_run.json`` + the PNG; run with ``--cpu`` to force
the host backend when no TPU is reachable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--iterations", type=int, default=300)
    ap.add_argument("--batch", type=int, default=200)
    ap.add_argument("--num-train", type=int, default=10000)
    ap.add_argument("--num-test", type=int, default=1000)
    ap.add_argument("--fid-samples", type=int, default=50000)
    ap.add_argument("--out", default="artifacts")
    ap.add_argument("--export-every", type=int, default=50)
    ap.add_argument("--compute-dtype", default=None)
    ap.add_argument("--cpu", action="store_true", help="force the host backend")
    ap.add_argument("--seed", type=int, default=666)
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from gan_deeplearning4j_tpu.data import DeviceResidentIterator
    from gan_deeplearning4j_tpu.data.dataset import one_hot_np
    from gan_deeplearning4j_tpu.data.mnist import load_mnist, write_mnist_csv
    from gan_deeplearning4j_tpu.eval import render_manifold
    from gan_deeplearning4j_tpu.eval.accuracy import accuracy_score
    from gan_deeplearning4j_tpu.eval.fid import (
        fid_score,
        frozen_feature_fn,
        graph_feature_fn,
    )
    from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment

    t_start = time.time()
    os.makedirs(args.out, exist_ok=True)
    tag, ((xtr, ytr), (xte, yte)) = load_mnist(
        num_train=args.num_train, num_test=args.num_test, seed=args.seed
    )
    print(f"data source: {tag}  train={xtr.shape}  test={xte.shape}", flush=True)

    cfg = ExperimentConfig(
        batch_size_train=args.batch,
        batch_size_pred=500,
        num_iterations=args.iterations,
        print_every=args.export_every,
        save_every=args.export_every,
        save_models=False,  # checkpoint once at the end, not per iteration
        output_dir=args.out,
        compute_dtype=args.compute_dtype,
        seed=args.seed,
    )
    exp = GanExperiment(cfg)
    # whole dataset resident in HBM once — steady state has NO host→device
    # traffic (MNIST-scale data vs ~16 GB HBM; round-3 finding: re-uploading
    # batches through the tunnel was the round-2 bottleneck)
    train_it = DeviceResidentIterator(xtr, one_hot_np(ytr, 10), batch_size=args.batch)
    test_it = DeviceResidentIterator(xte, one_hot_np(yte, 10), batch_size=500)
    # the accuracy CSV contract needs the test file on disk
    test_csv = os.path.join(args.out, "quality_test.csv")
    write_mnist_csv(test_csv, xte, yte)

    result = exp.run(train_it, test_it)
    ips = [h["images_per_sec"] for h in result["history"]]
    print(
        f"trained {result['iterations']} iterations; "
        f"median {np.median(ips):.1f} images/sec",
        flush=True,
    )
    exp.save_models()

    # manifold PNG (the DCGAN_Generated_Images.png artifact)
    manifold_csv = exp.export_manifold(result["iterations"])
    png = render_manifold(
        manifold_csv,
        os.path.join(args.out, "DCGAN_Generated_Images.png"),
        grid=cfg.latent_grid, side=cfg.height, channels=cfg.channels,
    )
    print(f"manifold: {png}", flush=True)

    # accuracy (cell-6 flow, in-process)
    preds_csv = exp.export_predictions(test_it, result["iterations"])
    preds = np.loadtxt(preds_csv, delimiter=",", ndmin=2)
    acc = accuracy_score(preds, yte)
    print(f"transfer-classifier accuracy: {acc * 100:.2f}%", flush=True)

    # FID@50k: generator samples vs the real training set, dis features
    import jax.numpy as jnp

    rng = np.random.default_rng(args.seed + 7)
    fakes = []
    bs = 1000
    t0 = time.time()
    for i in range(0, args.fid_samples, bs):
        n = min(bs, args.fid_samples - i)
        z = rng.random((n, cfg.z_size), dtype=np.float32) * 2.0 - 1.0
        out = exp._gen_fwd(exp.gen_params, jnp.asarray(z))
        fakes.append(np.asarray(out).reshape(n, cfg.num_features))
    fakes = np.concatenate(fakes, axis=0)
    # headline FID: the FROZEN seeded extractor — feature space fixed across
    # runs/rounds/models (round-2 VERDICT weak #4), so this number is
    # longitudinally comparable. The dis-feature FID stays as a secondary,
    # model-space diagnostic.
    frozen_fn = frozen_feature_fn(cfg.height, cfg.width, cfg.channels, seed=666)
    fid = fid_score(xtr, fakes, frozen_fn)
    dis_fn = graph_feature_fn(
        exp.dis, exp.dis_state.params, "dis_dense_layer_6", batch_size=500
    )
    fid_dis = fid_score(xtr, fakes, dis_fn)
    print(f"FID@{args.fid_samples // 1000}k frozen-features: {fid:.2f}  "
          f"dis-features (diagnostic): {fid_dis:.2f} "
          f"({time.time() - t0:.0f}s)", flush=True)

    report = {
        "data_source": tag,
        "iterations": result["iterations"],
        "batch_size": args.batch,
        "compute_dtype": args.compute_dtype or "f32",
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "accuracy": round(float(acc), 4),
        "fid_at": args.fid_samples,
        "fid_frozen_features": round(float(fid), 3),
        "fid_dis_features": round(float(fid_dis), 3),
        "images_per_sec_median": round(float(np.median(ips)), 2),
        "d_loss_final": result["history"][-1]["d_loss"],
        "g_loss_final": result["history"][-1]["g_loss"],
        "cv_loss_final": result["history"][-1]["cv_loss"],
        "wall_seconds": round(time.time() - t_start, 1),
        "timings": {k: round(v, 2) for k, v in result["timings"].items()},
    }
    with open(os.path.join(args.out, "quality_run.json"), "w") as fh:
        json.dump(report, fh, indent=2)
    print(json.dumps(report), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
