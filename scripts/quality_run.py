"""Long training run + quality eval (round-1 VERDICT item 3).

Trains the MNIST-family DCGAN + transfer classifier on the best available
real data (see ``data/mnist.py::load_mnist`` — on this image: the bundled UCI
handwritten digits upsampled to 28×28), then records the quality artifacts
the reference implies (gan.ipynb cells 5–6 + ``DCGAN_Generated_Images.png``):

- the 10×10 latent-manifold PNG (committed into ``artifacts/``),
- transfer-classifier accuracy on the held-out test split,
- FID@50k under the FROZEN extractor (longitudinally comparable) plus the
  dis-feature diagnostic FID (no Inception weights exist offline —
  BASELINE.md "Data provenance"),
- per-iteration throughput stats.

Generator quality is NOT monotone in training time — the classifier keeps
improving long after the discriminator has overpowered the generator (round-3
observation: 96% accuracy at iteration 4000, but the best-looking manifold
near iteration 2000). The run therefore tracks a quick frozen-feature FID at
every export boundary (``GanExperiment.run``'s ``eval_callback`` hook, one
fused generator→features device program per eval), snapshots the best
generator (device-side copies; train steps donate their buffers), and
reports BOTH: the headline manifold PNG comes from the best checkpoint
(paired with ``fid_frozen_features_best``), while the longitudinal metric
``fid_frozen_features`` stays bound to the FINAL model — selection minimizes
that very metric, so a min-over-checkpoints headline would carry best-of-N
bias across rounds.

Writes ``artifacts/quality_run.json`` + the PNG; run with ``--cpu`` to force
the host backend when no TPU is reachable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def sample_generator_rows(gen_fwd, z_size: int, num_samples: int, seed: int,
                          *, num_features=None, batch_size: int = 2500,
                          compute_dtype=None) -> np.ndarray:
    """Seeded latent draws → generator rows, chunked so one device round
    trip moves ``batch_size`` samples (the CLI's FID stage moves ~110k
    samples — tiny chunks made it the slowest part of the whole run).
    ``gen_fwd`` maps a (n, z_size) device batch to sample rows; the z
    stream is ``default_rng(seed)`` uniform in [-1, 1), drawn chunk by
    chunk in order — the exact stream the CLI has always used."""
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.runtime.dtype import compute_dtype_scope

    rng = np.random.default_rng(seed)
    fakes = []
    with compute_dtype_scope(compute_dtype):
        for i in range(0, num_samples, batch_size):
            n = min(batch_size, num_samples - i)
            z = rng.random((n, z_size), dtype=np.float32) * 2.0 - 1.0
            out = gen_fwd(jnp.asarray(z))
            fakes.append(np.asarray(out).reshape(
                n, num_features if num_features is not None else -1))
    return np.concatenate(fakes, axis=0)


def quality_probe(sample_fn, real_rows, *, z_size: int,
                  num_samples: int = 256, seed: int = 666,
                  classify_fn=None, labels=None, feature_fn=None) -> dict:
    """The importable FID / classifier-accuracy probe — one seeded,
    deterministic quality measurement returning a plain dict. The deploy
    canary gate (``deploy/canary.py``) runs THIS function on candidate and
    incumbent engines instead of shelling out to the CLI, so "quality"
    means the same thing in a quality run and in a reload decision.

    - ``sample_fn(z)`` maps a seeded (num_samples, z_size) latent batch in
      [-1, 1) to sample rows; the probe's FID is the Fréchet distance
      between those rows and ``real_rows`` under ``feature_fn`` (identity
      when None — raw-row features; pass ``eval.fid.frozen_feature_fn``
      for the image-family frozen space the CLI's headline FID uses).
    - ``classify_fn(real_rows)`` (optional) returns class probabilities;
      accuracy is argmax-vs-``labels`` (int ids or one-hot), None when
      either piece is missing.
    """
    from gan_deeplearning4j_tpu.eval.accuracy import accuracy_score
    from gan_deeplearning4j_tpu.eval.fid import FeatureStats, fid_from_stats

    if num_samples < 2:
        raise ValueError("num_samples must be >= 2 (covariance fit)")
    real_rows = np.asarray(real_rows, dtype=np.float32)
    rng = np.random.default_rng(seed)
    z = rng.random((num_samples, z_size), dtype=np.float32) * 2.0 - 1.0
    fakes = np.asarray(sample_fn(z), dtype=np.float32)
    fakes = fakes.reshape(num_samples, -1)
    featurize = feature_fn if feature_fn is not None else (lambda rows: rows)
    fid = fid_from_stats(
        FeatureStats.from_features(featurize(real_rows)),
        FeatureStats.from_features(featurize(fakes)),
    )
    accuracy = None
    if classify_fn is not None and labels is not None:
        accuracy = accuracy_score(np.asarray(classify_fn(real_rows)), labels)
    return {
        "fid": float(fid),
        "accuracy": accuracy,
        "num_samples": int(num_samples),
        "num_real": int(real_rows.shape[0]),
        "seed": int(seed),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--iterations", type=int, default=300)
    ap.add_argument("--batch", type=int, default=200)
    ap.add_argument("--num-train", type=int, default=10000)
    ap.add_argument("--num-test", type=int, default=1000)
    ap.add_argument("--fid-samples", type=int, default=50000)
    ap.add_argument("--out", default="artifacts")
    ap.add_argument("--export-every", type=int, default=50)
    ap.add_argument("--compute-dtype", default=None)
    # G/D-balance levers (round-5 VERDICT item 4) — set by the campaign's
    # sweep selector; defaults preserve the reference configuration
    ap.add_argument("--resample-label-noise", action="store_true")
    ap.add_argument("--dis-lr-decay-every", type=int, default=0)
    ap.add_argument("--dis-lr-decay-rate", type=float, default=1.0)
    ap.add_argument("--dis-lr", type=float, default=0.002)
    ap.add_argument("--gen-lr", type=float, default=0.004)
    ap.add_argument("--cpu", action="store_true", help="force the host backend")
    ap.add_argument("--seed", type=int, default=666)
    ap.add_argument("--no-select-best", action="store_true",
                    help="skip in-training FID tracking / best-checkpoint selection")
    ap.add_argument("--select-samples", type=int, default=2048,
                    help="generator samples per in-training quick-FID eval. "
                         "The quick FID fits a 224-dim covariance from these "
                         "samples, so its estimator noise floor scales like "
                         "dim/N — at 1024 samples near convergence the "
                         "selection can be decided by noise rather than real "
                         "quality differences (ADVICE r3); 2048+ keeps the "
                         "paired-z comparisons meaningful, and the headline "
                         "FID is final-model-bound regardless")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from gan_deeplearning4j_tpu.data import DeviceResidentIterator
    from gan_deeplearning4j_tpu.data.dataset import one_hot_np
    from gan_deeplearning4j_tpu.data.mnist import load_mnist, write_mnist_csv
    from gan_deeplearning4j_tpu.eval import render_manifold
    from gan_deeplearning4j_tpu.eval.accuracy import accuracy_score
    from gan_deeplearning4j_tpu.eval.fid import (
        fid_score,
        frozen_feature_fn,
        graph_feature_fn,
    )
    from gan_deeplearning4j_tpu.harness import ExperimentConfig, GanExperiment

    t_start = time.time()
    os.makedirs(args.out, exist_ok=True)
    tag, ((xtr, ytr), (xte, yte)) = load_mnist(
        num_train=args.num_train, num_test=args.num_test, seed=args.seed
    )
    print(f"data source: {tag}  train={xtr.shape}  test={xte.shape}", flush=True)

    cfg = ExperimentConfig(
        batch_size_train=args.batch,
        batch_size_pred=500,
        num_iterations=args.iterations,
        print_every=args.export_every,
        save_every=args.export_every,
        save_models=False,  # checkpoint once at the end, not per iteration
        output_dir=args.out,
        compute_dtype=args.compute_dtype,
        resample_label_noise=args.resample_label_noise,
        dis_lr_decay_every=args.dis_lr_decay_every,
        dis_lr_decay_rate=args.dis_lr_decay_rate,
        dis_learning_rate=args.dis_lr,
        gen_learning_rate=args.gen_lr,
        seed=args.seed,
    )
    exp = GanExperiment(cfg)
    # whole dataset resident in HBM once — steady state has NO host→device
    # traffic (MNIST-scale data vs ~16 GB HBM; round-3 finding: re-uploading
    # batches through the tunnel was the round-2 bottleneck)
    train_it = DeviceResidentIterator(xtr, one_hot_np(ytr, 10), batch_size=args.batch)
    test_it = DeviceResidentIterator(xte, one_hot_np(yte, 10), batch_size=500)
    # the accuracy CSV contract needs the test file on disk
    test_csv = os.path.join(args.out, "quality_test.csv")
    write_mnist_csv(test_csv, xte, yte)

    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.eval.fid import FeatureStats, fid_from_stats

    # large extraction chunks: each chunk is one device round trip (~0.5-1 s
    # through the tunnel), and the FID stage moves ~110k samples — 500-sample
    # chunks made it the slowest part of the whole run
    frozen_fn = frozen_feature_fn(
        cfg.height, cfg.width, cfg.channels, seed=666, batch_size=2500
    )

    # Real-set feature stats under the frozen extractor: computed ONCE and
    # reused by the quick-FID tracker and both full FID@50k scores below.
    real_stats = FeatureStats.from_features(frozen_fn(xtr))

    # In-training quick-FID tracker: fixed z (paired across evals). The
    # generator→frozen-features composition runs as ONE jitted device
    # program returning only (N, 224) features — no sample round-trip — and
    # the best params are snapshotted as fresh DEVICE copies (the train step
    # donates its buffers, so references must be copied; copying on device
    # avoids a ~tens-of-MB host download through the tunnel per improvement,
    # which early training would pay at nearly every boundary).
    best = {"iteration": None, "fid": None, "gen_params": None, "curve": []}
    eval_callback = None
    if not args.no_select_best:
        from gan_deeplearning4j_tpu.eval.fid import quick_fid_scorer

        score = quick_fid_scorer(
            exp, frozen_fn, real_stats,
            num_samples=args.select_samples, seed=args.seed + 13,
        )
        best["curve"] = score.curve

        def score_and_track(e, index):
            fid_q = score(e, index)
            if best["fid"] is None or fid_q < best["fid"]:
                best.update(
                    iteration=index, fid=fid_q,
                    gen_params=jax.tree_util.tree_map(jnp.copy, e.gen_params),
                )

        eval_callback = score_and_track

    result = exp.run(train_it, test_it, eval_callback=eval_callback)
    if eval_callback is not None:
        # the callback cadence usually misses the last iteration (it fires
        # at batch_counter % export_every == 0) — score the final generator
        # too; the scorer dedups when the cadence did land on it, so a
        # monotone-improving run selects the final state exactly once
        score_and_track(exp, result["iterations"])
    ips = [h["images_per_sec"] for h in result["history"]]
    print(
        f"trained {result['iterations']} iterations; "
        f"median {np.median(ips):.1f} images/sec",
        flush=True,
    )
    exp.save_models()
    # when the final iteration won the selection, best and final are the same
    # params — the final artifacts ARE the headline artifacts, so skip the
    # separate best-checkpoint render/zip/FID entirely
    selection_ran = best["iteration"] is not None
    best_is_final = not selection_ran or best["iteration"] == result["iterations"]

    # manifold PNGs (the DCGAN_Generated_Images.png artifact): headline from
    # the best-FID checkpoint when selection ran, final-iteration alongside
    manifold_csv = exp.export_manifold(result["iterations"])
    final_png_name = (
        "DCGAN_Generated_Images.png"
        if best_is_final else "DCGAN_Generated_Images_final.png"
    )
    png = render_manifold(
        manifold_csv,
        os.path.join(args.out, final_png_name),
        grid=cfg.latent_grid, side=cfg.height, channels=cfg.channels,
    )
    print(f"final-iteration manifold: {png}", flush=True)
    if not best_is_final:
        from gan_deeplearning4j_tpu.utils.serializer import write_model

        final_gen_params = exp.gen_params
        exp.gen_params = best["gen_params"]  # already a device-resident copy
        best_csv = exp.export_manifold(f"best_{best['iteration']}")
        png = render_manifold(
            best_csv,
            os.path.join(args.out, "DCGAN_Generated_Images.png"),
            grid=cfg.latent_grid, side=cfg.height, channels=cfg.channels,
        )
        # persist the generator the headline artifacts come from — the
        # regular save_models() zips hold the final-iteration state
        best_zip = os.path.join(
            args.out, f"{cfg.file_prefix}_gen_model_best.zip"
        )
        write_model(best_zip, exp.gen, exp.gen_params, save_updater=False)
        exp.gen_params = final_gen_params
        print(
            f"best-checkpoint manifold (iteration {best['iteration']}, "
            f"quick-FID {best['fid']:.2f}): {png}  saved: {best_zip}", flush=True,
        )

    # accuracy (cell-6 flow, in-process)
    preds_csv = exp.export_predictions(test_it, result["iterations"])
    preds = np.loadtxt(preds_csv, delimiter=",", ndmin=2)
    acc = accuracy_score(preds, yte)
    print(f"transfer-classifier accuracy: {acc * 100:.2f}%", flush=True)

    # FID@50k: generator samples vs the real training set. Headline FID: the
    # FROZEN seeded extractor — feature space fixed across runs/rounds/models
    # (round-2 VERDICT weak #4), so this number is longitudinally comparable.
    # The dis-feature FID stays as a secondary, model-space diagnostic.
    def sample_fakes(params) -> np.ndarray:
        # the module-level chunked sampler (same z stream, chunk size, and
        # dtype scope this CLI has always used — behavior identical)
        return sample_generator_rows(
            lambda z: exp._gen_fwd(params, z),
            cfg.z_size, args.fid_samples, args.seed + 7,
            num_features=cfg.num_features,
            compute_dtype=exp._compute_dtype,
        )

    def frozen_fid(fakes) -> float:
        return fid_from_stats(
            real_stats, FeatureStats.from_features(frozen_fn(fakes))
        )

    t0 = time.time()
    fakes = sample_fakes(exp.gen_params)
    print(f"sampled {len(fakes)} fakes ({time.time() - t0:.0f}s)", flush=True)
    fid = frozen_fid(fakes)
    print(f"frozen FID done ({time.time() - t0:.0f}s)", flush=True)
    dis_fn = graph_feature_fn(
        exp.dis, exp.dis_state.params, "dis_dense_layer_6", batch_size=2500
    )
    fid_dis = fid_score(xtr, fakes, dis_fn)
    print(f"dis-feature FID done ({time.time() - t0:.0f}s)", flush=True)
    # literature-comparable FID when the user mounts extractor weights
    # ($INCEPTION_WEIGHTS → eval/fid.py::inception_feature_fn; no egress on
    # this image, so the canonical pool3 weights can only arrive mounted).
    # Probe the env first — building the function without weights would
    # construct a frozen-extractor fallback only to throw it away.
    fid_inception = None
    inc_source = None
    inc_path = os.environ.get("INCEPTION_WEIGHTS")
    if inc_path and os.path.exists(inc_path):
        # best-effort: a malformed weights file must not discard a completed
        # multi-hour training run (the frozen/dis FIDs above already stand);
        # record the failure in the report instead of crashing
        try:
            from gan_deeplearning4j_tpu.eval.fid import inception_feature_fn

            inc_fn = inception_feature_fn(
                cfg.height, cfg.width, cfg.channels, path=inc_path, batch_size=2500
            )
            fid_inception = fid_score(xtr, fakes, inc_fn)
            inc_source = inc_fn.source
            print(f"inception FID ({inc_source}): {fid_inception:.2f}", flush=True)
        except Exception as exc:
            inc_source = f"error: {type(exc).__name__}: {exc}"
            print(f"inception FID skipped — {inc_source}", flush=True)
    fid_best = None
    if not best_is_final:
        fid_best = frozen_fid(sample_fakes(best["gen_params"]))
        print(f"FID@{args.fid_samples // 1000}k best checkpoint "
              f"(iteration {best['iteration']}): {fid_best:.2f}", flush=True)
    elif selection_ran:  # final won — its FID is the best checkpoint's
        fid_best = fid
    print(f"FID@{args.fid_samples // 1000}k frozen-features (final): {fid:.2f}  "
          f"dis-features (diagnostic): {fid_dis:.2f} "
          f"({time.time() - t0:.0f}s)", flush=True)

    report = {
        "data_source": tag,
        "iterations": result["iterations"],
        "batch_size": args.batch,
        "compute_dtype": args.compute_dtype or "f32",
        "levers": {
            "resample_label_noise": args.resample_label_noise,
            "dis_lr_decay_every": args.dis_lr_decay_every,
            "dis_lr_decay_rate": args.dis_lr_decay_rate,
            "dis_lr": args.dis_lr,
            "gen_lr": args.gen_lr,
        },
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "accuracy": round(float(acc), 4),
        "fid_at": args.fid_samples,
        # ALWAYS the final model — the longitudinally comparable figure
        # (selection minimizes this very metric, so a min-over-checkpoints
        # value would carry best-of-N bias; the selected value lives under
        # fid_frozen_features_best / best_checkpoint, paired with the
        # headline PNG)
        "fid_frozen_features": round(float(fid), 3),
        "fid_frozen_features_best": (
            None if fid_best is None else round(float(fid_best), 3)
        ),
        "fid_dis_features": round(float(fid_dis), 3),
        "fid_inception": (
            None if fid_inception is None else round(float(fid_inception), 3)
        ),
        "fid_inception_source": inc_source,
        "best_checkpoint": None if not selection_ran else {
            "iteration": best["iteration"],
            "is_final": best_is_final,
            "quick_fid": round(float(best["fid"]), 3),
            "fid_frozen_features": round(float(fid_best), 3),
            "quick_fid_curve": best["curve"],
        },
        "images_per_sec_median": round(float(np.median(ips)), 2),
        "d_loss_final": result["history"][-1]["d_loss"],
        "g_loss_final": result["history"][-1]["g_loss"],
        "cv_loss_final": result["history"][-1]["cv_loss"],
        "wall_seconds": round(time.time() - t_start, 1),
        "timings": {k: round(v, 2) for k, v in result["timings"].items()},
    }
    with open(os.path.join(args.out, "quality_run.json"), "w") as fh:
        json.dump(report, fh, indent=2)
    print(json.dumps(report), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
