"""Profile the fused DCGAN-MNIST iteration (round-1 VERDICT item 9).

Captures, for the config-1 workload (batch 64):

- a ``jax.profiler`` device trace (TensorBoard/Perfetto) under ``--trace-dir``,
- per-phase wall-clock from PhaseTimer,
- XLA post-optimization cost analysis of the fused program (FLOPs, bytes
  accessed → arithmetic intensity), per compute dtype,
- derived utilization (FLOPs / wall / peak) when on a known TPU.

Writes ``--out`` (JSON) for the committed PROFILE.md analysis. ``--cpu``
forces the host backend when no TPU is reachable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def profile_once(compute_dtype, batch, iters, trace_dir):
    import jax
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.harness import ExperimentConfig, make_experiment
    from gan_deeplearning4j_tpu.harness.experiment import (
        cost_analysis_dict, shape_struct,
    )
    from gan_deeplearning4j_tpu.runtime.dtype import compute_dtype_scope
    from gan_deeplearning4j_tpu.utils.profiling import device_trace

    cfg = ExperimentConfig(
        batch_size_train=batch, batch_size_pred=batch,
        num_iterations=iters, save_models=False, compute_dtype=compute_dtype,
    )
    exp = make_experiment(cfg)
    rng = np.random.default_rng(0)
    # Device-resident batch (the steady state under DevicePrefetchIterator):
    # feeding numpy per call re-uploads the same bytes synchronously every
    # iteration — through the axon tunnel that measures the link, not the
    # chip (the round-2 "3.8x roofline gap" in one line).
    feats = jnp.asarray(exp.family.synthetic_data(batch, exp.model_cfg, 0)[:batch])
    labels = jnp.asarray(
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch)]
    )
    jax.block_until_ready([feats, labels])

    def sync(losses):
        # a device→host VALUE read is the only true barrier here:
        # block_until_ready returns before execution completes on the
        # tunneled axon platform (measured round 3)
        np.asarray(next(iter(losses.values())))

    # warmup/compile outside the trace
    losses = exp.train_iteration(feats, labels)
    sync(losses)

    with device_trace(trace_dir):
        t0 = time.perf_counter()
        for _ in range(iters):
            with exp.timer.phase("fused_iteration") as sink:
                losses = exp.train_iteration(feats, labels)
                sink.extend(losses.values())
        sync(losses)
        wall = (time.perf_counter() - t0) / iters

    # post-optimization cost analysis of the fused executable
    f32 = jnp.float32
    args = (
        shape_struct(exp.dis_state), shape_struct(exp.gan_state),
        shape_struct(exp.cv_state), shape_struct(exp.gen_params),
        jax.ShapeDtypeStruct((batch, cfg.num_features), f32),
        jax.ShapeDtypeStruct((batch, cfg.num_classes), f32),
        jax.ShapeDtypeStruct((batch, 1), f32),
        jax.ShapeDtypeStruct((batch, 1), f32),
    )
    with compute_dtype_scope(exp._compute_dtype):
        cost = cost_analysis_dict(
            exp._fused.lower(*args).compile().cost_analysis()) or {}
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))
    return {
        "compute_dtype": compute_dtype or "f32",
        "sec_per_iter": round(wall, 5),
        "images_per_sec": round(batch / wall, 2),
        "flops_per_iter": flops,
        "bytes_accessed_per_iter": bytes_accessed,
        "arithmetic_intensity_flops_per_byte": round(flops / bytes_accessed, 2)
        if bytes_accessed else None,
        "achieved_flops_per_sec": round(flops / wall, 3) if flops else None,
        "phase_report": exp.timer.report(),
    }


def batch_sweep(batches, compute_dtype, iters=200):
    """Throughput vs batch size (PROFILE.md's predicted knob): marginal
    per-iteration cost from two chained windows with a single value-fetch
    fence each, so neither per-call dispatch nor the tunnel's fixed sync
    cost (~70-90 ms) pollutes the per-iteration number."""
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.harness import ExperimentConfig, make_experiment

    rows = []
    for batch in batches:
        cfg = ExperimentConfig(
            batch_size_train=batch, batch_size_pred=batch,
            num_iterations=10 ** 9, save_models=False, compute_dtype=compute_dtype,
        )
        exp = make_experiment(cfg)
        rng = np.random.default_rng(0)
        feats = jnp.asarray(exp.family.synthetic_data(batch, exp.model_cfg, 0)[:batch])
        labels = jnp.asarray(
            np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch)]
        )
        losses = exp.train_iteration(feats, labels)
        np.asarray(next(iter(losses.values())))

        def window(n):
            t0 = time.perf_counter()
            for _ in range(n):
                losses = exp.train_iteration(feats, labels)
            np.asarray(next(iter(losses.values())))
            return time.perf_counter() - t0

        window(10)  # settle
        short, long = window(iters // 4), window(iters)
        marginal = (long - short) / (iters - iters // 4)
        try:
            flops = exp.flops_per_iteration(batch)
        except Exception:
            flops = None
        rows.append({
            "batch": batch,
            "sec_per_iter": round(marginal, 6),
            "images_per_sec": round(batch / marginal, 2),
            "flops_per_iter": flops,
        })
        print(json.dumps(rows[-1]), flush=True)
    return rows


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--trace-dir", default="artifacts/trace")
    ap.add_argument("--out", default="artifacts/profile.json")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--sweep", default="64,128,256,512",
                    help="comma-separated batch sizes for the throughput "
                         "sweep ('' disables)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    results = {
        "platform": None, "device_kind": None, "batch": args.batch,
        "runs": [],
    }
    for dtype in (None, "bf16"):
        r = profile_once(dtype, args.batch, args.iters,
                         args.trace_dir + ("_bf16" if dtype else "_f32"))
        print(json.dumps({k: v for k, v in r.items() if k != "phase_report"}),
              flush=True)
        print(r["phase_report"], flush=True)
        results["runs"].append(r)
    if args.sweep:
        batches = [int(b) for b in args.sweep.split(",")]
        results["batch_sweep"] = {
            dtype or "f32": batch_sweep(batches, dtype) for dtype in (None, "bf16")
        }
    results["platform"] = jax.default_backend()
    results["device_kind"] = jax.devices()[0].device_kind
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as fh:
        json.dump(results, fh, indent=2)
    print(f"wrote {args.out}; traces under {args.trace_dir}_*", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
