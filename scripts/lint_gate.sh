#!/bin/bash
# jaxlint gate wrapper (jaxlint v2) — the two shapes CI and humans need:
#
#   scripts/lint_gate.sh                 # FAST: only .py files changed vs
#                                        #   the merge base with ${LINT_BASE:-HEAD}
#                                        #   (HEAD = uncommitted work only) —
#                                        #   the pre-commit shape
#   scripts/lint_gate.sh --full          # the whole tier-1 target set —
#                                        #   what tests/test_analysis.py's
#                                        #   TestTreeIsClean enforces; also
#                                        #   FAILS (exit 1) on stale baseline
#                                        #   entries — a fixed bug must leave
#                                        #   analysis/_baseline.json, not
#                                        #   haunt it (--prune-baseline)
#   LINT_BASE=main scripts/lint_gate.sh  # changed vs merge-base with main
#   LINT_FORMAT=sarif scripts/lint_gate.sh --full > lint.sarif  # CI annotators
#   LINT_PROFILE=1 scripts/lint_gate.sh --full  # per-phase/per-rule wall-time
#                                        #   table on stderr (report unchanged)
#   scripts/lint_gate.sh --mux           # the serving/mux seam only, with
#                                        #   the two engine-sharing rules
#                                        #   (JG016 swap seam, JG022
#                                        #   cross-generation registry) —
#                                        #   the fleet_drill --mux preflight
#
# Extra arguments pass through to the analyzer (--rules JG00x, --fix, ...).
# Exit codes are the analyzer's: 0 clean (modulo baseline + suppressions),
# 1 active findings or stale baseline entries, 2 usage/environment error.
cd "$(dirname "$0")/.." || exit 2
TARGETS=(gan_deeplearning4j_tpu bench.py scripts)
FORMAT="${LINT_FORMAT:-text}"
# Incremental parse cache: every shape (fast, --full, --mux) shares one
# content-addressed cache so repeat invocations — pre-commit after CI,
# the campaign's SARIF pass after its gate pass — skip re-parsing
# unchanged files. JAXLINT_CACHE_DIR overrides the location;
# LINT_CACHE=off bypasses the cache entirely (the analyzer honors it
# even when the dir is exported).
export JAXLINT_CACHE_DIR="${JAXLINT_CACHE_DIR:-${TMPDIR:-/tmp}/jaxlint_cache}"
EXTRA=()
[ -n "${LINT_PROFILE:-}" ] && EXTRA+=(--profile)
if [ "$1" = "--full" ]; then
  shift
  exec python -m gan_deeplearning4j_tpu.analysis "${TARGETS[@]}" \
    --format "$FORMAT" "${EXTRA[@]}" "$@"
fi
if [ "$1" = "--mux" ]; then
  shift
  exec python -m gan_deeplearning4j_tpu.analysis \
    gan_deeplearning4j_tpu/serving gan_deeplearning4j_tpu/deploy \
    gan_deeplearning4j_tpu/fleet \
    --rules JG016,JG022 --format "$FORMAT" "${EXTRA[@]}" "$@"
fi
exec python -m gan_deeplearning4j_tpu.analysis "${TARGETS[@]}" \
  --changed-only --diff-base "${LINT_BASE:-HEAD}" --format "$FORMAT" \
  "${EXTRA[@]}" "$@"
