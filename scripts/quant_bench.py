#!/usr/bin/env python
"""quant_bench — paired fp32 / bf16 / int8 serving economics, measured.

The quant plane's claim (docs/QUANT.md) is that a quantized variant is
*cheaper where the mux economics look*: fewer resident param bytes, no
worse per-row latency, and a smaller measured cost scalar — while the
canary gate confirms the quality loss stays inside the same relative
thresholds any reload candidate must clear. This bench measures all of
it in one process against one freshly published bundle:

1. **publish** — a tiny seeded MNIST-family experiment publishes its
   fp32 serving bundle (generator + dis-feature classifier, the paper's
   end product);
2. **build** — ``quant/variants.py`` derives the bf16 and int8 siblings
   from that bundle (same calibration seed every run);
3. **measure** — each variant's engine is profiled on the same bucket
   ladder (``quant/cost.py``): per-bucket min-of-rounds latency,
   resident param bytes, the cost scalar; blocks land in each bundle's
   manifest, exactly as a campaign would leave them for the mux;
4. **A/B** — paired alternating-round latency at the top bucket, fp32
   vs each variant per request kind (alternation cancels slow host
   drift the way serve_bench's ``--compare`` does);
5. **drift + canary** — max output deviation per kind on fixed seeded
   inputs, then the real CanaryGate evaluates each variant against the
   fp32 incumbent on labeled synthetic rows: a variant this bench
   ships numbers for is one the reload plane would actually admit.

Gating: ``scripts/bench_ledger.py`` tracks the recorded
``BENCH_quant_<round>.json`` under its ``quant`` family — bytes ratios
must stay below 1 and canary failures at 0, or the campaign fails.

Usage::

    JAX_PLATFORMS=cpu python scripts/quant_bench.py --smoke
    python scripts/quant_bench.py --record r01   # BENCH_quant_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _publish_fp32(workdir: str, seed: int) -> str:
    from gan_deeplearning4j_tpu.harness.config import ExperimentConfig
    from gan_deeplearning4j_tpu.harness.experiment import GanExperiment

    cfg = ExperimentConfig(
        batch_size_train=8, batch_size_pred=8, num_iterations=1,
        latent_grid=2, save_models=False, seed=seed,
        output_dir=os.path.join(workdir, "train_out"),
    )
    exp = GanExperiment(cfg)
    bundle = os.path.join(workdir, "fp32")
    exp.publish_for_serving(bundle)
    return bundle


def _paired_ab(base, other, *, rounds: int) -> dict:
    """Alternating-round min latency per kind at the top bucket: the
    variant's share of the fp32 time (< 1 means faster). Alternation
    keeps both sides exposed to the same host noise."""
    out = {}
    top = max(base.buckets)
    for kind in base.kinds:
        width = base.input_width(kind)
        rows = np.zeros((top, width), np.float32)
        best_base = best_other = float("inf")
        for _ in range(max(1, rounds)):
            t0 = time.perf_counter()
            base.run(kind, rows)
            best_base = min(best_base, time.perf_counter() - t0)
            t0 = time.perf_counter()
            other.run(kind, rows)
            best_other = min(best_other, time.perf_counter() - t0)
        out[kind] = {
            "fp32_s": best_base,
            "variant_s": best_other,
            "ratio": best_other / best_base if best_base > 0 else None,
        }
    return out


def _output_drift(base, other, *, seed: int) -> dict:
    out = {}
    for kind in base.kinds:
        width = base.input_width(kind)
        rows = np.random.default_rng(seed).random(
            (8, width)).astype(np.float32)
        a = np.asarray(base.run(kind, rows), np.float32)
        b = np.asarray(other.run(kind, rows), np.float32)
        out[kind] = float(np.max(np.abs(a - b)))
    return out


def run_bench(args) -> dict:
    from gan_deeplearning4j_tpu.data.mnist import synthetic_mnist
    from gan_deeplearning4j_tpu.deploy.canary import CanaryGate
    from gan_deeplearning4j_tpu.quant import (
        build_bf16_variant,
        build_int8_variant,
        measure_engine_cost,
        write_cost_block,
    )
    from gan_deeplearning4j_tpu.serving.engine import ServingEngine

    workdir = tempfile.mkdtemp(prefix="quant_bench_")
    try:
        t0 = time.time()
        fp32_dir = _publish_fp32(workdir, args.seed)
        dirs = {"fp32": fp32_dir,
                "bf16": os.path.join(workdir, "bf16"),
                "int8": os.path.join(workdir, "int8")}
        build_bf16_variant(fp32_dir, dirs["bf16"])
        build_int8_variant(fp32_dir, dirs["int8"])

        engines = {}
        costs = {}
        for name, d in dirs.items():
            engine = ServingEngine.from_bundle(
                d, buckets=args.buckets, export_gauge=False)
            engine.warmup()
            engines[name] = engine
            block = measure_engine_cost(engine, rounds=args.rounds)
            write_cost_block(d, block)
            costs[name] = block

        fp32 = engines["fp32"]
        variants = {}
        for name in ("bf16", "int8"):
            block = costs[name]
            variants[name] = {
                "resident_param_bytes": block["resident_param_bytes"],
                "bytes_ratio": (block["resident_param_bytes"]
                                / costs["fp32"]["resident_param_bytes"]),
                "cost_scalar": block["scalar"],
                "cost_ratio": block["scalar"] / costs["fp32"]["scalar"],
                "ab_latency": _paired_ab(fp32, engines[name],
                                         rounds=args.rounds),
                "output_drift": _output_drift(fp32, engines[name],
                                              seed=args.seed),
            }

        # the real admission gate, against the fp32 incumbent
        (rows, labels), _ = synthetic_mnist(
            num_train=args.canary_rows, num_test=1, seed=args.seed)
        gate = CanaryGate(rows, labels, num_samples=args.canary_samples,
                          seed=args.seed)
        canary = {}
        for name in ("bf16", "int8"):
            decision = gate.evaluate(engines[name], fp32)
            canary[name] = {"passed": decision.passed,
                            "reason": decision.reason,
                            "candidate": decision.candidate,
                            "incumbent": decision.incumbent}
            # next variant gates against the same fp32 incumbent
            gate._incumbent_cache = None
        failures = sum(1 for c in canary.values() if not c["passed"])

        results = {
            "fp32": {
                "resident_param_bytes": costs["fp32"][
                    "resident_param_bytes"],
                "cost_scalar": costs["fp32"]["scalar"],
                "per_row_s": costs["fp32"]["per_row_s"],
            },
            "bf16": variants["bf16"],
            "int8": variants["int8"],
            "canary": canary,
            "canary_failures": failures,
            "wall_s": time.time() - t0,
        }
        invariants = {
            # the residency halving bf16 exists for (exact: every float
            # leaf 4 -> 2 bytes), with slack for non-float metadata
            "bf16_bytes_halved": variants["bf16"]["bytes_ratio"] <= 0.6,
            # int8 shrinks only the classifier's dense vertices — any
            # real shrink counts, the exact ratio is model-shaped
            "int8_bytes_shrunk": variants["int8"]["bytes_ratio"] < 1.0,
            # cheaper where the mux ranks: bf16's measured scalar must
            # drop (the bytes factor halves exactly, dwarfing latency
            # noise). int8's is deliberately NOT gated: on hosts without
            # an int8 matmul path (CPU) the quant/dequant overhead can
            # price it above fp32 — and the measured plane's whole point
            # is that the mux then ranks it accordingly instead of
            # trusting a declared "int8 is cheap" fiction; the ledger
            # tracks the ratio as info either way
            "bf16_cost_cheaper": variants["bf16"]["cost_ratio"] < 1.0,
            "canary_admits_both": failures == 0,
        }
        return {
            "bench": "quant",
            "config": {
                "rounds": args.rounds,
                "buckets": list(args.buckets),
                "seed": args.seed,
                "smoke": bool(args.smoke),
                "platform": fp32.platform,
            },
            "results": results,
            "invariants": invariants,
            "ok": all(invariants.values()),
        }
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rounds", type=int, default=5,
                   help="timing rounds per (kind, bucket), min-of-rounds")
    p.add_argument("--buckets", default="1,8,32",
                   type=lambda s: tuple(int(b) for b in s.split(",")))
    p.add_argument("--canary-rows", type=int, default=64)
    p.add_argument("--canary-samples", type=int, default=32)
    p.add_argument("--seed", type=int, default=666)
    p.add_argument("--smoke", action="store_true",
                   help="small fixed shape for CI/campaign gating")
    p.add_argument("--record", default=None, metavar="TAG",
                   help="also write BENCH_quant_<TAG>.json at the repo root")
    p.add_argument("--output",
                   default=os.path.join(_REPO, "artifacts",
                                        "quant_bench.json"))
    args = p.parse_args(argv)

    if args.smoke:
        args.rounds = min(args.rounds, 2)
        args.buckets = (1, 8)
        args.canary_rows = min(args.canary_rows, 48)
        args.canary_samples = min(args.canary_samples, 16)

    summary = run_bench(args)
    os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                exist_ok=True)
    with open(args.output, "w") as fh:
        json.dump(summary, fh, indent=2)
        fh.write("\n")
    if args.record:
        with open(os.path.join(_REPO,
                               f"BENCH_quant_{args.record}.json"),
                  "w") as fh:
            json.dump(summary, fh, indent=2)
            fh.write("\n")
    sys.stdout.write(json.dumps(summary["results"], indent=2) + "\n")
    bad = [k for k, v in summary["invariants"].items() if not v]
    if bad:
        sys.stderr.write(f"quant_bench: invariants violated: {bad}\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
