#!/bin/bash
# Wait for the TPU tunnel to answer, then regenerate the full coherent
# quality-artifact set with the selection-enabled script.
cd /root/repo
for i in $(seq 1 300); do
  echo "$(date +%H:%M:%S) probe $i" >> tpu_poller2.log
  if timeout 150 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1; then
    echo "$(date +%H:%M:%S) TPU up — quality run" >> tpu_poller2.log
    python scripts/quality_run.py --iterations 4000 --batch 200 > quality_run.log 2>&1
    rc=$?
    echo "$(date +%H:%M:%S) quality rc=$rc" >> tpu_poller2.log
    # a mid-run tunnel drop kills the script non-zero: keep polling and
    # retry the whole run — only a completed run (rc=0) ends the loop
    if [ "$rc" -eq 0 ]; then exit 0; fi
  fi
  sleep 60
done
echo "$(date +%H:%M:%S) gave up" >> tpu_poller2.log
