#!/bin/bash
# Wait for the TPU tunnel to answer, then regenerate (1) the coherent
# quality-artifact set with the selection-enabled script and (2) the
# five-config bench read against the committed BENCH_BASELINES.json so
# artifacts/benchmarks.json carries non-null vs_baseline ratios (round-2
# VERDICT weak #7: cross-run stability evidence).
#
# The two steps are independent: each is attempted whenever the probe
# passes, succeeds only if its artifact says platform=tpu/degraded=false
# (both tools silently fall back to CPU if the tunnel drops mid-run — a
# CPU result must not clobber committed TPU artifacts; on contamination
# the git version is restored), and the loop always backs off 60 s.
cd /root/repo
quality_done=0
bench_done=0
for i in $(seq 1 300); do
  echo "$(date +%H:%M:%S) probe $i" >> tpu_poller2.log
  if timeout 150 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1; then
    if [ "$quality_done" -eq 0 ]; then
      echo "$(date +%H:%M:%S) TPU up — quality run" >> tpu_poller2.log
      # remove the previous JSON first: it is written LAST by the script, so
      # its presence with platform=tpu after the run proves THIS attempt
      # completed (a timeout-killed attempt must not false-pass against the
      # committed file)
      rm -f artifacts/quality_run.json
      timeout 2400 python scripts/quality_run.py --iterations 4000 --batch 200 > quality_run.log 2>&1
      rc=$?
      if [ "$rc" -eq 0 ] && python -c "import json,sys; sys.exit(0 if json.load(open('artifacts/quality_run.json'))['platform']=='tpu' else 1)" 2>/dev/null; then
        quality_done=1
      else
        git checkout -- artifacts/quality_run.json artifacts/DCGAN_Generated_Images.png 2>/dev/null
      fi
      echo "$(date +%H:%M:%S) quality rc=$rc done=$quality_done" >> tpu_poller2.log
    fi
    if [ "$bench_done" -eq 0 ]; then
      echo "$(date +%H:%M:%S) bench repeat" >> tpu_poller2.log
      rm -f artifacts/benchmarks.json  # same completed-attempt proof as above
      timeout 2400 python bench.py --config all --json artifacts/benchmarks.json > bench_all.log 2>&1
      rc=$?
      if [ "$rc" -eq 0 ] && python -c "
import json, sys
d = json.load(open('artifacts/benchmarks.json'))
ok = (not d['diagnostics']['degraded']
      and len(d['results']) == 5
      and all('metric' in r for r in d['results']))
sys.exit(0 if ok else 1)" 2>/dev/null; then
        bench_done=1
      else
        git checkout -- artifacts/benchmarks.json 2>/dev/null
      fi
      echo "$(date +%H:%M:%S) bench rc=$rc done=$bench_done" >> tpu_poller2.log
    fi
    if [ "$quality_done" -eq 1 ] && [ "$bench_done" -eq 1 ]; then exit 0; fi
  fi
  sleep 60
done
echo "$(date +%H:%M:%S) gave up" >> tpu_poller2.log
