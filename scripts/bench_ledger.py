#!/usr/bin/env python
"""bench_ledger — fold the repo's BENCH_*.json trajectory into one table.

Every drill and bench in this repo records a ``BENCH_<family>_<round>.json``
at the repo root (serve_bench, resilience_drill, reload_drill,
fleet_drill and its --autoscale/--mux/--alerts phases,
update_sharding_bench). Each file was a gate when it was recorded — and
then became archaeology: nothing machine-reads the *trajectory*, so a
regression between rounds is caught by a human eyeballing JSON diffs, if
at all (the ROADMAP's "TPU-measured truth" item). This script is the
machine gate:

1. **trend table** — group the records by family, extract each family's
   key metrics (the spec below names them), and print one row per
   (family, round) with the delta vs the family's baseline round.
2. **regression gate** — for direction-annotated metrics, compare the
   NEWEST round against the baseline round under a per-metric relative
   tolerance; exit nonzero when any metric regressed past it, when a
   hard bound (``max_abs`` — e.g. lost requests must be 0) is breached,
   or when the newest record of a family carries ``ok: false`` /
   a false invariant. Single-record families gate on their own
   invariants only (no delta exists yet).

``scripts/tpu_campaign.sh`` runs this as a post-step after a campaign's
steps land, so a chip session that quietly regressed a recorded metric
fails the campaign instead of shipping a worse number as the new normal.

Stdlib-only; works in jax-free containers.

Usage::

    python scripts/bench_ledger.py                  # table + gate
    python scripts/bench_ledger.py --json out.json  # also machine-readable
    python scripts/bench_ledger.py --baseline r01   # pin the compare round
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: BENCH_<family>_<round>.json; bare BENCH_<round>.json is the training
#: bench harness's raw dump family ("train")
_NAME_RE = re.compile(r"^BENCH_(?:(?P<family>.+)_)?(?P<round>r\d+)\.json$")


def _get(doc: dict, dotted: str):
    node = doc
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


class Metric:
    """One tracked number: where it lives, which direction is better,
    and how much relative movement the gate tolerates."""

    def __init__(self, label: str, paths, direction: str = "info",
                 tolerance: float = 0.25, max_abs=None):
        self.label = label
        self.paths = [paths] if isinstance(paths, str) else list(paths)
        self.direction = direction  # "higher" | "lower" | "info"
        self.tolerance = tolerance
        self.max_abs = max_abs

    def extract(self, doc: dict):
        for path in self.paths:
            value = _get(doc, path)
            if isinstance(value, (int, float)) and not isinstance(
                    value, bool):
                return float(value)
        return None


#: the per-family key-metric spec. "info" metrics land in the table but
#: never gate; hard bounds (max_abs) gate on every record's newest round.
SPEC = {
    "serving": [
        Metric("throughput_rps", "results.throughput_rps",
               "higher", 0.30),
        Metric("p99_batch_ms", "results.latency_ms.sample.p99", "info"),
        Metric("lost", "results.lost", "lower", 0.0, max_abs=0),
    ],
    "resilience": [
        Metric("ckpt_overhead_frac",
               "results.oracle.checkpoint_overhead_frac", "lower", 0.35),
        Metric("relaunches", "results.kill_recover.relaunches", "info"),
    ],
    "resilience_mh": [
        Metric("lost_steps", "results.lost_steps", "info"),
        Metric("recovery_wall_s",
               ["results.recovery_wall_s", "results.recovery.wall_s"],
               "info"),
    ],
    "reload": [
        Metric("swaps", "results.swap_phase.swaps_observed", "info"),
        Metric("lost", "results.requests.lost", "lower", 0.0, max_abs=0),
    ],
    "fleet": [
        Metric("answered", "results.requests.ok", "info"),
        Metric("lost", "results.requests.lost", "lower", 0.0, max_abs=0),
        Metric("errors", "results.requests.error", "lower", 0.0,
               max_abs=0),
    ],
    "autoscale": [
        Metric("p99_s", "results.latency.p99_s", "lower", 1.0),
        Metric("lost", "results.requests.lost", "lower", 0.0, max_abs=0),
    ],
    "mux": [
        Metric("lite_share", "results.split.lite_share_observed", "info"),
        Metric("lost", "results.ledger.lost", "lower", 0.0, max_abs=0),
    ],
    "update_sharding": [
        Metric("resident_ratio_m2",
               ["results.mesh_2.resident_ratio",
                "results.resident_ratio_mesh2"], "info"),
    ],
    "alerts": [
        Metric("lost", "results.requests.lost", "lower", 0.0, max_abs=0),
        Metric("false_fires", "results.false_fires", "lower", 0.0,
               max_abs=0),
    ],
    "quant": [
        # resident-bytes frontier: bf16 must stay ~halved (a creeping
        # ratio means float leaves stopped being cast), int8 below 1
        Metric("bf16_bytes_ratio", "results.bf16.bytes_ratio", "lower",
               0.10, max_abs=0.6),
        Metric("int8_bytes_ratio", "results.int8.bytes_ratio", "lower",
               0.10, max_abs=0.999),
        # the scalar the mux actually ranks by — track, don't gate (CPU
        # latency noise moves it); the per-record invariants gate < 1
        Metric("bf16_cost_ratio", "results.bf16.cost_ratio", "info"),
        Metric("int8_cost_ratio", "results.int8.cost_ratio", "info"),
        # the admission gate itself: a quant build the canary rejects
        # must fail the campaign, not ship as a ledger row
        Metric("canary_failures", "results.canary_failures", "lower",
               0.0, max_abs=0),
    ],
    "ladder": [
        # measured padded-rows waste, learned/baseline on identical
        # request draws — the traffic-shaped ladder must keep beating
        # the fixed 1/8/32/128 guess (< 1 means it does); the record's
        # own invariants additionally hard-gate zero-lost and
        # no-serve-time-compiles
        Metric("waste_ratio_measured", "waste.ratio", "lower", 0.25,
               max_abs=0.999),
        Metric("waste_ratio_analytic",
               "ladder.analytic_padded_rows.ratio", "info"),
        # compile-cache warm elasticity: track the warm/cold warmup
        # split, don't gate it (CPU wall noise; tiny bench models)
        Metric("warm_warmup_s", "elasticity.warm_warmup_s", "info"),
        Metric("cold_warmup_s", "elasticity.cold_warmup_s", "info"),
        Metric("lost",
               ["phases.learned.lost", "phases.baseline.lost"],
               "lower", 0.0, max_abs=0),
    ],
    "zoo": [
        # the conditional serving contract: every class must round-trip
        # staged == host, and after warmup the compile ledger must not
        # move — conditioning is a data change, never a compile surface
        Metric("parity_classes", "results.conditional.parity_classes",
               "info"),
        Metric("serve_compiles",
               "results.conditional.serve_compiles_total", "lower", 0.0,
               max_abs=0),
        # the mux exactly-one-answer ledger across two architecture-
        # distinct zoo variants (dcgan-mnist vs wgan_gp-cifar)
        Metric("mux_errors", "results.mux.errors", "lower", 0.0,
               max_abs=0),
        Metric("mux_lost", "results.mux.lost", "lower", 0.0, max_abs=0),
    ],
    "train": [],  # raw bench dumps: invariants/ok gating only
}


def _ok_flag(doc: dict):
    """The record's own verdict: an explicit ``ok`` bool, else all
    invariants true, else None (no verdict recorded)."""
    ok = doc.get("ok")
    if isinstance(ok, bool):
        return ok
    invariants = doc.get("invariants")
    if isinstance(invariants, dict) and invariants:
        return all(bool(v) for v in invariants.values())
    return None


def collect(root: str) -> dict:
    """{family: [(round, path, doc)]} sorted by round."""
    families: dict = {}
    for path in sorted(glob.glob(os.path.join(root, "BENCH_*.json"))):
        m = _NAME_RE.match(os.path.basename(path))
        if not m:
            continue
        family = m.group("family") or "train"
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"bench_ledger: {path}: unreadable ({exc})",
                  file=sys.stderr)
            doc = {}
        families.setdefault(family, []).append(
            (m.group("round"), os.path.basename(path), doc))
    for rounds in families.values():
        rounds.sort(key=lambda item: int(item[0][1:]))
    return families


def build_ledger(families: dict, baseline_round: str = None) -> dict:
    """The full trend + gate payload; ``regressions`` drives the exit
    code."""
    ledger = {"families": {}, "regressions": []}
    for family, rounds in sorted(families.items()):
        metrics = SPEC.get(family, [])
        base_idx = 0
        if baseline_round is not None:
            for i, (rnd, _, _) in enumerate(rounds):
                if rnd == baseline_round:
                    base_idx = i
                    break
        base_round, _, base_doc = rounds[base_idx]
        rows = []
        for rnd, fname, doc in rounds:
            row = {"round": rnd, "file": fname, "ok": _ok_flag(doc),
                   "metrics": {}}
            for metric in metrics:
                value = metric.extract(doc)
                base_value = metric.extract(base_doc)
                entry = {"value": value, "direction": metric.direction}
                if (value is not None and base_value not in (None, 0)
                        and rnd != base_round):
                    entry["delta_vs_" + base_round] = (
                        value / base_value - 1.0)
                row["metrics"][metric.label] = entry
            rows.append(row)
        ledger["families"][family] = {
            "baseline": base_round, "rounds": rows}

        # -- gate: newest vs baseline ---------------------------------
        newest_round, newest_file, newest_doc = rounds[-1]
        if _ok_flag(newest_doc) is False:
            ledger["regressions"].append(
                f"{family}/{newest_round}: record carries a failed "
                f"verdict (ok/invariants false) — {newest_file}")
        for metric in metrics:
            value = metric.extract(newest_doc)
            if value is None:
                continue
            if metric.max_abs is not None and value > metric.max_abs:
                ledger["regressions"].append(
                    f"{family}/{newest_round}: {metric.label} = "
                    f"{value:g} breaches the hard bound "
                    f"<= {metric.max_abs:g}")
                continue
            if metric.direction == "info" or newest_round == base_round:
                continue
            base_value = metric.extract(base_doc)
            if base_value in (None, 0) or math.isnan(base_value):
                continue
            ratio = value / base_value
            if metric.direction == "higher" and ratio < 1 - metric.tolerance:
                ledger["regressions"].append(
                    f"{family}/{newest_round}: {metric.label} fell to "
                    f"{ratio:.2f}x of {base_round} ({value:g} vs "
                    f"{base_value:g}; tolerance -{metric.tolerance:.0%})")
            elif metric.direction == "lower" and ratio > 1 + metric.tolerance:
                ledger["regressions"].append(
                    f"{family}/{newest_round}: {metric.label} rose to "
                    f"{ratio:.2f}x of {base_round} ({value:g} vs "
                    f"{base_value:g}; tolerance +{metric.tolerance:.0%})")
    return ledger


def render(ledger: dict) -> str:
    out = []
    for family, data in sorted(ledger["families"].items()):
        out.append(f"{family}  (baseline {data['baseline']})")
        for row in data["rounds"]:
            ok = {True: "ok", False: "FAIL", None: "-"}[row["ok"]]
            cells = []
            for label, entry in row["metrics"].items():
                if entry["value"] is None:
                    continue
                cell = f"{label}={entry['value']:g}"
                for key, delta in entry.items():
                    if key.startswith("delta_vs_"):
                        cell += f" ({delta:+.1%} vs {key[9:]})"
                cells.append(cell)
            out.append(f"  {row['round']:>4s}  [{ok:>4s}]  "
                       + ("  ".join(cells) if cells else "(no key metrics)"))
    out.append("")
    if ledger["regressions"]:
        out.append("REGRESSIONS:")
        out.extend(f"  {line}" for line in ledger["regressions"])
    else:
        out.append("no regressions past tolerance")
    return "\n".join(out)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--root", default=_REPO,
                   help="directory holding BENCH_*.json (default: repo "
                        "root)")
    p.add_argument("--baseline", default=None, metavar="ROUND",
                   help="round tag to measure deltas against (default: "
                        "each family's earliest round)")
    p.add_argument("--json", default=None, metavar="PATH",
                   help="also write the ledger as JSON")
    args = p.parse_args(argv)

    families = collect(args.root)
    if not families:
        print(f"bench_ledger: no BENCH_*.json under {args.root}",
              file=sys.stderr)
        return 1
    ledger = build_ledger(families, baseline_round=args.baseline)
    print(render(ledger))
    if args.json:
        os.makedirs(os.path.dirname(os.path.abspath(args.json)),
                    exist_ok=True)
        with open(args.json, "w") as fh:
            json.dump(ledger, fh, indent=2)
            fh.write("\n")
    return 1 if ledger["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
