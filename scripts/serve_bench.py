#!/usr/bin/env python
"""serve_bench — closed-loop load generator for the serving subsystem.

Builds fresh MNIST-family artifacts (generator + discriminator-feature
classifier), publishes them through the serializer exactly as a training
run would, loads them back through the serving loader, then drives the
in-process service with a mixed workload: every worker thread loops
submit→wait→submit (closed loop) over randomized request kinds and batch
sizes, followed by an OVERLOAD phase (tiny queue, tight deadlines, more
clients than slots) that proves shedding stays explicit under pressure.
Writes a BENCH-style JSON artifact with throughput, latency percentiles,
per-stage pipeline breakdown (assemble/device/complete), batch-occupancy
histogram, shed counts, and the compile ledger — and FAILS (exit 1) if
any serving invariant breaks:

- zero lost requests: every submit returns ok or an explicit shed, in
  the main phase AND the overload phase;
- bounded compiles: per-kind XLA compiles ≤ the engine's declared bound
  (ladder size × replicas, +1 bulk lane when multi-replica);
- no serve-time compiles: after warmup, the compile count per kind must
  not move (mixed request sizes ride the padded buckets, never re-compile).

CPU runs (the CI shapes)::

    JAX_PLATFORMS=cpu python scripts/serve_bench.py \\
        --requests 200 --threads 8 --buckets 1,8,32 \\
        --output artifacts/serve_bench.json

    JAX_PLATFORMS=cpu python scripts/serve_bench.py --smoke   # campaign gate
    JAX_PLATFORMS=cpu python scripts/serve_bench.py --replicas 2
    JAX_PLATFORMS=cpu python scripts/serve_bench.py --legacy  # PR 3 path A/B

``--replicas N`` on a CPU host forces N virtual host devices (the flag
must land before jax initializes, which is why it is handled at the top
of ``main``); on a real TPU it routes across the chips that exist.
``--record TAG`` additionally writes ``BENCH_serving_<TAG>.json`` at the
repo root so the serving perf trajectory is tracked alongside the
training bench files.

``--replay TRACE.json`` (ISSUE 19) switches to the traffic-shaped
ladder bench: replay a checked-in heavy-tail request-size trace twice
against the same bundle — once on the fixed ``--replay-baseline``
ladder, once on the ladder ``solve_ladder`` learns from the trace at
the same compile budget — and report measured padded-rows waste, p99,
and compile counts side by side (identical request draws, so the
comparison is paired). The replay run also measures compile-cache warm
elasticity: cold engine warmup fills the persistent XLA cache, then a
fresh engine re-warms from it — the scale-up-to-routable delta a
restarted fleet worker sees. ``--record TAG`` writes
``BENCH_ladder_<TAG>.json`` (the ``ladder`` ledger family)::

    JAX_PLATFORMS=cpu python scripts/serve_bench.py \\
        --replay scripts/data/heavy_tail_trace.json --record r01
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def build_bundle(directory: str, seed: int = 666, classes: int = 0) -> dict:
    """Fresh (untrained) MNIST artifacts through the REAL publish path:
    build graphs, then write serving checkpoints + manifest with
    ``write_model`` — the bench exercises the same loader a trained bundle
    would hit, and weights don't change the serving-layer physics.

    ``classes > 0`` builds the CONDITIONAL variant (docs/ZOO.md): the
    generator's input grows by the one-hot label block exactly as the
    conditional trainer builds it, and the returned ``scenario`` dict is
    the zoo manifest block a conditional bundle would declare — so the
    engine the bench loads is conditional end to end."""
    import dataclasses

    from gan_deeplearning4j_tpu.harness import ExperimentConfig
    from gan_deeplearning4j_tpu.models import registry
    from gan_deeplearning4j_tpu.utils import write_model

    cfg = ExperimentConfig(seed=seed)
    family = registry.get("mnist")
    model_cfg = family.make_model_config(cfg)
    dis = family.build_discriminator(model_cfg)
    gen_cfg = model_cfg
    scenario = None
    if classes > 0:
        gen_cfg = dataclasses.replace(
            model_cfg, z_size=model_cfg.z_size + classes)
        from gan_deeplearning4j_tpu.zoo.manifest import ScenarioManifest

        scenario = ScenarioManifest(
            architecture="dcgan", conditioning="class", dataset="mnist",
            resolution=cfg.height, num_classes=classes,
            z_size=model_cfg.z_size,
        ).to_dict()
    gen = family.build_generator(gen_cfg)
    dis_params = dis.init()
    cv, cv_params = family.build_transfer_classifier(dis, dis_params, model_cfg)
    gen_path = os.path.join(directory, "bench_gen_serving.zip")
    cv_path = os.path.join(directory, "bench_CV_serving.zip")
    write_model(gen_path, gen, gen.init(), save_updater=False)
    write_model(cv_path, cv, cv_params, save_updater=False)
    return {
        "generator": gen_path,
        "classifier": cv_path,
        "feature_vertex": list(family.dis_to_cv.values())[-1],
        "z_size": model_cfg.z_size,
        "num_features": cfg.num_features,
        "classes": classes,
        "scenario": scenario,
    }


def _drive(service, kinds, width, sizes, requests, threads, seed,
           timeout=None, classes=0):
    """Closed-loop phase: ``threads`` clients loop submit→wait→submit.
    Returns (statuses, rows_done, elapsed) — one status per request, the
    zero-lost ledger. ``classes > 0`` drives the conditional sample kind:
    the last ``classes`` columns of each sample row are a real one-hot
    label block (random class per row), matching what the HTTP seam's
    ``?class=k`` appends — the padded buckets see the rows a conditional
    deployment would actually serve."""
    statuses = []
    lock = threading.Lock()
    per_thread = requests // threads
    rows_done = [0]

    def worker(widx: int) -> None:
        rng = np.random.default_rng(seed + widx)
        for _ in range(per_thread):
            kind = kinds[rng.integers(len(kinds))]
            n = int(sizes[rng.integers(len(sizes))])
            if kind == "sample" and classes > 0:
                z = rng.random(
                    (n, width[kind] - classes), dtype=np.float32) * 2.0 - 1.0
                onehot = np.eye(classes, dtype=np.float32)[
                    rng.integers(classes, size=n)]
                rows = np.concatenate([z, onehot], axis=1)
            else:
                rows = rng.random((n, width[kind]), dtype=np.float32)
                if kind == "sample":
                    rows = rows * 2.0 - 1.0
            res = service.batcher.submit(kind, rows, timeout=timeout)
            with lock:
                statuses.append(res.status)
                if res.ok:
                    rows_done[0] += res.data.shape[0]

    workers = [
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(threads)
    ]
    t0 = time.perf_counter()
    for t in workers:
        t.start()
    for t in workers:
        t.join()
    return statuses, rows_done[0], time.perf_counter() - t0


def _make_service(engine, args, legacy: bool):
    from gan_deeplearning4j_tpu.serving import InferenceService, MicroBatcher

    if legacy:
        # the PR 3 path, same artifacts/knobs: host-side concat+pad
        # assembly on replica 0 and a strictly serial flush loop
        class _LegacyService:
            def __init__(self, engine):
                self.engine = engine
                self.batcher = MicroBatcher(
                    engine.run_host,
                    max_batch=engine.buckets[-1],
                    max_latency=args.max_latency,
                    max_queue=args.max_queue,
                    default_timeout=args.timeout,
                    pipeline_depth=1,
                )

            def metrics(self):
                return {**self.batcher.metrics(),
                        "compile_counts": self.engine.compile_counts}

            def close(self):
                self.batcher.close()

        return _LegacyService(engine)
    return InferenceService(
        engine,
        max_latency=args.max_latency,
        max_queue=args.max_queue,
        default_timeout=args.timeout,
        warmup=False,  # the bench warms (and times) the engine itself
        pipeline_depth=args.pipeline_depth,
    )


def run_bench(args) -> dict:
    from gan_deeplearning4j_tpu.serving import InferenceService, ServingEngine

    with tempfile.TemporaryDirectory() as tmp:
        bundle = build_bundle(tmp, seed=args.seed)
        engine = ServingEngine.from_checkpoints(
            generator=bundle["generator"],
            classifier=bundle["classifier"],
            buckets=args.buckets,
            feature_vertex=bundle["feature_vertex"],
            replicas=args.replicas,
        )
        t_compile = time.perf_counter()
        engine.warmup()
        compile_s = time.perf_counter() - t_compile
        warm_compiles = engine.compile_counts
        service = _make_service(engine, args, args.legacy)

        width = {"sample": bundle["z_size"],
                 "classify": bundle["num_features"],
                 "features": bundle["num_features"]}
        kinds = list(engine.kinds)
        sizes = [s for s in args.sizes if s <= max(args.buckets)]

        statuses, rows_ok, elapsed = _drive(
            service, kinds, width, sizes, args.requests, args.threads,
            args.seed,
        )
        metrics = service.metrics()
        service.close()
        # snapshot HERE, before the overload/compare phases observe into
        # the same process-wide series: the artifact's telemetry section
        # must agree with its own main-phase latency_ms, not mix in
        # tight-deadline overload traffic
        from gan_deeplearning4j_tpu.telemetry.registry import get_registry

        telemetry_snapshot = get_registry().snapshot()

        # -- overload phase: more clients than queue slots, tight deadlines;
        # every submit must still get exactly one explicit result
        overload = {"requests": 0}
        if args.overload_requests > 0:
            ob = InferenceService(
                engine,
                max_latency=args.max_latency,
                max_queue=args.overload_queue,
                default_timeout=args.overload_timeout,
                warmup=False,
                pipeline_depth=args.pipeline_depth,
            )
            o_statuses, _, o_elapsed = _drive(
                ob, kinds, width, sizes, args.overload_requests,
                args.overload_threads, args.seed + 1000,
            )
            ob.close()
            overload = {
                "requests": (args.overload_threads
                             * (args.overload_requests
                                // args.overload_threads)),
                "returned": len(o_statuses),
                "ok": sum(1 for s in o_statuses if s == "ok"),
                "shed": sum(1 for s in o_statuses
                            if s in ("overloaded", "deadline")),
                "errors": sum(1 for s in o_statuses if s == "error"),
                "elapsed_s": o_elapsed,
                "max_queue": args.overload_queue,
                "timeout_s": args.overload_timeout,
            }

        # -- compare phase: alternate fast-path and legacy (PR 3) rounds in
        # THIS process against the same warm engine — paired rounds cancel
        # the machine noise that cross-process A/B runs soak up
        compare = None
        if args.compare > 0:
            rounds = []
            for _ in range(args.compare):
                row = {}
                for label, legacy in (("fast", False), ("legacy", True)):
                    svc = _make_service(engine, args, legacy)
                    _, rows_n, secs = _drive(
                        svc, kinds, width, sizes, args.requests,
                        args.threads, args.seed,
                    )
                    row[f"{label}_flushes"] = svc.metrics()["flushes"]
                    svc.close()
                    row[label] = rows_n / secs if secs > 0 else 0.0
                row["ratio"] = (row["fast"] / row["legacy"]
                                if row["legacy"] > 0 else 0.0)
                rounds.append(row)
            ratios = sorted(r["ratio"] for r in rounds)
            compare = {
                "rounds": rounds,
                "median_ratio": ratios[len(ratios) // 2],
            }

        serve_compiles = engine.serve_compile_counts
        compile_counts = engine.compile_counts
        max_compiles = engine.expected_max_compiles
        replica_dispatches = engine.stats()["replica_dispatches"]

    submitted = args.threads * (args.requests // args.threads)
    lost = submitted - len(statuses)
    ok = sum(1 for s in statuses if s == "ok")
    shed = sum(1 for s in statuses if s in ("overloaded", "deadline"))
    errors = sum(1 for s in statuses if s == "error")
    o_lost = overload.get("requests", 0) - overload.get("returned", 0)
    summary = {
        "bench": "serve_bench",
        "config": {
            "requests": submitted,
            "threads": args.threads,
            "buckets": list(args.buckets),
            "sizes": sizes,
            "replicas": args.replicas,
            "pipeline_depth": args.pipeline_depth,
            "legacy": bool(args.legacy),
            "max_latency_s": args.max_latency,
            "max_queue": args.max_queue,
            "timeout_s": args.timeout,
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "results": {
            "ok": ok,
            "shed": shed,
            "errors": errors,
            "lost": lost,
            "elapsed_s": elapsed,
            "warmup_compile_s": compile_s,
            "warmup_compile_counts": warm_compiles,
            "throughput_rps": submitted / elapsed if elapsed > 0 else 0.0,
            "throughput_rows_per_s": rows_ok / elapsed if elapsed > 0 else 0.0,
            "latency_ms": metrics["latency_ms"],
            "batch_occupancy": metrics["batch_occupancy"],
            "flushes": metrics["flushes"],
            "pipeline": metrics["pipeline"],
            "compile_counts": compile_counts,
            "serve_compile_counts": serve_compiles,
            "replica_dispatches": replica_dispatches,
        },
        "overload": overload,
        "compare": compare,
        # the same registry series a /metrics?format=prom scrape would
        # have exposed at the end of the MAIN phase — one definition for
        # bench artifacts and live metrics
        "telemetry": telemetry_snapshot,
        "invariants": {
            "zero_lost": lost == 0 and errors == 0,
            "overload_zero_lost": (
                o_lost == 0 and overload.get("errors", 0) == 0),
            "compiles_bounded": all(
                c <= max_compiles for c in compile_counts.values()
            ),
            "no_serve_time_compiles": all(
                c == 0 for c in serve_compiles.values()
            ),
        },
    }
    return summary


def _replay_phase(engine, args, kinds, width, trace_sizes, threads,
                  classes=0):
    """Drive one engine over the trace draws; return its measured side
    of the A/B (waste, latency, compiles, zero-lost ledger)."""
    from gan_deeplearning4j_tpu.serving import InferenceService

    service = InferenceService(
        engine,
        max_latency=args.max_latency,
        max_queue=args.max_queue,
        default_timeout=args.timeout,
        warmup=False,  # the replay warms (and times) the engine itself
        pipeline_depth=args.pipeline_depth,
    )
    statuses, rows_ok, elapsed = _drive(
        service, kinds, width, trace_sizes, len(trace_sizes), threads,
        args.seed, classes=classes,
    )
    metrics = service.metrics()
    stats = engine.stats()
    flush_counts = service.batcher.size_histogram.merged()
    service.close()
    submitted = threads * (len(trace_sizes) // threads)
    wasted = stats["padded_rows_wasted"]
    return flush_counts, {
        "buckets": list(engine.buckets),
        "requests": submitted,
        "ok": sum(1 for s in statuses if s == "ok"),
        "shed": sum(1 for s in statuses if s in ("overloaded", "deadline")),
        "errors": sum(1 for s in statuses if s == "error"),
        "lost": submitted - len(statuses),
        "rows_ok": rows_ok,
        "elapsed_s": elapsed,
        "throughput_rows_per_s": rows_ok / elapsed if elapsed > 0 else 0.0,
        "latency_ms": metrics["latency_ms"],
        "padded_rows_wasted": dict(wasted),
        "padded_rows_wasted_total": sum(wasted.values()),
        "compile_counts": dict(engine.compile_counts),
        "compiles_total": sum(engine.compile_counts.values()),
        "serve_compile_counts": dict(engine.serve_compile_counts),
        "expected_max_compiles": engine.expected_max_compiles,
    }


def run_replay(args) -> dict:
    """Paired heavy-tail replay: learned ladder vs fixed baseline at the
    same compile budget, plus compile-cache warm elasticity.

    The learned ladder is solved from the FLUSH-size histogram the
    baseline pass records — the same signal the reload plane learns from
    a live incumbent — because the engine pads coalesced flushes, not
    individual submits. Both passes replay identical request draws, so
    the waste comparison is paired."""
    import jax

    from gan_deeplearning4j_tpu.serving import (
        ServingEngine,
        expected_waste,
        solve_ladder,
    )

    with open(args.replay) as fh:
        trace = json.load(fh)
    trace_sizes = [int(s) for s in trace.get("sizes", []) if int(s) >= 1]
    if not trace_sizes:
        raise SystemExit(f"replay trace {args.replay} has no sizes")
    # a conditional trace (docs/ZOO.md) declares the class count; the
    # replay then builds a conditional bundle and drives full-width
    # sample rows (latent + one-hot) through the same ladder DP — the
    # learned ladder is solved from conditional-kind traffic
    classes = int((trace.get("conditional") or {}).get("classes", 0))
    threads = args.threads
    if args.smoke:
        trace_sizes = trace_sizes[:96]
        threads = min(threads, 4)

    baseline = tuple(sorted(set(args.replay_baseline)))
    top = baseline[-1]

    # main() enabled the persistent cache BEFORE any jax compile (jax
    # latches a disabled cache at the process's first compile otherwise);
    # the tiny bench models compile in <1s, below the default persist
    # threshold, so the replay lowers it for the elasticity measurement.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    with tempfile.TemporaryDirectory() as tmp:
        bundle = build_bundle(tmp, seed=args.seed, classes=classes)
        width = {"sample": bundle["z_size"] + classes,
                 "classify": bundle["num_features"],
                 "features": bundle["num_features"]}

        def build(buckets):
            return ServingEngine.from_checkpoints(
                generator=bundle["generator"],
                classifier=bundle["classifier"],
                buckets=buckets,
                feature_vertex=bundle["feature_vertex"],
                replicas=args.replicas,
                scenario=bundle["scenario"],
            )

        # -- calibration + baseline measurement: the incumbent-shaped
        # pass. Its cold warmup fills the persistent cache (timed for
        # the elasticity half), and its batcher histogram is the solver
        # input — exactly what a live reload learns from.
        base_engine = build(baseline)
        t0 = time.perf_counter()
        base_engine.warmup()
        cold_s = time.perf_counter() - t0
        kinds = list(base_engine.kinds)
        flush_counts, baseline_phase = _replay_phase(
            base_engine, args, kinds, width, trace_sizes, threads,
            classes=classes)

        learned = solve_ladder(flush_counts, budget=len(baseline), top=top)
        analytic = {
            "baseline_rows": expected_waste(flush_counts, baseline),
            "learned_rows": expected_waste(flush_counts, learned),
        }
        analytic["ratio"] = (
            analytic["learned_rows"] / analytic["baseline_rows"]
            if analytic["baseline_rows"] > 0 else 0.0)

        engine = build(learned)
        engine.warmup()
        _, learned_phase = _replay_phase(
            engine, args, kinds, width, trace_sizes, threads,
            classes=classes)

        # -- elasticity: a fresh engine on the ladder the cold pass
        # compiled re-warms from the persistent cache — the same AOT
        # reuse a restarted fleet worker (scale_up_one, rolling upgrade)
        # gets from a shared --compilation-cache dir.
        jax.clear_caches()  # drop in-memory executables, keep persistent
        warm_engine = build(baseline)
        t0 = time.perf_counter()
        warm_engine.warmup()
        warm_s = time.perf_counter() - t0
        elasticity = {
            "cache_dir": args.compilation_cache,
            "cold_warmup_s": cold_s,
            "warm_warmup_s": warm_s,
            "speedup": cold_s / warm_s if warm_s > 0 else 0.0,
        }

    measured = {
        "baseline_rows": baseline_phase["padded_rows_wasted_total"],
        "learned_rows": learned_phase["padded_rows_wasted_total"],
    }
    measured["ratio"] = (
        measured["learned_rows"] / measured["baseline_rows"]
        if measured["baseline_rows"] > 0 else 0.0)

    summary = {
        "bench": "serve_replay",
        "config": {
            "trace": os.path.relpath(args.replay, _REPO),
            "trace_name": trace.get("name"),
            "requests": len(trace_sizes),
            "distinct_sizes": len(set(trace_sizes)),
            "threads": threads,
            "replicas": args.replicas,
            "conditional_classes": classes,
            "smoke": bool(args.smoke),
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "ladder": {
            "baseline": list(baseline),
            "learned": list(learned),
            "budget": len(baseline),
            "analytic_padded_rows": analytic,
            "solved_from_flush_sizes": {
                str(s): c for s, c in sorted(flush_counts.items())},
        },
        "phases": {
            "baseline": baseline_phase,
            "learned": learned_phase,
        },
        "waste": measured,
        "elasticity": elasticity,
        "invariants": {
            "zero_lost": all(
                p["lost"] == 0 and p["errors"] == 0
                for p in (baseline_phase, learned_phase)),
            "no_serve_time_compiles": all(
                c == 0
                for p in (baseline_phase, learned_phase)
                for c in p["serve_compile_counts"].values()),
            "compiles_bounded": all(
                c <= p["expected_max_compiles"]
                for p in (baseline_phase, learned_phase)
                for c in p["compile_counts"].values()),
            "learned_ladder_within_budget": len(learned) <= len(baseline),
            "learned_waste_not_worse": (
                analytic["learned_rows"] <= analytic["baseline_rows"]
                and measured["learned_rows"] <= measured["baseline_rows"]),
        },
    }
    return summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--threads", type=int, default=8)
    p.add_argument("--buckets", default="1,8,32",
                   type=lambda s: tuple(int(b) for b in s.split(",")))
    p.add_argument("--sizes", default="1,2,5,8,13,32",
                   type=lambda s: [int(b) for b in s.split(",")],
                   help="request batch sizes the generator mixes over")
    p.add_argument("--replicas", type=int, default=1,
                   help="devices to route across (CPU: forces this many "
                        "virtual host devices)")
    p.add_argument("--pipeline-depth", type=int, default=None,
                   help="in-flight flush window (default: 2 per replica)")
    p.add_argument("--legacy", action="store_true",
                   help="measure the PR 3 path: host concat+pad assembly, "
                        "serial flushes, replica 0 only")
    p.add_argument("--compare", type=int, default=0, metavar="ROUNDS",
                   help="after the main phase, alternate ROUNDS of "
                        "fast-path vs legacy rounds in-process and report "
                        "the paired speedup (noise-robust A/B)")
    p.add_argument("--max-latency", type=float, default=0.002)
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--timeout", type=float, default=30.0)
    p.add_argument("--overload-requests", type=int, default=64,
                   help="overload-phase request count (0 disables the phase)")
    p.add_argument("--overload-threads", type=int, default=16)
    p.add_argument("--overload-queue", type=int, default=4)
    p.add_argument("--overload-timeout", type=float, default=0.5)
    p.add_argument("--replay", default=None, metavar="TRACE.json",
                   help="replay a recorded request-size trace: learned "
                        "ladder vs --replay-baseline at the same compile "
                        "budget, plus compile-cache warm elasticity")
    p.add_argument("--replay-baseline", default="1,8,32,128",
                   type=lambda s: tuple(int(b) for b in s.split(",")),
                   help="fixed ladder the learned one is paired against "
                        "(the pre-ISSUE-19 default)")
    p.add_argument("--smoke", action="store_true",
                   help="small fixed shape for CI/campaign gating")
    p.add_argument("--seed", type=int, default=666)
    p.add_argument("--record", default=None, metavar="TAG",
                   help="also write BENCH_serving_<TAG>.json at the repo root")
    p.add_argument("--compilation-cache", default=None, metavar="DIR",
                   help="persistent XLA compile cache dir (restarts reuse "
                        "AOT artifacts)")
    p.add_argument("--telemetry", action="store_true",
                   help="enable span tracing for the bench run (metrics "
                        "are always on)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="write a Chrome trace of the bench run here "
                        "(implies --telemetry; fold it with "
                        "scripts/trace_report.py)")
    p.add_argument("--output", default=os.path.join(_REPO, "artifacts", "serve_bench.json"))
    args = p.parse_args(argv)

    if args.smoke:
        args.requests = min(args.requests, 48)
        args.threads = min(args.threads, 4)
        args.buckets = (1, 8)
        args.sizes = [1, 3, 8]
        args.overload_requests = min(args.overload_requests, 32)
        args.overload_threads = min(args.overload_threads, 8)

    # forcing virtual host devices must happen before jax initializes. The
    # flag only affects the HOST (CPU) platform — on a real TPU the bench
    # routes across the chips that exist and this is inert — so it is safe
    # to set unconditionally (covers CPU-only hosts with JAX_PLATFORMS
    # unset, where jax silently falls back to 1 CPU device).
    if args.replicas > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={args.replicas}"
            ).strip()

    if args.replay and not args.compilation_cache:
        # the replay's elasticity phase needs a persistent cache; it must
        # be enabled HERE, before the first jax compile — jax latches a
        # disabled cache at first compile and ignores later dir changes
        args.compilation_cache = tempfile.mkdtemp(prefix="serve_replay_xla_")

    if args.compilation_cache:
        from gan_deeplearning4j_tpu.runtime.environment import (
            enable_compilation_cache,
        )

        enable_compilation_cache(args.compilation_cache)

    from gan_deeplearning4j_tpu.telemetry.trace import TRACER

    if args.telemetry or args.trace:
        TRACER.enable()

    summary = run_replay(args) if args.replay else run_bench(args)
    if args.trace:
        TRACER.dump(args.trace, {"source": "serve_bench",
                                 "smoke": bool(args.smoke)})
    os.makedirs(os.path.dirname(os.path.abspath(args.output)), exist_ok=True)
    with open(args.output, "w") as fh:
        json.dump(summary, fh, indent=2)
        fh.write("\n")
    if args.record:
        family = "ladder" if args.replay else "serving"
        with open(os.path.join(_REPO, f"BENCH_{family}_{args.record}.json"),
                  "w") as fh:
            json.dump(summary, fh, indent=2)
            fh.write("\n")
    headline = ({"ladder": summary["ladder"], "waste": summary["waste"],
                 "elasticity": summary["elasticity"]}
                if args.replay else summary["results"])
    sys.stdout.write(json.dumps(headline, indent=2) + "\n")
    if summary.get("compare"):
        sys.stdout.write(json.dumps({"compare": summary["compare"]}, indent=2)
                         + "\n")
    bad = [k for k, v in summary["invariants"].items() if not v]
    if bad:
        sys.stderr.write(f"serve_bench: invariants violated: {bad}\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
