#!/usr/bin/env python
"""serve_bench — closed-loop load generator for the serving subsystem.

Builds fresh MNIST-family artifacts (generator + discriminator-feature
classifier), publishes them through the serializer exactly as a training
run would, loads them back through the serving loader, then drives the
in-process service with a mixed workload: every worker thread loops
submit→wait→submit (closed loop) over randomized request kinds and batch
sizes. Writes a BENCH-style JSON artifact with throughput, latency
percentiles, batch-occupancy histogram, shed counts, and the distinct-
compile count — and FAILS (exit 1) if any serving invariant breaks:

- zero lost requests: every submit returns ok or an explicit shed;
- bounded compiles: per-kind XLA compiles ≤ the bucket-ladder size
  (mixed request sizes must ride the padded buckets, never re-compile).

CPU run (the CI shape)::

    JAX_PLATFORMS=cpu python scripts/serve_bench.py \\
        --requests 200 --threads 8 --buckets 1,8,32 \\
        --output artifacts/serve_bench.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def build_bundle(directory: str, seed: int = 666) -> dict:
    """Fresh (untrained) MNIST artifacts through the REAL publish path:
    build graphs, then write serving checkpoints + manifest with
    ``write_model`` — the bench exercises the same loader a trained bundle
    would hit, and weights don't change the serving-layer physics."""
    from gan_deeplearning4j_tpu.harness import ExperimentConfig
    from gan_deeplearning4j_tpu.models import registry
    from gan_deeplearning4j_tpu.utils import write_model

    cfg = ExperimentConfig(seed=seed)
    family = registry.get("mnist")
    model_cfg = family.make_model_config(cfg)
    dis = family.build_discriminator(model_cfg)
    gen = family.build_generator(model_cfg)
    dis_params = dis.init()
    cv, cv_params = family.build_transfer_classifier(dis, dis_params, model_cfg)
    gen_path = os.path.join(directory, "bench_gen_serving.zip")
    cv_path = os.path.join(directory, "bench_CV_serving.zip")
    write_model(gen_path, gen, gen.init(), save_updater=False)
    write_model(cv_path, cv, cv_params, save_updater=False)
    return {
        "generator": gen_path,
        "classifier": cv_path,
        "feature_vertex": list(family.dis_to_cv.values())[-1],
        "z_size": model_cfg.z_size,
        "num_features": cfg.num_features,
    }


def run_bench(args) -> dict:
    from gan_deeplearning4j_tpu.serving import InferenceService, ServingEngine

    with tempfile.TemporaryDirectory() as tmp:
        bundle = build_bundle(tmp, seed=args.seed)
        engine = ServingEngine.from_checkpoints(
            generator=bundle["generator"],
            classifier=bundle["classifier"],
            buckets=args.buckets,
            feature_vertex=bundle["feature_vertex"],
        )
        t_compile = time.perf_counter()
        engine.warmup()
        compile_s = time.perf_counter() - t_compile
        service = InferenceService(
            engine,
            max_latency=args.max_latency,
            max_queue=args.max_queue,
            default_timeout=args.timeout,
            warmup=False,
        )

        width = {"sample": bundle["z_size"],
                 "classify": bundle["num_features"],
                 "features": bundle["num_features"]}
        kinds = list(engine.kinds)
        sizes = [s for s in args.sizes if s <= max(args.buckets)]
        statuses = []  # one entry per request — the zero-lost ledger
        lock = threading.Lock()
        per_thread = args.requests // args.threads
        rows_done = [0]

        def worker(widx: int) -> None:
            rng = np.random.default_rng(args.seed + widx)
            for i in range(per_thread):
                kind = kinds[rng.integers(len(kinds))]
                n = int(sizes[rng.integers(len(sizes))])
                rows = rng.random((n, width[kind]), dtype=np.float32)
                if kind == "sample":
                    rows = rows * 2.0 - 1.0
                res = service.batcher.submit(kind, rows)
                with lock:
                    statuses.append(res.status)
                    if res.ok:
                        rows_done[0] += res.data.shape[0]

        threads = [
            threading.Thread(target=worker, args=(w,), daemon=True)
            for w in range(args.threads)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        metrics = service.metrics()
        service.close()

    submitted = args.threads * per_thread
    lost = submitted - len(statuses)
    ok = sum(1 for s in statuses if s == "ok")
    shed = sum(1 for s in statuses if s in ("overloaded", "deadline"))
    errors = sum(1 for s in statuses if s == "error")
    compile_counts = metrics["compile_counts"]
    summary = {
        "bench": "serve_bench",
        "config": {
            "requests": submitted,
            "threads": args.threads,
            "buckets": list(args.buckets),
            "sizes": sizes,
            "max_latency_s": args.max_latency,
            "max_queue": args.max_queue,
            "timeout_s": args.timeout,
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "results": {
            "ok": ok,
            "shed": shed,
            "errors": errors,
            "lost": lost,
            "elapsed_s": elapsed,
            "warmup_compile_s": compile_s,
            "throughput_rps": submitted / elapsed if elapsed > 0 else 0.0,
            "throughput_rows_per_s": rows_done[0] / elapsed if elapsed > 0 else 0.0,
            "latency_ms": metrics["latency_ms"],
            "batch_occupancy": metrics["batch_occupancy"],
            "flushes": metrics["flushes"],
            "compile_counts": compile_counts,
        },
        "invariants": {
            "zero_lost": lost == 0 and errors == 0,
            "compiles_bounded": all(
                c <= len(args.buckets) for c in compile_counts.values()
            ),
        },
    }
    return summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--threads", type=int, default=8)
    p.add_argument("--buckets", default="1,8,32",
                   type=lambda s: tuple(int(b) for b in s.split(",")))
    p.add_argument("--sizes", default="1,2,5,8,13,32",
                   type=lambda s: [int(b) for b in s.split(",")],
                   help="request batch sizes the generator mixes over")
    p.add_argument("--max-latency", type=float, default=0.002)
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument("--timeout", type=float, default=30.0)
    p.add_argument("--seed", type=int, default=666)
    p.add_argument("--output", default=os.path.join(_REPO, "artifacts", "serve_bench.json"))
    args = p.parse_args(argv)

    summary = run_bench(args)
    os.makedirs(os.path.dirname(os.path.abspath(args.output)), exist_ok=True)
    with open(args.output, "w") as fh:
        json.dump(summary, fh, indent=2)
        fh.write("\n")
    sys.stdout.write(json.dumps(summary["results"], indent=2) + "\n")
    bad = [k for k, v in summary["invariants"].items() if not v]
    if bad:
        sys.stderr.write(f"serve_bench: invariants violated: {bad}\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
