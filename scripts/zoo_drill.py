#!/usr/bin/env python
"""zoo_drill — the model-zoo end-to-end drill (docs/ZOO.md).

One scenario manifest drives everything the zoo promises, in-process:

1. **conditional dcgan-mnist** — ``ScenarioManifest`` → ``experiment_config``
   → a tiny training window fed through the STREAMING input pipeline
   (``zoo/streaming.py`` double buffering behind the iterator contract) →
   ``publish_for_serving`` (the bundle's ``serving.json`` carries the zoo
   block) → a live ``InferenceService``. Every class is then served through
   the conditional sampling kind (``POST /v1/sample?class=k``) and checked
   BIT-EXACT against the engine's un-staged host path on the same
   latent+one-hot rows — per-class staged-vs-host parity. After warmup the
   serve-time compile ledger must stay at zero (the one-hot rides the padded
   buckets; conditioning adds no compile surface), and the error contract
   holds: bare latent-width rows 400 with a pointer to ``?class=``,
   out-of-range classes 400, ``?class=`` on a non-sample kind 400s.
2. **wgan_gp cifar_shaped** — the second trainable architecture: manifest →
   config (power-of-two 32×32×3, the WGAN stage constraint) → one critic
   round through the SAME streaming iterator → publish → the serving loader
   boots it as family ``wgan_gp`` and samples.
3. **mux** — both bundles behind one ``MuxRegistry``/``MuxService``: two
   genuinely DIFFERENT architectures (conditional conv-mnist vs WGAN-GP
   cifar), each priced by ``measure_bundle_cost`` on the ladder it serves
   (measured, not declared — docs/QUANT.md), driven concurrently with
   pinned full-width probes. The exactly-one-answer ledger must hold: every
   request returns ok, none lost, and the two variants' architectures and
   measured costs are distinct.

CPU shapes::

    JAX_PLATFORMS=cpu python scripts/zoo_drill.py --smoke \\
        --output artifacts/zoo_drill_smoke.json

``--record TAG`` additionally writes ``BENCH_zoo_<TAG>.json`` at the repo
root (the ``zoo`` ledger family — scripts/bench_ledger.py gates on it).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def log(msg: str) -> None:
    print(f"[zoo-drill] {msg}", file=sys.stderr, flush=True)


def _streamed_window(dataset: str, batch_size: int, iterations: int,
                     num_classes: int, seed: int):
    """A (K, B, F) training window (+ one-hot labels) pulled through the
    streaming double-buffered iterator — the drill trains through the
    same data plane docs/ZOO.md ships, not a shortcut around it."""
    from gan_deeplearning4j_tpu.zoo.datasets import load_dataset
    from gan_deeplearning4j_tpu.zoo.streaming import (
        StreamingDataSetIterator,
        array_source,
    )

    rows_needed = batch_size * iterations
    (x, y), _ = load_dataset(dataset, num_train=max(rows_needed, 64),
                             num_test=16, seed=seed)
    source, n = array_source(x, y)
    it = StreamingDataSetIterator(source, n, batch_size=batch_size,
                                  shuffle=True, seed=seed, block_batches=2)
    feats, labels = [], []
    while len(feats) < iterations:
        if not it.has_next():
            it.reset()
        batch = it.next()
        f = np.asarray(batch.features)
        if f.shape[0] < batch_size:  # ragged tail: next epoch
            continue
        feats.append(f)
        labels.append(np.eye(num_classes, dtype=np.float32)[
            np.asarray(batch.labels).astype(int)])
    it.close()
    return np.stack(feats), np.stack(labels)


def train_conditional(workdir: str, args) -> tuple:
    """Phase 1a: scenario → streamed tiny train → published zoo bundle."""
    from gan_deeplearning4j_tpu.harness import GanExperiment
    from gan_deeplearning4j_tpu.zoo import ScenarioManifest

    scenario = ScenarioManifest(
        architecture="dcgan", conditioning="class", dataset="mnist",
        resolution=28, num_classes=10, z_size=4)
    cfg = scenario.experiment_config(
        seed=args.seed, batch_size_train=args.batch_size)
    feats, labels = _streamed_window(
        "mnist", cfg.batch_size_train, args.iterations, cfg.num_classes,
        args.seed)
    exp = GanExperiment(cfg)
    t0 = time.perf_counter()
    exp.train_iterations(feats, labels)
    train_s = time.perf_counter() - t0
    bundle_dir = os.path.join(workdir, "bundle_cond_mnist")
    exp.publish_for_serving(bundle_dir)
    with open(os.path.join(bundle_dir, "serving.json")) as fh:
        manifest = json.load(fh)
    log(f"conditional bundle published ({args.iterations} streamed "
        f"iterations, {train_s:.1f}s): zoo={manifest.get('zoo')}")
    return bundle_dir, scenario, {
        "iterations": args.iterations,
        "train_s": train_s,
        "zoo_block": manifest.get("zoo"),
    }


def serve_conditional(bundle_dir: str, args, results: dict,
                      invariants: dict) -> None:
    """Phase 1b: the conditional sampling kind, per-class parity, zero
    serve-time compiles, and the 400 contract."""
    from gan_deeplearning4j_tpu.serving import InferenceService, ServingEngine

    engine = ServingEngine.from_bundle(bundle_dir)
    engine.warmup()
    service = InferenceService(engine, warmup=False)
    classes = engine.class_count
    latent = engine.latent_width("sample")
    rng = np.random.default_rng(args.seed + 7)
    parity = []
    statuses = []
    for k in range(classes):
        z = (rng.random((3, latent), dtype=np.float32) * 2.0 - 1.0)
        status, body = service.handle(
            "POST", f"/v1/sample?class={k}", {"data": z.tolist()})
        statuses.append(status)
        if status != 200:
            parity.append(False)
            continue
        staged = np.asarray(body["data"], dtype=np.float32)
        onehot = np.zeros((3, classes), dtype=np.float32)
        onehot[:, k] = 1.0
        host = engine.run_host(
            "sample", np.concatenate([z, onehot], axis=1))
        parity.append(bool(np.array_equal(staged, np.asarray(host))))
    serve_compiles = dict(engine.serve_compile_counts)
    # the 400 contract: bare latent rows, out-of-range class, class on a
    # non-sample kind
    z = rng.random((2, latent), dtype=np.float32)
    st_bare, body_bare = service.handle(
        "POST", "/v1/sample", {"data": z.tolist()})
    st_range, _ = service.handle(
        "POST", f"/v1/sample?class={classes + 2}", {"data": z.tolist()})
    st_kind, _ = service.handle(
        "POST", "/v1/classify?class=1",
        {"data": np.zeros((1, engine.input_width("classify"))).tolist()})
    service.close()
    results["conditional"] = {
        "classes": classes,
        "latent_width": latent,
        "parity_per_class": parity,
        "parity_classes": sum(parity),
        "serve_compile_counts": serve_compiles,
        "serve_compiles_total": sum(serve_compiles.values()),
        "bare_latent_status": st_bare,
        "out_of_range_status": st_range,
        "class_on_classify_status": st_kind,
    }
    invariants["per_class_parity"] = (
        len(parity) == classes and all(parity)
        and all(s == 200 for s in statuses))
    invariants["zero_serve_time_compiles"] = all(
        c == 0 for c in serve_compiles.values())
    invariants["conditional_error_contract"] = (
        st_bare == 400 and st_range == 400 and st_kind == 400
        and "class" in (body_bare or {}).get("error", ""))
    log(f"conditional serving: parity {sum(parity)}/{classes}, "
        f"serve compiles {serve_compiles}, 400s "
        f"({st_bare}, {st_range}, {st_kind})")


def train_wgan(workdir: str, args) -> tuple:
    """Phase 2: the second architecture — WGAN-GP on cifar_shaped, one
    streamed critic-round window, published and boot-checked."""
    from gan_deeplearning4j_tpu.harness.wgan_experiment import (
        WganGpExperiment,
    )
    from gan_deeplearning4j_tpu.serving import ServingEngine
    from gan_deeplearning4j_tpu.zoo import ScenarioManifest

    scenario = ScenarioManifest(
        architecture="wgan_gp", conditioning="none", dataset="cifar_shaped",
        resolution=32)
    cfg = scenario.experiment_config(
        seed=args.seed + 1, batch_size_train=args.batch_size, n_critic=2)
    feats, _ = _streamed_window(
        "cifar_shaped", cfg.batch_size_train, max(1, args.iterations // 2),
        cfg.num_classes, args.seed + 1)
    exp = WganGpExperiment(cfg)
    t0 = time.perf_counter()
    exp.train_iterations(feats)
    train_s = time.perf_counter() - t0
    bundle_dir = os.path.join(workdir, "bundle_wgan_cifar")
    exp.publish_for_serving(bundle_dir)
    with open(os.path.join(bundle_dir, "serving.json")) as fh:
        manifest = json.load(fh)
    engine = ServingEngine.from_bundle(bundle_dir)
    sample = engine.run_host(
        "sample",
        np.zeros((2, engine.input_width("sample")), dtype=np.float32))
    boots = sample.shape == (2, cfg.num_features)
    log(f"wgan bundle published ({train_s:.1f}s): family "
        f"{manifest.get('family')}, zoo={manifest.get('zoo')}, "
        f"boot sample {sample.shape}")
    return bundle_dir, scenario, {
        "train_s": train_s,
        "family": manifest.get("family"),
        "zoo_block": manifest.get("zoo"),
        "boot_sample_ok": boots,
    }


def run_mux(cond_dir: str, wgan_dir: str, args, results: dict,
            invariants: dict) -> None:
    """Phase 3: two architecture-distinct zoo variants behind one mux,
    measured costs, pinned concurrent load, zero-lost ledger."""
    from gan_deeplearning4j_tpu.quant import measure_bundle_cost
    from gan_deeplearning4j_tpu.serving.mux import MuxRegistry, MuxService
    from gan_deeplearning4j_tpu.zoo import scenario_from_bundle

    ladder = tuple(int(b) for b in args.buckets.split(","))
    # price each variant on the ladder the registry will serve it on (a
    # variable, not a literal at the seam — JG031): both enter measured
    measure_bundle_cost(cond_dir, buckets=ladder, rounds=2)
    measure_bundle_cost(wgan_dir, buckets=ladder, rounds=2)
    registry = MuxRegistry(
        buckets=ladder, budget=2,
        batcher_kwargs={"max_latency": 0.002, "max_queue": 64,
                        "default_timeout": 10.0})
    registry.add("cond_mnist", bundle_path=cond_dir, cost=1.0, weight=0.5)
    registry.add("wgan_cifar", bundle_path=wgan_dir, cost=1.0, weight=0.5)
    registry.ensure_resident("cond_mnist")
    registry.ensure_resident("wgan_cifar")
    svc = MuxService(registry)
    widths = {
        name: registry.engine_for(name).input_width("sample")
        for name in ("cond_mnist", "wgan_cifar")
    }
    classes = registry.engine_for("cond_mnist").class_count

    per_thread = max(1, args.mux_requests // (2 * args.mux_threads))
    counts_lock = threading.Lock()
    counts = {"sent": 0, "ok": 0, "errors": 0, "answered": 0}

    def client(tid: int, name: str) -> None:
        rng = np.random.default_rng(args.seed + 100 + tid)
        for _ in range(per_thread):
            n = int(rng.integers(1, ladder[-1] + 1))
            if name == "cond_mnist":
                z = rng.random(
                    (n, widths[name] - classes), dtype=np.float32) * 2 - 1
                onehot = np.eye(classes, dtype=np.float32)[
                    rng.integers(classes, size=n)]
                rows = np.concatenate([z, onehot], axis=1)
            else:
                rows = rng.random((n, widths[name]), dtype=np.float32) * 2 - 1
            with counts_lock:
                counts["sent"] += 1
            status, body = svc.handle(
                "POST", "/v1/sample",
                {"data": rows.tolist(), "model": name})
            with counts_lock:
                counts["answered"] += 1
                if status == 200 and len(body.get("data", [])) == n:
                    counts["ok"] += 1
                else:
                    counts["errors"] += 1

    threads = [
        threading.Thread(target=client, args=(i, name), daemon=True)
        for i, name in enumerate(
            ["cond_mnist", "wgan_cifar"] * args.mux_threads)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0

    costs = registry.costs()
    sources = registry.cost_sources()
    arch = {
        "cond_mnist": getattr(
            scenario_from_bundle(cond_dir), "architecture", None),
        "wgan_cifar": getattr(
            scenario_from_bundle(wgan_dir), "architecture", None),
    }
    mux_counts = _served_per_model()
    svc.close()
    results["mux"] = {
        "ladder": list(ladder),
        "widths": widths,
        "architectures": arch,
        "costs": costs,
        "cost_sources": sources,
        "sent": counts["sent"],
        "answered": counts["answered"],
        "ok": counts["ok"],
        "errors": counts["errors"],
        "lost": counts["sent"] - counts["answered"],
        "elapsed_s": elapsed,
        "served_per_model": mux_counts,
    }
    invariants["mux_architectures_distinct"] = (
        arch["cond_mnist"] == "dcgan" and arch["wgan_cifar"] == "wgan_gp"
        and widths["cond_mnist"] != widths["wgan_cifar"])
    invariants["mux_costs_measured_and_distinct"] = (
        sources.get("cond_mnist") == "measured"
        and sources.get("wgan_cifar") == "measured"
        and costs["cond_mnist"] != costs["wgan_cifar"])
    invariants["mux_both_variants_serve"] = (
        mux_counts.get("cond_mnist", 0) > 0
        and mux_counts.get("wgan_cifar", 0) > 0)
    invariants["mux_zero_lost"] = (
        counts["sent"] == counts["answered"] == counts["ok"]
        and counts["errors"] == 0)
    log(f"mux: {counts['ok']}/{counts['sent']} ok in {elapsed:.1f}s, "
        f"costs {costs} ({sources}), architectures {arch}")


def _served_per_model() -> dict:
    from gan_deeplearning4j_tpu.telemetry.registry import get_registry

    out: dict = {}
    for s in (get_registry().snapshot()
              .get("mux_requests_total", {}).get("series", [])):
        labels = s.get("labels", {})
        if labels.get("status") == "ok":
            out[labels.get("model")] = (
                out.get(labels.get("model"), 0) + float(s.get("value", 0)))
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--smoke", action="store_true",
                   help="campaign/CI shape: tiny windows, short mux load")
    p.add_argument("--iterations", type=int, default=4,
                   help="conditional training window length K")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--buckets", default="1,8",
                   help="mux ladder (comma ints); variants are priced on it")
    p.add_argument("--mux-requests", type=int, default=96)
    p.add_argument("--mux-threads", type=int, default=3,
                   help="client threads PER VARIANT in the mux phase")
    p.add_argument("--seed", type=int, default=666)
    p.add_argument("--workdir", default=None,
                   help="keep bundles here instead of a temp dir")
    p.add_argument("--output", default=None, metavar="PATH")
    p.add_argument("--record", default=None, metavar="TAG",
                   help="also write BENCH_zoo_<TAG>.json at the repo root")
    args = p.parse_args(argv)

    if args.smoke:
        args.iterations = min(args.iterations, 2)
        args.mux_requests = min(args.mux_requests, 48)
        args.mux_threads = min(args.mux_threads, 2)

    workdir = args.workdir or tempfile.mkdtemp(prefix="zoo_drill_")
    cleanup = args.workdir is None
    os.makedirs(workdir, exist_ok=True)
    results: dict = {}
    invariants: dict = {}
    t_start = time.monotonic()

    cond_dir, _, cond_info = train_conditional(workdir, args)
    results["conditional_train"] = cond_info
    invariants["conditional_bundle_declares_zoo"] = (
        (cond_info["zoo_block"] or {}).get("conditioning") == "class")
    serve_conditional(cond_dir, args, results, invariants)

    wgan_dir, _, wgan_info = train_wgan(workdir, args)
    results["wgan_train"] = wgan_info
    invariants["wgan_bundle_is_wgan_family"] = (
        wgan_info["family"] == "wgan_gp"
        and (wgan_info["zoo_block"] or {}).get("architecture") == "wgan_gp"
        and wgan_info["boot_sample_ok"])

    run_mux(cond_dir, wgan_dir, args, results, invariants)

    ok = all(invariants.values()) and bool(invariants)
    payload = {
        "bench": "zoo_drill",
        "config": {
            "smoke": bool(args.smoke),
            "seed": args.seed,
            "iterations": args.iterations,
            "batch_size": args.batch_size,
            "buckets": args.buckets,
            "mux_requests": args.mux_requests,
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
        },
        "wall_seconds": time.monotonic() - t_start,
        "results": results,
        "invariants": invariants,
        "ok": ok,
    }
    text = json.dumps(payload, indent=2)
    print(text)
    if args.output:
        os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                    exist_ok=True)
        with open(args.output, "w") as fh:
            fh.write(text + "\n")
    if args.record:
        with open(os.path.join(_REPO, f"BENCH_zoo_{args.record}.json"),
                  "w") as fh:
            fh.write(text + "\n")
    if cleanup and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        log(f"INVARIANT BREACH — bundles kept at {workdir}")
    for name, good in sorted(invariants.items()):
        log(f"invariant {name}: {'ok' if good else 'BREACH'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
