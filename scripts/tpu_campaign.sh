#!/bin/bash
# Round-5 on-chip campaign, tunnel-outage-tolerant: waits for the TPU to
# answer, then in priority order
#   (1) bench capture, TWO passes: pass 1 measures against the ROUND-4
#       baselines (ratios land in artifacts/benchmarks_vs_prev.json — the
#       cross-round improvement record) and refreshes BENCH_BASELINES.json
#       at the window-128 protocol via --update-baselines (ADVICE r4 medium:
#       the old baselines were captured at window 32, convolving protocol
#       with performance); pass 2 rides the warm compile cache and writes
#       artifacts/benchmarks.json with clean same-protocol ratios — the
#       repeatability check that replaces round 4's contaminated config-2
#       row (VERDICT r4 item 3).
#   (2) the MFU ceiling calibration (VERDICT r4 item 5),
#   (3) the finished tuning sweep: resumes the 6 completed round-4 grid
#       arms, runs the 3 killed ones + the 4 lever arms (VERDICT r4 item 4),
#   (4) the long quality run, configured by the sweep's winner (selector
#       below picks min final-quick-FID among accuracy >= 0.94 arms).
# Each step validates its artifact and restores the committed state on
# failure (a timeout-killed or CPU-degraded attempt must not clobber
# committed TPU evidence).
cd /root/repo || exit 1
# Preflight (jaxlint v2): the campaign holds the chip exclusively for hours —
# refuse to start it on a tree that fails the static gate tier-1 enforces
# (full-tree mode: the campaign runs committed AND uncommitted code).
if ! bash scripts/lint_gate.sh --full > lint_gate.log 2>&1; then
  echo "$(date +%H:%M:%S) jaxlint gate failed — campaign aborted (see lint_gate.log)" >> tpu_poller.log
  exit 1
fi
# Auditable artifact: the SARIF snapshot of the gate the campaign ran
# under lands next to the BENCH records, so "what did the analyzer say
# about the exact tree that produced these numbers" has a durable answer.
# The same pass snapshots the lifecycle-index stats (paired-resource
# opens/transfers/leaks the JG027-29 rules saw) beside it. This second
# invocation rides the parse cache the gate run above just warmed
# (lint_gate.sh exports JAXLINT_CACHE_DIR), so it costs roughly the
# rules phase, not a second full parse.
mkdir -p artifacts
LINT_FORMAT=sarif bash scripts/lint_gate.sh --full \
  --lifecycle-stats artifacts/lint_lifecycle_stats.json \
  > artifacts/lint_gate.sarif 2>> tpu_poller.log \
  || echo "$(date +%H:%M:%S) sarif artifact emission failed (gate already passed — continuing)" >> tpu_poller.log
# Serving smoke (CPU, small fixed shape): the campaign ships artifacts a
# serving replica must be able to load and serve — refuse to start if the
# serve path regressed (zero-lost / bounded-compile / no-serve-time-compile
# invariants, enforced by serve_bench's own exit code). Pinned to CPU so it
# never touches the chip the campaign is about to hold. The smoke runs with
# span tracing ON and captures a Chrome trace artifact — the telemetry
# plane itself is gated (docs/OBSERVABILITY.md).
if ! JAX_PLATFORMS=cpu timeout 600 python scripts/serve_bench.py --smoke \
    --trace artifacts/serve_bench_smoke_trace.json \
    --output artifacts/serve_bench_smoke.json > serve_bench_smoke.log 2>&1; then
  echo "$(date +%H:%M:%S) serve_bench smoke failed — campaign aborted (see serve_bench_smoke.log)" >> tpu_poller.log
  exit 1
fi
# Trace gate: fold the smoke's Chrome trace into the occupancy report.
# trace_report exits nonzero on a missing, malformed, or span-free trace —
# a telemetry regression that silently stops recording must abort here,
# not be discovered after the chip-hours are spent.
if ! timeout 120 python scripts/trace_report.py \
    artifacts/serve_bench_smoke_trace.json \
    --json artifacts/serve_bench_smoke_trace_report.json \
    > trace_report.log 2>&1; then
  echo "$(date +%H:%M:%S) trace_report gate failed — campaign aborted (see trace_report.log)" >> tpu_poller.log
  exit 1
fi
# Ladder replay smoke (CPU, checked-in heavy-tail trace): the learned
# bucket ladder must keep beating the fixed 1/8/32/128 default on the
# padded-rows objective at the same compile budget, with the zero-lost /
# no-serve-time-compile invariants intact, and the persistent-cache warm
# warmup must still measure (serve_bench --replay exits nonzero on any
# invariant breach — docs/SERVING.md "Learned ladder & warm elasticity").
if ! JAX_PLATFORMS=cpu timeout 600 python scripts/serve_bench.py --smoke \
    --replay scripts/data/heavy_tail_trace.json \
    --output artifacts/serve_replay_smoke.json > serve_replay_smoke.log 2>&1; then
  echo "$(date +%H:%M:%S) ladder replay smoke failed — campaign aborted (see serve_replay_smoke.log)" >> tpu_poller.log
  exit 1
fi
# Model-zoo smoke (CPU, in-process): one scenario manifest must carry a
# conditional dcgan-mnist from streamed training through publish to
# per-class ?class=k parity with zero serve-time compiles, boot a
# WGAN-GP cifar bundle through the same loader, and mux the two
# architecture-distinct variants with measured costs and a zero-lost
# ledger (zoo_drill exits nonzero on any invariant breach — docs/ZOO.md).
if ! JAX_PLATFORMS=cpu timeout 600 python scripts/zoo_drill.py --smoke \
    --output artifacts/zoo_drill_smoke.json > zoo_drill_smoke.log 2>&1; then
  echo "$(date +%H:%M:%S) zoo drill smoke failed — campaign aborted (see zoo_drill_smoke.log)" >> tpu_poller.log
  exit 1
fi
# Resilience smoke (CPU, subprocess kill drill): the campaign's long runs
# survive preemption only if the supervisor/store contract holds — refuse
# to start if bit-exact resume, corruption quarantine, or the relaunch
# budget regressed (enforced by the drill's own exit code). Pinned to CPU
# so it never touches the chip the campaign is about to hold.
if ! JAX_PLATFORMS=cpu timeout 600 python scripts/resilience_drill.py --smoke \
    --output artifacts/resilience_smoke.json > resilience_smoke.log 2>&1; then
  echo "$(date +%H:%M:%S) resilience drill smoke failed — campaign aborted (see resilience_smoke.log)" >> tpu_poller.log
  exit 1
fi
# Multihost resilience smoke (CPU, 2-worker coordinated-checkpoint gang):
# the mesh plane's all-or-nothing commit and elastic reshard-on-restore
# are the multi-worker campaign's crash-safety story — refuse to start if
# a worker kill or a coordinator killed inside the commit window can
# surface a partial generation, or if a 2-written store stops restoring
# bit-exactly on 1- and 2-worker meshes (enforced by the drill's own exit
# code). Pinned to CPU so it never touches the chip.
if ! JAX_PLATFORMS=cpu timeout 900 python scripts/resilience_drill.py --smoke \
    --multihost 2 \
    --output artifacts/resilience_mh_smoke.json > resilience_mh_smoke.log 2>&1; then
  echo "$(date +%H:%M:%S) multihost resilience drill smoke failed — campaign aborted (see resilience_mh_smoke.log)" >> tpu_poller.log
  exit 1
fi
# Update-sharding parity smoke (CPU, forced host devices): the campaign's
# long mesh runs may train with sharded optimizer updates — refuse to
# start if sharded-vs-replicated parity (documented tolerance), the
# ~1/N per-device resident-updater-bytes invariant, or the compute↔
# checkpoint shard mapping regressed (enforced by the bench's own exit
# code). Pinned to CPU so it never touches the chip.
if ! JAX_PLATFORMS=cpu timeout 900 python scripts/update_sharding_bench.py --smoke \
    --output artifacts/update_sharding_smoke.json > update_sharding_smoke.log 2>&1; then
  echo "$(date +%H:%M:%S) update-sharding parity smoke failed — campaign aborted (see update_sharding_smoke.log)" >> tpu_poller.log
  exit 1
fi
# Reload smoke (CPU, subprocess train→serve loop): the campaign's artifacts
# feed a fleet that updates weights while serving — refuse to start if the
# zero-downtime swap, the canary quarantine, or the supervisor's serve-
# publish cadence regressed (>=2 swaps with zero lost/shed, poisoned
# generation quarantined and never served — enforced by the drill's own
# exit code). Pinned to CPU so it never touches the chip.
if ! JAX_PLATFORMS=cpu timeout 900 python scripts/reload_drill.py --smoke \
    --output artifacts/reload_smoke.json > reload_smoke.log 2>&1; then
  echo "$(date +%H:%M:%S) reload drill smoke failed — campaign aborted (see reload_smoke.log)" >> tpu_poller.log
  exit 1
fi
# Fleet smoke (CPU, 2 workers + router, real SIGKILL/SIGSTOP/rolling
# upgrade/poison): the campaign's artifacts feed a multi-process fleet —
# refuse to start if exactly-one-answer, retry-budget bounding, half-open
# re-admission, rolling convergence, or fleet-wide quarantine regressed
# (enforced by the drill's own exit code). Pinned to CPU so it never
# touches the chip.
if ! JAX_PLATFORMS=cpu timeout 1800 python scripts/fleet_drill.py --smoke \
    --output artifacts/fleet_smoke.json \
    --trace-out artifacts/fleet_smoke_trace.json > fleet_smoke.log 2>&1; then
  echo "$(date +%H:%M:%S) fleet drill smoke failed — campaign aborted (see fleet_smoke.log)" >> tpu_poller.log
  exit 1
fi
# The fleet's merged trace must fold: the drill already asserted one
# trace id spans the router + two worker pids; this gate re-runs
# trace_report standalone on the artifact so a regression in the fold
# path itself (not just the drill's inline call) aborts the campaign.
if ! timeout 120 python scripts/trace_report.py \
    artifacts/fleet_smoke_trace.json \
    --json artifacts/fleet_smoke_trace_report.json \
    > fleet_trace_report.log 2>&1; then
  echo "$(date +%H:%M:%S) fleet trace_report gate failed — campaign aborted (see fleet_trace_report.log)" >> tpu_poller.log
  exit 1
fi
# Autoscale smoke (CPU, elastic fleet under a ~10x closed-loop burst):
# the campaign's artifacts feed a fleet that resizes itself — refuse to
# start if the elastic story regressed: grow to max with a mid-resize
# SIGKILL recovered, brownout engaging only at max size, large-slab
# shedding honest, zero lost, bounded p99, drain back to min after
# quiesce (enforced by the drill's own exit code). Pinned to CPU so it
# never touches the chip.
if ! JAX_PLATFORMS=cpu timeout 1500 python scripts/fleet_drill.py --smoke \
    --autoscale \
    --output artifacts/fleet_autoscale_smoke.json > fleet_autoscale_smoke.log 2>&1; then
  echo "$(date +%H:%M:%S) fleet autoscale smoke failed — campaign aborted (see fleet_autoscale_smoke.log)" >> tpu_poller.log
  exit 1
fi
# Mux smoke (CPU, multi-model multiplexing plane, docs/MULTIPLEX.md):
# refuse to start if weighted splitting, the 1%->100% canary ramp with
# SLO auto-rollback, or the per-model brownout shed order regressed —
# two variants behind a 10/90 split with zero lost, an injected burn
# rolling a ramp back before a clean ramp completes, and the expensive
# variant shedding first under synthetic overload (enforced by the
# drill's own exit code). Pinned to CPU so it never touches the chip.
if ! JAX_PLATFORMS=cpu timeout 1200 python scripts/fleet_drill.py --smoke \
    --mux \
    --output artifacts/fleet_mux_smoke.json > fleet_mux_smoke.log 2>&1; then
  echo "$(date +%H:%M:%S) fleet mux smoke failed — campaign aborted (see fleet_mux_smoke.log)" >> tpu_poller.log
  exit 1
fi
# Alerts smoke (CPU, fleet with the alerting plane on): the campaign's
# fleet pages a human when something breaks — refuse to start if the
# fire-and-resolve story regressed: worker_down firing on a real SIGKILL
# with the dead pid labeled and an exemplar trace id resolvable in the
# merged /debug/trace, latency anomaly firing under an overload ramp,
# both resolving after quiesce, zero false fires in the calm audit
# windows, zero-lost ledger (enforced by the drill's own exit code).
# Pinned to CPU so it never touches the chip.
if ! JAX_PLATFORMS=cpu timeout 1500 python scripts/fleet_drill.py --smoke \
    --alerts \
    --output artifacts/fleet_alerts_smoke.json > fleet_alerts_smoke.log 2>&1; then
  echo "$(date +%H:%M:%S) fleet alerts smoke failed — campaign aborted (see fleet_alerts_smoke.log)" >> tpu_poller.log
  exit 1
fi
# Quant smoke (CPU, bf16/int8 variant builders + measured cost,
# docs/QUANT.md): the campaign's mux economics rank by MEASURED cost —
# refuse to start if the variant builders, the cost profiler, or the
# canary admission of a quantized sibling regressed: bf16 resident
# bytes halved, int8 classifier shrunk, bf16 measured scalar below
# fp32, both variants admitted by the real canary gate (enforced by
# the bench's own exit code). Pinned to CPU so it never touches the
# chip; the artifact lands next to the SARIF/lifecycle census so every
# campaign ships the quant economics it ran under.
if ! JAX_PLATFORMS=cpu timeout 900 python scripts/quant_bench.py --smoke \
    --output artifacts/quant_bench_smoke.json > quant_bench_smoke.log 2>&1; then
  echo "$(date +%H:%M:%S) quant bench smoke failed — campaign aborted (see quant_bench_smoke.log)" >> tpu_poller.log
  exit 1
fi
bench_done=0
ceiling_done=0
tune_done=0
quality_done=0
# Hard stop: the TPU is exclusive per process, so this campaign must be GONE
# well before the round-end driver bench needs the chip. Default 8.5 h from
# launch; override with CAMPAIGN_BUDGET_S. A started step may run past the
# deadline by its own timeout at worst — the margin accounts for that.
deadline=$(( $(date +%s) + ${CAMPAIGN_BUDGET_S:-30600} ))
for i in $(seq 1 300); do
  if [ "$(date +%s)" -ge "$deadline" ]; then
    echo "$(date +%H:%M:%S) campaign deadline — exiting (bench=$bench_done ceiling=$ceiling_done tune=$tune_done quality=$quality_done)" >> tpu_poller.log
    exit 0
  fi
  echo "$(date +%H:%M:%S) probe $i" >> tpu_poller.log
  if timeout 150 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1; then
    if [ "$bench_done" -eq 0 ]; then
      echo "$(date +%H:%M:%S) TPU up — bench pass 1 (vs round-4 baselines + refresh)" >> tpu_poller.log
      rm -f artifacts/benchmarks.json artifacts/benchmarks_vs_prev.json
      GDT_BENCH_BUDGET=1800 timeout 1900 python bench.py \
        --json artifacts/benchmarks_vs_prev.json --update-baselines \
        > bench_all.log 2>&1
      rc=$?
      echo "$(date +%H:%M:%S) bench pass 2 (clean window-128 ratios, warm cache)" >> tpu_poller.log
      GDT_BENCH_BUDGET=1200 timeout 1300 python bench.py \
        --json artifacts/benchmarks.json > bench_all2.log 2>&1
      rc2=$?
      if python - <<'EOF' 2>/dev/null
import json, sys
ok = True
for path, need_ratio in (("artifacts/benchmarks_vs_prev.json", False),
                         ("artifacts/benchmarks.json", True)):
    d = json.load(open(path))
    rs = d["results"]
    ok = ok and (not d["degraded"] and len(rs) == 8
                 and all("error" not in r and not r.get("stale")
                         and not r.get("skipped") for r in rs))
    if need_ratio:
        ok = ok and all(r.get("vs_baseline") is not None for r in rs)
sys.exit(0 if ok else 1)
EOF
      then
        bench_done=1
      else
        git checkout -- artifacts/benchmarks.json artifacts/benchmarks_vs_prev.json BENCH_BASELINES.json 2>/dev/null
        git ls-files --error-unmatch artifacts/benchmarks_vs_prev.json >/dev/null 2>&1 || rm -f artifacts/benchmarks_vs_prev.json
        git ls-files --error-unmatch artifacts/benchmarks.json >/dev/null 2>&1 || rm -f artifacts/benchmarks.json
      fi
      echo "$(date +%H:%M:%S) bench rc=$rc/$rc2 done=$bench_done" >> tpu_poller.log
    fi
    if [ "$ceiling_done" -eq 0 ]; then
      echo "$(date +%H:%M:%S) mfu ceiling calibration" >> tpu_poller.log
      rm -f artifacts/mfu_ceiling.json
      timeout 900 python scripts/mfu_ceiling.py > mfu_ceiling.log 2>&1
      rc=$?
      if [ "$rc" -eq 0 ] && python -c "import json,sys; sys.exit(0 if json.load(open('artifacts/mfu_ceiling.json'))['platform']!='cpu' else 1)" 2>/dev/null; then
        ceiling_done=1
      else
        git checkout -- artifacts/mfu_ceiling.json 2>/dev/null
        git ls-files --error-unmatch artifacts/mfu_ceiling.json >/dev/null 2>&1 || rm -f artifacts/mfu_ceiling.json
      fi
      echo "$(date +%H:%M:%S) ceiling rc=$rc done=$ceiling_done" >> tpu_poller.log
    fi
    if [ "$tune_done" -eq 0 ]; then
      echo "$(date +%H:%M:%S) tuning sweep (resume + levers)" >> tpu_poller.log
      rm -f artifacts/tuning_sweep.json
      timeout 3000 python scripts/tune_sweep.py > tune_sweep.log 2>&1
      rc=$?
      if [ "$rc" -eq 0 ] && python -c "
import json,sys
d=json.load(open('artifacts/tuning_sweep.json'))
sys.exit(0 if d['platform']!='cpu' and len(d['arms'])>=13 else 1)" 2>/dev/null; then
        tune_done=1
      else
        rm -f artifacts/tuning_sweep.json
      fi
      echo "$(date +%H:%M:%S) tune rc=$rc done=$tune_done" >> tpu_poller.log
    fi
    if [ "$tune_done" -eq 1 ] && [ "$quality_done" -eq 0 ]; then
      echo "$(date +%H:%M:%S) quality run (sweep-selected levers)" >> tpu_poller.log
      # selector: min final quick FID among arms with accuracy >= 0.94
      # (the round-5 target is final-model quality at >= 96% accuracy);
      # decay cadence is rescaled from the 1200-iteration screen to the
      # 4000-iteration run so the decay-per-progress profile is preserved
      QFLAGS=$(python - <<'EOF' 2>/dev/null
import json
flags = []
try:
    d = json.load(open("artifacts/tuning_sweep.json"))
    arms = [a for a in d["arms"] if a.get("accuracy", 0) >= 0.94]
    arms = arms or d["arms"]
    best = min(arms, key=lambda a: a["final_quick_fid"])
    if best.get("resample_label_noise"):
        flags.append("--resample-label-noise")
    every = int(best.get("dis_lr_decay_every", 0) or 0)
    if every:
        every = max(1, round(every * 4000 / d.get("iterations", 1200)))
        flags += ["--dis-lr-decay-every", str(every),
                  "--dis-lr-decay-rate", str(best.get("dis_lr_decay_rate", 1.0))]
    flags += ["--dis-lr", str(best.get("dis_lr", 0.002)),
              "--gen-lr", str(best.get("gen_lr", 0.004))]
except Exception:
    pass
print(" ".join(flags))
EOF
)
      echo "$(date +%H:%M:%S) selected flags: $QFLAGS" >> tpu_poller.log
      # quality_run.json is written LAST by the script, so its presence with
      # platform=tpu after the run proves THIS attempt completed
      rm -f artifacts/quality_run.json
      timeout 2400 python scripts/quality_run.py --iterations 4000 --batch 200 $QFLAGS > quality_run.log 2>&1
      rc=$?
      if [ "$rc" -eq 0 ] && python -c "import json,sys; sys.exit(0 if json.load(open('artifacts/quality_run.json'))['platform']=='tpu' else 1)" 2>/dev/null; then
        quality_done=1
      else
        # restore the FULL quality output set — but ONLY the quality files:
        # a blanket `git checkout -- artifacts/` would also revert the
        # benchmarks.json the bench step just captured (tracked files back
        # to HEAD; untracked leftovers — model zips, finals, manifolds —
        # removed; git clean never touches tracked benchmarks.json)
        git checkout -- artifacts/quality_run.json artifacts/DCGAN_Generated_Images.png 2>/dev/null
        git clean -fdq -e benchmarks_vs_prev.json -e benchmarks.json -e mfu_ceiling.json -e tuning_sweep.json artifacts/ 2>/dev/null
      fi
      echo "$(date +%H:%M:%S) quality rc=$rc done=$quality_done" >> tpu_poller.log
    fi
    if [ "$bench_done" -eq 1 ] && [ "$ceiling_done" -eq 1 ] && [ "$tune_done" -eq 1 ] && [ "$quality_done" -eq 1 ]; then
      # Post-step: the bench ledger folds every BENCH_*.json into one
      # trend table and exits nonzero when the newest round of any
      # family regressed past its tolerance (or breached a hard bound
      # like lost>0) — the "TPU-measured truth" machine gate: a campaign
      # that quietly made a recorded number worse must fail here, not
      # ship the worse number as the new baseline.
      if ! timeout 120 python scripts/bench_ledger.py \
          --json artifacts/bench_ledger.json > bench_ledger.log 2>&1; then
        echo "$(date +%H:%M:%S) bench ledger gate failed — regression recorded (see bench_ledger.log)" >> tpu_poller.log
        exit 1
      fi
      exit 0
    fi
  fi
  sleep 60
done
echo "$(date +%H:%M:%S) gave up" >> tpu_poller.log
