#!/bin/bash
# Round-3 on-chip campaign, tunnel-outage-tolerant: waits for the TPU to
# answer, then runs the full bench (writing BENCH_BASELINES.json) and the
# long quality run. Safe to re-run; logs to bench_all.log / quality_run.log.
cd /root/repo
for i in $(seq 1 200); do
  echo "$(date +%H:%M:%S) probe $i" >> tpu_poller.log
  if timeout 150 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1; then
    echo "$(date +%H:%M:%S) TPU up — running campaign" >> tpu_poller.log
    python bench.py --config all --json artifacts/benchmarks.json --update-baselines > bench_all.log 2>&1
    echo "$(date +%H:%M:%S) bench rc=$?" >> tpu_poller.log
    python scripts/quality_run.py --iterations 4000 --batch 200 > quality_run.log 2>&1
    echo "$(date +%H:%M:%S) quality rc=$?" >> tpu_poller.log
    exit 0
  fi
  sleep 100
done
echo "$(date +%H:%M:%S) gave up" >> tpu_poller.log
