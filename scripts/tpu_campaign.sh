#!/bin/bash
# Round-4 on-chip campaign, tunnel-outage-tolerant: waits for the TPU to
# answer, then (1) captures all seven bench configs and refreshes
# BENCH_BASELINES.json, (2) re-runs the bench against those baselines so
# artifacts/benchmarks.json carries non-null vs_baseline for every config,
# (3) runs the long quality run. Each step validates its artifact and
# restores the committed state on failure (ADVICE r3: a timeout-killed or
# CPU-degraded attempt must not clobber committed TPU evidence, and the
# restore must cover the FULL output set, not just two files).
cd /root/repo || exit 1
bench_done=0
profile_done=0
quality_done=0
tune_done=0
# Hard stop: the TPU is exclusive per process, so this campaign must be GONE
# well before the round-end driver bench needs the chip. Default 8.5 h from
# launch; override with CAMPAIGN_BUDGET_S. A started step may run past the
# deadline by its own timeout at worst — the margin accounts for that.
deadline=$(( $(date +%s) + ${CAMPAIGN_BUDGET_S:-30600} ))
for i in $(seq 1 300); do
  if [ "$(date +%s)" -ge "$deadline" ]; then
    echo "$(date +%H:%M:%S) campaign deadline — exiting (bench=$bench_done profile=$profile_done quality=$quality_done tune=$tune_done)" >> tpu_poller.log
    exit 0
  fi
  echo "$(date +%H:%M:%S) probe $i" >> tpu_poller.log
  if timeout 150 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1; then
    if [ "$bench_done" -eq 0 ]; then
      echo "$(date +%H:%M:%S) TPU up — bench capture" >> tpu_poller.log
      rm -f artifacts/benchmarks.json  # written fresh; absence after a kill is detectable
      GDT_BENCH_BUDGET=1500 timeout 1600 python bench.py --json artifacts/benchmarks.json > bench_all.log 2>&1
      rc=$?
      # Adopt baselines ONLY for metrics that have none yet (the round-4
      # configs 1b/4b). The round-3 baselines stay untouched so vs_baseline
      # keeps measuring cross-round improvement, not self-comparison.
      python - <<'EOF' 2>/dev/null
import json
try:
    d = json.load(open("artifacts/benchmarks.json"))
    base = json.load(open("BENCH_BASELINES.json"))
except Exception:
    raise SystemExit(0)
if d.get("degraded"):
    raise SystemExit(0)
changed = False
for r in d.get("results", []):
    m = r.get("metric")
    if m and m not in base and "error" not in r and not r.get("stale"):
        base[m] = r["value"]
        changed = True
if changed:
    json.dump(base, open("BENCH_BASELINES.json", "w"), indent=2)
EOF
      # second pass rides the warm compilation cache (~seconds per config)
      # and reads the now-complete baselines -> non-null vs_baseline
      GDT_BENCH_BUDGET=900 timeout 1000 python bench.py --json artifacts/benchmarks.json > bench_all2.log 2>&1
      rc2=$?
      if python - <<'EOF' 2>/dev/null
import json, sys
d = json.load(open("artifacts/benchmarks.json"))
rs = d["results"]
ok = (not d["degraded"]
      and len(rs) == 7
      and all("error" not in r and not r.get("stale") and not r.get("skipped")
              for r in rs)
      and all(r.get("vs_baseline") is not None for r in rs))
sys.exit(0 if ok else 1)
EOF
      then
        bench_done=1
      else
        git checkout -- artifacts/benchmarks.json BENCH_BASELINES.json 2>/dev/null
      fi
      echo "$(date +%H:%M:%S) bench rc=$rc/$rc2 done=$bench_done" >> tpu_poller.log
    fi
    if [ "$profile_done" -eq 0 ]; then
      echo "$(date +%H:%M:%S) wgan profile" >> tpu_poller.log
      rm -f artifacts/profile_wgan.json
      timeout 900 python scripts/profile_wgan.py > profile_wgan.log 2>&1
      rc=$?
      if [ "$rc" -eq 0 ] && python -c "import json,sys; sys.exit(0 if json.load(open('artifacts/profile_wgan.json'))['platform']!='cpu' else 1)" 2>/dev/null; then
        profile_done=1
      else
        git checkout -- artifacts/profile_wgan.json 2>/dev/null
      fi
      echo "$(date +%H:%M:%S) wgan profile rc=$rc done=$profile_done" >> tpu_poller.log
    fi
    if [ "$quality_done" -eq 0 ]; then
      echo "$(date +%H:%M:%S) quality run" >> tpu_poller.log
      # quality_run.json is written LAST by the script, so its presence with
      # platform=tpu after the run proves THIS attempt completed
      rm -f artifacts/quality_run.json
      timeout 2400 python scripts/quality_run.py --iterations 4000 --batch 200 > quality_run.log 2>&1
      rc=$?
      if [ "$rc" -eq 0 ] && python -c "import json,sys; sys.exit(0 if json.load(open('artifacts/quality_run.json'))['platform']=='tpu' else 1)" 2>/dev/null; then
        quality_done=1
      else
        # restore the FULL quality output set — but ONLY the quality files:
        # a blanket `git checkout -- artifacts/` would also revert the
        # benchmarks.json the bench step just captured (tracked files back
        # to HEAD; untracked leftovers — model zips, finals, manifolds —
        # removed; git clean never touches tracked benchmarks.json)
        git checkout -- artifacts/quality_run.json artifacts/DCGAN_Generated_Images.png 2>/dev/null
        git clean -fdq artifacts/ 2>/dev/null
      fi
      echo "$(date +%H:%M:%S) quality rc=$rc done=$quality_done" >> tpu_poller.log
    fi
    if [ "$quality_done" -eq 1 ] && [ "$tune_done" -eq 0 ]; then
      # LAST priority: the LR sweep (round-3 weak #7) only runs once the
      # round's primary artifacts are secured
      echo "$(date +%H:%M:%S) tuning sweep" >> tpu_poller.log
      rm -f artifacts/tuning_sweep.json
      timeout 3000 python scripts/tune_sweep.py > tune_sweep.log 2>&1
      rc=$?
      if [ "$rc" -eq 0 ] && python -c "import json,sys; sys.exit(0 if json.load(open('artifacts/tuning_sweep.json'))['platform']!='cpu' else 1)" 2>/dev/null; then
        tune_done=1
      else
        rm -f artifacts/tuning_sweep.json
      fi
      echo "$(date +%H:%M:%S) tune rc=$rc done=$tune_done" >> tpu_poller.log
    fi
    if [ "$bench_done" -eq 1 ] && [ "$profile_done" -eq 1 ] && [ "$quality_done" -eq 1 ] && [ "$tune_done" -eq 1 ]; then exit 0; fi
  fi
  sleep 60
done
echo "$(date +%H:%M:%S) gave up" >> tpu_poller.log
