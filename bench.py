"""Benchmark harness for the BASELINE.md configs.

Architecture (round-4 hardening — VERDICT r3 item 1): a PARENT process that
never imports jax orchestrates CHILD processes that do all measurement. The
round-3 bench lost the round's deliverable (rc=124, zero output) because the
measuring process itself hung inside native code — backend init through the
axon tunnel can block ``import jax`` for minutes even when the chip is dead,
and Python cannot interrupt a thread stuck in XLA. The parent, being pure
Python + subprocess, can always enforce deadlines with ``kill()``, and a
watchdog thread backstops the whole run with ``os._exit``.

Output protocol: EVERY stdout line the parent prints is a complete,
self-contained summary JSON ``{"metric": ..., "value": N, "unit": ...,
"vs_baseline": R, "results": [...]}`` — one preliminary line at startup
(before any backend touch, marked ``"preliminary": true, "stale": true``),
one refreshed line per config result, one final line. Whatever instant the
process is killed, the LAST stdout line is valid parseable data.

Round-5 hardening (VERDICT r4 item 1): the consumer that matters — the
driver — keeps only a bounded TAIL of stdout (2,000 chars), and rounds 3-4
silently overflowed it by embedding full per-config diagnostics in the
final line (`BENCH_r03/r04.json`: ``parsed: null``, ``tail_len`` pegged at
2000). Stdout lines now carry a COMPACT per-config summary only
(``{config, value, vs_baseline, degraded}`` + short error/skip labels);
every line is enforced < MAX_LINE_CHARS by construction and, should it still
overflow, by explicit tail-row truncation that keeps the line parseable and
records itself in diagnostics (round 6 — the previous bare-assert guard
vanished under ``python -O``, jaxlint JG003). The full diagnostics still
exist — they go to the ``--json`` artifact file.

Round-5 degraded baselines (VERDICT r4 item 2): ``BENCH_BASELINES.json``
gains a ``_platform_baselines.cpu`` namespace (seeded from the round-4
dead-chip drill) so a CPU-fallback run reports a real ``vs_baseline``
against the matching platform+protocol, labeled ``baseline_platform:
"cpu"``, instead of nulling the regression signal for the whole outage
round. Matches the reference running CPU or GPU through one code path with
comparable output either way (dl4jGANComputerVision.java:92,103-113).

Bring-up ladder (capped ~3 min total; round 3's could burn ~19 min): the
first accelerator child's init doubles as the probe — if it reports ready,
the same process proceeds to measure (no double init). If it never comes up,
the parent falls back to a CPU child with the axon boot hook STRIPPED from
the env (the ``sitecustomize`` relay dial hangs even under
``JAX_PLATFORMS=cpu`` when the chip is down — reproduced round 4) running a
CHEAP variant: per-dispatch timing, ~0.5 s windows (XLA:CPU makes scan
programs pathologically slow to build AND run — measured 70-140 s compile,
tens of seconds per call). A child that stalls mid-bench (chip dying
mid-run, round 3's exact failure) is killed and the remaining configs go to
a fresh child while budget remains.

Configs (BASELINE.md): 1 DCGAN-MNIST b64 (headline, incl. bf16
compute/storage variants), 1b DCGAN-MNIST b256 (capacity point, VERDICT r3
item 6), 2 tabular MLP-GAN, 3 CIFAR-10 DCGAN, 4 CelebA-64 data-parallel,
4b CelebA-64 faithful param-averaging device loop (VERDICT r3 item 5),
5 WGAN-GP (scan window 32 since round 4, VERDICT r3 item 4). Default
``--config all`` runs headline-first order 1, 5, 1b, 2, 3, 4, 4b; configs
that no longer fit the budget are reported as skipped with their stale
baseline value instead of silence.

``--update-baselines`` persists measured values into ``BENCH_BASELINES.json``
so later rounds report honest ``vs_baseline`` ratios.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import subprocess
import sys
import threading
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
BASELINES_FILE = os.path.join(_REPO, "BENCH_BASELINES.json")

# Measurement windows (FULL: on an accelerator). Round-2 VERDICT weak #7: a
# fixed 20 iterations is ~0.17 s at TPU speed — inside host-jitter noise.
# The timed loop (a) calibrates the chunk size up until one chunk costs >=
# min_chunk_s, so the device->host sync fence that closes a chunk (~70 ms
# through the axon tunnel, measured round 3) is amortized to noise, then
# (b) accumulates chunks until min_measured_s of work and >= min_chunks
# chunks (so a cross-chunk stddev exists). Iterations inside a chunk stay
# pipelined — no per-iteration sync.
# The full-path device-loop depth, = ExperimentConfig.loss_fetch_every's
# default: the bench measures the run() loop's own steady state (round 4
# raised 32 -> 128; WGAN-GP stays at 32 below — grad-of-grad scan memory).
FULL_WINDOW = 128
FULL_OPTS = {
    "warmup": 3, "timed_iters": 20, "min_chunk_s": 1.0, "min_measured_s": 3.0,
    "min_chunks": 3, "max_chunks": 50, "max_iters_per_chunk": 5000,
    "scan_cap": FULL_WINDOW, "cheap": False,
}
# CHEAP: degraded-CPU fallback. XLA:CPU compiles the per-dispatch fused step
# in ~15 s but a scan program in 70-140 s (and then runs it in tens of
# seconds per call — measured round 4), so the cheap path times the
# per-dispatch step only (scan_cap 1) with tiny windows: labeled data within
# a couple of minutes, same code path family as the real thing.
CHEAP_OPTS = {
    "warmup": 1, "timed_iters": 2, "min_chunk_s": 0.1, "min_measured_s": 0.5,
    "min_chunks": 2, "max_chunks": 6, "max_iters_per_chunk": 50,
    "scan_cap": 1, "cheap": True,
}
# WGAN-GP's device-loop depth (ADVICE r4: named, not a drifting literal).
# Smaller than FULL_WINDOW because each scanned round carries grad-of-grad
# (gradient-penalty) intermediates for n_critic=5 critic minibatches: window
# 128 would hold 4x the live rematerialization state of the DCGAN step and
# was observed to regress throughput; 32 already brought cross-chunk jitter
# from 25.6% to 1.25% (round 4, PROFILE.md).
WGAN_WINDOW = 32

# Hard cap on every stdout line the parent emits. The driver keeps only a
# 2,000-char tail of stdout; a line that outgrows it is unparseable at the
# only point of consumption (the round-3/4 failure mode). 1900 leaves slack
# for a trailing newline and future key growth.
MAX_LINE_CHARS = 1900

# Peak dense-matmul throughput per chip, bf16 (the MFU denominator; MFU is
# reported against the bf16 peak for BOTH compute dtypes — a consistent,
# conservative convention, since f32 work still occupies the same MXU).
PEAK_FLOPS_BY_KIND = [
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
]

# metric name + unit per config, known WITHOUT running anything — the
# preliminary/skip lines are built from this table + the baselines file.
CONFIG_META = {
    "1": ("dcgan_mnist_images_per_sec_per_chip", "images/sec"),
    "1b": ("dcgan_mnist_b256_images_per_sec_per_chip", "images/sec"),
    "2": ("tabular_mlp_gan_rows_per_sec_per_chip", "rows/sec"),
    "2b": ("tabular_mlp_gan_b4096_rows_per_sec_per_chip", "rows/sec"),
    "3": ("dcgan_cifar10_images_per_sec_per_chip", "images/sec"),
    "4": ("dcgan_celeba64_dp_images_per_sec", "images/sec"),
    "4b": ("dcgan_celeba64_param_averaging_images_per_sec", "images/sec"),
    "5": ("wgan_gp_cifar10_images_per_sec_per_chip", "images/sec"),
}
CONFIG_ORDER = ["1", "5", "1b", "2", "2b", "3", "4", "4b"]
HEADLINE = "1"

# sitecustomize in this image dials the TPU relay from EVERY python process
# when these are set — including ones pinned to CPU — and that dial hangs
# when the chip is down; the CPU fallback child must run without them
AXON_BOOT_VARS = (
    "PALLAS_AXON_POOL_IPS", "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE",
    "PALLAS_AXON_REMOTE_COMPILE",
)


def load_baselines() -> dict:
    """Per-metric baselines recorded by a previous round (``None``/absent →
    no baseline yet; vs_baseline is then null, not a fake 1.0). Top-level
    metric keys are the accelerator (TPU) baselines;
    ``_platform_baselines.cpu`` holds the degraded-CPU cheap-protocol
    baselines (VERDICT r4 item 2)."""
    try:
        with open(BASELINES_FILE) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {}


def annotate_vs_baseline(r: dict, baselines: dict, degraded: bool) -> None:
    """Attach ``vs_baseline`` (+ provenance) to one measured result, against
    the baseline namespace matching the platform that produced it. Degraded
    runs compare to ``_platform_baselines.cpu`` — same cheap protocol, same
    shapes — and are labeled ``baseline_platform: "cpu"`` so an outage round
    still carries a regression signal (VERDICT r4 item 2). Accelerator runs
    whose baseline was captured under a different device-loop window get a
    ``baseline_window`` annotation (ADVICE r4: a protocol change must not
    silently masquerade as a performance change)."""
    if degraded:
        base = baselines.get("_platform_baselines", {}).get("cpu", {}) \
                        .get(r["metric"])
        if base:
            r["vs_baseline"] = round(r["value"] / base, 3)
            r["baseline_platform"] = "cpu"
        else:
            r["vs_baseline"] = None
        return
    base = baselines.get(r["metric"])
    if not base:
        r["vs_baseline"] = None
        return
    r["vs_baseline"] = round(r["value"] / base, 3)
    r["baseline_platform"] = "tpu"
    captured = baselines.get("_meta", {}).get("capture_window", {}) \
                        .get(r["metric"])
    effective = r.get("device_loop_window") or 1
    if captured is not None and captured != effective:
        r["baseline_window"] = captured


def merge_baselines(baselines: dict, results) -> dict:
    """The ``--update-baselines`` merge as a pure function. Measured
    accelerator values land at top level with their device-loop window
    stamped into ``_meta.capture_window`` (ADVICE r4: the provenance record
    must not go stale on refresh); measured DEGRADED values land in
    ``_platform_baselines.cpu`` — a CPU number must never overwrite a TPU
    baseline. Stale/errored entries never merge."""
    merged = json.loads(json.dumps(baselines)) if baselines else {}
    for r in results:
        if "metric" not in r or "error" in r or r.get("stale"):
            continue
        if r.get("degraded"):
            merged.setdefault("_platform_baselines", {}) \
                  .setdefault("cpu", {})[r["metric"]] = r["value"]
        else:
            merged[r["metric"]] = r["value"]
            merged.setdefault("_meta", {}).setdefault("capture_window", {})[
                r["metric"]] = r.get("device_loop_window") or 1
    return merged


def _peak_flops(device_kind: str):
    kind = (device_kind or "").lower()
    for key, peak in PEAK_FLOPS_BY_KIND:
        if key in kind:
            return peak
    return None


# ===========================================================================
# child: the only side that imports jax
# ===========================================================================

def _bench_experiment(family: str, batch: int, *, height=28, width=28, channels=1,
                      num_features=None, z_size=2, distributed="none", mesh=None,
                      compute_dtype=None, param_dtype=None, n_critic=5,
                      scan_window=0, opts=FULL_OPTS, deadline=None):
    """Throughput + FLOPs of the full alternating iteration for one family.
    Every family (wgan_gp included) goes through the same harness factory.

    ``scan_window=K>1`` times the DEVICE-LOOP path (``train_iterations``:
    K iterations per dispatch via lax.scan) — the run()-loop's own steady
    state; 0/1 times the per-dispatch path. The effective window is capped
    by ``opts['scan_cap']``. ``deadline`` (epoch seconds) truncates chunk
    accumulation — a truncated result is labeled, not silently short."""
    import jax
    import numpy as np

    from gan_deeplearning4j_tpu.harness import ExperimentConfig, make_experiment

    scan_window = min(scan_window, opts["scan_cap"]) if scan_window else 0
    num_features = num_features or height * width * channels
    cfg = ExperimentConfig(
        model_family=family, batch_size_train=batch, batch_size_pred=batch,
        height=height, width=width, channels=channels, num_features=num_features,
        z_size=z_size, num_iterations=opts["warmup"] + opts["timed_iters"],
        save_models=False, distributed=distributed, compute_dtype=compute_dtype,
        param_dtype=param_dtype, n_critic=n_critic,
    )
    exp = make_experiment(cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    feats = exp.family.synthetic_data(batch, exp.model_cfg, 0)[:batch]
    labels = np.eye(cfg.num_classes, dtype=np.float32)[
        rng.integers(0, cfg.num_classes, size=batch)
    ]
    # Measure with the batch already resident in HBM — the steady state of
    # the real training loop, where DevicePrefetchIterator overlaps the
    # host→device copy with the running step. Feeding numpy per call instead
    # re-uploads the same bytes synchronously every iteration and (on a
    # tunneled chip) measures the link, not the step: ~6.5x slower at
    # batch 64 (round-3 finding — the round-2 "3.8x roofline gap" was
    # exactly this).
    import jax.numpy as jnp

    sharding = getattr(
        getattr(exp, "dis_trainer", None), "batch_sharding", lambda: None
    )()
    if sharding is not None:
        feats = jax.device_put(feats, sharding)
        labels = jax.device_put(labels, sharding)
    else:
        feats = jnp.asarray(feats)
        labels = jnp.asarray(labels)
    jax.block_until_ready([feats, labels])

    iters_per_call = 1
    if scan_window > 1 and getattr(exp, "_supports_device_loop", False):
        iters_per_call = scan_window
        # K distinct windows of the same resident batch, stacked (K, B, …)
        feats = jnp.broadcast_to(feats, (scan_window,) + feats.shape)
        labels = jnp.broadcast_to(labels, (scan_window,) + labels.shape)
        if sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            stacked = NamedSharding(exp.mesh, P(None, "data"))
            feats = jax.device_put(feats, stacked)
            labels = jax.device_put(labels, stacked)
        step = lambda: exp.train_iterations(feats, labels)  # noqa: E731
    else:
        step = lambda: exp.train_iteration(feats, labels)  # noqa: E731

    def sync(losses) -> None:
        # Fetch one loss VALUE to fence the chunk: on the tunneled axon
        # platform block_until_ready returns before execution finishes
        # (measured round 3), so only a device→host read is a true barrier.
        # The losses chain through every iteration, so reading the last
        # one forces the whole chunk.
        np.asarray(next(iter(losses.values())))

    for _ in range(opts["warmup"]):
        losses = step()
    sync(losses)

    def run_chunk(n: int) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            losses = step()
        sync(losses)
        return time.perf_counter() - t0

    def out_of_time() -> bool:
        return deadline is not None and time.time() > deadline

    # calibrate the chunk size (undersized calibration chunks are discarded)
    truncated = False
    chunk_iters = opts["timed_iters"]
    t = run_chunk(chunk_iters)
    while (t < opts["min_chunk_s"] and chunk_iters < opts["max_iters_per_chunk"]
           and not out_of_time()):
        chunk_iters = min(
            opts["max_iters_per_chunk"],
            max(chunk_iters + 1, int(chunk_iters * 1.2 * opts["min_chunk_s"] / t)),
        )
        t = run_chunk(chunk_iters)
    chunk_secs = [t]
    while len(chunk_secs) < opts["max_chunks"] and (
        sum(chunk_secs) < opts["min_measured_s"]
        or len(chunk_secs) < opts["min_chunks"]
    ):
        if out_of_time():
            truncated = True
            break
        chunk_secs.append(run_chunk(chunk_iters))
    elapsed = sum(chunk_secs)
    iters = chunk_iters * len(chunk_secs) * iters_per_call
    per_iter = np.asarray(chunk_secs) / (chunk_iters * iters_per_call)
    try:
        flops = exp.flops_per_iteration(batch)
    except Exception as exc:  # cost model must never sink the measurement
        print(f"# cost analysis failed: {type(exc).__name__}: {exc}", file=sys.stderr)
        flops = None
    return {
        "items_per_sec": iters * batch / elapsed,
        "sec_per_iter": elapsed / iters,
        "sec_per_iter_std": (
            float(per_iter.std(ddof=1)) if len(chunk_secs) > 1 else None
        ),
        "timed_iters": iters,
        "measured_seconds": round(elapsed, 3),
        "device_loop_window": iters_per_call if iters_per_call > 1 else None,
        "flops_per_iter": flops,
        "truncated": truncated or None,
    }


def _with_mfu(measure: dict, diag: dict) -> dict:
    peak = _peak_flops(diag.get("device_kind"))
    mfu = None
    if peak and measure["flops_per_iter"]:
        mfu = measure["flops_per_iter"] / (measure["sec_per_iter"] * peak)
    sec = measure["sec_per_iter"]
    std = measure["sec_per_iter_std"]
    out = {
        "value": measure["items_per_sec"],
        "mfu": round(mfu, 4) if mfu is not None else None,
        "flops_per_iter": measure["flops_per_iter"],
        "sec_per_iter": round(sec, 6),
        "iter_time_jitter": round(std / sec, 4) if std is not None and sec else None,
        "timed_iters": measure["timed_iters"],
        "measured_seconds": measure["measured_seconds"],
        "device_loop_window": measure["device_loop_window"],
    }
    if measure.get("truncated"):
        out["truncated"] = True
    return out


def bench_mnist(diag, opts, deadline):
    """Config 1 + the bf16-vs-f32 delta (VERDICT r1 item 4). Headline value
    is the faster precision through the device loop (this workload is
    HBM-bandwidth-bound, so f32 usually wins on-chip: bf16 adds conversion
    bytes); both precisions AND the per-dispatch path are reported when the
    budget allows — the f32 device-loop number alone is enough to headline,
    so the extra variants are budget-gated, not mandatory."""
    f32 = _bench_experiment("mnist", 64, compute_dtype=None, scan_window=FULL_WINDOW,
                            opts=opts, deadline=deadline)
    best, dtype = f32, "f32"
    extras = {}
    cheap = opts["cheap"]
    if not cheap and not (deadline and time.time() > deadline - 30):
        bf16 = _bench_experiment("mnist", 64, compute_dtype="bf16",
                                 scan_window=FULL_WINDOW, opts=opts, deadline=deadline)
        extras["bf16_images_per_sec"] = round(bf16["items_per_sec"], 2)
        extras["bf16_speedup_vs_f32"] = round(
            bf16["items_per_sec"] / f32["items_per_sec"], 3
        )
        if bf16["items_per_sec"] > f32["items_per_sec"]:
            best, dtype = bf16, "bf16"
    if not cheap and not (deadline and time.time() > deadline - 30):
        # bf16 STORAGE (params + updater state bf16 — round-4 VERDICT item
        # 3): the half-the-HBM-bytes lever for this bandwidth-bound config;
        # compute is bf16 too (pure-bf16, zero casts)
        bf16s = _bench_experiment("mnist", 64, param_dtype="bf16",
                                  compute_dtype="bf16", scan_window=FULL_WINDOW,
                                  opts=opts, deadline=deadline)
        extras["bf16_storage_images_per_sec"] = round(bf16s["items_per_sec"], 2)
        extras["bf16_storage_speedup_vs_f32"] = round(
            bf16s["items_per_sec"] / f32["items_per_sec"], 3
        )
        if bf16s["items_per_sec"] > best["items_per_sec"]:
            best, dtype = bf16s, "bf16_storage"
    if not cheap and not (deadline and time.time() > deadline - 20):
        dispatch = _bench_experiment("mnist", 64, compute_dtype=None,
                                     opts=opts, deadline=deadline)
        extras["per_dispatch_images_per_sec"] = round(dispatch["items_per_sec"], 2)
    out = {"metric": CONFIG_META["1"][0], "unit": CONFIG_META["1"][1],
           "compute_dtype": dtype, **_with_mfu(best, diag)}
    out["f32_images_per_sec"] = round(f32["items_per_sec"], 2)
    out.update(extras)
    return out


def bench_mnist_b256(diag, opts, deadline):
    """Config 1b — the capacity point (VERDICT r3 item 6): batch 256 reaches
    ~28% MFU / ~123k img/s on v5e (PROFILE.md batch sweep); a baselined bench
    config regression-guards it, PROFILE.md alone does not."""
    m = _bench_experiment("mnist", 256, compute_dtype=None, scan_window=FULL_WINDOW,
                          opts=opts, deadline=deadline)
    return {"metric": CONFIG_META["1b"][0], "unit": CONFIG_META["1b"][1],
            "compute_dtype": "f32", **_with_mfu(m, diag)}


def bench_tabular(diag, opts, deadline):
    m = _bench_experiment(
        "tabular", 256, num_features=32, z_size=8, height=1, width=1, channels=1,
        compute_dtype="bf16", scan_window=FULL_WINDOW, opts=opts, deadline=deadline,
    )
    return {"metric": CONFIG_META["2"][0], "unit": CONFIG_META["2"][1],
            "compute_dtype": "bf16", **_with_mfu(m, diag)}


def bench_tabular_b4096(diag, opts, deadline):
    """Config 2b (VERDICT r4 item 6): the tabular MLP-GAN at CAPACITY batch.
    Config 2's batch-256 point is dispatch-bound (2.4% MFU, 65 µs/iter at
    window 32 — artifacts/benchmarks.json); at these tiny layer shapes the
    honest capacity fix is a bigger batch, mirroring the 1→1b treatment.
    Batch 4096 keeps the same feature/latent shapes as config 2 so the two
    rows isolate the batch-size lever.

    Degraded-CPU note (round 6): like WGAN-GP, the real shape only stalls
    on XLA:CPU — batch 4096 per-dispatch steps run seconds each, so a
    cheap-protocol round would time nothing inside its windows. The cheap
    path runs batch 512 at the SAME feature/latent shapes, labeled
    ``cheap_shape``, with a matching ``_platform_baselines.cpu`` seed — so
    an outage round reports a non-null ``vs_baseline`` for 2b instead of
    nulling the capacity row."""
    if opts["cheap"]:
        m = _bench_experiment(
            "tabular", 512, num_features=32, z_size=8, height=1, width=1,
            channels=1, compute_dtype="bf16", scan_window=FULL_WINDOW,
            opts=opts, deadline=deadline,
        )
        return {"metric": CONFIG_META["2b"][0], "unit": CONFIG_META["2b"][1],
                "compute_dtype": "bf16", "cheap_shape": "32f b512",
                **_with_mfu(m, diag)}
    m = _bench_experiment(
        "tabular", 4096, num_features=32, z_size=8, height=1, width=1, channels=1,
        compute_dtype="bf16", scan_window=FULL_WINDOW, opts=opts, deadline=deadline,
    )
    return {"metric": CONFIG_META["2b"][0], "unit": CONFIG_META["2b"][1],
            "compute_dtype": "bf16", **_with_mfu(m, diag)}


def bench_cifar10(diag, opts, deadline):
    m = _bench_experiment(
        "cifar10", 64, height=32, width=32, channels=3, z_size=64,
        compute_dtype="bf16", scan_window=FULL_WINDOW, opts=opts, deadline=deadline,
    )
    return {"metric": CONFIG_META["3"][0], "unit": CONFIG_META["3"][1],
            "compute_dtype": "bf16", **_with_mfu(m, diag)}


def bench_celeba64(diag, opts, deadline):
    """Data-parallel over all visible devices (v5e-8 in the target rig; on a
    single chip this degenerates to a 1-device mesh — still the DP code path)."""
    from gan_deeplearning4j_tpu.runtime import TpuEnvironment

    mesh = TpuEnvironment().make_mesh()
    n = mesh.devices.size
    m = _bench_experiment(
        "celeba64", 8 * n, height=64, width=64, channels=3, z_size=64,
        distributed="pmean", mesh=mesh, compute_dtype="bf16", scan_window=FULL_WINDOW,
        opts=opts, deadline=deadline,
    )
    return {"metric": CONFIG_META["4"][0], "unit": CONFIG_META["4"][1],
            "compute_dtype": "bf16", "devices": n, **_with_mfu(m, diag)}


def bench_celeba64_avg(diag, opts, deadline):
    """Config 4b (round-4 VERDICT item 5): the FAITHFUL parameter-averaging
    mode through its scan device loop (shard_map per-fit averaging rounds,
    ``_build_fused_avg_body``), at config 4's exact shapes — the
    examples/step-matched comparison row against the pmean mode."""
    from gan_deeplearning4j_tpu.runtime import TpuEnvironment

    mesh = TpuEnvironment().make_mesh()
    n = mesh.devices.size
    m = _bench_experiment(
        "celeba64", 8 * n, height=64, width=64, channels=3, z_size=64,
        distributed="param_averaging", mesh=mesh, compute_dtype="bf16",
        scan_window=FULL_WINDOW, opts=opts, deadline=deadline,
    )
    return {"metric": CONFIG_META["4b"][0], "unit": CONFIG_META["4b"][1],
            "compute_dtype": "bf16", "devices": n, **_with_mfu(m, diag)}


def bench_wgan_gp(diag, opts, deadline):
    """Config 5 through the same harness (registry family since round 2).
    320 = 5 critic minibatches of 64; value counts real images consumed.
    Round 4: scan window raised 8 → 32 (VERDICT r3 item 4 — the 25.6%
    cross-chunk jitter at window 8 was dispatch-boundary noise).

    Degraded-CPU note: XLA:CPU needs >400 s just to COMPILE the grad-of-grad
    round at the real shape (measured round 4), so the cheap path runs a tiny
    shape instead, labeled ``cheap_shape`` — it proves the code path and
    yields a number where the real shape would only ever yield a stall."""
    if opts["cheap"]:
        m = _bench_experiment(
            "wgan_gp", 20, height=8, width=8, channels=1, num_features=64,
            z_size=4, compute_dtype="bf16", n_critic=5, scan_window=WGAN_WINDOW,
            opts=opts, deadline=deadline,
        )
        return {"metric": CONFIG_META["5"][0], "unit": CONFIG_META["5"][1],
                "compute_dtype": "bf16", "cheap_shape": "8x8x1 b20",
                **_with_mfu(m, diag)}
    m = _bench_experiment(
        "wgan_gp", 320, height=32, width=32, channels=3, num_features=3072,
        z_size=128, compute_dtype="bf16", n_critic=5, scan_window=WGAN_WINDOW,
        opts=opts, deadline=deadline,
    )
    return {"metric": CONFIG_META["5"][0], "unit": CONFIG_META["5"][1],
            "compute_dtype": "bf16", **_with_mfu(m, diag)}


CONFIGS = {
    "1": bench_mnist,
    "1b": bench_mnist_b256,
    "2": bench_tabular,
    "2b": bench_tabular_b4096,
    "3": bench_cifar10,
    "4": bench_celeba64,
    "4b": bench_celeba64_avg,
    "5": bench_wgan_gp,
}


def _child_emit(obj: dict) -> None:
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def child_main(args) -> None:
    """Measurement side. Protocol on stdout, one JSON object per line:
    ``{"event": "ready", ...diag}`` once the backend is up, then
    ``{"event": "result", ...}`` per config, then ``{"event": "done"}``.
    The parent owns all deadline enforcement — this process may be killed at
    any moment, which is safe because results stream out as they exist."""
    import jax

    devices = jax.devices()
    platform = jax.default_backend()
    diag = {
        "platform": platform,
        "device_kind": devices[0].device_kind if devices else None,
        "devices": len(devices),
        "degraded": platform == "cpu",
    }
    _child_emit({"event": "ready", **diag})
    opts = CHEAP_OPTS if args.opts == "cheap" else FULL_OPTS
    baselines = load_baselines()
    deadline = args.measure_deadline or None
    for k in args.configs.split(","):
        try:
            r = CONFIGS[k](diag, opts, deadline)
        except Exception as exc:
            metric, unit = CONFIG_META[k]
            r = {"metric": metric, "unit": unit,
                 "error": f"{type(exc).__name__}: {exc}"}
        else:
            r["value"] = round(float(r["value"]), 2)
            annotate_vs_baseline(r, baselines, diag["degraded"])
        r.update(config=k, platform=platform,
                 device_kind=diag["device_kind"], degraded=diag["degraded"])
        _child_emit({"event": "result", **r})
    _child_emit({"event": "done"})


# ===========================================================================
# parent: orchestration, reporting, deadline enforcement — jax-free
# ===========================================================================

class Reporter:
    """Holds per-config results and re-emits the whole summary line each time
    anything changes. The headline metric/value/vs_baseline tracks config 1
    (or the first requested config); until it is measured, the stale baseline
    value stands in so a kill at ANY point leaves parseable data."""

    def __init__(self, keys, baselines, json_path, t0):
        self.keys = list(keys)
        self.baselines = baselines
        self.json_path = json_path
        self.t0 = t0
        self.headline_key = HEADLINE if HEADLINE in self.keys else self.keys[0]
        self.results = {}  # key -> result dict
        self.diag = {"platform": None, "device_kind": None, "degraded": True,
                     "attempts": []}
        self.lock = threading.Lock()

    def stale_entry(self, key, reason) -> dict:
        metric, unit = CONFIG_META[key]
        return {
            "config": key, "metric": metric, "unit": unit,
            "value": self.baselines.get(metric), "vs_baseline": None,
            "stale": True, "skipped": reason,
        }

    def set_result(self, key, result) -> None:
        with self.lock:
            self.results[key] = result
        self.emit()

    @staticmethod
    def _compact(res: dict) -> dict:
        """One per-config stdout row: the keys VERDICT r4 item 1 allows —
        identity, value, regression signal, platform honesty — plus SHORT
        error/skip labels. Everything else (mfu, jitter, flops, windows,
        dtype variants) lives only in the ``--json`` artifact; it fattened
        exactly the line that must stay under the driver's tail window."""
        out = {"config": res.get("config"), "value": res.get("value"),
               "vs_baseline": res.get("vs_baseline")}
        if res.get("degraded") is not None:
            out["degraded"] = res["degraded"]
        if res.get("baseline_platform"):
            out["baseline_platform"] = res["baseline_platform"]
        if res.get("stale"):
            out["stale"] = True
        if res.get("skipped"):
            out["skipped"] = str(res["skipped"])[:60]
        if res.get("error"):
            out["error"] = str(res["error"])[:80]
        return out

    def _summary(self, compact: bool) -> dict:
        h = self.results.get(self.headline_key)
        metric, unit = CONFIG_META[self.headline_key]
        out = {"metric": metric, "unit": unit}
        if h is not None and "value" in h and not h.get("stale"):
            out["value"] = h["value"]
            out["vs_baseline"] = h.get("vs_baseline")
            for extra in ("mfu", "compute_dtype", "baseline_platform"):
                if h.get(extra) is not None:
                    out[extra] = h[extra]
        else:
            out.update(value=self.baselines.get(metric), vs_baseline=None,
                       stale=True, preliminary=True)
        out["platform"] = self.diag.get("platform")
        out["device_kind"] = self.diag.get("device_kind")
        out["degraded"] = self.diag.get("degraded", True)
        out["elapsed_seconds"] = round(time.time() - self.t0, 1)
        # every requested config appears exactly once: measured, errored, or
        # a stale placeholder — silence is never an output state
        rows = [self.results.get(k, self.stale_entry(k, "not reached"))
                for k in self.keys]
        out["results"] = [self._compact(r) for r in rows] if compact else rows
        return out

    def _fit_line(self, summary: dict) -> str:
        """The summary as a guaranteed-parseable line under MAX_LINE_CHARS.

        The driver reads a 2,000-char stdout tail; an oversize line is a
        protocol violation that silently voids the round (rounds 3-4). The
        round-5 guard was a bare assert — stripped under ``python -O``
        (jaxlint JG003), i.e. absent exactly when deployed optimized. Now an
        oversize line is REPAIRED: per-config rows are dropped from the tail
        until the line fits (headline fields always survive), the drop is
        visible in the line itself (``results_truncated``) and recorded in
        diagnostics, which reach the ``--json`` artifact on the next write."""
        line = json.dumps(summary)
        if len(line) < MAX_LINE_CHARS:
            return line
        rows = summary.get("results", [])
        dropped = 0
        while rows and len(line) >= MAX_LINE_CHARS:
            rows.pop()
            dropped += 1
            summary["results_truncated"] = dropped
            line = json.dumps(summary)
        if len(line) >= MAX_LINE_CHARS:  # pathological: keep the headline only
            summary = {"metric": summary.get("metric"),
                       "value": summary.get("value"),
                       "vs_baseline": summary.get("vs_baseline"),
                       "results_truncated": dropped}
            line = json.dumps(summary)
        self.diag["stdout_truncation"] = {
            "rows_dropped": dropped, "line_chars": len(line),
            "limit": MAX_LINE_CHARS,
        }
        return line

    def emit(self) -> None:
        with self.lock:
            line = self._fit_line(self._summary(compact=True))
            sys.stdout.write(line + "\n")
            sys.stdout.flush()
            if self.json_path:
                tmp = self.json_path + ".tmp"
                with open(tmp, "w") as fh:
                    json.dump({"diagnostics": self.diag,
                               **self._summary(compact=False)}, fh, indent=2)
                os.replace(tmp, self.json_path)


class HostLock:
    """Single-measurer lockfile (VERDICT r4 weak #5 → item 3). The round-4
    config-2 capture was poisoned 41% by a pytest run sharing the host — the
    tabular config is host-dispatch-bound (65 µs/iter), so host contention
    IS measurement error. The guard was procedural (a playbook rule); this
    makes it mechanical: bench instances exclude each other via a pidfile,
    and a dead owner's lock is stolen (the watchdog's ``os._exit`` skips
    cleanup by design, so staleness must be handled).

    Round-6 TOCTOU hardening: the pid is written to a private temp file
    first and the pidfile only ever appears WITH its content (atomic
    ``os.link`` of the pre-written temp) — the old O_CREAT|O_EXCL-then-write
    had a window where a reader saw an empty pidfile, parsed pid 0, judged
    the owner dead, and stole a live lock. Stealing a stale lock renames it
    ASIDE first — a step exactly one stealer can win (the loser's rename
    raises ENOENT) — then re-races the atomic link; renaming our own file
    over the stale path directly would let two concurrent stealers both
    "acquire". An empty pidfile younger than ``grace_s`` (legacy writer
    mid-write) is treated as HELD, not stale; release verifies the lock
    still carries our pid before unlinking."""

    def __init__(self, path: str, grace_s: float = 10.0):
        self.path = path
        self.grace_s = grace_s
        self.acquired = False

    def acquire(self) -> str | None:
        """None on success, else a short human-readable refusal reason."""
        tmp = f"{self.path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as fh:
                fh.write(str(os.getpid()))
        except OSError as exc:
            return f"lock {self.path}: cannot write pidfile: {exc}"
        try:
            for _ in range(3):  # link -> (steal or re-probe) -> link again
                try:
                    os.link(tmp, self.path)  # atomic create-with-content
                    self.acquired = True
                    return None
                except FileExistsError:
                    pass
                except OSError as exc:  # filesystem without hard links
                    return f"lock {self.path}: {exc}"
                try:
                    st = os.stat(self.path)
                    with open(self.path) as fh:
                        raw = fh.read().strip()
                except OSError:
                    continue  # vanished between link and read — retry
                if not raw:
                    if time.time() - st.st_mtime < self.grace_s:
                        return (f"lock {self.path} held (pidfile still being "
                                f"written, age < {self.grace_s:.0f}s)")
                    pid = 0
                else:
                    try:
                        pid = int(raw)
                    except ValueError:
                        pid = 0
                if pid and _pid_alive(pid):
                    return f"lock {self.path} held by live pid {pid}"
                # stale: move it aside — the one step a single stealer wins
                # (see class docstring) — then re-race the link above
                grave = f"{self.path}.stale.{os.getpid()}"
                try:
                    os.rename(self.path, grave)
                except OSError:
                    continue  # another stealer won; re-probe the fresh lock
                try:
                    os.unlink(grave)
                except OSError:
                    pass
            return f"lock {self.path} could not be acquired"
        finally:
            try:  # gone already when acquisition went through rename
                os.unlink(tmp)
            except OSError:
                pass

    def release(self) -> None:
        if not self.acquired:
            return
        self.acquired = False
        try:
            with open(self.path) as fh:
                if fh.read().strip() != str(os.getpid()):
                    return  # stolen from us (we were judged dead) — not ours
        except OSError:
            return
        try:
            os.unlink(self.path)
        except OSError:
            pass


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


def host_load_status(max_load: float) -> dict | None:
    """1-minute load average vs the busy threshold. The threshold defaults
    LOW (1.0): a single concurrent pytest run — load ~1 — was enough to
    poison the dispatch-bound config by 41% (round 4)."""
    try:
        load1 = os.getloadavg()[0]
    except (OSError, AttributeError):
        return None
    return {"load1": round(load1, 2), "max_load": max_load,
            "busy": load1 > max_load}


def arm_watchdog(deadline: float) -> None:
    """Backstop wall-budget enforcement (the parent is pure Python, but a
    pathological child-pipe state or filesystem stall must still not blow the
    driver budget). Results are flushed the moment they exist, so the exit
    loses nothing."""

    def fire():
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                break
            time.sleep(min(remaining, 2.0))
        print("# wall budget exhausted — exiting with the data flushed so far",
              file=sys.stderr)
        sys.stderr.flush()
        os._exit(0)

    threading.Thread(target=fire, daemon=True, name="bench-watchdog").start()


class Child:
    """One measurement subprocess + a reader thread feeding a line queue."""

    def __init__(self, keys, mode: str, cpu: bool, measure_deadline: float):
        env = dict(os.environ)
        if cpu:
            for var in AXON_BOOT_VARS:
                env.pop(var, None)
            env["JAX_PLATFORMS"] = "cpu"
        cmd = [
            sys.executable, "-u", os.path.abspath(__file__), "--child",
            "--configs", ",".join(keys), "--opts", mode,
            "--measure-deadline", str(measure_deadline),
        ]
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=sys.stderr, text=True,
            env=env, cwd=_REPO,
        )
        self.q: "queue.Queue" = queue.Queue()
        threading.Thread(target=self._read, daemon=True).start()

    def _read(self):
        try:
            for line in self.proc.stdout:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    self.q.put(json.loads(line))
                except ValueError:
                    pass
        except Exception:
            pass
        self.q.put({"event": "eof"})

    def next_event(self, timeout: float):
        """Next protocol event, or None on timeout."""
        try:
            return self.q.get(timeout=max(0.0, timeout))
        except queue.Empty:
            return None

    def kill(self):
        try:
            self.proc.kill()
            self.proc.wait(timeout=5)
        except Exception:
            pass


def run_child(keys, mode, cpu, ready_timeout, per_config_timeout, reporter,
              measure_deadline):
    """Drive one child over ``keys``. Returns (status, remaining_keys):
    status ∈ {ok, no_ready, stalled, child_exit}. On ``stalled`` the FIRST
    remaining key is the one that hung (parent marks it; a fresh child can
    try the rest)."""
    label = "cpu" if cpu else "accel"
    print(f"# spawning {label} child for configs {','.join(keys)}", file=sys.stderr)
    sys.stderr.flush()
    child = Child(keys, mode, cpu, measure_deadline)
    t0 = time.time()
    ev = child.next_event(ready_timeout)
    if ev is None or ev.get("event") != "ready":
        child.kill()
        status = "no_ready" if ev is None else "child_exit"
        reporter.diag["attempts"].append({
            "child": label, "ok": False, "seconds": round(time.time() - t0, 1),
            "error": f"{status} within {ready_timeout:.0f}s",
        })
        print(f"# {label} child: {status} after {time.time() - t0:.0f}s",
              file=sys.stderr)
        sys.stderr.flush()
        return status, list(keys)
    diag = {k: v for k, v in ev.items() if k != "event"}
    reporter.diag["attempts"].append({
        "child": label, "ok": True, "seconds": round(time.time() - t0, 1),
        "platform": diag.get("platform"),
    })
    if not cpu and diag.get("platform") == "cpu":
        # the TPU plugin errored FAST instead of hanging and jax fell back
        # to CPU inside the "accelerator" child — running FULL_OPTS on
        # XLA:CPU would serially stall every config (70-140 s compiles);
        # hand the whole list to the cheap CPU phase instead
        child.kill()
        print("# accel child came up on CPU — routing to cheap CPU fallback",
              file=sys.stderr)
        sys.stderr.flush()
        return "came_up_cpu", list(keys)
    reporter.diag.update(diag)
    reporter.emit()
    pending = list(keys)
    while pending:
        budget = min(per_config_timeout, measure_deadline + 30 - time.time())
        ev = child.next_event(budget)
        if ev is None:
            child.kill()
            return "stalled", pending
        if ev.get("event") == "result":
            ev.pop("event")
            k = ev.setdefault("config", pending[0])
            reporter.set_result(k, ev)
            if k in pending:
                pending.remove(k)
        elif ev.get("event") in ("done", "eof"):
            if pending:
                return "child_exit", pending
            break
    child.kill()  # reap; harmless if already exited
    return "ok", []


def parent_main(args) -> None:
    t0 = time.time()
    keys = [k for k in CONFIG_ORDER if args.config in ("all", k)]
    baselines = load_baselines()
    reporter = Reporter(keys, baselines, args.json, t0)
    # 1) preliminary line BEFORE any backend touch: a kill can never again
    #    mean zero data (round 3: rc=124, parsed=null)
    reporter.emit()
    # 2) quiet-host guard (VERDICT r4 item 3): measurement on a contended
    #    host is not a measurement. warn (default) records + proceeds;
    #    require aborts with the refusal in the still-parseable output.
    lock = HostLock(args.lock_file) if args.lock_file else None
    # the host lock is paired with the release in the finally:
    # every exit — the require-mode SystemExit, a mid-run error,
    # the normal path — gives the lock back exactly once
    try:
        if args.quiet_host != "off":
            problems = []
            if lock is not None:
                err = lock.acquire()
                if err:
                    problems.append(err)
            load = host_load_status(args.max_load)
            if load is not None:
                reporter.diag["host_load"] = load
                if load["busy"]:
                    problems.append(
                        f"load1 {load['load1']} > max_load {load['max_load']}")
            if problems:
                reporter.diag["quiet_host"] = {"mode": args.quiet_host,
                                               "problems": problems}
                for msg in problems:
                    print(f"# quiet-host ({args.quiet_host}): {msg}", file=sys.stderr)
                sys.stderr.flush()
                if args.quiet_host == "require":
                    for k in keys:
                        reporter.set_result(
                            k, reporter.stale_entry(k, "host not quiet"))
                    raise SystemExit(3)
        # 3) hard wall budget; 8 s reserve so the final flush always lands
        deadline = t0 + args.budget
        arm_watchdog(deadline - 8)
        measure_deadline = deadline - 15

        pending = list(keys)
        env_pin = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip().lower()
        if env_pin == "cpu":
            # deliberate CPU pin: the accelerator phase cannot succeed, skip it
            reporter.diag["attempts"].append(
                {"skipped_accelerator": "JAX_PLATFORMS=cpu pinned in env"})
            accel_done = False
        else:
            # bring-up ladder: the child's init IS the probe (ready event). Two
            # attempts, ~3 min cap total (VERDICT r3: the old ladder burned ~19
            # min before a byte of output).
            accel_done = False
            ladder_deadline = t0 + min(180.0, 0.35 * args.budget)
            attempt = 0
            while pending and time.time() < measure_deadline - 30:
                attempt += 1
                ready_budget = min(args.probe_timeout * attempt,
                                   ladder_deadline - time.time())
                if not accel_done and ready_budget < 15:
                    break  # ladder exhausted without ever reaching ready
                per_cfg = 240.0 if "1" in pending else 150.0
                status, pending = run_child(
                    pending, "full", False,
                    ready_budget if not accel_done else 120.0,
                    per_cfg, reporter, measure_deadline,
                )
                if status == "ok":
                    accel_done = True
                    break
                if status == "came_up_cpu":
                    break  # plugin errored fast, jax fell back — cheap CPU phase
                if status == "stalled":
                    # the chip died mid-config (round 3's exact failure): label
                    # the hung config, keep going with a fresh child — its init
                    # doubles as the is-it-still-alive re-probe
                    accel_done = True  # we DID reach the accelerator once
                    k = pending.pop(0)
                    e = reporter.stale_entry(k, "stalled on accelerator")
                    reporter.set_result(k, e)
                    continue
                if status in ("no_ready", "child_exit") and accel_done:
                    break  # accelerator came up once, now gone — fall to CPU
                # never came up: retry within the ladder, else give up
                if time.time() >= ladder_deadline - 15:
                    break

        if pending and time.time() < measure_deadline - 20:
            # CPU fallback for whatever the accelerator never measured — cheap
            # variant, axon boot hook stripped (its relay dial hangs when the
            # chip is down, even under JAX_PLATFORMS=cpu)
            restarts = 0
            while pending and time.time() < measure_deadline - 20 and restarts < 4:
                restarts += 1
                status, pending = run_child(
                    pending, "cheap", True, 90.0, 150.0, reporter, measure_deadline,
                )
                if status == "ok":
                    break
                if status == "stalled" and pending:
                    # only a config that was actually IN FLIGHT gets blamed; a
                    # no_ready/child_exit spawn failure just retries the same
                    # list (bounded by the restarts counter)
                    k = pending.pop(0)
                    reporter.set_result(
                        k, reporter.stale_entry(k, "cpu fallback stalled"))
        for k in pending:
            reporter.set_result(k, reporter.stale_entry(
                k, f"budget: {deadline - time.time():.0f}s left"))

        if args.update_baselines:
            merged = merge_baselines(baselines, reporter.results.values())
            if merged != baselines:
                with open(BASELINES_FILE, "w") as fh:
                    json.dump(merged, fh, indent=2)
                print(f"# baselines updated: {BASELINES_FILE}", file=sys.stderr)
    finally:
        if lock is not None:
            lock.release()
    reporter.emit()
    if any("error" in r for r in reporter.results.values()):
        raise SystemExit(1)


def main() -> None:
    p = argparse.ArgumentParser(description="BASELINE.md bench harness")
    p.add_argument("--config", default="all", choices=[*CONFIGS, "all"],
                   help="BASELINE config number (default: all, headline-first "
                        "order, budget-gated)")
    p.add_argument("--json", default=None, help="also write full results here")
    p.add_argument("--update-baselines", action="store_true",
                   help=f"record measured values into {os.path.basename(BASELINES_FILE)}")
    p.add_argument("--budget", type=float,
                   default=float(os.environ.get("GDT_BENCH_BUDGET", 480.0)),
                   help="hard wall budget in seconds — the process EXITS (with "
                        "the data flushed so far) when it expires")
    p.add_argument("--probe-timeout", type=float, default=80.0,
                   help="seconds allowed for the first accelerator child to "
                        "report ready (doubles on the retry, capped by the "
                        "~3 min ladder budget)")
    p.add_argument("--quiet-host", default="warn",
                   choices=["warn", "require", "off"],
                   help="host-contention guard: warn (default) records "
                        "contention in diagnostics and proceeds; require "
                        "refuses to measure (exit 3) on a busy host or held "
                        "lock; off skips lock and load check entirely")
    p.add_argument("--lock-file", default="/tmp/gdt_bench.lock",
                   help="single-measurer pidfile ('' disables); a dead "
                        "owner's lock is stolen automatically")
    p.add_argument("--max-load", type=float,
                   default=float(os.environ.get("GDT_BENCH_MAX_LOAD", 1.0)),
                   help="1-min load average above which the host counts as "
                        "busy (round 4: one concurrent pytest run — load ~1 "
                        "— poisoned the dispatch-bound config by 41%%)")
    # child-mode internals
    p.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--configs", default="", help=argparse.SUPPRESS)
    p.add_argument("--opts", default="full", choices=["full", "cheap"],
                   help=argparse.SUPPRESS)
    p.add_argument("--measure-deadline", type=float, default=0.0,
                   help=argparse.SUPPRESS)
    args = p.parse_args()
    if args.child:
        child_main(args)
    else:
        parent_main(args)


if __name__ == "__main__":
    main()
