"""Benchmark harness for the five BASELINE.md configs.

Default (what the driver runs): config 1 — DCGAN-MNIST alternating-loop
throughput at batch 64 (the reference topology,
dl4jGANComputerVision.java:117-314) — printed as ONE JSON line.

``--config N|all`` runs the other configs (tabular MLP-GAN, CIFAR-10 DCGAN,
CelebA-64 data-parallel, WGAN-GP); ``--json benchmarks.json`` also writes the
full result list. The reference publishes no numbers (BASELINE.md), so these
runs *establish* the baseline; vs_baseline reports against the recorded
targets below once they exist."""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

# First recorded real-TPU numbers per config become the baselines to beat.
BASELINES = {
    "dcgan_mnist_images_per_sec_per_chip": None,
    "tabular_mlp_gan_rows_per_sec_per_chip": None,
    "dcgan_cifar10_images_per_sec_per_chip": None,
    "dcgan_celeba64_dp_images_per_sec": None,
    "wgan_gp_cifar10_images_per_sec_per_chip": None,
}

WARMUP_ITERS = 3
TIMED_ITERS = 20


def _bench_experiment(family: str, batch: int, *, height=28, width=28, channels=1,
                      num_features=None, z_size=2, distributed="none", mesh=None):
    """Throughput of the full alternating iteration for one GAN family."""
    import jax

    from gan_deeplearning4j_tpu.harness.config import ExperimentConfig
    from gan_deeplearning4j_tpu.harness.experiment import GanExperiment

    num_features = num_features or height * width * channels
    cfg = ExperimentConfig(
        model_family=family, batch_size_train=batch, batch_size_pred=batch,
        height=height, width=width, channels=channels, num_features=num_features,
        z_size=z_size, num_iterations=WARMUP_ITERS + TIMED_ITERS,
        save_models=False, distributed=distributed,
    )
    exp = GanExperiment(cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    feats = exp.family.synthetic_data(batch, exp.model_cfg, 0)[:batch]
    labels = np.eye(cfg.num_classes, dtype=np.float32)[
        rng.integers(0, cfg.num_classes, size=batch)
    ]
    for _ in range(WARMUP_ITERS):
        losses = exp.train_iteration(feats, labels)
    jax.block_until_ready(losses)
    t0 = time.perf_counter()
    for _ in range(TIMED_ITERS):
        losses = exp.train_iteration(feats, labels)
    jax.block_until_ready(losses)
    return TIMED_ITERS * batch / (time.perf_counter() - t0)


def bench_mnist():
    return {
        "metric": "dcgan_mnist_images_per_sec_per_chip",
        "value": _bench_experiment("mnist", 64),
        "unit": "images/sec",
    }


def bench_tabular():
    return {
        "metric": "tabular_mlp_gan_rows_per_sec_per_chip",
        "value": _bench_experiment(
            "tabular", 256, num_features=32, z_size=8, height=1, width=1, channels=1
        ),
        "unit": "rows/sec",
    }


def bench_cifar10():
    return {
        "metric": "dcgan_cifar10_images_per_sec_per_chip",
        "value": _bench_experiment(
            "cifar10", 64, height=32, width=32, channels=3, z_size=64
        ),
        "unit": "images/sec",
    }


def bench_celeba64():
    """Data-parallel over all visible devices (v5e-8 in the target rig; on a
    single chip this degenerates to a 1-device mesh — still the DP code path)."""
    from gan_deeplearning4j_tpu.runtime import TpuEnvironment

    mesh = TpuEnvironment().make_mesh()
    n = mesh.devices.size
    return {
        "metric": "dcgan_celeba64_dp_images_per_sec",
        "value": _bench_experiment(
            "celeba64", 8 * n, height=64, width=64, channels=3, z_size=64,
            distributed="pmean", mesh=mesh,
        ),
        "unit": "images/sec",
        "devices": n,
    }


def bench_wgan_gp():
    import jax

    from gan_deeplearning4j_tpu.models import wgan_gp

    cfg = wgan_gp.WganGpConfig()
    tr = wgan_gp.WganGpTrainer(cfg)
    critic_state, gen_state = tr.init_states(seed=0)
    batch = 64
    rng = np.random.default_rng(0)
    real = rng.random((cfg.n_critic, batch, cfg.num_features), dtype=np.float32)
    key = jax.random.PRNGKey(0)
    for _ in range(WARMUP_ITERS):
        key, sub = jax.random.split(key)
        critic_state, gen_state, c_loss, _ = tr.train_round(critic_state, gen_state, real, sub)
    jax.block_until_ready(c_loss)
    t0 = time.perf_counter()
    for _ in range(TIMED_ITERS):
        key, sub = jax.random.split(key)
        critic_state, gen_state, c_loss, _ = tr.train_round(critic_state, gen_state, real, sub)
    jax.block_until_ready(c_loss)
    # images/sec counts every critic batch + the generator batch
    per_round = (cfg.n_critic + 1) * batch
    return {
        "metric": "wgan_gp_cifar10_images_per_sec_per_chip",
        "value": TIMED_ITERS * per_round / (time.perf_counter() - t0),
        "unit": "images/sec",
    }


CONFIGS = {
    "1": bench_mnist,
    "2": bench_tabular,
    "3": bench_cifar10,
    "4": bench_celeba64,
    "5": bench_wgan_gp,
}


def main() -> None:
    p = argparse.ArgumentParser(description="BASELINE.md bench harness")
    p.add_argument("--config", default="1", choices=[*CONFIGS, "all"],
                   help="BASELINE config number (default 1: DCGAN MNIST)")
    p.add_argument("--json", default=None, help="also write full results here")
    args = p.parse_args()

    keys = list(CONFIGS) if args.config == "all" else [args.config]
    results = []
    failed = False
    for k in keys:
        try:
            r = CONFIGS[k]()
        except Exception as exc:  # keep earlier (expensive) results on failure
            print(json.dumps({"config": k, "error": f"{type(exc).__name__}: {exc}"}))
            failed = True
            continue
        base = BASELINES.get(r["metric"])
        r["value"] = round(float(r["value"]), 2)
        r["vs_baseline"] = round(r["value"] / base, 3) if base else 1.0
        results.append(r)
        print(json.dumps(r))
        if args.json:  # flush after every config, not only at the end
            with open(args.json, "w") as fh:
                json.dump(results, fh, indent=2)
    if failed:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
