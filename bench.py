"""Benchmark harness for the five BASELINE.md configs.

Default (what the driver runs): config 1 — DCGAN-MNIST alternating-loop
throughput at batch 64 (the reference topology,
dl4jGANComputerVision.java:117-314) — printed as ONE JSON line carrying
images/sec, MFU, and the bf16-vs-f32 delta.

``--config N|all`` runs the other configs (tabular MLP-GAN, CIFAR-10 DCGAN,
CelebA-64 data-parallel, WGAN-GP); ``--json benchmarks.json`` also writes the
full result list; ``--update-baselines`` persists measured values into
``BENCH_BASELINES.json`` so later rounds report honest ``vs_baseline`` ratios.

Backend bring-up is hardened against the round-1 failure (the TPU PJRT
plugin hanging or erroring at init): the backend is first probed in a
SUBPROCESS with a timeout, retried with backoff, and on exhaustion the bench
falls back to CPU with every result line marked ``"degraded": true`` and the
probe log attached — a dead chip yields labeled data + diagnostics instead
of rc=1 and nothing (VERDICT r1 weak #1).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))
BASELINES_FILE = os.path.join(_REPO, "BENCH_BASELINES.json")

WARMUP_ITERS = 3
TIMED_ITERS = 20  # starting chunk size AND the per-chunk iteration floor
# Round-2 VERDICT weak #7: a fixed 20 iterations is ~0.17 s at TPU speed —
# inside host-jitter noise. The timed loop therefore (a) calibrates the
# chunk size up until one chunk costs >= MIN_CHUNK_SECONDS, so the
# device→host sync fence that closes a chunk (~70 ms through the axon
# tunnel, measured round 3) is amortized to noise, then (b) accumulates
# chunks until MIN_MEASURED_SECONDS of work (and >= MIN_CHUNKS chunks, so a
# cross-chunk stddev exists). Iterations inside a chunk stay pipelined — no
# per-iteration sync.
MIN_CHUNK_SECONDS = 1.0
MIN_MEASURED_SECONDS = 3.0
MIN_CHUNKS = 3
MAX_CHUNKS = 50
MAX_ITERS_PER_CHUNK = 5000

# Peak dense-matmul throughput per chip, bf16 (the MFU denominator; MFU is
# reported against the bf16 peak for BOTH compute dtypes — a consistent,
# conservative convention, since f32 work still occupies the same MXU).
PEAK_FLOPS_BY_KIND = [
    ("v6", 918e12),  # Trillium
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
]


def load_baselines() -> dict:
    """Per-metric baselines recorded by a previous round (``None``/absent →
    no baseline yet; vs_baseline is then null, not a fake 1.0)."""
    try:
        with open(BASELINES_FILE) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {}


def _peak_flops(device_kind: str):
    kind = (device_kind or "").lower()
    for key, peak in PEAK_FLOPS_BY_KIND:
        if key in kind:
            return peak
    return None


# ---------------------------------------------------------------------------
# backend bring-up (VERDICT r1 weak #1: survive a flaky/hanging TPU init)
# ---------------------------------------------------------------------------

def probe_backend(timeout: float) -> dict:
    """Try backend init in a subprocess — a hang or crash there cannot take
    the bench process down with it."""
    code = (
        "import jax,json;d=jax.devices();"
        "print(json.dumps({'platform':jax.default_backend(),"
        "'n':len(d),'kind':d[0].device_kind}))"
    )
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {
            "ok": False, "seconds": round(time.time() - t0, 1),
            "error": f"backend init exceeded {timeout}s (hang)",
        }
    out = {"ok": r.returncode == 0, "seconds": round(time.time() - t0, 1)}
    if r.returncode == 0:
        try:
            out.update(json.loads(r.stdout.strip().splitlines()[-1]))
        except (ValueError, IndexError):
            out["ok"] = False
            out["error"] = f"unparseable probe output: {r.stdout[-300:]!r}"
    else:
        out["error"] = (r.stderr or r.stdout)[-500:]
    return out


def bring_up_backend(retries: int, probe_timeout: float, backoff: float) -> dict:
    """Probe with bounded retry/backoff; fall back to CPU when the
    accelerator never comes up. Returns the diagnostics dict; after this the
    in-process jax platform is pinned accordingly."""
    diag = {
        "attempts": [],
        "env": {
            k: os.environ.get(k)
            for k in ("JAX_PLATFORMS", "XLA_FLAGS", "PJRT_DEVICE", "TPU_NAME")
            if os.environ.get(k) is not None
        },
    }
    for i in range(retries):
        # escalate the budget: round-1's failure mode was an init that stalls
        # many minutes — a short fixed probe would abandon a slow-but-alive
        # chip, so later attempts wait up to 4x longer (capped so raised
        # flags keep roughly the wall time they advertise)
        p = probe_backend(probe_timeout * min(2 ** i, 4))
        diag["attempts"].append(p)
        print(f"# backend probe {i + 1}/{retries}: {p}", file=sys.stderr)
        if p.get("ok") and p.get("platform") != "cpu":
            diag.update(platform=p["platform"], device_kind=p.get("kind"),
                        devices=p.get("n"), degraded=False)
            return diag
        if p.get("ok") and p.get("platform") == "cpu":
            # deliberate CPU pin (e.g. JAX_PLATFORMS=cpu): deterministic —
            # re-probing with backoff cannot change it, skip straight to the
            # CPU path (still marked degraded: baselines are TPU numbers)
            break
        if i + 1 < retries:
            time.sleep(backoff * (i + 1))
    # accelerator unavailable — measure on CPU but say so loudly
    import jax

    jax.config.update("jax_platforms", "cpu")
    diag.update(platform="cpu", device_kind="cpu", devices=None, degraded=True)
    return diag


# ---------------------------------------------------------------------------
# the five configs
# ---------------------------------------------------------------------------

def _bench_experiment(family: str, batch: int, *, height=28, width=28, channels=1,
                      num_features=None, z_size=2, distributed="none", mesh=None,
                      compute_dtype=None, n_critic=5, scan_window=0):
    """Throughput + FLOPs of the full alternating iteration for one family.
    Every family (wgan_gp included) goes through the same harness factory.

    ``scan_window=K>1`` times the DEVICE-LOOP path (``train_iterations``:
    K iterations per dispatch via lax.scan) — the run()-loop's own steady
    state; 0 times the per-dispatch path. Families without the fused path
    (wgan_gp's bespoke trainer) silently fall back to per-dispatch."""
    import jax

    from gan_deeplearning4j_tpu.harness import ExperimentConfig, make_experiment

    num_features = num_features or height * width * channels
    cfg = ExperimentConfig(
        model_family=family, batch_size_train=batch, batch_size_pred=batch,
        height=height, width=width, channels=channels, num_features=num_features,
        z_size=z_size, num_iterations=WARMUP_ITERS + TIMED_ITERS,
        save_models=False, distributed=distributed, compute_dtype=compute_dtype,
        n_critic=n_critic,
    )
    exp = make_experiment(cfg, mesh=mesh)
    rng = np.random.default_rng(0)
    feats = exp.family.synthetic_data(batch, exp.model_cfg, 0)[:batch]
    labels = np.eye(cfg.num_classes, dtype=np.float32)[
        rng.integers(0, cfg.num_classes, size=batch)
    ]
    # Measure with the batch already resident in HBM — the steady state of
    # the real training loop, where DevicePrefetchIterator overlaps the
    # host→device copy with the running step. Feeding numpy per call instead
    # re-uploads the same bytes synchronously every iteration and (on a
    # tunneled chip) measures the link, not the step: ~6.5x slower at
    # batch 64 (round-3 finding — the round-2 "3.8x roofline gap" was
    # exactly this).
    import jax.numpy as jnp

    sharding = getattr(
        getattr(exp, "dis_trainer", None), "batch_sharding", lambda: None
    )()
    if sharding is not None:
        feats = jax.device_put(feats, sharding)
        labels = jax.device_put(labels, sharding)
    else:
        feats = jnp.asarray(feats)
        labels = jnp.asarray(labels)
    jax.block_until_ready([feats, labels])

    iters_per_call = 1
    if scan_window > 1 and getattr(exp, "_supports_device_loop", False):
        iters_per_call = scan_window
        # K distinct windows of the same resident batch, stacked (K, B, …)
        feats = jnp.broadcast_to(feats, (scan_window,) + feats.shape)
        labels = jnp.broadcast_to(labels, (scan_window,) + labels.shape)
        if sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            stacked = NamedSharding(exp.mesh, P(None, "data"))
            feats = jax.device_put(feats, stacked)
            labels = jax.device_put(labels, stacked)
        step = lambda: exp.train_iterations(feats, labels)  # noqa: E731
    else:
        step = lambda: exp.train_iteration(feats, labels)  # noqa: E731

    def sync(losses) -> None:
        # Fetch one loss VALUE to fence the chunk: on the tunneled axon
        # platform block_until_ready returns before execution finishes
        # (measured round 3), so only a device→host read is a true barrier.
        # The losses chain through every iteration, so reading the last
        # one forces the whole chunk.
        np.asarray(next(iter(losses.values())))

    for _ in range(WARMUP_ITERS):
        losses = step()
    sync(losses)

    def run_chunk(n: int) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            losses = step()
        sync(losses)
        return time.perf_counter() - t0

    # calibrate the chunk size (undersized calibration chunks are discarded)
    chunk_iters = TIMED_ITERS
    t = run_chunk(chunk_iters)
    while t < MIN_CHUNK_SECONDS and chunk_iters < MAX_ITERS_PER_CHUNK:
        chunk_iters = min(
            MAX_ITERS_PER_CHUNK,
            max(chunk_iters + 1, int(chunk_iters * 1.2 * MIN_CHUNK_SECONDS / t)),
        )
        t = run_chunk(chunk_iters)
    chunk_secs = [t]
    while len(chunk_secs) < MAX_CHUNKS and (
        sum(chunk_secs) < MIN_MEASURED_SECONDS or len(chunk_secs) < MIN_CHUNKS
    ):
        chunk_secs.append(run_chunk(chunk_iters))
    elapsed = sum(chunk_secs)
    iters = chunk_iters * len(chunk_secs) * iters_per_call
    # MIN_CHUNKS >= 2 is guaranteed by the loop above, so a cross-chunk
    # stddev always exists
    per_iter = np.asarray(chunk_secs) / (chunk_iters * iters_per_call)
    try:
        flops = exp.flops_per_iteration(batch)
    except Exception as exc:  # cost model must never sink the measurement
        print(f"# cost analysis failed: {type(exc).__name__}: {exc}", file=sys.stderr)
        flops = None
    return {
        "items_per_sec": iters * batch / elapsed,
        "sec_per_iter": elapsed / iters,
        "sec_per_iter_std": float(per_iter.std(ddof=1)),
        "timed_iters": iters,
        "measured_seconds": round(elapsed, 3),
        "device_loop_window": iters_per_call if iters_per_call > 1 else None,
        "flops_per_iter": flops,
    }


def _with_mfu(measure: dict, diag: dict) -> dict:
    peak = _peak_flops(diag.get("device_kind"))
    mfu = None
    if peak and measure["flops_per_iter"]:
        mfu = measure["flops_per_iter"] / (measure["sec_per_iter"] * peak)
    sec = measure["sec_per_iter"]
    std = measure["sec_per_iter_std"]
    return {
        "value": measure["items_per_sec"],
        "mfu": round(mfu, 4) if mfu is not None else None,
        "flops_per_iter": measure["flops_per_iter"],
        "sec_per_iter": round(sec, 6),
        "iter_time_jitter": round(std / sec, 4) if sec else None,
        "timed_iters": measure["timed_iters"],
        "measured_seconds": measure["measured_seconds"],
        "device_loop_window": measure["device_loop_window"],
    }


def bench_mnist(diag):
    """Config 1 + the bf16-vs-f32 delta (VERDICT r1 item 4). Headline value
    is the faster precision through the device loop (this workload is
    HBM-bandwidth-bound, so f32 usually wins on-chip: bf16 adds conversion
    bytes); both precisions AND the per-dispatch path are reported."""
    bf16 = _bench_experiment("mnist", 64, compute_dtype="bf16", scan_window=32)
    f32 = _bench_experiment("mnist", 64, compute_dtype=None, scan_window=32)
    dispatch = _bench_experiment("mnist", 64, compute_dtype=None)
    best, dtype = (bf16, "bf16") if bf16["items_per_sec"] >= f32["items_per_sec"] \
        else (f32, "f32")
    out = {"metric": "dcgan_mnist_images_per_sec_per_chip", "unit": "images/sec",
           "compute_dtype": dtype, **_with_mfu(best, diag)}
    out["f32_images_per_sec"] = round(f32["items_per_sec"], 2)
    out["bf16_images_per_sec"] = round(bf16["items_per_sec"], 2)
    out["bf16_speedup_vs_f32"] = round(
        bf16["items_per_sec"] / f32["items_per_sec"], 3
    )
    out["per_dispatch_images_per_sec"] = round(dispatch["items_per_sec"], 2)
    return out


def bench_tabular(diag):
    m = _bench_experiment(
        "tabular", 256, num_features=32, z_size=8, height=1, width=1, channels=1,
        compute_dtype="bf16", scan_window=32,
    )
    return {"metric": "tabular_mlp_gan_rows_per_sec_per_chip", "unit": "rows/sec",
            "compute_dtype": "bf16", **_with_mfu(m, diag)}


def bench_cifar10(diag):
    m = _bench_experiment(
        "cifar10", 64, height=32, width=32, channels=3, z_size=64,
        compute_dtype="bf16", scan_window=32,
    )
    return {"metric": "dcgan_cifar10_images_per_sec_per_chip", "unit": "images/sec",
            "compute_dtype": "bf16", **_with_mfu(m, diag)}


def bench_celeba64(diag):
    """Data-parallel over all visible devices (v5e-8 in the target rig; on a
    single chip this degenerates to a 1-device mesh — still the DP code path)."""
    from gan_deeplearning4j_tpu.runtime import TpuEnvironment

    mesh = TpuEnvironment().make_mesh()
    n = mesh.devices.size
    m = _bench_experiment(
        "celeba64", 8 * n, height=64, width=64, channels=3, z_size=64,
        distributed="pmean", mesh=mesh, compute_dtype="bf16", scan_window=32,
    )
    return {"metric": "dcgan_celeba64_dp_images_per_sec", "unit": "images/sec",
            "compute_dtype": "bf16", "devices": n, **_with_mfu(m, diag)}


def bench_wgan_gp(diag):
    """Config 5 through the same harness (registry family since round 2).
    320 = 5 critic minibatches of 64; value counts real images consumed."""
    m = _bench_experiment(
        "wgan_gp", 320, height=32, width=32, channels=3, num_features=3072,
        z_size=128, compute_dtype="bf16", n_critic=5, scan_window=8,
    )
    return {"metric": "wgan_gp_cifar10_images_per_sec_per_chip", "unit": "images/sec",
            "compute_dtype": "bf16", **_with_mfu(m, diag)}


CONFIGS = {
    "1": bench_mnist,
    "2": bench_tabular,
    "3": bench_cifar10,
    "4": bench_celeba64,
    "5": bench_wgan_gp,
}


def main() -> None:
    p = argparse.ArgumentParser(description="BASELINE.md bench harness")
    p.add_argument("--config", default="1", choices=[*CONFIGS, "all"],
                   help="BASELINE config number (default 1: DCGAN MNIST)")
    p.add_argument("--json", default=None, help="also write full results here")
    p.add_argument("--update-baselines", action="store_true",
                   help=f"record measured values into {os.path.basename(BASELINES_FILE)}")
    p.add_argument("--retries", type=int, default=3,
                   help="backend probe attempts before CPU fallback")
    p.add_argument("--probe-timeout", type=float, default=150.0,
                   help="base seconds per backend-init probe (escalates up to "
                        "4x on retries)")
    p.add_argument("--backoff", type=float, default=30.0,
                   help="base seconds between probe attempts")
    args = p.parse_args()

    diag = bring_up_backend(args.retries, args.probe_timeout, args.backoff)
    baselines = load_baselines()

    keys = list(CONFIGS) if args.config == "all" else [args.config]
    results = []
    failed = False
    for k in keys:
        try:
            r = CONFIGS[k](diag)
        except Exception as exc:  # keep earlier (expensive) results on failure
            r = {"config": k, "error": f"{type(exc).__name__}: {exc}"}
            failed = True
        else:
            r["value"] = round(float(r["value"]), 2)
            base = baselines.get(r["metric"])
            # null when no baseline exists or the run is degraded-CPU (a CPU
            # number against a TPU baseline would be meaningless)
            r["vs_baseline"] = (
                round(r["value"] / base, 3) if base and not diag["degraded"] else None
            )
        r["platform"] = diag["platform"]
        r["device_kind"] = diag.get("device_kind")
        r["degraded"] = diag["degraded"]
        results.append(r)
        print(json.dumps(r))
        if args.json:  # flush after every config (errors included), not
            # only at the end — a trailing failure must not lose the file
            with open(args.json, "w") as fh:
                json.dump({"diagnostics": diag, "results": results}, fh, indent=2)
    if args.update_baselines and not diag["degraded"]:
        merged = dict(baselines)
        merged.update({
            r["metric"]: r["value"] for r in results if "metric" in r
        })
        with open(BASELINES_FILE, "w") as fh:
            json.dump(merged, fh, indent=2)
        print(f"# baselines updated: {BASELINES_FILE}", file=sys.stderr)
    if failed:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
