"""Headline benchmark: DCGAN-MNIST alternating-loop throughput (images/sec/chip).

Runs the reference topology (dl4jGANComputerVision.java:117-314) at batch 64
(BASELINE.json config 1) through the full alternating iteration — dis fit,
weight sync, gan fit, sync, classifier fit — on whatever device jax provides,
and prints ONE JSON line. The reference publishes no numbers (BASELINE.md), so
this run *establishes* the baseline; vs_baseline is reported against the
recorded target in this file once one exists.
"""

from __future__ import annotations

import json
import time

import numpy as np

# First recorded real-TPU number for this config becomes the baseline to beat.
# None until a driver run on real hardware records one.
BASELINE_IMAGES_PER_SEC = None

WARMUP_ITERS = 3
TIMED_ITERS = 20
BATCH = 64


def main() -> None:
    from gan_deeplearning4j_tpu.harness.config import ExperimentConfig
    from gan_deeplearning4j_tpu.harness.experiment import GanExperiment

    cfg = ExperimentConfig(
        batch_size_train=BATCH,
        batch_size_pred=BATCH,
        num_iterations=WARMUP_ITERS + TIMED_ITERS,
        save_models=False,
    )
    exp = GanExperiment(cfg)

    rng = np.random.default_rng(0)
    features = rng.random((BATCH, cfg.num_features), dtype=np.float32)
    labels = np.eye(cfg.num_classes, dtype=np.float32)[
        rng.integers(0, cfg.num_classes, size=BATCH)
    ]

    import jax

    for _ in range(WARMUP_ITERS):
        losses = exp.train_iteration(features, labels)
    jax.block_until_ready(losses)

    t0 = time.perf_counter()
    for _ in range(TIMED_ITERS):
        losses = exp.train_iteration(features, labels)
    jax.block_until_ready(losses)  # iterations pipeline; settle before timing
    elapsed = time.perf_counter() - t0

    images_per_sec = TIMED_ITERS * BATCH / elapsed
    vs = (
        images_per_sec / BASELINE_IMAGES_PER_SEC
        if BASELINE_IMAGES_PER_SEC
        else 1.0
    )
    print(
        json.dumps(
            {
                "metric": "dcgan_mnist_images_per_sec_per_chip",
                "value": round(images_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
