"""InputType — declared input shapes driving graph shape inference.

Analog of DL4J's ``InputType`` (the reference declares
``InputType.convolutionalFlat(28,28,1)`` at dl4jGANComputerVision.java:165 and
``feedForward(2)`` implicitly via the z input). Shapes exclude the batch axis.
Convolutional activations are NHWC (TPU-native layout; DL4J is NCHW — the
flat<->cnn preprocessors keep DL4J's element ordering at the boundaries).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class InputType:
    kind: str  # "ff" | "cnn" | "cnn_flat"
    shape: Tuple[int, ...]  # ff: (features,); cnn/cnn_flat: (h, w, c)

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType("ff", (int(size),))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn", (int(height), int(width), int(channels)))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        """Flat (N, h*w*c) input to be consumed by conv layers — DL4J's
        ``convolutionalFlat`` (dl4jGANComputerVision.java:165)."""
        return InputType("cnn_flat", (int(height), int(width), int(channels)))

    # -- derived quantities -------------------------------------------------
    @property
    def features(self) -> int:
        if self.kind == "ff":
            return self.shape[0]
        h, w, c = self.shape
        return h * w * c

    @property
    def channels(self) -> int:
        if self.kind == "ff":
            raise ValueError("feed-forward InputType has no channel axis")
        return self.shape[2]

    def array_shape(self, batch: int | None = None) -> Tuple[int, ...]:
        """Concrete array shape (batch leading; None → batch omitted)."""
        if self.kind == "ff" or self.kind == "cnn_flat":
            core = (self.features,)
        else:
            core = self.shape
        return core if batch is None else (batch,) + core

    def to_dict(self) -> dict:
        return {"kind": self.kind, "shape": list(self.shape)}

    @staticmethod
    def from_dict(d: dict) -> "InputType":
        return InputType(d["kind"], tuple(d["shape"]))

    def __str__(self) -> str:
        if self.kind == "ff":
            return f"FeedForward({self.shape[0]})"
        h, w, c = self.shape
        flat = "Flat" if self.kind == "cnn_flat" else ""
        return f"Convolutional{flat}({h}x{w}x{c})"
