"""Input preprocessors — layout adapters at layer boundaries.

The reference uses ``FeedForwardToCnnPreProcessor(7,7,128)`` to feed a dense
activation into the generator's conv stack (dl4jGANComputerVision.java:200),
and DL4J implicitly inserts cnn→ff flattening before dense layers. DL4J's
element order is NCHW; our activations are NHWC (TPU layout), so both
preprocessors reshape through the channels-first ordering to keep flat-vector
semantics identical to the reference — the transposes are free under XLA
(layout assignment folds them into the adjacent conv/GEMM).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from gan_deeplearning4j_tpu.nn.input_type import InputType


@dataclasses.dataclass(frozen=True)
class FeedForwardToCnnPreProcessor:
    """(N, c*h*w) flat → (N, h, w, c) NHWC, interpreting the flat vector in
    DL4J's (c, h, w) row-major order."""

    height: int
    width: int
    channels: int

    def __call__(self, x):
        n = x.shape[0]
        y = x.reshape(n, self.channels, self.height, self.width)
        return jnp.transpose(y, (0, 2, 3, 1))

    def output_type(self, in_type: InputType) -> InputType:
        expect = self.channels * self.height * self.width
        if in_type.features != expect:
            raise ValueError(
                f"FeedForwardToCnn({self.height},{self.width},{self.channels}) expects "
                f"{expect} features, got {in_type.features}"
            )
        return InputType.convolutional(self.height, self.width, self.channels)

    def to_dict(self) -> dict:
        return {
            "type": "ff_to_cnn",
            "height": self.height,
            "width": self.width,
            "channels": self.channels,
        }


@dataclasses.dataclass(frozen=True)
class CnnToFeedForwardPreProcessor:
    """(N, h, w, c) NHWC → (N, c*h*w) flat in DL4J's (c, h, w) order."""

    def __call__(self, x):
        n = x.shape[0]
        y = jnp.transpose(x, (0, 3, 1, 2))
        return y.reshape(n, -1)

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.feed_forward(in_type.features)

    def to_dict(self) -> dict:
        return {"type": "cnn_to_ff"}


@dataclasses.dataclass(frozen=True)
class FlatToCnnPreProcessor:
    """(N, h*w*c) flat image rows → (N, h, w, c). Used for ``cnn_flat``
    declared inputs (DL4J ``convolutionalFlat``): MNIST CSV rows are h*w
    row-major pixels (single channel), dl4jGANComputerVision.java:165,372-377.
    """

    height: int
    width: int
    channels: int

    def __call__(self, x):
        n = x.shape[0]
        # CSV rows are (h, w) row-major per channel-last convention
        return x.reshape(n, self.height, self.width, self.channels)

    def output_type(self, in_type: InputType) -> InputType:
        return InputType.convolutional(self.height, self.width, self.channels)

    def to_dict(self) -> dict:
        return {
            "type": "flat_to_cnn",
            "height": self.height,
            "width": self.width,
            "channels": self.channels,
        }


def preprocessor_from_dict(d: dict):
    t = d["type"]
    if t == "ff_to_cnn":
        return FeedForwardToCnnPreProcessor(d["height"], d["width"], d["channels"])
    if t == "cnn_to_ff":
        return CnnToFeedForwardPreProcessor()
    if t == "flat_to_cnn":
        return FlatToCnnPreProcessor(d["height"], d["width"], d["channels"])
    raise KeyError(f"unknown preprocessor type {t!r}")
