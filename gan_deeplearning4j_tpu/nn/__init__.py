"""Graph/module system — the DL4J ``ComputationGraph`` capability surface,
rebuilt functionally for JAX (SURVEY §2.2 D6-D7, D11).

Key properties preserved from the reference (dl4jGANComputerVision.java:118-314):
stable layer names, named-parameter get/set (the weight-sync protocol at
:429-542 depends on it), per-layer updater configs, declared-InputType shape
inference, ``init``/``summary``/``output``, and transfer-learning graph
surgery (:337-364). Parameters are a plain nested dict pytree — jit/pjit
shardable, checkpointable, and name-addressable.
"""

from gan_deeplearning4j_tpu.nn.input_type import InputType
from gan_deeplearning4j_tpu.nn.layers import (
    ActivationLayer,
    BatchNormalization,
    ConvolutionLayer,
    Deconvolution2D,
    DenseLayer,
    DropoutLayer,
    Layer,
    LossLayer,
    OutputLayer,
    SubsamplingLayer,
    Upsampling2D,
    register_layer,
)
from gan_deeplearning4j_tpu.nn.preprocessors import (
    CnnToFeedForwardPreProcessor,
    FeedForwardToCnnPreProcessor,
)
from gan_deeplearning4j_tpu.nn.graph import ComputationGraph, GraphBuilder, GraphConfig
from gan_deeplearning4j_tpu.nn.transfer import FineTuneConfiguration, TransferLearning

__all__ = [
    "InputType",
    "Layer",
    "ActivationLayer",
    "BatchNormalization",
    "ConvolutionLayer",
    "Deconvolution2D",
    "DenseLayer",
    "DropoutLayer",
    "LossLayer",
    "OutputLayer",
    "SubsamplingLayer",
    "Upsampling2D",
    "CnnToFeedForwardPreProcessor",
    "FeedForwardToCnnPreProcessor",
    "register_layer",
    "ComputationGraph",
    "GraphBuilder",
    "GraphConfig",
    "FineTuneConfiguration",
    "TransferLearning",
]
