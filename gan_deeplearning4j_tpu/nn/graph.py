"""ComputationGraph — named-layer DAG with shape inference (SURVEY §2.2 D6).

The reference builds three graphs through DL4J's
``NeuralNetConfiguration.Builder → graphBuilder() → ComputationGraph``
(dl4jGANComputerVision.java:118-314). This module reproduces that capability
surface functionally:

- ``GraphBuilder``: ``add_inputs`` / ``set_input_types`` / ``add_layer`` /
  ``add_vertex`` / ``set_outputs`` with graph-level defaults (seed, default
  activation, weight init, L2, grad-clip, default updater) that per-layer
  settings override — DL4J's config inheritance.
- Automatic boundary preprocessors from declared InputTypes (DL4J inserts
  FeedForwardToCnn/CnnToFeedForward implicitly; the flat→cnn insertion in
  front of the first BatchNorm mirrors DL4J's CNNFlat handling, which is why
  the reference's ``dis_batch_layer_1`` normalizes 1 channel, not 784
  features).
- ``ComputationGraph``: ``init`` (seeded, deterministic), ``apply`` (pure:
  params in → outputs + updated BN stats out), ``output`` (inference),
  ``loss`` (output-layer losses + L2), ``summary``, named-param
  ``get_param``/``set_param``/``copy_params`` (the reference's weight-sync
  protocol, :429-542), and ``to_dict``/``from_dict`` for checkpointing.

Everything is jit-compatible: params are a nested dict pytree, ``train`` is a
static flag, and the vertex iteration is unrolled Python (static graph), so
XLA sees one flat computation to fuse.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from gan_deeplearning4j_tpu.nn.input_type import InputType
from gan_deeplearning4j_tpu.nn.layers import (
    BatchNormalization,
    ConvolutionLayer,
    Deconvolution2D,
    DenseLayer,
    Layer,
    LossLayer,
    OutputLayer,
    SubsamplingLayer,
    Upsampling2D,
    layer_from_dict,
)
from gan_deeplearning4j_tpu.nn.preprocessors import (
    CnnToFeedForwardPreProcessor,
    FlatToCnnPreProcessor,
    preprocessor_from_dict,
)
from gan_deeplearning4j_tpu.optim.updaters import RmsProp, UpdaterSpec, updater_from_dict

_CNN_LAYERS = (ConvolutionLayer, Deconvolution2D, SubsamplingLayer, Upsampling2D)
_FF_LAYERS = (DenseLayer,)  # OutputLayer subclasses DenseLayer


@dataclasses.dataclass(frozen=True)
class GraphConfig:
    """Graph-level defaults (DL4J NeuralNetConfiguration.Builder chain,
    dl4jGANComputerVision.java:121-129): seed, SGD optimization algo,
    elementwise grad clip @1.0, L2 1e-4, tanh default activation, Xavier."""

    seed: int = 666
    default_activation: str = "tanh"
    weight_init: str = "xavier"
    l2: float = 0.0
    gradient_clip: Optional[str] = None  # "elementwise" | "global_norm" | None
    gradient_clip_value: float = 1.0
    updater: UpdaterSpec = RmsProp(0.001)
    optimization_algo: str = "sgd"  # informational, as in the reference

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["updater"] = self.updater.to_dict()
        return d

    @staticmethod
    def from_dict(d: dict) -> "GraphConfig":
        d = dict(d)
        d["updater"] = updater_from_dict(d["updater"])
        return GraphConfig(**d)


@dataclasses.dataclass(frozen=True)
class MergeVertex:
    """Concatenate inputs along the trailing feature/channel axis (DL4J
    MergeVertex)."""

    def apply(self, xs: Sequence[jnp.ndarray]):
        return jnp.concatenate(list(xs), axis=-1)

    def output_type(self, in_types: Sequence[InputType]) -> InputType:
        kinds = {t.kind for t in in_types}
        if kinds == {"ff"}:
            return InputType.feed_forward(sum(t.shape[0] for t in in_types))
        if kinds == {"cnn"}:
            h, w, _ = in_types[0].shape
            return InputType.convolutional(h, w, sum(t.shape[2] for t in in_types))
        raise ValueError(f"MergeVertex: incompatible input kinds {kinds}")

    def to_dict(self) -> dict:
        return {"type": "MergeVertex"}


@dataclasses.dataclass(frozen=True)
class ElementWiseVertex:
    """Elementwise combine (DL4J ElementWiseVertex: Add/Subtract/Product)."""

    op: str = "add"

    def apply(self, xs: Sequence[jnp.ndarray]):
        out = xs[0]
        for x in xs[1:]:
            if self.op == "add":
                out = out + x
            elif self.op == "product":
                out = out * x
            elif self.op == "subtract":
                out = out - x
            else:
                raise ValueError(f"unknown elementwise op {self.op!r}")
        return out

    def output_type(self, in_types: Sequence[InputType]) -> InputType:
        return in_types[0]

    def to_dict(self) -> dict:
        return {"type": "ElementWiseVertex", "op": self.op}


def _vertex_from_dict(d: dict):
    if d["type"] == "MergeVertex":
        return MergeVertex()
    if d["type"] == "ElementWiseVertex":
        return ElementWiseVertex(d["op"])
    raise KeyError(f"unknown vertex type {d['type']!r}")


@dataclasses.dataclass(frozen=True)
class VertexSpec:
    """A resolved node: either a Layer (with optional preprocessor) or a
    combining vertex. ``raw_layer`` keeps the pre-default-resolution config
    (None fields = "inherit") so graph surgery can re-resolve against a
    fine-tuned config, DL4J FineTuneConfiguration-style."""

    name: str
    inputs: Tuple[str, ...]
    layer: Optional[Layer] = None
    vertex: Optional[object] = None
    preprocessor: Optional[object] = None
    in_type: Optional[InputType] = None
    out_type: Optional[InputType] = None
    raw_layer: Optional[Layer] = None


class GraphBuilder:
    """DL4J ``graphBuilder()`` analog."""

    def __init__(self, config: GraphConfig = GraphConfig()):
        self.config = config
        self._inputs: List[str] = []
        self._input_types: List[InputType] = []
        self._nodes: List[dict] = []
        self._outputs: List[str] = []
        self._names: set = set()

    def add_inputs(self, *names: str) -> "GraphBuilder":
        for n in names:
            if n in self._names:
                raise ValueError(f"duplicate name {n!r}")
            self._names.add(n)
        self._inputs.extend(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    def add_layer(
        self, name: str, layer: Layer, *inputs: str, preprocessor=None
    ) -> "GraphBuilder":
        if name in self._names:
            raise ValueError(f"duplicate name {name!r}")
        self._names.add(name)
        self._nodes.append(
            {"name": name, "layer": layer, "inputs": tuple(inputs), "preprocessor": preprocessor}
        )
        return self

    def add_vertex(self, name: str, vertex, *inputs: str) -> "GraphBuilder":
        if name in self._names:
            raise ValueError(f"duplicate name {name!r}")
        self._names.add(name)
        self._nodes.append({"name": name, "vertex": vertex, "inputs": tuple(inputs)})
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    # ------------------------------------------------------------------
    def _resolve_layer_defaults(self, layer: Layer) -> Layer:
        """Fill in None fields from the graph config (DL4J inheritance)."""
        updates = {}
        if layer.activation is None and not isinstance(
            layer, (BatchNormalization, Upsampling2D, SubsamplingLayer)
        ):
            updates["activation"] = self.config.default_activation
        if layer.activation is None and isinstance(layer, BatchNormalization):
            updates["activation"] = "identity"
        if layer.weight_init is None:
            updates["weight_init"] = self.config.weight_init
        if layer.updater is None:
            updates["updater"] = self.config.updater
        if layer.l2 is None:
            updates["l2"] = self.config.l2
        return dataclasses.replace(layer, **updates) if updates else layer

    @staticmethod
    def _auto_preprocessor(layer: Layer, in_type: InputType):
        """DL4J's implicit InputType adaptation."""
        if isinstance(layer, (*_CNN_LAYERS, BatchNormalization)) and in_type.kind == "cnn_flat":
            h, w, c = in_type.shape
            return FlatToCnnPreProcessor(h, w, c)
        if isinstance(layer, _FF_LAYERS) and in_type.kind == "cnn":
            return CnnToFeedForwardPreProcessor()
        return None

    def build(self) -> "ComputationGraph":
        if not self._inputs:
            raise ValueError("graph has no inputs")
        if not self._outputs:
            raise ValueError("graph has no outputs (set_outputs)")
        if len(self._input_types) != len(self._inputs):
            raise ValueError(
                f"{len(self._inputs)} inputs but {len(self._input_types)} input types declared"
            )

        known: Dict[str, InputType] = dict(zip(self._inputs, self._input_types))
        # flat declared inputs consumed by ff layers act as plain feature vectors
        specs: List[VertexSpec] = []
        pending = list(self._nodes)
        # topological resolve (nodes may be declared in any order)
        progress = True
        while pending and progress:
            progress = False
            remaining = []
            for node in pending:
                if all(i in known for i in node["inputs"]):
                    specs.append(self._finalize_node(node, known))
                    known[node["name"]] = specs[-1].out_type
                    progress = True
                else:
                    remaining.append(node)
            pending = remaining
        if pending:
            missing = {i for n in pending for i in n["inputs"] if i not in known}
            raise ValueError(f"unresolvable graph: missing vertices {sorted(missing)}")

        for o in self._outputs:
            if o not in known:
                raise ValueError(f"output {o!r} is not a graph vertex")

        return ComputationGraph(
            config=self.config,
            input_names=tuple(self._inputs),
            input_types=tuple(self._input_types),
            vertices=tuple(specs),
            output_names=tuple(self._outputs),
        )

    def _finalize_node(self, node: dict, known: Dict[str, InputType]) -> VertexSpec:
        in_types = [known[i] for i in node["inputs"]]
        if "vertex" in node:
            vertex = node["vertex"]
            return VertexSpec(
                name=node["name"],
                inputs=node["inputs"],
                vertex=vertex,
                in_type=in_types[0],
                out_type=vertex.output_type(in_types),
            )
        layer = self._resolve_layer_defaults(node["layer"])
        if len(in_types) != 1:
            raise ValueError(f"layer {node['name']!r} must have exactly one input")
        in_type = in_types[0]
        pre = node.get("preprocessor") or self._auto_preprocessor(layer, in_type)
        if pre is not None:
            in_type = pre.output_type(in_type)
        elif in_type.kind == "cnn_flat" and isinstance(layer, _FF_LAYERS):
            in_type = InputType.feed_forward(in_type.features)
        return VertexSpec(
            name=node["name"],
            inputs=node["inputs"],
            layer=layer,
            preprocessor=pre,
            in_type=in_type,
            out_type=layer.output_type(in_type),
            raw_layer=node["layer"],
        )


class ComputationGraph:
    """Immutable compiled graph topology + pure init/apply."""

    def __init__(self, config, input_names, input_types, vertices, output_names):
        self.config: GraphConfig = config
        self.input_names: Tuple[str, ...] = input_names
        self.input_types: Tuple[InputType, ...] = input_types
        self.vertices: Tuple[VertexSpec, ...] = vertices
        self.output_names: Tuple[str, ...] = output_names
        self._by_name = {v.name: v for v in vertices}

    # -- introspection ------------------------------------------------------
    def vertex(self, name: str) -> VertexSpec:
        return self._by_name[name]

    def layer_names(self) -> List[str]:
        return [v.name for v in self.vertices if v.layer is not None]

    def layer_updaters(self) -> Dict[str, UpdaterSpec]:
        """Per-layer updater specs for layers that own parameters (consumed by
        GraphOptimizer — the reference's per-layer ``.updater(...)`` calls)."""
        return {
            v.name: v.layer.updater
            for v in self.vertices
            if v.layer is not None and v.layer.has_params()
        }

    def param_roles(self) -> Dict[str, Dict[str, str]]:
        return {
            v.name: v.layer.param_roles()
            for v in self.vertices
            if v.layer is not None and v.layer.has_params()
        }

    def output_layers(self) -> List[VertexSpec]:
        return [
            v
            for v in self.vertices
            if v.name in self.output_names and isinstance(v.layer, (OutputLayer, LossLayer))
        ]

    # -- init ---------------------------------------------------------------
    def init(self, seed: Optional[int] = None) -> Dict[str, Dict[str, jnp.ndarray]]:
        """Initialize params deterministically from the config seed (the
        reference seeds every graph with 666, dl4jGANComputerVision.java:121)."""
        root = jax.random.PRNGKey(self.config.seed if seed is None else seed)
        params: Dict[str, Dict[str, jnp.ndarray]] = {}
        for idx, v in enumerate(self.vertices):
            if v.layer is None or not v.layer.has_params():
                continue
            key = jax.random.fold_in(root, idx)
            params[v.name] = v.layer.init(key, v.in_type)
        return params

    # -- forward ------------------------------------------------------------
    def apply(
        self,
        params: Dict,
        inputs: Union[jnp.ndarray, Dict[str, jnp.ndarray]],
        *,
        train: bool = False,
        rng=None,
    ):
        """Feed-forward. Returns (outputs, new_params) where new_params carries
        BN running-stat updates when train=True (identical tree otherwise)."""
        acts, new_params = self._traverse(params, inputs, train=train, rng=rng)
        outputs = {o: acts[o] for o in self.output_names}
        return outputs, new_params

    def _traverse(self, params: Dict, inputs, *, train: bool, rng=None):
        """Shared forward traversal: returns (all activations, new_params)."""
        if not isinstance(inputs, dict):
            if len(self.input_names) != 1:
                raise ValueError("graph has multiple inputs; pass a dict")
            inputs = {self.input_names[0]: inputs}
        acts: Dict[str, jnp.ndarray] = dict(inputs)
        new_params = dict(params)
        for idx, v in enumerate(self.vertices):
            if v.vertex is not None:
                acts[v.name] = v.vertex.apply([acts[i] for i in v.inputs])
                continue
            x = acts[v.inputs[0]]
            if v.preprocessor is not None:
                x = v.preprocessor(x)
            layer_rng = None
            if rng is not None:
                layer_rng = jax.random.fold_in(rng, idx)
            y, updates = v.layer.apply(
                params.get(v.name, {}), x, train=train, rng=layer_rng
            )
            if updates:
                new_params[v.name] = {**params[v.name], **updates}
            acts[v.name] = y
        return acts, new_params

    def output(self, params: Dict, inputs, *, train: bool = False):
        """Inference convenience (DL4J ``graph.output(x)``): returns the single
        output array, or a dict for multi-output graphs."""
        outs, _ = self.apply(params, inputs, train=train)
        if len(self.output_names) == 1:
            return outs[self.output_names[0]]
        return outs

    def feed_forward(self, params: Dict, inputs, *, train: bool = False, rng=None):
        """Per-vertex activation map (DL4J ``ComputationGraph.feedForward``):
        {vertex name: activation}, inputs included. Used for feature
        extraction (e.g. FID on discriminator features) and debugging."""
        acts, _ = self._traverse(params, inputs, train=train, rng=rng)
        return acts

    # -- loss ---------------------------------------------------------------
    def l2_penalty(self, params: Dict) -> jnp.ndarray:
        """0.5 * l2 * ||W||² summed over weight-role params (DL4J L2 score
        term; reference l2=1e-4, dl4jGANComputerVision.java:123)."""
        total = jnp.zeros((), jnp.float32)
        for v in self.vertices:
            if v.layer is None or not v.layer.has_params():
                continue
            l2 = v.layer.l2 or 0.0
            if l2 <= 0.0:
                continue
            roles = v.layer.param_roles()
            for pname, role in roles.items():
                if role == "weight":
                    w = params[v.name][pname]
                    total = total + 0.5 * l2 * jnp.sum(w.astype(jnp.float32) ** 2)
        return total

    def loss(self, params: Dict, inputs, labels, *, train: bool = True, rng=None):
        """Total training loss: sum of output-layer losses + L2 penalty.
        Returns (loss, (outputs, new_params))."""
        outs, new_params = self.apply(params, inputs, train=train, rng=rng)
        if not isinstance(labels, dict):
            if len(self.output_names) != 1:
                raise ValueError("graph has multiple outputs; pass labels as a dict")
            labels = {self.output_names[0]: labels}
        out_layers = self.output_layers()
        if not out_layers:
            raise ValueError("graph has no loss-bearing output layers")
        total = jnp.zeros((), jnp.float32)
        for v in out_layers:
            total = total + v.layer.loss_fn(outs[v.name], labels[v.name])
        total = total + self.l2_penalty(params)
        return total, (outs, new_params)

    # -- named-parameter protocol ------------------------------------------
    @staticmethod
    def get_param(params: Dict, layer: str, name: str) -> jnp.ndarray:
        """DL4J ``graph.getLayer(l).getParam(n)``
        (dl4jGANComputerVision.java:429-542)."""
        return params[layer][name]

    @staticmethod
    def set_param(params: Dict, layer: str, name: str, value) -> Dict:
        """Functional DL4J ``setParam``: returns a new params tree."""
        if layer not in params:
            raise KeyError(f"unknown layer {layer!r}")
        if name not in params[layer]:
            raise KeyError(f"layer {layer!r} has no param {name!r}")
        if tuple(params[layer][name].shape) != tuple(value.shape):
            raise ValueError(
                f"shape mismatch setting {layer}/{name}: "
                f"{params[layer][name].shape} vs {value.shape}"
            )
        new_layer = {**params[layer], name: value}
        return {**params, layer: new_layer}

    @staticmethod
    def copy_params(src_params: Dict, dst_params: Dict, mapping: Dict[str, str]) -> Dict:
        """Bulk named-parameter copy — the reference's weight-sync protocol
        (12 dis→gan, 16 gan→gen, 10 dis→CV copies per iteration,
        dl4jGANComputerVision.java:429-542) as one functional op. ``mapping``
        is {src_layer: dst_layer}; all params of each layer are copied."""
        out = dict(dst_params)
        for src_layer, dst_layer in mapping.items():
            if src_layer not in src_params:
                raise KeyError(f"source layer {src_layer!r} not in params")
            if dst_layer not in out:
                raise KeyError(f"dest layer {dst_layer!r} not in params")
            for pname, value in src_params[src_layer].items():
                if pname not in out[dst_layer]:
                    raise KeyError(f"dest layer {dst_layer!r} has no param {pname!r}")
                if tuple(out[dst_layer][pname].shape) != tuple(value.shape):
                    raise ValueError(
                        f"shape mismatch copying {src_layer}/{pname} -> {dst_layer}: "
                        f"{value.shape} vs {out[dst_layer][pname].shape}"
                    )
            out[dst_layer] = {**out[dst_layer], **dict(src_params[src_layer])}
        return out

    # -- reporting ----------------------------------------------------------
    def param_shapes(self) -> Dict[str, Dict[str, jax.ShapeDtypeStruct]]:
        """Abstract param tree (shapes/dtypes only) — no device allocation."""
        return jax.eval_shape(self.init)

    def param_count(self, params: Optional[Dict] = None) -> int:
        params = params if params is not None else self.param_shapes()
        return sum(int(p.size) for lp in params.values() for p in lp.values())

    def summary(self, params: Optional[Dict] = None) -> str:
        """DL4J ``graph.summary()`` analog (printed by the reference after
        every build, dl4jGANComputerVision.java:167,223,312,365)."""
        params = params if params is not None else self.param_shapes()
        rows = [("Name (type)", "In", "Out", "# Params")]
        for name, t in zip(self.input_names, self.input_types):
            rows.append((f"{name} (Input)", "-", str(t), "0"))
        total = 0
        for v in self.vertices:
            kind = v.layer.kind if v.layer is not None else type(v.vertex).__name__
            n = sum(int(p.size) for p in params.get(v.name, {}).values())
            total += n
            pre = f" [+{type(v.preprocessor).__name__}]" if v.preprocessor is not None else ""
            rows.append((f"{v.name} ({kind}){pre}", str(v.in_type), str(v.out_type), str(n)))
        widths = [max(len(r[i]) for r in rows) for i in range(4)]
        lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip() for r in rows]
        lines.insert(1, "-" * (sum(widths) + 6))
        lines.append("-" * (sum(widths) + 6))
        lines.append(f"Total params: {total}")
        return "\n".join(lines)

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> dict:
        nodes = []
        for v in self.vertices:
            node = {"name": v.name, "inputs": list(v.inputs)}
            if v.layer is not None:
                node["layer"] = v.layer.to_dict()
                if v.preprocessor is not None:
                    node["preprocessor"] = v.preprocessor.to_dict()
            else:
                node["vertex"] = v.vertex.to_dict()
            nodes.append(node)
        return {
            "config": self.config.to_dict(),
            "inputs": list(self.input_names),
            "input_types": [t.to_dict() for t in self.input_types],
            "nodes": nodes,
            "outputs": list(self.output_names),
        }

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraph":
        builder = GraphBuilder(GraphConfig.from_dict(d["config"]))
        builder.add_inputs(*d["inputs"])
        builder.set_input_types(*[InputType.from_dict(t) for t in d["input_types"]])
        for node in d["nodes"]:
            if "layer" in node:
                pre = (
                    preprocessor_from_dict(node["preprocessor"])
                    if "preprocessor" in node
                    else None
                )
                builder.add_layer(
                    node["name"], layer_from_dict(node["layer"]), *node["inputs"],
                    preprocessor=pre,
                )
            else:
                builder.add_vertex(node["name"], _vertex_from_dict(node["vertex"]), *node["inputs"])
        builder.set_outputs(*d["outputs"])
        return builder.build()
