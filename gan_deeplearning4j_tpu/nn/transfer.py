"""Transfer-learning graph surgery (SURVEY §2.2 D11).

The reference builds its classifier from the trained discriminator with DL4J's
``TransferLearning.GraphBuilder`` (dl4jGANComputerVision.java:337-364):

- ``fineTuneConfiguration`` re-applies the common hyperparams with a fresh
  updater (:338-350);
- ``setFeatureExtractor("dis_dense_layer_6")`` freezes everything up to and
  including that vertex (:352) — in this framework, as in the reference's own
  freezing mechanism, "frozen" = updater learning rate 0.0 (:84,187,277);
- ``removeVertexAndConnections``/``removeVertexKeepConnections`` drops the old
  output head (:353);
- ``addLayer`` appends the new head (:354-363).

The builder is functional: ``build()`` returns a new (graph, params) pair;
retained layers carry their trained parameters over, new layers are freshly
initialized from the fine-tune seed.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from gan_deeplearning4j_tpu.nn.graph import ComputationGraph, GraphBuilder, GraphConfig
from gan_deeplearning4j_tpu.nn.layers import Layer
from gan_deeplearning4j_tpu.optim.updaters import UpdaterSpec


@dataclasses.dataclass(frozen=True)
class FineTuneConfiguration:
    """Global training-config override applied to the surgered graph (DL4J
    FineTuneConfiguration, dl4jGANComputerVision.java:338-350). ``None`` fields
    keep the source graph's values."""

    seed: Optional[int] = None
    default_activation: Optional[str] = None
    weight_init: Optional[str] = None
    l2: Optional[float] = None
    gradient_clip: Optional[str] = None
    gradient_clip_value: Optional[float] = None
    updater: Optional[UpdaterSpec] = None
    optimization_algo: Optional[str] = None

    def apply_to(self, config: GraphConfig) -> GraphConfig:
        updates = {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(self)
            if getattr(self, f.name) is not None
        }
        return dataclasses.replace(config, **updates)


class TransferLearning:
    """DL4J ``TransferLearning.GraphBuilder`` analog, functional."""

    def __init__(self, graph: ComputationGraph, params: Dict):
        self._graph = graph
        self._params = params
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._freeze_until: Optional[str] = None
        self._removed: List[str] = []
        self._added: List[dict] = []
        self._new_outputs: Optional[List[str]] = None

    def fine_tune_configuration(self, cfg: FineTuneConfiguration) -> "TransferLearning":
        self._fine_tune = cfg
        return self

    def set_feature_extractor(self, vertex_name: str) -> "TransferLearning":
        """Freeze all layers up to and including ``vertex_name`` (LR→0.0)."""
        if vertex_name not in {v.name for v in self._graph.vertices}:
            raise KeyError(f"unknown vertex {vertex_name!r}")
        self._freeze_until = vertex_name
        return self

    def remove_vertex_keep_connections(self, name: str) -> "TransferLearning":
        """Drop a vertex, splicing its inputs into its consumers (DL4J
        ``removeVertexKeepConnections``): anything that consumed the removed
        vertex consumes its inputs instead. The reference uses it to drop the
        old output head before appending a new one (:353-363)."""
        if name not in {v.name for v in self._graph.vertices}:
            raise KeyError(f"unknown vertex {name!r}")
        self._removed.append(name)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str) -> "TransferLearning":
        self._added.append({"name": name, "layer": layer, "inputs": tuple(inputs)})
        return self

    def set_outputs(self, *names: str) -> "TransferLearning":
        self._new_outputs = list(names)
        return self

    def build(self) -> Tuple[ComputationGraph, Dict]:
        src = self._graph
        config = src.config
        if self._fine_tune is not None:
            config = self._fine_tune.apply_to(config)

        # layers frozen = all vertices in topo order up to freeze_until
        frozen = set()
        if self._freeze_until is not None:
            for v in src.vertices:
                frozen.add(v.name)
                if v.name == self._freeze_until:
                    break

        # removed vertices are spliced out: consumers inherit their inputs
        splice = {
            v.name: list(v.inputs) for v in src.vertices if v.name in self._removed
        }

        def rewire(inputs):
            out: List[str] = []
            for i in inputs:
                if i in splice:
                    out.extend(rewire(splice[i]))
                else:
                    out.append(i)
            return tuple(out)

        builder = GraphBuilder(config)
        builder.add_inputs(*src.input_names)
        builder.set_input_types(*src.input_types)
        kept: List[str] = []
        for v in src.vertices:
            if v.name in self._removed:
                continue
            inputs = rewire(v.inputs)
            if v.vertex is not None:
                builder.add_vertex(v.name, v.vertex, *inputs)
                continue
            # re-resolve inherited (None) fields against the fine-tuned config,
            # and — DL4J FineTuneConfiguration semantics — let explicitly set
            # fine-tune values override retained non-frozen layers' own
            # updater/l2 (activation/weight_init act as defaults only).
            layer = v.raw_layer if v.raw_layer is not None else v.layer
            if v.name in frozen and v.layer.has_params():
                # freeze = resolved updater with LR 0.0 (reference :84)
                layer = dataclasses.replace(
                    layer, updater=v.layer.updater.with_learning_rate(0.0)
                )
            elif self._fine_tune is not None:
                overrides = {}
                if self._fine_tune.updater is not None:
                    overrides["updater"] = self._fine_tune.updater
                if self._fine_tune.l2 is not None:
                    overrides["l2"] = self._fine_tune.l2
                if overrides:
                    layer = dataclasses.replace(layer, **overrides)
            builder.add_layer(v.name, layer, *inputs, preprocessor=v.preprocessor)
            kept.append(v.name)
        for node in self._added:
            builder.add_layer(node["name"], node["layer"], *node["inputs"])

        outputs = self._new_outputs
        if outputs is None:
            # DL4J addLayer does not change outputs: keep surviving ones, and
            # only if the removed head left none does the last added layer
            # become the output (the reference's new-head case, :353-363)
            outputs = [o for o in src.output_names if o not in self._removed]
            if not outputs and self._added:
                outputs = [self._added[-1]["name"]]
            if not outputs:
                raise ValueError("no outputs survive surgery; call set_outputs")
        builder.set_outputs(*outputs)
        new_graph = builder.build()

        # params: carry over retained layers, init only the genuinely new ones
        # (fresh values come from the canonical ComputationGraph.init scheme so
        # transfer-built and freshly built graphs initialize identically)
        fresh = None
        new_params = {}
        for v in new_graph.vertices:
            if v.layer is None or not v.layer.has_params():
                continue
            if v.name in self._params and v.name in kept:
                new_params[v.name] = dict(self._params[v.name])
            else:
                if fresh is None:
                    fresh = new_graph.init()
                new_params[v.name] = fresh[v.name]
        return new_graph, new_params
