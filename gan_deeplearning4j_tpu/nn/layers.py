"""Layer zoo — the layers the reference exercises plus the few extras its
wider configs need (SURVEY §2.2 D7).

Each layer is a frozen config dataclass with pure ``init``/``apply``:

- ``init(key, in_type) -> params``: a flat dict of named arrays. Names match
  DL4J's (``W``/``b``; BatchNorm ``gamma``/``beta``/``mean``/``var``) because
  the reference's weight-sync protocol addresses params by
  (layer, name) — dl4jGANComputerVision.java:429-542.
- ``apply(params, x, train, rng) -> (y, state_updates)``: ``state_updates`` is
  a dict of non-trainable params rewritten during the training forward pass
  (BatchNorm running stats) or None.
- ``output_type(in_type)``: shape inference for GraphBuilder.
- ``param_roles()``: name -> role ("weight" | "bias" | "state"); L2 applies to
  weights only, updaters skip "state".

All compute dispatches to the functional ops layer (XLA→MXU), never inline
math, so pallas/XLA-level optimization happens in exactly one place.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from gan_deeplearning4j_tpu.nn.input_type import InputType
from gan_deeplearning4j_tpu.ops import activations as act_ops
from gan_deeplearning4j_tpu.ops import conv as conv_ops
from gan_deeplearning4j_tpu.ops import initializers as init_ops
from gan_deeplearning4j_tpu.ops import linear as linear_ops
from gan_deeplearning4j_tpu.ops import losses as loss_ops
from gan_deeplearning4j_tpu.ops import norm as norm_ops
from gan_deeplearning4j_tpu.optim.updaters import UpdaterSpec, updater_from_dict
from gan_deeplearning4j_tpu.runtime.dtype import get_default_dtype

IntPair = Union[int, Tuple[int, int]]

_pair = conv_ops._pair  # single int-or-tuple normalizer shared with the ops layer


@dataclasses.dataclass(frozen=True)
class Layer:
    """Base layer config. ``activation``/``weight_init``/``updater``/``l2`` of
    None mean "inherit the graph default" (resolved by GraphBuilder, matching
    DL4J's NeuralNetConfiguration defaulting)."""

    activation: Optional[str] = None
    weight_init: Optional[str] = None
    updater: Optional[UpdaterSpec] = None
    l2: Optional[float] = None

    # -- to be implemented by subclasses -----------------------------------
    def init(self, key, in_type: InputType) -> Dict[str, jnp.ndarray]:
        return {}

    def apply(self, params, x, *, train: bool, rng=None):
        raise NotImplementedError

    def output_type(self, in_type: InputType) -> InputType:
        raise NotImplementedError

    def param_roles(self) -> Dict[str, str]:
        return {}

    # -- common helpers -----------------------------------------------------
    @property
    def kind(self) -> str:
        return type(self).__name__

    def _act(self, x):
        return act_ops.get(self.activation or "identity")(x)

    def has_params(self) -> bool:
        return bool(self.param_roles())

    def to_dict(self) -> dict:
        d = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if isinstance(v, UpdaterSpec):
                v = v.to_dict()
            d[f.name] = v
        d["type"] = self.kind
        return d


@dataclasses.dataclass(frozen=True)
class DenseLayer(Layer):
    """Fully-connected layer (DL4J DenseLayer; e.g.
    dl4jGANComputerVision.java:155-158 dense 1024)."""

    n_out: int = 0
    n_in: Optional[int] = None  # inferred from in_type when None

    def _n_in(self, in_type: InputType) -> int:
        return self.n_in if self.n_in is not None else in_type.features

    def init(self, key, in_type):
        n_in = self._n_in(in_type)
        kw, _ = jax.random.split(key)
        w = init_ops.get(self.weight_init or "xavier")(kw, (n_in, self.n_out), get_default_dtype())
        b = jnp.zeros((self.n_out,), get_default_dtype())
        return {"W": w, "b": b}

    def apply(self, params, x, *, train: bool, rng=None):
        return self._act(linear_ops.dense(x, params["W"], params["b"])), None

    def output_type(self, in_type):
        return InputType.feed_forward(self.n_out)

    def param_roles(self):
        return {"W": "weight", "b": "bias"}


@dataclasses.dataclass(frozen=True)
class OutputLayer(DenseLayer):
    """Dense + attached loss (DL4J OutputLayer: XENT sigmoid at
    dl4jGANComputerVision.java:159-162, MCXENT softmax at :358-362)."""

    loss: str = "xent"

    def loss_fn(self, probs, labels):
        return loss_ops.get(self.loss)(probs, labels)


@dataclasses.dataclass(frozen=True)
class LossLayer(Layer):
    """Parameterless loss attachment (for WGAN critics etc.): passes input
    through an activation and binds a loss."""

    loss: str = "mse"

    def apply(self, params, x, *, train: bool, rng=None):
        return self._act(x), None

    def output_type(self, in_type):
        return in_type

    def loss_fn(self, preds, labels):
        return loss_ops.get(self.loss)(preds, labels)


@dataclasses.dataclass(frozen=True)
class BatchNormalization(Layer):
    """BatchNorm over the trailing feature/channel axis (DL4J
    BatchNormalization; dis at dl4jGANComputerVision.java:132-135, gen at
    :186,197-199). Running ``mean``/``var`` are named params with role
    "state" — updated by the training forward pass and copied between graphs
    by name in the reference's sync protocol (:437-440,498-500,523-527)."""

    decay: float = norm_ops.DEFAULT_DECAY
    eps: float = norm_ops.DEFAULT_EPS

    @staticmethod
    def _n_features(in_type: InputType) -> int:
        return in_type.shape[-1] if in_type.kind == "cnn" else in_type.features

    def init(self, key, in_type):
        n = self._n_features(in_type)
        dt = get_default_dtype()
        return {
            "gamma": jnp.ones((n,), dt),
            "beta": jnp.zeros((n,), dt),
            "mean": jnp.zeros((n,), dt),
            "var": jnp.ones((n,), dt),
        }

    def apply(self, params, x, *, train: bool, rng=None):
        if train:
            y, new_mean, new_var = norm_ops.batch_norm_train(
                x, params["gamma"], params["beta"], params["mean"], params["var"],
                eps=self.eps, decay=self.decay,
            )
            return self._act(y), {"mean": new_mean, "var": new_var}
        y = norm_ops.batch_norm_inference(
            x, params["gamma"], params["beta"], params["mean"], params["var"], eps=self.eps
        )
        return self._act(y), None

    def output_type(self, in_type):
        return in_type

    def param_roles(self):
        # DL4J applies no L2 to BN gamma/beta; roles "gain"/"bias" are exempt
        return {"gamma": "gain", "beta": "bias", "mean": "state", "var": "state"}


@dataclasses.dataclass(frozen=True)
class ConvolutionLayer(Layer):
    """2-D convolution (DL4J ConvolutionLayer; 5x5 s2 at
    dl4jGANComputerVision.java:136-139, 5x5 s1 p2 at :207-213). Kernel stored
    HWIO; shape semantics = DL4J Truncate mode."""

    kernel: IntPair = 5
    stride: IntPair = 1
    padding: IntPair = 0
    n_out: int = 0
    n_in: Optional[int] = None

    def _n_in(self, in_type: InputType) -> int:
        return self.n_in if self.n_in is not None else in_type.channels

    def init(self, key, in_type):
        kh, kw = _pair(self.kernel)
        n_in = self._n_in(in_type)
        wkey, _ = jax.random.split(key)
        w = init_ops.get(self.weight_init or "xavier")(
            wkey, (kh, kw, n_in, self.n_out), get_default_dtype()
        )
        b = jnp.zeros((self.n_out,), get_default_dtype())
        return {"W": w, "b": b}

    def apply(self, params, x, *, train: bool, rng=None):
        y = conv_ops.conv2d(x, params["W"], params["b"], stride=self.stride, padding=self.padding)
        return self._act(y), None

    def output_type(self, in_type):
        h, w, _ = in_type.shape
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        return InputType.convolutional(
            conv_ops.conv_out_size(h, kh, sh, ph),
            conv_ops.conv_out_size(w, kw, sw, pw),
            self.n_out,
        )

    def param_roles(self):
        return {"W": "weight", "b": "bias"}


@dataclasses.dataclass(frozen=True)
class Deconvolution2D(ConvolutionLayer):
    """Transposed convolution (DL4J Deconvolution2D — unused by the reference
    graphs but part of the DL4J layer surface and the BASELINE.md CIFAR/CelebA
    configs)."""

    def apply(self, params, x, *, train: bool, rng=None):
        y = conv_ops.conv2d_transpose(
            x, params["W"], params["b"], stride=self.stride, padding=self.padding
        )
        return self._act(y), None

    def output_type(self, in_type):
        h, w, _ = in_type.shape
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        return InputType.convolutional(
            (h - 1) * sh - 2 * ph + kh,
            (w - 1) * sw - 2 * pw + kw,
            self.n_out,
        )


@dataclasses.dataclass(frozen=True)
class SubsamplingLayer(Layer):
    """Pooling (DL4J SubsamplingLayer MAX 2x2 s1,
    dl4jGANComputerVision.java:140-143,150-154)."""

    pool: str = "max"
    kernel: IntPair = 2
    stride: IntPair = 2
    padding: IntPair = 0

    def apply(self, params, x, *, train: bool, rng=None):
        if self.pool == "max":
            y = conv_ops.max_pool2d(x, kernel=self.kernel, stride=self.stride, padding=self.padding)
        elif self.pool == "avg":
            y = conv_ops.avg_pool2d(x, kernel=self.kernel, stride=self.stride, padding=self.padding)
        else:
            raise ValueError(f"unknown pool type {self.pool!r}")
        return self._act(y), None

    def output_type(self, in_type):
        h, w, c = in_type.shape
        kh, kw = _pair(self.kernel)
        sh, sw = _pair(self.stride)
        ph, pw = _pair(self.padding)
        return InputType.convolutional(
            conv_ops.conv_out_size(h, kh, sh, ph),
            conv_ops.conv_out_size(w, kw, sw, pw),
            c,
        )


@dataclasses.dataclass(frozen=True)
class Upsampling2D(Layer):
    """Nearest-neighbor upsampling (DL4J Upsampling2D,
    dl4jGANComputerVision.java:201-206)."""

    size: IntPair = 2

    def apply(self, params, x, *, train: bool, rng=None):
        return conv_ops.upsample2d(x, scale=self.size), None

    def output_type(self, in_type):
        h, w, c = in_type.shape
        sh, sw = _pair(self.size)
        return InputType.convolutional(h * sh, w * sw, c)


@dataclasses.dataclass(frozen=True)
class ActivationLayer(Layer):
    """Standalone activation."""

    def apply(self, params, x, *, train: bool, rng=None):
        return self._act(x), None

    def output_type(self, in_type):
        return in_type


@dataclasses.dataclass(frozen=True)
class DropoutLayer(Layer):
    """Inverted dropout (train-only; DL4J semantics — unused by the reference
    graphs, part of the wider surface)."""

    rate: float = 0.5

    def apply(self, params, x, *, train: bool, rng=None):
        if not train or self.rate <= 0.0:
            return x, None
        if rng is None:
            raise ValueError("DropoutLayer needs an rng key when train=True")
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0), None

    def output_type(self, in_type):
        return in_type


_LAYER_CLASSES = {
    c.__name__: c
    for c in (
        DenseLayer,
        OutputLayer,
        LossLayer,
        BatchNormalization,
        ConvolutionLayer,
        Deconvolution2D,
        SubsamplingLayer,
        Upsampling2D,
        ActivationLayer,
        DropoutLayer,
    )
}

#: layer types owned by optional subsystems, resolved on first use so a
#: quantized checkpoint round-trips without nn/ importing quant/ (and a
#: process that never loads one pays no import)
_EXTERNAL_LAYER_MODULES = {
    "QuantDenseLayer": "gan_deeplearning4j_tpu.quant.layers",
}


def register_layer(cls):
    """Register a Layer subclass for ``layer_from_dict`` resolution — the
    extension point quant/ (and any future subsystem with its own layer
    types) registers through. Usable as a class decorator."""
    _LAYER_CLASSES[cls.__name__] = cls
    return cls


def layer_from_dict(d: dict) -> Layer:
    d = dict(d)
    kind = d.pop("type")
    if kind not in _LAYER_CLASSES and kind in _EXTERNAL_LAYER_MODULES:
        import importlib

        importlib.import_module(_EXTERNAL_LAYER_MODULES[kind])
    if kind not in _LAYER_CLASSES:
        raise KeyError(f"unknown layer type {kind!r}")
    if d.get("updater") is not None:
        d["updater"] = updater_from_dict(d["updater"])
    for k in ("kernel", "stride", "padding", "size"):
        if isinstance(d.get(k), list):
            d[k] = tuple(d[k])
    return _LAYER_CLASSES[kind](**d)
