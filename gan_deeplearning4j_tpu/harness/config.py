"""ExperimentConfig — the reference's hyperparameter block as one typed,
overridable config (SURVEY §5 config/flag system).

The reference hardcodes everything as ``static final`` constants
(dl4jGANComputerVision.java:66-92) and *ignores* its CLI args (:99-101).
Field-for-field the defaults below equal the reference's values; unlike the
reference they are overridable from JSON and argparse.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from typing import Optional, Sequence


@dataclasses.dataclass
class ExperimentConfig:
    # -- model family (BASELINE.md configs; "mnist" is the reference app) ----
    # "mnist" | "tabular" | "image" (+aliases "cifar10"/"celeba64")
    model_family: str = "mnist"

    # -- batching & shapes (dl4jGANComputerVision.java:66-81) ---------------
    batch_size_train: int = 200
    batch_size_pred: int = 500
    num_features: int = 784
    num_classes: int = 10
    num_classes_dis: int = 1
    num_iterations: int = 2  # the while-loop bound (:72,408)
    latent_grid: int = 10  # 10×10 sample grid (:74-75)
    height: int = 28
    width: int = 28
    channels: int = 1
    z_size: int = 2

    # -- learning rates & reg (:82-86) --------------------------------------
    dis_learning_rate: float = 0.002
    gen_learning_rate: float = 0.004
    frozen_learning_rate: float = 0.0
    l2: float = 1e-4
    grad_clip: float = 1.0
    seed: int = 666  # (:85)

    # -- cadences & paths (:76-77,87-90) -------------------------------------
    print_every: int = 1
    save_every: int = 1
    data_dir: str = "data"
    output_dir: str = "output"
    file_prefix: str = "mnist"
    save_models: bool = True
    # Checkpoint every k-th iteration (reference cadence: every iteration,
    # dl4jGANComputerVision.java:605-619 — the default preserves it). Larger
    # values also re-enable the lax.scan device loop between checkpoint
    # boundaries, which per-iteration checkpointing forbids.
    checkpoint_every: int = 1
    resume: bool = False  # restore states from output_dir before training

    # -- model zoo scenario axes (zoo/manifest.py, docs/ZOO.md) --------------
    # "none" keeps the reference's unconditional generator; "class" widens
    # the generator/gan input to [z | one-hot(class)] (the label embedding
    # is the first dense layer's extra rows) and trains the generator on
    # the real batch's labels. The discriminator — and through it the
    # transfer classifier — stays unconditional, so the paper's dis-feature
    # transfer claim is untouched. Serving-side, a conditional bundle
    # accepts ``POST /v1/sample?class=k`` (docs/SERVING.md).
    conditioning: str = "none"
    # Which dataset identity this run trains against ("mnist" |
    # "fashion_mnist" | "cifar_shaped"). Keys the zoo data loaders AND the
    # canary gate's real-rows identity: a bundle is only FID-scored against
    # reals of its own dataset (deploy/canary.py fails closed on mismatch).
    dataset: str = "mnist"

    # -- WGAN-GP (BASELINE.md config 5; ignored by the XENT families) --------
    # critic steps per generator step; the incoming train batch is split into
    # n_critic equal critic minibatches (batch_size_train % n_critic == 0)
    n_critic: int = 5
    gp_lambda: float = 10.0

    # -- dis-LR step decay (round-5 VERDICT item 4) --------------------------
    # The G/D late-imbalance lever: every `dis_lr_decay_every` iterations the
    # discriminator's EFFECTIVE learning rate is multiplied by
    # `dis_lr_decay_rate` (staircase schedule). Computed inside the jitted
    # step from the carried step counter — a traced scalar, so it works
    # unchanged inside the lax.scan device loop with zero recompiles.
    # Applies on the fused paths (single-chip, pmean, and the averaging
    # device loop); 0 = off. The default (off) preserves the reference's
    # constant-LR behavior (dl4jGANComputerVision.java:82-86).
    dis_lr_decay_every: int = 0
    dis_lr_decay_rate: float = 1.0

    # -- label softening (:404-406) ------------------------------------------
    label_softening: float = 0.05
    # The reference samples the ±0.05·randn noise ONCE and reuses it every
    # batch — a quirk SURVEY §7 says to decide deliberately. Default preserves
    # reference behavior; True resamples per batch (standard practice).
    resample_label_noise: bool = False

    # -- distributed (the Spark local[4] + TrainingMaster block, :317-330) ---
    # "none": single chip; "pmean": per-step gradient sync over the mesh;
    # "param_averaging": k-step synchronous parameter averaging (reference
    # semantics, averagingFrequency=10 :326).
    distributed: str = "none"
    # Cross-replica weight-update sharding (parallel/update_sharding.py):
    # with distributed="pmean", partition the flat param/updater key space
    # across the data axis (the mesh checkpoint plane's round-robin, so
    # checkpoint shard files map 1:1 onto compute shards), reduce-scatter
    # grads, apply the optimizer update only for owned keys (updater state
    # resident at ~1/N per device), and all-gather the params. Identical
    # math to the replicated update — proven bit-exact on the CPU backend
    # by the parity tests (docs/RESILIENCE.md, update-sharding section).
    update_sharding: bool = False
    averaging_frequency: int = 10
    batch_size_per_worker: int = 200
    prefetch: int = 0  # workerPrefetchNumBatches (:328); >0 enables device prefetch
    use_accelerator: bool = True  # the useGpu flag (:92)
    # Mixed precision: "bf16" runs matmuls/convs in bfloat16 on the MXU with
    # f32 accumulation (params stay f32); None/"f32" computes in f32. The
    # reference pins global f32 (Nd4j.setDataType, :105) — bf16 is the
    # TPU-native analog of its cuDNN tensor-core path (Java/pom.xml:124-128).
    compute_dtype: Optional[str] = None
    # Parameter STORAGE dtype (round-4 VERDICT item 3): "bf16" stores params
    # + updater state (RmsProp cache etc.) in bfloat16, halving the HBM
    # traffic of this bandwidth-bound workload (roofline intensity 15-17 vs
    # ridge ~240, PROFILE.md). Implies compute_dtype=bf16 when that is unset
    # — pure-bf16 is the no-cast configuration; the f32-master alternative
    # (f32 params + bf16 compute) is exactly compute_dtype="bf16" alone.
    # None/"f32" keeps reference-parity f32 storage.
    param_dtype: Optional[str] = None

    # -- observability --------------------------------------------------------
    metrics_jsonl: Optional[str] = None
    profile_dir: Optional[str] = None
    # Fetch loss scalars from the device every k iterations in ONE batched
    # read (a per-step read is a pipeline barrier — ~200 ms through a
    # tunneled chip vs ~2-4 ms of device work; the reference never reads
    # losses at all, SURVEY §5). 1 = fetch every step. Also the device-loop
    # window bound: larger values amortize both the fetch and per-dispatch
    # latency further (the fetch costs ~90 ms fixed regardless of k).
    loss_fetch_every: int = 128

    def __post_init__(self) -> None:
        if self.param_dtype is not None and self.compute_dtype is None:
            from gan_deeplearning4j_tpu.runtime.dtype import parse_compute_dtype

            if parse_compute_dtype(self.param_dtype) is not None:
                # pure-bf16: computing in f32 from bf16 params would just
                # add cast traffic — storage dtype implies the compute dtype
                self.compute_dtype = "bf16"

    def validate(self) -> "ExperimentConfig":
        if self.model_family != "tabular" and self.num_features != (
            self.height * self.width * self.channels
        ):
            raise ValueError(
                f"num_features {self.num_features} != h*w*c "
                f"{self.height * self.width * self.channels}"
            )
        if self.distributed not in ("none", "pmean", "param_averaging"):
            raise ValueError(f"unknown distributed mode {self.distributed!r}")
        if self.update_sharding and self.distributed != "pmean":
            # param_averaging keeps per-worker DIVERGENT updater state
            # between averaging boundaries — there is no replicated update
            # to shard; single-chip has no data axis. Only the per-step
            # gradient-sync mode has the replicated-update redundancy this
            # optimization removes.
            raise ValueError(
                "update_sharding requires distributed='pmean' (the per-step "
                "gradient-sync mesh path); param_averaging workers hold "
                "divergent local updater state and 'none' has no mesh axis "
                "to shard over"
            )
        if self.dis_lr_decay_every < 0:
            raise ValueError("dis_lr_decay_every must be >= 0 (0 = off)")
        if self.checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        if self.dis_lr_decay_every and not 0.0 < self.dis_lr_decay_rate <= 1.0:
            raise ValueError(
                f"dis_lr_decay_rate {self.dis_lr_decay_rate} must be in (0, 1]"
            )
        if self.conditioning not in ("none", "class"):
            raise ValueError(
                f"unknown conditioning {self.conditioning!r} "
                f"(want 'none' or 'class')"
            )
        if self.conditioning == "class":
            if self.num_classes < 2:
                raise ValueError(
                    "class-conditional training needs num_classes >= 2 "
                    "(the one-hot label embedding is the condition)"
                )
            if self.distributed == "param_averaging":
                raise ValueError(
                    "conditioning='class' runs on the fused paths (single-"
                    "chip or pmean); the param-averaging phased path keeps "
                    "the reference's unconditional loop"
                )
        from gan_deeplearning4j_tpu.runtime.dtype import parse_compute_dtype

        parse_compute_dtype(self.compute_dtype)  # raises on unknown dtype
        parse_compute_dtype(self.param_dtype)
        from gan_deeplearning4j_tpu.models import registry

        family = registry.get(self.model_family)  # raises on unknown family
        if family.name == "wgan_gp":
            if self.conditioning == "class":
                raise ValueError(
                    "conditioning='class' is a GraphTrainer-family feature "
                    "(the fused alternating loop concatenates the label "
                    "embedding); the WGAN-GP critic-round program is "
                    "unconditional — queued in ROADMAP.md"
                )
            if self.n_critic < 1 or self.batch_size_train % self.n_critic:
                raise ValueError(
                    f"wgan_gp: batch_size_train {self.batch_size_train} must be "
                    f"divisible by n_critic {self.n_critic}"
                )
            if self.distributed == "param_averaging":
                raise ValueError(
                    "wgan_gp supports distributed='pmean' (per-step sync over "
                    "the mesh); k-step parameter averaging is a reference-"
                    "parity mode for the XENT families"
                )
            if self.update_sharding:
                raise ValueError(
                    "update_sharding is implemented for the GraphTrainer "
                    "families; the WGAN-GP trainer keeps the replicated "
                    "update (its critic-round program is its own)"
                )
        return self

    # -- overrides ------------------------------------------------------------
    @staticmethod
    def from_json(path: str) -> "ExperimentConfig":
        with open(path) as fh:
            return ExperimentConfig(**json.load(fh)).validate()

    def to_json(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(dataclasses.asdict(self), fh, indent=2)

    @staticmethod
    def parser() -> argparse.ArgumentParser:
        """Argparse with one flag per field (the CLI the reference echoes but
        ignores, made real)."""
        p = argparse.ArgumentParser(
            prog="gan_deeplearning4j_tpu",
            description="DCGAN-MNIST experiment (TPU-native rebuild)",
        )
        p.add_argument("--config", type=str, default=None, help="JSON config file")
        for f in dataclasses.fields(ExperimentConfig):
            arg = "--" + f.name.replace("_", "-")
            if f.type == "bool" or isinstance(f.default, bool):
                p.add_argument(arg, type=lambda s: s.lower() in ("1", "true", "yes"),
                               default=None, metavar="BOOL")
            elif f.default is None or f.type.startswith("Optional"):
                p.add_argument(arg, type=str, default=None)
            else:
                p.add_argument(arg, type=type(f.default), default=None)
        return p

    @staticmethod
    def from_args(argv: Optional[Sequence[str]] = None) -> "ExperimentConfig":
        args = vars(ExperimentConfig.parser().parse_args(argv))
        config_path = args.pop("config", None)
        base = (
            ExperimentConfig.from_json(config_path)
            if config_path
            else ExperimentConfig()
        )
        overrides = {k: v for k, v in args.items() if v is not None}
        return dataclasses.replace(base, **overrides).validate()
