"""WganGpExperiment — WGAN-GP as a full framework citizen.

Round 1 shipped WGAN-GP as a side-car trainer outside the registry; this
wraps :class:`~gan_deeplearning4j_tpu.models.wgan_gp.WganGpTrainer` in the
``GanExperiment`` surface so the CLI, checkpoint/resume, metrics, exports,
prefetch, and bench all apply (BASELINE.md config 5).

Loop semantics differ from the reference's XENT loop
(dl4jGANComputerVision.java:408-621): one "iteration" is one WGAN-GP *round* —
``n_critic`` critic steps followed by one generator step (Gulrajani et al.
2017, Algorithm 1). The incoming real batch is split into ``n_critic`` equal
critic minibatches, so ``batch_size_train`` plays the role of the round's
total real-image budget; the generator batch matches one critic minibatch.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gan_deeplearning4j_tpu.harness.config import ExperimentConfig
from gan_deeplearning4j_tpu.harness.experiment import (
    _MESH_SHARD_RE,
    GanExperiment,
    cost_analysis_dict,
    latent_grid,
    shape_struct,
)
from gan_deeplearning4j_tpu.models import registry
from gan_deeplearning4j_tpu.models.wgan_gp import WganGpTrainer
from gan_deeplearning4j_tpu.parallel.trainer import TrainState
from gan_deeplearning4j_tpu.runtime import TpuEnvironment
from gan_deeplearning4j_tpu.runtime.dtype import (
    compute_dtype_scope,
    parse_compute_dtype,
)
from gan_deeplearning4j_tpu.utils import write_model
from gan_deeplearning4j_tpu.utils.metrics import MetricsLogger
from gan_deeplearning4j_tpu.utils.profiling import PhaseTimer
from gan_deeplearning4j_tpu.utils.serializer import read_model


class WganGpExperiment(GanExperiment):
    """GanExperiment-surface wrapper over the fused WGAN-GP trainer.

    Inherits only the generic ``run()`` loop (exports/checkpoints/metrics
    cadence) and ``export_predictions``'s no-classifier refusal; model
    construction, the training round, and (de)serialization are WGAN-GP's
    own — there is no stacked ``gan`` graph and no named-param sync protocol
    to reuse.
    """

    def __init__(self, config: ExperimentConfig = None, mesh=None):
        # deliberately NOT calling GanExperiment.__init__: the three-graph
        # protocol does not apply; only run()'s loop is shared
        from gan_deeplearning4j_tpu.runtime.environment import enable_compilation_cache

        enable_compilation_cache()  # skipped super().__init__ would have done this
        config = config if config is not None else ExperimentConfig(model_family="wgan_gp")
        self.config = config.validate()
        cfg = config
        self._compute_dtype = parse_compute_dtype(cfg.compute_dtype)
        self.family = registry.get(cfg.model_family)
        self.model_cfg = self.family.make_model_config(cfg)

        if mesh is None and cfg.distributed != "none":
            mesh = TpuEnvironment().make_mesh()
        self.mesh = mesh

        self.trainer = WganGpTrainer(self.model_cfg, mesh=mesh)
        with compute_dtype_scope(self._compute_dtype):
            self.critic_state, self.gen_state = self.trainer.init_states(seed=cfg.seed)
        self._param_dtype = parse_compute_dtype(cfg.param_dtype)
        if self._param_dtype is not None:  # bf16 storage (VERDICT r4 item 3)
            self.critic_state = self._cast_state(self.critic_state)
            self.gen_state = self._cast_state(self.gen_state)
        # GanExperiment.run() hooks: no transfer classifier; the prefetch
        # sharding probe reads dis_trainer
        self.cv = None
        self.cv_trainer = None
        self.dis_trainer = self.trainer

        self._gen_fwd = jax.jit(
            lambda p, z: self.trainer.generator.output(p, z, train=False)
        )
        # serving-publish surface: the inherited publish_for_serving writes
        # ``self.gen``/``self.gen_params`` (generator-only bundle, cv=None)
        self.gen = self.trainer.generator
        # Per-round RNG is DERIVED, not carried: fold_in(base, gen step).
        # The generator steps exactly once per round, so every round gets a
        # distinct key — and a resumed run (gen step restored from the
        # checkpoint) replays the identical key stream, which is what makes
        # supervisor resume bit-exact (tests/test_zoo.py). A carried
        # split-per-round key would never be checkpointed and diverge.
        self._base_key = jax.random.PRNGKey(cfg.seed + 2)
        self._z_grid = latent_grid(cfg.latent_grid, self.model_cfg.z_size)

        self.timer = PhaseTimer()
        self.metrics = MetricsLogger(cfg.metrics_jsonl)
        self.batch_counter = 0
        # run()'s windowed device loop works here too (train_iterations
        # scans whole WGAN-GP rounds)
        self._supports_device_loop = True

    # ------------------------------------------------------------------
    def train_iteration(self, real_features, real_labels=None) -> Dict:
        """One WGAN-GP round. ``real_labels`` is accepted (the run() loop is
        label-agnostic) and ignored — the critic is unsupervised."""
        with compute_dtype_scope(self._compute_dtype):
            return self._train_round(real_features)

    def _train_round(self, real_features) -> Dict:
        n = self.model_cfg.n_critic
        real = jnp.asarray(real_features, jnp.float32)
        b = int(real.shape[0])
        if b == 0:
            raise ValueError("empty batch")
        if b < n:
            # ragged epoch tail smaller than one row per critic step: pad by
            # cycling (bounded duplication, same policy as the averaging
            # trainer's tail handling)
            real = jnp.tile(real, (-(-n // b), 1))[:n]
            b = n
        elif b % n:
            # drop the < n_critic remainder rows rather than aborting the run
            # (the XENT path accepts arbitrary b; config validation keeps the
            # configured batch divisible, so this only fires on epoch tails)
            b = (b // n) * n
            real = real[:b]
        batches = real.reshape(n, b // n, -1)
        sub = jax.random.fold_in(self._base_key, int(self.gen_state.step))
        with self.timer.phase("train_round"):
            self.critic_state, self.gen_state, c_loss, g_loss = self.trainer.train_round(
                self.critic_state, self.gen_state, batches, sub
            )
        # device scalars, same contract as the fused DCGAN path
        return {"d_loss": c_loss, "g_loss": g_loss, "cv_loss": jnp.float32(jnp.nan)}

    def train_iterations(self, features, labels=None) -> Dict:
        """K WGAN-GP rounds in ONE device dispatch (the scan device loop —
        same contract as GanExperiment.train_iterations). ``features``:
        (K, B, num_features); a B not divisible by n_critic gets the same
        tail policy as the sequential round (pad-by-cycling / drop
        remainder). ``labels`` accepted and ignored — the critic is
        unsupervised."""
        del labels
        n = self.model_cfg.n_critic
        with compute_dtype_scope(self._compute_dtype):
            rounds = jnp.asarray(features, jnp.float32)
            k, b = int(rounds.shape[0]), int(rounds.shape[1])
            # same tail policy as _train_round: pad-by-cycling below one row
            # per critic step, else drop the < n_critic remainder rows
            if b < n:
                rounds = jnp.tile(rounds, (1, -(-n // b), 1))[:, :n]
                b = n
            elif b % n:
                b = (b // n) * n
                rounds = rounds[:, :b]
            rounds = rounds.reshape(k, n, b // n, -1)
            # same derivation as the sequential round: keyed off the gen
            # step at window entry (the scan folds per-round on top)
            sub = jax.random.fold_in(self._base_key, int(self.gen_state.step))
            with self.timer.phase("train_rounds"):
                (
                    self.critic_state,
                    self.gen_state,
                    c_losses,
                    g_losses,
                ) = self.trainer.train_rounds(
                    self.critic_state, self.gen_state, rounds, sub
                )
        nan = jnp.full((k,), jnp.nan, jnp.float32)
        return {"d_loss": c_losses, "g_loss": g_losses, "cv_loss": nan}

    @property
    def gen_params(self):
        """The sampler's current params — lets the inherited
        ``export_manifold`` drive the WGAN generator unchanged."""
        return self.gen_state.params

    # -- cost model ------------------------------------------------------
    def flops_per_iteration(self, batch_size=None) -> float:
        """FLOPs of one WGAN-GP round (critic scan + generator step) from
        XLA's post-optimization cost analysis — includes the grad-of-grad
        penalty as compiled. None if the backend has no cost model.

        Scan caveat (round-4 finding, scripts/profile_wgan.py): XLA's
        cost_analysis counts a ``lax.scan`` body ONCE, independent of trip
        count — verified by lowering the round at n_critic 2 vs 4 (identical
        "flops"). The critic round therefore multiplies by ``n_critic``;
        without it every WGAN MFU reads ~n_critic× too low (round 3's 3.2%
        was really ~16%)."""
        mcfg = self.model_cfg
        b = batch_size or self.config.batch_size_train
        n = mcfg.n_critic
        f32 = jnp.float32
        struct = shape_struct
        key = jax.ShapeDtypeStruct((2,), jnp.uint32)
        with compute_dtype_scope(self._compute_dtype):
            critic = cost_analysis_dict(self.trainer._critic_round.lower(
                struct(self.critic_state), struct(self.gen_state.params),
                jax.ShapeDtypeStruct((n, b // n, mcfg.num_features), f32), key,
            ).compile().cost_analysis())
            gen = cost_analysis_dict(self.trainer._gen_step.lower(
                struct(self.gen_state), struct(self.critic_state.params),
                jax.ShapeDtypeStruct((b // n, mcfg.z_size), f32),
            ).compile().cost_analysis())
        if not critic or "flops" not in critic or not gen or "flops" not in gen:
            return None
        return float(critic["flops"]) * n + float(gen["flops"])

    # -- exports --------------------------------------------------------
    # export_manifold is inherited from GanExperiment: it reads
    # ``self._gen_fwd``/``self.gen_params``, both provided here.

    def sample(self, num: int, seed: int = 0) -> np.ndarray:
        """(num, H, W, C) generator samples for eval/FID."""
        with compute_dtype_scope(self._compute_dtype):
            out = self.trainer.sample(self.gen_state, jax.random.PRNGKey(seed), num)
        return np.asarray(out)

    # -- checkpointing --------------------------------------------------
    def save_models(self, directory: Optional[str] = None) -> List[str]:
        """Critic + generator zips with updater state, same format/cadence as
        the four-model save (ModelSerializer analog). ``directory`` overrides
        ``config.output_dir`` — the resilience store's publish callback
        writes through it, same contract as GanExperiment.save_models."""
        cfg = self.config
        directory = directory or cfg.output_dir
        os.makedirs(directory, exist_ok=True)
        paths = []
        for name, graph, state in (
            ("critic", self.trainer.critic, self.critic_state),
            ("gen", self.trainer.generator, self.gen_state),
        ):
            path = os.path.join(directory, f"{cfg.file_prefix}_{name}_model.zip")
            write_model(path, graph, state, save_updater=True)
            paths.append(path)
        return paths

    # -- supervisor / mesh-publish surface (resilience/supervisor.py) ----
    def _publish_step(self) -> int:
        # no stacked gan graph; the generator steps once per round
        return int(self.gen_state.step)

    def digest_states(self) -> Dict:
        """Canonical states for bit-exactness digests — the supervisor's
        restore-verification contract (both carried states are plain trees,
        no update sharding here, so no tree-form conversion is needed)."""
        return {"critic": self.critic_state, "gen": self.gen_state}

    def _flat_state(self) -> Dict:
        """Flat ``<model>/{params|updater|step}`` namespace for the mesh
        checkpoint plane — same shape as GanExperiment._flat_state, with the
        WGAN pair in place of the four-graph protocol (the generator here
        carries updater state: it is a trained model, not a frozen sampler)."""
        from gan_deeplearning4j_tpu.utils.serializer import _flatten

        flat: Dict = {}
        for name, state in (("critic", self.critic_state),
                            ("gen", self.gen_state)):
            _flatten(f"{name}/params", state.params, flat)
            _flatten(f"{name}/updater", state.opt_state, flat)
            flat[f"{name}/step"] = state.step
        return flat

    def _load_models_sharded(self, directory: str, shard_files: List[str],
                             stored) -> int:
        from gan_deeplearning4j_tpu.utils.serializer import _unflatten

        flat = self._merged_shard_state(directory, shard_files)

        def train_state(model: str) -> TrainState:
            return TrainState(
                _unflatten(flat, f"{model}/params"),
                _unflatten(flat, f"{model}/updater"),
                jnp.asarray(int(np.asarray(flat[f"{model}/step"])), jnp.int32),
            )

        self.critic_state = stored(train_state("critic"))
        self.gen_state = stored(train_state("gen"))
        self.batch_counter = int(self.gen_state.step)
        return self.batch_counter

    def load_models(self, directory: Optional[str] = None) -> int:
        cfg = self.config
        directory = directory or cfg.output_dir
        prefix = os.path.join(directory, cfg.file_prefix)

        def _stored(st: TrainState) -> TrainState:
            if self._param_dtype is not None:
                st = self._cast_state(st)
            if self.mesh is not None:
                st = jax.device_put(
                    st,
                    jax.sharding.NamedSharding(self.mesh, jax.sharding.PartitionSpec()),
                )
            # Re-own every restored leaf through a compiled multiply-by-one
            # BEFORE the train round's donation sees it: on CPU the implicit
            # transfer of a checkpoint's numpy array can be zero-copy, so
            # the donated buffer aliases memory the runtime does not own and
            # freeing it corrupts the glibc heap a few allocations later
            # (replicated device_put over virtual host-platform devices
            # carries the same hazard). A real compute op forces fresh
            # executable-owned output allocations — jnp.copy lowers to an
            # elidable alias, which does NOT; x*1 is bit-exact.
            return jax.jit(lambda s: jax.tree_util.tree_map(
                lambda a: a * 1, s))(st)

        # elastic mesh restore: a generation of *_state_shard-K-of-M.zip
        # files merges back regardless of M, same contract as GanExperiment
        shard_files = sorted(
            n for n in os.listdir(directory)
            if _MESH_SHARD_RE.search(n) and n.startswith(cfg.file_prefix)
        )
        if shard_files:
            return self._load_models_sharded(directory, shard_files, _stored)

        def _state(path: str) -> TrainState:
            _, params, opt_state, step = read_model(path)
            return _stored(TrainState(params, opt_state, jnp.asarray(step, jnp.int32)))

        self.critic_state = _state(f"{prefix}_critic_model.zip")
        self.gen_state = _state(f"{prefix}_gen_model.zip")
        self.batch_counter = int(self.gen_state.step)
        return self.batch_counter
