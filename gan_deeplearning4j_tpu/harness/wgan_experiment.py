"""WganGpExperiment — WGAN-GP as a full framework citizen.

Round 1 shipped WGAN-GP as a side-car trainer outside the registry; this
wraps :class:`~gan_deeplearning4j_tpu.models.wgan_gp.WganGpTrainer` in the
``GanExperiment`` surface so the CLI, checkpoint/resume, metrics, exports,
prefetch, and bench all apply (BASELINE.md config 5).

Loop semantics differ from the reference's XENT loop
(dl4jGANComputerVision.java:408-621): one "iteration" is one WGAN-GP *round* —
``n_critic`` critic steps followed by one generator step (Gulrajani et al.
2017, Algorithm 1). The incoming real batch is split into ``n_critic`` equal
critic minibatches, so ``batch_size_train`` plays the role of the round's
total real-image budget; the generator batch matches one critic minibatch.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gan_deeplearning4j_tpu.harness.config import ExperimentConfig
from gan_deeplearning4j_tpu.harness.experiment import (
    GanExperiment,
    cost_analysis_dict,
    latent_grid,
    shape_struct,
)
from gan_deeplearning4j_tpu.models import registry
from gan_deeplearning4j_tpu.models.wgan_gp import WganGpTrainer
from gan_deeplearning4j_tpu.parallel.trainer import TrainState
from gan_deeplearning4j_tpu.runtime import TpuEnvironment
from gan_deeplearning4j_tpu.runtime.dtype import (
    compute_dtype_scope,
    parse_compute_dtype,
)
from gan_deeplearning4j_tpu.utils import write_model
from gan_deeplearning4j_tpu.utils.metrics import MetricsLogger
from gan_deeplearning4j_tpu.utils.profiling import PhaseTimer
from gan_deeplearning4j_tpu.utils.serializer import read_model


class WganGpExperiment(GanExperiment):
    """GanExperiment-surface wrapper over the fused WGAN-GP trainer.

    Inherits only the generic ``run()`` loop (exports/checkpoints/metrics
    cadence) and ``export_predictions``'s no-classifier refusal; model
    construction, the training round, and (de)serialization are WGAN-GP's
    own — there is no stacked ``gan`` graph and no named-param sync protocol
    to reuse.
    """

    def __init__(self, config: ExperimentConfig = None, mesh=None):
        # deliberately NOT calling GanExperiment.__init__: the three-graph
        # protocol does not apply; only run()'s loop is shared
        from gan_deeplearning4j_tpu.runtime.environment import enable_compilation_cache

        enable_compilation_cache()  # skipped super().__init__ would have done this
        config = config if config is not None else ExperimentConfig(model_family="wgan_gp")
        self.config = config.validate()
        cfg = config
        self._compute_dtype = parse_compute_dtype(cfg.compute_dtype)
        self.family = registry.get(cfg.model_family)
        self.model_cfg = self.family.make_model_config(cfg)

        if mesh is None and cfg.distributed != "none":
            mesh = TpuEnvironment().make_mesh()
        self.mesh = mesh

        self.trainer = WganGpTrainer(self.model_cfg, mesh=mesh)
        with compute_dtype_scope(self._compute_dtype):
            self.critic_state, self.gen_state = self.trainer.init_states(seed=cfg.seed)
        self._param_dtype = parse_compute_dtype(cfg.param_dtype)
        if self._param_dtype is not None:  # bf16 storage (VERDICT r4 item 3)
            self.critic_state = self._cast_state(self.critic_state)
            self.gen_state = self._cast_state(self.gen_state)
        # GanExperiment.run() hooks: no transfer classifier; the prefetch
        # sharding probe reads dis_trainer
        self.cv = None
        self.cv_trainer = None
        self.dis_trainer = self.trainer

        self._gen_fwd = jax.jit(
            lambda p, z: self.trainer.generator.output(p, z, train=False)
        )
        self._key = jax.random.PRNGKey(cfg.seed + 2)
        self._z_grid = latent_grid(cfg.latent_grid, self.model_cfg.z_size)

        self.timer = PhaseTimer()
        self.metrics = MetricsLogger(cfg.metrics_jsonl)
        self.batch_counter = 0
        # run()'s windowed device loop works here too (train_iterations
        # scans whole WGAN-GP rounds)
        self._supports_device_loop = True

    # ------------------------------------------------------------------
    def train_iteration(self, real_features, real_labels=None) -> Dict:
        """One WGAN-GP round. ``real_labels`` is accepted (the run() loop is
        label-agnostic) and ignored — the critic is unsupervised."""
        with compute_dtype_scope(self._compute_dtype):
            return self._train_round(real_features)

    def _train_round(self, real_features) -> Dict:
        n = self.model_cfg.n_critic
        real = jnp.asarray(real_features, jnp.float32)
        b = int(real.shape[0])
        if b == 0:
            raise ValueError("empty batch")
        if b < n:
            # ragged epoch tail smaller than one row per critic step: pad by
            # cycling (bounded duplication, same policy as the averaging
            # trainer's tail handling)
            real = jnp.tile(real, (-(-n // b), 1))[:n]
            b = n
        elif b % n:
            # drop the < n_critic remainder rows rather than aborting the run
            # (the XENT path accepts arbitrary b; config validation keeps the
            # configured batch divisible, so this only fires on epoch tails)
            b = (b // n) * n
            real = real[:b]
        batches = real.reshape(n, b // n, -1)
        self._key, sub = jax.random.split(self._key)
        with self.timer.phase("train_round"):
            self.critic_state, self.gen_state, c_loss, g_loss = self.trainer.train_round(
                self.critic_state, self.gen_state, batches, sub
            )
        # device scalars, same contract as the fused DCGAN path
        return {"d_loss": c_loss, "g_loss": g_loss, "cv_loss": jnp.float32(jnp.nan)}

    def train_iterations(self, features, labels=None) -> Dict:
        """K WGAN-GP rounds in ONE device dispatch (the scan device loop —
        same contract as GanExperiment.train_iterations). ``features``:
        (K, B, num_features); a B not divisible by n_critic gets the same
        tail policy as the sequential round (pad-by-cycling / drop
        remainder). ``labels`` accepted and ignored — the critic is
        unsupervised."""
        del labels
        n = self.model_cfg.n_critic
        with compute_dtype_scope(self._compute_dtype):
            rounds = jnp.asarray(features, jnp.float32)
            k, b = int(rounds.shape[0]), int(rounds.shape[1])
            # same tail policy as _train_round: pad-by-cycling below one row
            # per critic step, else drop the < n_critic remainder rows
            if b < n:
                rounds = jnp.tile(rounds, (1, -(-n // b), 1))[:, :n]
                b = n
            elif b % n:
                b = (b // n) * n
                rounds = rounds[:, :b]
            rounds = rounds.reshape(k, n, b // n, -1)
            self._key, sub = jax.random.split(self._key)
            with self.timer.phase("train_rounds"):
                (
                    self.critic_state,
                    self.gen_state,
                    c_losses,
                    g_losses,
                ) = self.trainer.train_rounds(
                    self.critic_state, self.gen_state, rounds, sub
                )
        nan = jnp.full((k,), jnp.nan, jnp.float32)
        return {"d_loss": c_losses, "g_loss": g_losses, "cv_loss": nan}

    @property
    def gen_params(self):
        """The sampler's current params — lets the inherited
        ``export_manifold`` drive the WGAN generator unchanged."""
        return self.gen_state.params

    # -- cost model ------------------------------------------------------
    def flops_per_iteration(self, batch_size=None) -> float:
        """FLOPs of one WGAN-GP round (critic scan + generator step) from
        XLA's post-optimization cost analysis — includes the grad-of-grad
        penalty as compiled. None if the backend has no cost model.

        Scan caveat (round-4 finding, scripts/profile_wgan.py): XLA's
        cost_analysis counts a ``lax.scan`` body ONCE, independent of trip
        count — verified by lowering the round at n_critic 2 vs 4 (identical
        "flops"). The critic round therefore multiplies by ``n_critic``;
        without it every WGAN MFU reads ~n_critic× too low (round 3's 3.2%
        was really ~16%)."""
        mcfg = self.model_cfg
        b = batch_size or self.config.batch_size_train
        n = mcfg.n_critic
        f32 = jnp.float32
        struct = shape_struct
        key = jax.ShapeDtypeStruct((2,), jnp.uint32)
        with compute_dtype_scope(self._compute_dtype):
            critic = cost_analysis_dict(self.trainer._critic_round.lower(
                struct(self.critic_state), struct(self.gen_state.params),
                jax.ShapeDtypeStruct((n, b // n, mcfg.num_features), f32), key,
            ).compile().cost_analysis())
            gen = cost_analysis_dict(self.trainer._gen_step.lower(
                struct(self.gen_state), struct(self.critic_state.params),
                jax.ShapeDtypeStruct((b // n, mcfg.z_size), f32),
            ).compile().cost_analysis())
        if not critic or "flops" not in critic or not gen or "flops" not in gen:
            return None
        return float(critic["flops"]) * n + float(gen["flops"])

    # -- exports --------------------------------------------------------
    # export_manifold is inherited from GanExperiment: it reads
    # ``self._gen_fwd``/``self.gen_params``, both provided here.

    def sample(self, num: int, seed: int = 0) -> np.ndarray:
        """(num, H, W, C) generator samples for eval/FID."""
        with compute_dtype_scope(self._compute_dtype):
            out = self.trainer.sample(self.gen_state, jax.random.PRNGKey(seed), num)
        return np.asarray(out)

    # -- checkpointing --------------------------------------------------
    def save_models(self) -> List[str]:
        """Critic + generator zips with updater state, same format/cadence as
        the four-model save (ModelSerializer analog)."""
        cfg = self.config
        os.makedirs(cfg.output_dir, exist_ok=True)
        paths = []
        for name, graph, state in (
            ("critic", self.trainer.critic, self.critic_state),
            ("gen", self.trainer.generator, self.gen_state),
        ):
            path = os.path.join(cfg.output_dir, f"{cfg.file_prefix}_{name}_model.zip")
            write_model(path, graph, state, save_updater=True)
            paths.append(path)
        return paths

    def load_models(self, directory: Optional[str] = None) -> int:
        cfg = self.config
        prefix = os.path.join(directory or cfg.output_dir, cfg.file_prefix)

        def _state(path: str) -> TrainState:
            _, params, opt_state, step = read_model(path)
            st = TrainState(params, opt_state, jnp.asarray(step, jnp.int32))
            if self._param_dtype is not None:
                st = self._cast_state(st)
            if self.mesh is not None:
                st = jax.device_put(
                    st,
                    jax.sharding.NamedSharding(self.mesh, jax.sharding.PartitionSpec()),
                )
            return st

        self.critic_state = _state(f"{prefix}_critic_model.zip")
        self.gen_state = _state(f"{prefix}_gen_model.zip")
        self.batch_counter = int(self.gen_state.step)
        return self.batch_counter
