"""Experiment harness — the application layer (SURVEY §1 layer A, §2.1 I1-I17).

The reference's entire runtime is one class, ``dl4jGANComputerVision``:
config constants, three graphs, the transfer classifier, the alternating
train loop with named-parameter weight sync, CSV exports, and per-iteration
checkpointing. This package is that application rebuilt on the TPU-native
stack: :class:`ExperimentConfig` (the ~24-constant block, CLI/JSON
overridable) and :class:`GanExperiment` (the loop).
"""

from gan_deeplearning4j_tpu.harness.config import ExperimentConfig
from gan_deeplearning4j_tpu.harness.experiment import GanExperiment


def make_experiment(config: ExperimentConfig, mesh=None):
    """Experiment factory: dispatches to the family's custom experiment class
    (wgan_gp) or the standard three-graph :class:`GanExperiment`. The CLI and
    bench go through here so every registry family is a first-class run."""
    from gan_deeplearning4j_tpu.models import registry

    family = registry.get(config.model_family)
    if family.make_experiment is not None:
        return family.make_experiment(config, mesh)
    return GanExperiment(config, mesh=mesh)


__all__ = ["ExperimentConfig", "GanExperiment", "make_experiment"]
