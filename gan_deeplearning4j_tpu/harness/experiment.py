"""GanExperiment — the alternating training loop (SURVEY §2.1 I13-I17, §3.2).

One iteration reproduces the reference's hot loop (dl4jGANComputerVision.java:
408-621):

1. real batch from the train iterator; fake batch from the frozen sampler
   ``gen`` on z ~ U(−1,1);
2. discriminator fit on [real + softened-1 labels, fake + softened-0 labels];
3. named-param sync dis → gan frozen tail (12 copies → one bulk map);
4. GAN fit on [z, labels=1] — the generator step through the frozen D;
5. sync gan → gen (refresh the sampler), dis → classifier feature layers;
6. classifier fit on the real labeled batch;
7. exports: 10×10 z-grid manifold CSV + batched test-set predictions CSV;
8. all four models checkpointed with updater state.

TPU-native differences: the "param copies" are pytree rebinds (no data
movement — the arrays stay in HBM and are shared by reference); exports do
one batched device→host fetch instead of per-scalar ``getDouble`` reads
(the §3.3 pathology); and the Spark layer is replaced by the mesh trainers.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gan_deeplearning4j_tpu.data import (
    ArrayDataSetIterator,
    DevicePrefetchIterator,
    write_csv,
)
from gan_deeplearning4j_tpu.harness.config import ExperimentConfig
from gan_deeplearning4j_tpu.models import registry
from gan_deeplearning4j_tpu.nn import ComputationGraph
from gan_deeplearning4j_tpu.parallel import (
    GraphTrainer,
    ParameterAveragingTrainer,
    TrainState,
)
from gan_deeplearning4j_tpu.runtime import TpuEnvironment
from gan_deeplearning4j_tpu.runtime.dtype import (
    compute_dtype_scope,
    parse_compute_dtype,
)
from gan_deeplearning4j_tpu.utils import write_model
from gan_deeplearning4j_tpu.utils.metrics import MetricsLogger
from gan_deeplearning4j_tpu.utils.profiling import PhaseTimer, device_trace

logger = logging.getLogger(__name__)

# one shard of a mesh-coordinated checkpoint (resilience/mesh.py):
# <prefix>_state_shard-<K>-of-<M>.zip — presence of any such file marks a
# generation directory as mesh-sharded and routes load_models through the
# elastic merge path
_MESH_SHARD_RE = re.compile(r"_state_shard-(\d{4})-of-(\d{4})\.zip$")


def shape_struct(tree):
    """Pytree of ShapeDtypeStructs mirroring ``tree`` — for AOT lowering
    (the FLOPs cost model) without touching real buffers."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.asarray(x).dtype), tree
    )


def cost_analysis_dict(cost):
    """``compiled.cost_analysis()`` compat: modern jax returns one dict,
    0.4.x wheels a list of per-computation dicts (entry computation first).
    Returns the entry dict, or None when the backend has no cost model."""
    if isinstance(cost, (list, tuple)):
        return cost[0] if cost else None
    return cost


def _one_opt_step(graph, opt, state: TrainState, feats, labels, key,
                  lr_scale=None):
    """One optimizer step on one minibatch — the traced core both fused-body
    builders (GraphTrainer mode and shard_map averaging mode) scan over.
    ``lr_scale`` (traced scalar or None) rescales the effective LR — the
    dis-LR decay schedule's entry point (GraphOptimizer.step)."""

    def loss_fn(p):
        loss, (_, new_p) = graph.loss(p, feats, labels, train=True, rng=key)
        return loss, new_p

    (loss, new_params), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        state.params
    )
    params, opt_state = opt.step(new_params, grads, state.opt_state,
                                 lr_scale=lr_scale)
    return TrainState(params, opt_state, state.step + 1), loss


def _dis_lr_scale(cfg: ExperimentConfig, dis_step):
    """The staircase decay factor for the discriminator at a given carried
    step counter (two dis optimizer steps per alternating iteration, so
    ``iteration = dis_step // 2``). A traced expression of the step — usable
    inside jit AND inside the scan device loop, where the iteration advances
    in-carry; returns None when the schedule is off (zero overhead)."""
    if not cfg.dis_lr_decay_every or cfg.dis_lr_decay_rate == 1.0:
        return None
    iteration = dis_step // 2
    return jnp.power(
        jnp.float32(cfg.dis_lr_decay_rate),
        (iteration // cfg.dis_lr_decay_every).astype(jnp.float32),
    )


def _rebind(src: TrainState, dst: TrainState, mapping) -> TrainState:
    """Weight sync as pure pytree rewiring (the reference's setParam blocks,
    :429-542) — zero copies inside a jitted program."""
    return TrainState(
        ComputationGraph.copy_params(src.params, dst.params, mapping),
        dst.opt_state,
        dst.step,
    )


def latent_grid(n: int, z_size: int = 2) -> np.ndarray:
    """The n×n manifold grid over linspace(−1,1,n)² (reference :382-389).
    For z_size > 2 the remaining dims are zero (grid spans the first two)."""
    line = np.linspace(-1.0, 1.0, n, dtype=np.float32)
    a, b = np.meshgrid(line, line, indexing="ij")
    grid = np.zeros((n * n, z_size), dtype=np.float32)
    grid[:, 0] = a.ravel()
    grid[:, 1 % z_size] = b.ravel()
    return grid


class GanExperiment:
    """The application loop, assembled from the framework layers."""

    def __init__(self, config: ExperimentConfig = ExperimentConfig(), mesh=None):
        from gan_deeplearning4j_tpu.runtime.environment import enable_compilation_cache

        enable_compilation_cache()  # idempotent; $GDT_COMPILATION_CACHE=off opts out
        self.config = config.validate()
        cfg = config
        # Mixed precision: ops read the compute dtype at TRACE time, so every
        # jitted program built/first-called under this experiment must trace
        # inside a compute_dtype_scope (train_iteration and the exports wrap
        # themselves; see those methods).
        self._compute_dtype = parse_compute_dtype(cfg.compute_dtype)
        self.family = registry.get(cfg.model_family)
        self.model_cfg = self.family.make_model_config(cfg)
        self.dis_to_gan, self.gan_to_gen = self.family.sync_maps(self.model_cfg)
        # Class conditioning (zoo/manifest.py, docs/ZOO.md): the generator
        # and stacked GAN take [z | one-hot(class)] — the label embedding is
        # the extra one-hot rows of the first dense layer — while the
        # discriminator (and through it the transfer classifier) stays
        # unconditional, preserving the paper's dis-feature transfer claim.
        # model_cfg keeps the BASE z_size; only the gen/gan graph configs
        # widen. Weight-sync maps are layer-NAME keyed, so they are width-
        # agnostic and carry over unchanged.
        self._cond_classes = cfg.num_classes if cfg.conditioning == "class" else 0

        if mesh is None and cfg.distributed != "none":
            mesh = TpuEnvironment().make_mesh()
        self.mesh = mesh

        # the three graphs (+ MNIST's transfer classifier, I4-I6, I11)
        gen_cfg = (
            dataclasses.replace(
                self.model_cfg,
                z_size=self.model_cfg.z_size + self._cond_classes,
            )
            if self._cond_classes
            else self.model_cfg
        )
        self.dis = self.family.build_discriminator(self.model_cfg)
        self.gen = self.family.build_generator(gen_cfg)
        self.gan = self.family.build_gan(gen_cfg)
        dis_params = self.dis.init()
        if self.family.build_transfer_classifier is not None:
            self.cv, cv_params = self.family.build_transfer_classifier(
                self.dis, dis_params, self.model_cfg
            )
            # the classifier OWNS its bytes (the reference setParam-copies
            # dis→CV every iteration, :516-542): sharing leaves with
            # dis_params would alias two donated arguments of one jitted
            # step — rejected by PJRT's Execute for the scan program
            cv_params = jax.tree_util.tree_map(jnp.copy, cv_params)
        else:
            self.cv, cv_params = None, None

        self.dis_trainer = self._make_trainer(self.dis)
        self.gan_trainer = self._make_trainer(self.gan)
        self.dis_state = self.dis_trainer.init_state(params=dis_params)
        self.gan_state = self.gan_trainer.init_state()
        if self.cv is not None:
            self.cv_trainer = self._make_trainer(self.cv)
            self.cv_state = self.cv_trainer.init_state(params=cv_params)
        else:
            self.cv_trainer, self.cv_state = None, None
        self.gen_params = self.gen.init()
        # bf16 param storage (round-4 VERDICT item 3): cast every float leaf
        # of params + updater state once at init; all jitted programs then
        # carry bf16 state end to end — half the HBM bytes per step on a
        # workload whose roofline is bandwidth-bound. Init/serialization stay
        # f32-defined (cast on entry, dtype-tagged in checkpoints).
        self._param_dtype = parse_compute_dtype(cfg.param_dtype)
        if self._param_dtype is not None:
            cast = self._cast_state
            self.dis_state = cast(self.dis_state)
            self.gan_state = cast(self.gan_state)
            if self.cv_state is not None:
                self.cv_state = cast(self.cv_state)
            self.gen_params = cast(self.gen_params)
        # Cross-replica weight-update sharding (the ROADMAP mesh item's
        # compute half): installed AFTER every state exists, because the
        # ownership partition is taken over the FULL _flat_state() key
        # namespace — that is what makes compute shard k own exactly the
        # updater keys checkpoint shard k writes (no format change).
        if cfg.update_sharding:
            self._enable_update_sharding()
        self._gen_fwd = jax.jit(lambda p, z: self.gen.output(p, z, train=False))

        # label-softening noise, sampled ONCE like the reference (:404-406)
        # unless resample_label_noise asks for per-batch redraws
        rng = np.random.default_rng(cfg.seed)
        self._noise_rng = rng
        b = cfg.batch_size_train
        self._eps_real = self._soft_noise(b)
        self._eps_fake = self._soft_noise(b)
        self._z_rng = np.random.default_rng(cfg.seed + 1)
        self._z_grid = self._with_condition(latent_grid(cfg.latent_grid, cfg.z_size))

        self.timer = PhaseTimer()
        self.metrics = MetricsLogger(cfg.metrics_jsonl)
        self.batch_counter = 0
        self._soft_cache: Dict[int, tuple] = {}

        # With plain GraphTrainers (single-chip or per-step pmean) the whole
        # alternating iteration fuses into ONE compiled XLA program: the three
        # fits run back to back in HBM and the reference's 38 setParam copies
        # (:429-542) become pure pytree rewiring — zero device copies, one
        # dispatch per iteration instead of ~10 (crucial when each dispatch
        # pays host↔TPU latency). Parameter-averaging mode keeps the phased
        # path, since its fit has its own shard_map program.
        self._fused = (
            self._build_fused_iteration()
            if all(
                isinstance(t, GraphTrainer)
                for t in (self.dis_trainer, self.gan_trainer, self.cv_trainer)
                if t is not None
            )
            else None
        )
        # the scan-of-K device loop, built lazily on first train_iterations
        self._fused_multi = None
        self._supports_device_loop = self._fused is not None
        if self._fused is None and cfg.distributed == "param_averaging" \
                and self.mesh is not None:
            # faithful-averaging mode gets its own device loop (round-4
            # VERDICT item 5): the scanned shard_map program below feeds
            # _build_multi_iteration in place of the fused GraphTrainer body
            self._fused_body = self._build_fused_avg_body()
            self._supports_device_loop = True

    # -- update sharding (parallel/update_sharding.py) -------------------
    def _enable_update_sharding(self) -> None:
        """Partition every trainer's update computation + updater state
        over the mesh data axis. The global key list is the sorted
        ``_flat_state()`` namespace — the same partition input the mesh
        checkpoint plane's ``serializer.shard_keys`` uses, so compute and
        checkpoint shards coincide key-for-key (RmsProp/stateless specs;
        multi-field state is owned as a unit by its first key's shard)."""
        from gan_deeplearning4j_tpu.parallel.update_sharding import (
            UpdateShardingPlan,
        )
        from gan_deeplearning4j_tpu.utils.serializer import _element_count

        global_keys = {k: _element_count(v)
                       for k, v in self._flat_state().items()}
        models = [("dis", self.dis_trainer, "dis_state"),
                  ("gan", self.gan_trainer, "gan_state")]
        if self.cv is not None:
            models.append(("CV", self.cv_trainer, "cv_state"))
        for name, trainer, attr in models:
            state = getattr(self, attr)
            trainer.enable_update_sharding(UpdateShardingPlan(
                trainer.graph, trainer.optimizer, state.params, self.mesh,
                data_axis=trainer.data_axis, model_name=name,
                global_keys=global_keys,
            ))
            setattr(self, attr, trainer.place_state(TrainState(
                state.params,
                trainer.plan.pack_state(state.opt_state),
                state.step,
            )))

    def _tree_state(self, trainer, state: TrainState) -> TrainState:
        """The canonical tree-form view of a TrainState — what checkpoints
        serialize and digests are taken over. Identity when the updater
        state is already a tree; under update sharding the packed rows
        are unpacked (a gather of this process's own devices)."""
        from gan_deeplearning4j_tpu.parallel.update_sharding import (
            PackedOptState,
        )

        if isinstance(state.opt_state, PackedOptState):
            return TrainState(
                state.params,
                trainer.plan.unpack_state(state.opt_state),
                state.step,
            )
        return state

    def digest_states(self) -> Dict:
        """Canonical (tree-form) states for bit-exactness digests — one
        definition shared by the resilience supervisor and the parity
        tests, identical across replicated and update-sharded modes."""
        out = {
            "dis": self._tree_state(self.dis_trainer, self.dis_state),
            "gan": self._tree_state(self.gan_trainer, self.gan_state),
            "gen": self.gen_params,
        }
        if self.cv is not None:
            out["CV"] = self._tree_state(self.cv_trainer, self.cv_state)
        return out

    def _state_jit_sharding(self, trainer, state):
        """jit in/out sharding for one model state: a replicated prefix
        normally; the packed-rows placement pytree under update
        sharding."""
        rep = jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec())
        from gan_deeplearning4j_tpu.parallel.update_sharding import (
            PackedOptState,
        )
        from gan_deeplearning4j_tpu.parallel.trainer import state_shardings

        if isinstance(state, TrainState) and isinstance(
                state.opt_state, PackedOptState):
            return state_shardings(state, trainer.plan)
        return rep

    # ------------------------------------------------------------------
    def _make_trainer(self, graph: ComputationGraph):
        cfg = self.config
        if cfg.distributed == "param_averaging":
            return ParameterAveragingTrainer(
                graph,
                self.mesh,
                batch_size_per_worker=cfg.batch_size_per_worker,
                averaging_frequency=cfg.averaging_frequency,
            )
        mesh = self.mesh if cfg.distributed == "pmean" else None
        return GraphTrainer(graph, mesh=mesh)

    def _cast_state(self, state):
        """Cast every floating leaf of a TrainState / params tree to the
        param storage dtype (ints — step counters, Adam's t — stay)."""

        def leaf(x):
            x = jnp.asarray(x)
            return x.astype(self._param_dtype) if jnp.issubdtype(
                x.dtype, jnp.floating
            ) else x

        return jax.tree_util.tree_map(leaf, state)

    def _soft_noise(self, n: int) -> np.ndarray:
        return (
            self.config.label_softening
            * self._noise_rng.standard_normal((n, 1)).astype(np.float32)
        )

    def _with_condition(self, z: np.ndarray) -> np.ndarray:
        """Widen host-side latents with a cycling one-hot class column block
        (row i conditions on class i mod C) — identity when unconditional.
        Keeps every host z consumer (grid export, phased-path draws) valid
        against the widened generator input."""
        if not self._cond_classes:
            return z
        labels = np.arange(z.shape[0]) % self._cond_classes
        onehot = np.eye(self._cond_classes, dtype=np.float32)[labels]
        return np.concatenate([z, onehot], axis=1)

    def _sample_z(self, n: int) -> np.ndarray:
        """z ~ U(−1,1) via rand·2−1 (reference :420,465); conditional runs
        append the cycling one-hot embedding."""
        return self._with_condition(
            self._z_rng.random((n, self.config.z_size), dtype=np.float32) * 2.0 - 1.0
        )

    @staticmethod
    def _copied_layers(src_params: Dict, mapping: Dict[str, str]) -> Dict:
        """Materialized device copies of the mapped layers. The copy is
        required for correctness under buffer donation: the source trainer's
        jitted step donates its state buffers, so the destination model must
        own its bytes — exactly the semantics of the reference's setParam
        copies (:429-542), still a device-to-device HBM copy, no host hop."""
        return {
            layer: {p: jnp.copy(v) for p, v in src_params[layer].items()}
            for layer in mapping
        }

    def _sync(self, src_state, dst_state: TrainState, mapping: Dict[str, str]) -> TrainState:
        """Named-param weight sync (the reference's setParam blocks :429-542)."""
        src = src_state.params if isinstance(src_state, TrainState) else src_state
        return TrainState(
            ComputationGraph.copy_params(self._copied_layers(src, mapping), dst_state.params, mapping),
            dst_state.opt_state,
            dst_state.step,
        )

    def _build_fused_iteration(self):
        """Jit the full alternating iteration (§3.2 steps a–f) as one program."""
        gen_graph = self.gen
        one_step, rebind = _one_opt_step, _rebind
        z_size = self.model_cfg.z_size
        cond = self._cond_classes > 0
        base_key = jax.random.PRNGKey(self.config.seed + 2)
        cfg = self.config
        resample = cfg.resample_label_noise
        softening = cfg.label_softening

        def fused(
            dis_state, gan_state, cv_state, gen_params,
            real_f, real_l, soft1, soft0,
        ):
            # Per-iteration randomness keyed off the step counter (no host
            # RNG round trip): one fold_in, then an independent subkey per
            # consumer — z draws AND each optimizer step's loss rng, so
            # dropout-style layers get fresh masks every step and every
            # phase (round-2 VERDICT weak #5: a constant key here would
            # repeat masks forever).
            b = real_f.shape[0]
            key = jax.random.fold_in(base_key, dis_state.step)
            if resample:
                # Per-batch label-noise resampling (the G/D-balance lever,
                # round-5 VERDICT item 4) derived from the SAME per-step key
                # stream — fresh ε every iteration with no host round trip,
                # so the lever works inside the scan device loop too. The
                # passed soft1/soft0 are ignored. When off, the split stays
                # 6-way so the reference-quirk RNG stream is bit-identical
                # to prior rounds.
                k_fake, k_gan, k_d1, k_d2, k_g, k_c, k_s1, k_s0 = (
                    jax.random.split(key, 8)
                )
                soft1 = 1.0 + softening * jax.random.normal(
                    k_s1, (b, 1), jnp.float32
                )
                soft0 = softening * jax.random.normal(k_s0, (b, 1), jnp.float32)
            else:
                k_fake, k_gan, k_d1, k_d2, k_g, k_c = jax.random.split(key, 6)
            dis_scale = _dis_lr_scale(cfg, dis_state.step)
            z_fake = jax.random.uniform(k_fake, (b, z_size), jnp.float32, -1.0, 1.0)
            z_gan = jax.random.uniform(k_gan, (b, z_size), jnp.float32, -1.0, 1.0)
            if cond:
                # class conditioning: condition BOTH generator passes on the
                # real batch's labels — the dis sees matched real/fake class
                # mix and the generator step learns p(x|class). The base-z
                # RNG stream is untouched (same draws as unconditional).
                onehot = real_l.astype(jnp.float32)
                z_fake = jnp.concatenate([z_fake, onehot], axis=1)
                z_gan = jnp.concatenate([z_gan, onehot], axis=1)
            # (a) fake batch from the frozen sampler
            fake = gen_graph.output(gen_params, z_fake, train=False)
            fake = fake.reshape(real_f.shape)
            # (b) dis fit: real→soft1 then fake→soft0, two optimizer steps
            dis_state, d1 = one_step(
                self.dis, self.dis_trainer.optimizer, dis_state, real_f, soft1,
                k_d1, lr_scale=dis_scale,
            )
            dis_state, d2 = one_step(
                self.dis, self.dis_trainer.optimizer, dis_state, fake, soft0,
                k_d2, lr_scale=dis_scale,
            )
            # (c) dis → gan frozen tail
            gan_state = rebind(dis_state, gan_state, self.dis_to_gan)
            # (d) generator step through the frozen D on [z, ones]
            ones = jnp.ones((z_gan.shape[0], 1), jnp.float32)
            gan_state, g = one_step(
                self.gan, self.gan_trainer.optimizer, gan_state, z_gan, ones, k_g
            )
            # (e) gan → gen refresh; dis → classifier features
            gen_params = ComputationGraph.copy_params(
                gan_state.params, gen_params, self.gan_to_gen
            )
            if self.cv is not None:
                cv_state = rebind(dis_state, cv_state, self.family.dis_to_cv)
                # (f) classifier step on the real labeled batch
                cv_state, c = one_step(
                    self.cv, self.cv_trainer.optimizer, cv_state, real_f, real_l, k_c
                )
            else:  # family without a transfer classifier: cv_state is a dummy
                c = jnp.float32(jnp.nan)
            return dis_state, gan_state, cv_state, gen_params, (d1 + d2) / 2.0, g, c

        kwargs = {"donate_argnums": (0, 1, 2, 3)}
        if self.mesh is not None:
            rep = jax.sharding.NamedSharding(self.mesh, jax.sharding.PartitionSpec())
            data = jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec("data")
            )
            states = self._fused_state_shardings()
            kwargs["in_shardings"] = states + (rep,) + (data,) * 4
            kwargs["out_shardings"] = states + (rep,) * 4
        # keep the traceable body around: _build_multi_iteration scans it
        self._fused_body = fused
        return jax.jit(fused, **kwargs)

    def _fused_state_shardings(self):
        """in/out shardings of the three carried TrainStates — replicated
        prefixes normally, the packed-rows pytrees under update sharding
        (gen_params stays a replicated prefix either way)."""
        return (
            self._state_jit_sharding(self.dis_trainer, self.dis_state),
            self._state_jit_sharding(self.gan_trainer, self.gan_state),
            self._state_jit_sharding(self.cv_trainer, self.cv_state),
        )

    def _build_fused_avg_body(self):
        """The alternating iteration under FAITHFUL parameter averaging as
        one shard_map program (round-4 VERDICT item 5).

        Semantics: per-fit averaging rounds — each graph's fit is one local
        optimizer step per worker on its shard of the batch (two sequential
        steps for the discriminator's real-then-fake pair), followed by an
        arithmetic mean of params AND updater state across the mesh. This is
        the cadence the reference's loop actually exercises: every
        ``sparkGraph.fit`` call per iteration carries fewer minibatches than
        ``averagingFrequency(10)`` (the dis fit has 2, the gan/cv fits 1 —
        dl4jGANComputerVision.java:414-421,462-471,544-545), and DL4J
        averages at the fit boundary regardless, so averaging happens once
        per fit — exactly what this program does, minus the Spark
        serialization. The k-step ``averaging_frequency`` semantics remain
        fully exercised on the trainer surface
        (``ParameterAveragingTrainer.fit/fit_round/fit_rounds``).

        Differences from the phased path (``_train_iteration``'s
        ``trainer.fit`` route, still used for single dispatches): worker-local
        RNG draws derive from the step counter + ``axis_index`` (no host
        round trip), and each worker sees a contiguous shard of both the real
        and fake minibatches rather than the phased path's worker-major
        regrouping. Both are documented DL4J-analog layouts; losses are
        cross-worker means either way."""
        try:
            from jax import shard_map as _shard_map
        except ImportError:  # pragma: no cover - older wheel: experimental
            from jax.experimental.shard_map import shard_map as _shard_map
        from jax.sharding import PartitionSpec as P

        from gan_deeplearning4j_tpu.parallel.param_averaging import _average_tree

        axis = "data"
        gen_graph = self.gen
        one_step, rebind = _one_opt_step, _rebind
        z_size = self.model_cfg.z_size
        base_key = jax.random.PRNGKey(self.config.seed + 2)
        cfg = self.config
        resample = cfg.resample_label_noise
        softening = cfg.label_softening

        def avg(state: TrainState) -> TrainState:
            return TrainState(
                _average_tree(state.params, axis),
                _average_tree(state.opt_state, axis),
                state.step,
            )

        def body(dis_state, gan_state, cv_state, gen_params,
                 real_f, real_l, soft1, soft0):
            widx = jax.lax.axis_index(axis)
            b = real_f.shape[0]  # per-worker rows
            key = jax.random.fold_in(base_key, dis_state.step)

            def wkey(k):  # worker-distinct subkey for local draws/dropout
                return jax.random.fold_in(k, widx)

            if resample:
                # per-batch ε, worker-distinct rows (each worker softens its
                # own shard — the phased path's per-row noise layout)
                k_fake, k_gan, k_d1, k_d2, k_g, k_c, k_s1, k_s0 = (
                    jax.random.split(key, 8)
                )
                soft1 = 1.0 + softening * jax.random.normal(
                    wkey(k_s1), (b, 1), jnp.float32
                )
                soft0 = softening * jax.random.normal(
                    wkey(k_s0), (b, 1), jnp.float32
                )
            else:
                k_fake, k_gan, k_d1, k_d2, k_g, k_c = jax.random.split(key, 6)
            dis_scale = _dis_lr_scale(cfg, dis_state.step)
            z_fake = jax.random.uniform(
                wkey(k_fake), (b, z_size), jnp.float32, -1.0, 1.0
            )
            fake = gen_graph.output(gen_params, z_fake, train=False)
            fake = fake.reshape(real_f.shape)
            # dis "fit": two local steps (real→soft1, fake→soft0) then ONE
            # average — the 2-element-List<DataSet> fit boundary
            dis_state, d1 = one_step(
                self.dis, self.dis_trainer.optimizer, dis_state,
                real_f, soft1, wkey(k_d1), lr_scale=dis_scale,
            )
            dis_state, d2 = one_step(
                self.dis, self.dis_trainer.optimizer, dis_state,
                fake, soft0, wkey(k_d2), lr_scale=dis_scale,
            )
            dis_state = avg(dis_state)
            gan_state = rebind(dis_state, gan_state, self.dis_to_gan)
            z_gan = jax.random.uniform(
                wkey(k_gan), (b, z_size), jnp.float32, -1.0, 1.0
            )
            ones = jnp.ones((b, 1), jnp.float32)
            gan_state, g = one_step(
                self.gan, self.gan_trainer.optimizer, gan_state,
                z_gan, ones, wkey(k_g),
            )
            gan_state = avg(gan_state)
            gen_params = ComputationGraph.copy_params(
                gan_state.params, gen_params, self.gan_to_gen
            )
            if self.cv is not None:
                cv_state = rebind(dis_state, cv_state, self.family.dis_to_cv)
                cv_state, c = one_step(
                    self.cv, self.cv_trainer.optimizer, cv_state,
                    real_f, real_l, wkey(k_c),
                )
                cv_state = avg(cv_state)
                c = jax.lax.pmean(c, axis)
            else:
                c = jnp.float32(jnp.nan)
            d = jax.lax.pmean((d1 + d2) / 2.0, axis)
            g = jax.lax.pmean(g, axis)
            return dis_state, gan_state, cv_state, gen_params, d, g, c

        return _shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(), P(), P(), P(), P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(),) * 7,
        )

    def _build_multi_iteration(self):
        """The DEVICE-SIDE training loop: ``lax.scan`` of the fused iteration
        over a (K, B, …) window of batches — K full alternating iterations
        (each with its own weight updates, syncs, and per-step RNG, identical
        math to K sequential ``train_iteration`` calls) in ONE XLA dispatch.

        This is the idiomatic TPU shape for a hot loop: the host's only jobs
        are feeding windows and reading back a (K,) loss stack, so per-call
        dispatch latency — milliseconds on a tunneled chip — amortizes over
        the window. The reference's Spark driver re-enters the JVM loop per
        batch (dl4jGANComputerVision.java:408-621); XLA's equivalent of that
        driver round-trip is exactly what this removes."""
        body = self._fused_body

        def multi(dis_state, gan_state, cv_state, gen_params, feats, labels, soft1, soft0):
            def step(carry, xs):
                dis, gan, cv, gen = carry
                f, l = xs
                dis, gan, cv, gen, d, g, c = body(dis, gan, cv, gen, f, l, soft1, soft0)
                return (dis, gan, cv, gen), (d, g, c)

            (dis, gan, cv, gen), (ds, gs, cs) = jax.lax.scan(
                step, (dis_state, gan_state, cv_state, gen_params), (feats, labels)
            )
            return dis, gan, cv, gen, ds, gs, cs

        kwargs = {"donate_argnums": (0, 1, 2, 3)}
        if self.mesh is not None:
            rep = jax.sharding.NamedSharding(self.mesh, jax.sharding.PartitionSpec())
            stacked = jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec(None, "data")
            )
            data = jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec("data")
            )
            states = self._fused_state_shardings()
            kwargs["in_shardings"] = states + (rep,) + (stacked,) * 2 + (data,) * 2
            kwargs["out_shardings"] = states + (rep,) + (rep,) * 3
        return jax.jit(multi, **kwargs)

    def _eps_slices(self, b: int):
        """The once-sampled label noise for batch size ``b``, extending it
        when a larger batch appears (the extension is itself drawn once and
        reused — preserves the reference's sample-once quirk, :404-406)."""
        if b > self._eps_real.shape[0]:
            extra = b - self._eps_real.shape[0]
            self._eps_real = np.concatenate([self._eps_real, self._soft_noise(extra)])
            self._eps_fake = np.concatenate([self._eps_fake, self._soft_noise(extra)])
        return self._eps_real[:b], self._eps_fake[:b]

    def _soft_labels(self, b: int):
        """Fixed softened labels (1+ε, 0+ε) for batch size ``b``, resident
        in HBM, cached per batch size."""
        if b not in self._soft_cache:
            eps_r, eps_f = self._eps_slices(b)
            self._soft_cache[b] = (
                jnp.asarray(1.0 + eps_r),
                jnp.asarray(0.0 + eps_f),
            )
        return self._soft_cache[b]

    def train_iterations(self, features, labels) -> Dict:
        """K full alternating iterations in ONE device dispatch (the
        ``lax.scan`` device loop — see ``_build_multi_iteration``).

        ``features``: (K, B, num_features); ``labels``: (K, B, num_classes).
        Identical math to K sequential ``train_iteration`` calls — same
        per-iteration weight updates, weight syncs, and per-step RNG (the
        scan body derives each step's key from the carried step counter
        exactly like the per-dispatch path). Returns (K,)-shaped DEVICE loss
        arrays (no sync; fetch when needed).

        In parameter-averaging mode the scanned body is the shard_map
        per-fit-averaging program (``_build_fused_avg_body``) instead of the
        fused GraphTrainer body — same window contract, faithful averaging
        semantics. With ``resample_label_noise`` the scanned body redraws the
        softening ε from the per-step key stream each iteration (round 5), so
        the lever runs at full device-loop speed. Unavailable in averaging
        mode without a mesh."""
        if not getattr(self, "_supports_device_loop", False):
            raise ValueError(
                "train_iterations requires the fused path (single-chip, "
                "per-step pmean, or param_averaging on a mesh)"
            )
        with compute_dtype_scope(self._compute_dtype):
            b = int(features.shape[1])
            soft1, soft0 = self._soft_labels(b)
            if self._fused_multi is None:
                self._fused_multi = self._build_multi_iteration()
            (
                self.dis_state,
                self.gan_state,
                self.cv_state,
                self.gen_params,
                d_losses,
                g_losses,
                cv_losses,
            ) = self._fused_multi(
                self.dis_state, self.gan_state, self.cv_state, self.gen_params,
                jnp.asarray(features), jnp.asarray(labels), soft1, soft0,
            )
        return {"d_loss": d_losses, "g_loss": g_losses, "cv_loss": cv_losses}

    def _fit_batch(self, trainer, state, features, labels, batch_size: int):
        """One fit on one in-memory batch. GraphTrainer takes the device
        arrays straight into its jitted step (no host hop); the
        parameter-averaging trainer keeps its iterator surface."""
        if isinstance(trainer, GraphTrainer):
            state, loss = trainer.train_step(state, features, labels)
            return state, [loss]
        it = ArrayDataSetIterator(
            np.asarray(features), np.asarray(labels), batch_size=batch_size
        )
        return trainer.fit(state, it)

    # ------------------------------------------------------------------
    def train_iteration(self, real_features, real_labels) -> Dict:
        """One full alternating iteration (§3.2) under the configured compute
        dtype (jit traces happen on the first call, inside the scope)."""
        with compute_dtype_scope(self._compute_dtype):
            return self._train_iteration(real_features, real_labels)

    def _train_iteration(self, real_features, real_labels) -> Dict:
        """One full alternating iteration (§3.2). Inputs are the real batch:
        features (B, num_features) in [0,1] and one-hot labels (B, classes).

        Everything stays in HBM between phases: the fake batch, the dis/gan/cv
        minibatches, and the weight syncs are device arrays end to end. In
        fused mode the returned losses are *device scalars* (no sync — back-to-
        back iterations pipeline); in parameter-averaging mode they are host
        floats. ``run()`` normalizes to floats before logging."""
        cfg = self.config
        b = int(real_features.shape[0])
        real_features = jnp.asarray(real_features)
        real_labels = jnp.asarray(real_labels)

        if self._fused is not None:
            # once-sampled noise, extended for oversized batches, cached
            # device-resident per batch size; under resample_label_noise the
            # fused body redraws ε in-program and ignores these values
            soft1, soft0 = self._soft_labels(b)
            with self.timer.phase("train_fused"):
                (
                    self.dis_state,
                    self.gan_state,
                    self.cv_state,
                    self.gen_params,
                    d_loss,
                    g_loss,
                    cv_loss,
                ) = self._fused(
                    self.dis_state, self.gan_state, self.cv_state, self.gen_params,
                    real_features, real_labels, soft1, soft0,
                )
            # losses stay on device — the reference never logs losses at all
            # (SURVEY §5), so don't stall the pipeline; callers float() lazily
            return {"d_loss": d_loss, "g_loss": g_loss, "cv_loss": cv_loss}

        # phased (param-averaging) path: host-side softened labels
        if cfg.resample_label_noise:
            eps_r, eps_f = self._soft_noise(b), self._soft_noise(b)
        else:
            eps_r, eps_f = self._eps_slices(b)

        # (a) fake batch from the frozen sampler
        with self.timer.phase("sample_fake") as sink:
            fake = self._gen_fwd(self.gen_params, jnp.asarray(self._sample_z(b)))
            fake = fake.reshape(b, cfg.num_features)
            sink.append(fake)

        # (b) discriminator fit on [real→soft 1, fake→soft 0] — two
        # minibatches in order, exactly the reference's 2-element
        # List<DataSet> (:414-421), i.e. two optimizer steps
        with self.timer.phase("train_dis") as sink:
            d_losses = []
            if isinstance(self.dis_trainer, GraphTrainer):
                # two jitted steps, one compiled shape (batch b), data in HBM
                for feats, labels in (
                    (real_features, 1.0 + jnp.asarray(eps_r)),
                    (fake, 0.0 + jnp.asarray(eps_f)),
                ):
                    self.dis_state, loss = self.dis_trainer.train_step(
                        self.dis_state, feats, labels
                    )
                    d_losses.append(loss)
            else:
                # the averaging trainer takes both minibatches in one fit,
                # like the reference's 2-element RDD
                feats = np.concatenate([np.asarray(real_features), np.asarray(fake)])
                labels = np.concatenate([1.0 + eps_r, 0.0 + eps_f])
                self.dis_state, d_losses = self.dis_trainer.fit(
                    self.dis_state, ArrayDataSetIterator(feats, labels, batch_size=b)
                )
            sink.extend(d_losses)

        # (c) dis → gan frozen tail (:429-460)
        self.gan_state = self._sync(self.dis_state, self.gan_state, self.dis_to_gan)

        # (d) generator step through the frozen D: [z, ones] (:462-471)
        with self.timer.phase("train_gan") as sink:
            z = jnp.asarray(self._sample_z(b))
            ones = jnp.ones((b, 1), jnp.float32)
            self.gan_state, g_losses = self._fit_batch(
                self.gan_trainer, self.gan_state, z, ones, b
            )
            sink.extend(g_losses)

        # (e) gan → gen refresh (:473-510); dis → classifier features (:512-542)
        self.gen_params = ComputationGraph.copy_params(
            self._copied_layers(self.gan_state.params, self.gan_to_gen),
            self.gen_params,
            self.gan_to_gen,
        )
        cv_losses = []
        if self.cv is not None:
            self.cv_state = self._sync(self.dis_state, self.cv_state, self.family.dis_to_cv)

            # (f) classifier step on the real labeled batch (:544-545)
            with self.timer.phase("train_cv") as sink:
                self.cv_state, cv_losses = self._fit_batch(
                    self.cv_trainer, self.cv_state, real_features, real_labels, b
                )
                sink.extend(cv_losses)

        return {
            "d_loss": float(np.mean([float(l) for l in d_losses])) if d_losses else float("nan"),
            "g_loss": float(np.mean([float(l) for l in g_losses])) if g_losses else float("nan"),
            "cv_loss": float(np.mean([float(l) for l in cv_losses])) if cv_losses else float("nan"),
        }

    # -- cost model ------------------------------------------------------
    def flops_per_iteration(self, batch_size: Optional[int] = None) -> Optional[float]:
        """FLOPs of one full alternating iteration from XLA's post-optimization
        cost analysis of the fused program (exact for what actually runs —
        fwd+bwd for dis(×2)/gan/cv plus the sampler forward). None when the
        phased path is active (param-averaging) or the backend exposes no
        cost model. Feeds the bench's MFU line (BASELINE.json metric)."""
        if self._fused is None:
            return None
        cfg = self.config
        b = batch_size or cfg.batch_size_train
        f32 = jnp.float32
        struct = shape_struct
        args = (
            struct(self.dis_state), struct(self.gan_state), struct(self.cv_state),
            struct(self.gen_params),
            jax.ShapeDtypeStruct((b, cfg.num_features), f32),
            jax.ShapeDtypeStruct((b, cfg.num_classes), f32),
            jax.ShapeDtypeStruct((b, 1), f32),
            jax.ShapeDtypeStruct((b, 1), f32),
        )
        with compute_dtype_scope(self._compute_dtype):
            cost = cost_analysis_dict(
                self._fused.lower(*args).compile().cost_analysis()
            )
        if not cost or "flops" not in cost:
            return None
        return float(cost["flops"])

    # -- exports (I15) --------------------------------------------------
    def export_manifold(self, index: int) -> str:
        """Decode the z-grid and write ``{prefix}_out_{index}.csv`` —
        (grid², num_features) rows, one batched host fetch (:550-570)."""
        cfg = self.config
        with compute_dtype_scope(self._compute_dtype):
            out = self._gen_fwd(self.gen_params, jnp.asarray(self._z_grid))
        out = np.asarray(out).reshape(self._z_grid.shape[0], cfg.num_features)
        os.makedirs(cfg.output_dir, exist_ok=True)
        path = os.path.join(cfg.output_dir, f"{cfg.file_prefix}_out_{index}.csv")
        write_csv(path, out, precision=6)
        return path

    def export_predictions(self, test_iterator, index: int) -> str:
        """Batched test-set inference → ``{prefix}_test_predictions_{index}.csv``
        (:572-598): reset, stream batches through the classifier, vstack."""
        cfg = self.config
        if self.cv is None:
            raise ValueError(
                f"family {self.family.name!r} has no transfer classifier to predict with"
            )
        test_iterator.reset()
        chunks: List[np.ndarray] = []
        with compute_dtype_scope(self._compute_dtype):
            while test_iterator.has_next():
                batch = test_iterator.next()
                chunks.append(
                    np.asarray(self.cv_trainer.output(self.cv_state, batch.features))
                )
        preds = np.vstack(chunks) if chunks else np.zeros((0, cfg.num_classes))
        os.makedirs(cfg.output_dir, exist_ok=True)
        path = os.path.join(
            cfg.output_dir, f"{cfg.file_prefix}_test_predictions_{index}.csv"
        )
        write_csv(path, preds, precision=6)
        return path

    def _publish_step(self) -> int:
        """The step counter published artifacts are labeled with. The gan
        graph steps once per loop iteration here; the WGAN-GP experiment
        (no stacked gan) overrides this with its generator's step."""
        return int(self.gan_state.step)

    def save_models(self, directory: Optional[str] = None) -> List[str]:
        """All four models with updater state, every iteration (I16).
        ``directory`` overrides ``config.output_dir`` — the resume entry
        point the resilience store's publish callback writes through (a
        generation stages into its own directory, never the live one)."""
        cfg = self.config
        directory = directory or cfg.output_dir
        os.makedirs(directory, exist_ok=True)
        out = []
        models = [
            ("dis", self.dis, self._tree_state(self.dis_trainer, self.dis_state)),
            ("gan", self.gan, self._tree_state(self.gan_trainer, self.gan_state)),
            ("gen", self.gen, self.gen_params),
        ]
        if self.cv is not None:
            models.append(("CV", self.cv, self._tree_state(self.cv_trainer, self.cv_state)))
        for name, graph, state in models:
            path = os.path.join(directory, f"{cfg.file_prefix}_{name}_model.zip")
            write_model(path, graph, state, save_updater=True)
            out.append(path)
        return out

    # -- mesh-sharded checkpoints (resilience/mesh.py) --------------------
    def _flat_state(self) -> Dict:
        """Every trained state as ONE flat ``<model>/{params|updater|step}/
        ...`` dict — the key namespace the mesh checkpoint plane shards
        over. Sorted-key determinism is what lets N workers agree on a
        partition without communicating."""
        from gan_deeplearning4j_tpu.utils.serializer import _flatten

        dis = self._tree_state(self.dis_trainer, self.dis_state)
        gan = self._tree_state(self.gan_trainer, self.gan_state)
        flat: Dict = {}
        _flatten("dis/params", dis.params, flat)
        _flatten("dis/updater", dis.opt_state, flat)
        flat["dis/step"] = dis.step
        _flatten("gan/params", gan.params, flat)
        _flatten("gan/updater", gan.opt_state, flat)
        flat["gan/step"] = gan.step
        _flatten("gen/params", self.gen_params, flat)
        if self.cv is not None:
            cv = self._tree_state(self.cv_trainer, self.cv_state)
            _flatten("CV/params", cv.params, flat)
            _flatten("CV/updater", cv.opt_state, flat)
            flat["CV/step"] = cv.step
        return flat

    def save_model_shard(self, directory: str, shard_index: int,
                         shard_count: int) -> List[str]:
        """Write THIS worker's shard of the trained state (its slice of
        the deterministic key partition) into ``directory`` — the per-
        worker writer of the mesh store's coordinated publish. Returns the
        relative filenames written (the shard manifest's file list).
        ``shard_count=1`` degenerates to a full single-file checkpoint in
        the same format."""
        from gan_deeplearning4j_tpu.utils.serializer import (
            shard_keys,
            write_state_shard,
        )

        flat = self._flat_state()
        mine = shard_keys(flat, shard_index, shard_count)
        name = (f"{self.config.file_prefix}_state_shard-"
                f"{shard_index:04d}-of-{shard_count:04d}.zip")
        write_state_shard(
            os.path.join(directory, name),
            {k: flat[k] for k in mine},
            meta={
                "shard_index": int(shard_index),
                "shard_count": int(shard_count),
                "step": self._publish_step(),
                "total_keys": len(flat),
                # compute-side update sharding: when on, this worker's
                # resident updater rows are exactly this shard's updater
                # keys — the 1:1 compute↔checkpoint mapping the drill's
                # shard-mismatch messages surface
                "update_sharding": bool(self.config.update_sharding),
            },
        )
        return [name]

    def publish_for_serving(self, directory: Optional[str] = None,
                            store=None) -> Dict:
        """Publish the trained INFERENCE artifacts — the paper's end product:
        the generator used only for sampling plus the discriminator-feature
        classifier (SURVEY §0) — as a serving bundle the ``serving/``
        subsystem loads without any training code.

        Unlike ``save_models`` this drops updater state (a serving replica
        never steps an optimizer — shipping RmsProp caches would double the
        artifact for nothing) and writes a ``serving.json`` manifest naming
        the checkpoints, the feature vertex for the features endpoint, and
        the request shapes. Every file lands via write-to-temp + atomic
        rename (``write_model`` and the manifest both), so a reload loop
        polling the directory can never observe a truncated artifact.

        ``store`` (a ``resilience.CheckpointStore``) publishes the bundle
        as a digest-verified store *generation* instead of a bare
        directory: the manifest's ``generation`` field is then the version
        a bundle-reload loop keys on (None for unversioned directory
        publishes — no serving behavior change either way)."""
        if store is not None:
            # single-writer store: the number reserved here is the number
            # publish() assigns, and the check below makes any future
            # concurrent-writer regression loud instead of silently
            # mislabeling the bundle
            number = store.next_number()
            result: Dict = {}
            generation = store.publish(
                lambda d: result.update(
                    self._write_serving_bundle(d, generation=number)
                ),
                step=self._publish_step(),
                extra={"kind": "serving"},
            )
            if generation.number != number:
                raise RuntimeError(
                    f"serving bundle labeled generation {number} but the "
                    f"store assigned {generation.number} — concurrent writer?"
                )
            return {**result, "directory": generation.path}
        cfg = self.config
        directory = directory or os.path.join(cfg.output_dir, "serving")
        os.makedirs(directory, exist_ok=True)
        manifest = self._write_serving_bundle(directory, generation=None)
        return {**manifest, "directory": directory}

    def _write_serving_bundle(self, directory: str,
                              generation: Optional[int]) -> Dict:
        """Write the gen(+CV) serving checkpoints and ``serving.json`` into
        ``directory``; returns the manifest."""
        import json as _json
        import tempfile as _tempfile

        cfg = self.config
        gen_name = f"{cfg.file_prefix}_gen_serving.zip"
        write_model(
            os.path.join(directory, gen_name), self.gen, self.gen_params,
            save_updater=False,
        )
        cv_name = None
        feature_vertex = None
        if self.cv is not None:
            cv_name = f"{cfg.file_prefix}_CV_serving.zip"
            write_model(
                os.path.join(directory, cv_name), self.cv, self.cv_state,
                save_updater=False,
            )
            # the deepest dis-derived layer — the features the classifier
            # was transfer-built on (mnist: dis_dense_layer_6)
            feature_vertex = list(self.family.dis_to_cv.values())[-1]
        manifest = {
            "format_version": 1,
            "family": self.family.name,
            "generator": gen_name,
            "classifier": cv_name,
            "feature_vertex": feature_vertex,
            "z_size": int(self.model_cfg.z_size),
            "num_features": int(cfg.num_features),
            "num_classes": int(cfg.num_classes),
            "step": self._publish_step(),
            "generation": generation,
        }
        # Scenario identity (zoo/manifest.py): the serving engine keys the
        # conditional `sample?class=k` kind off this block and the canary
        # gate keys its real-rows identity off it. Absent for configs
        # outside the zoo axes (tabular etc.) — those serve as before.
        from gan_deeplearning4j_tpu.zoo.manifest import scenario_from_config

        scenario = scenario_from_config(cfg)
        if scenario is not None:
            manifest["zoo"] = scenario.to_dict()
        fd, tmp = _tempfile.mkstemp(dir=directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as fh:
                _json.dump(manifest, fh, indent=2)
                fh.write("\n")
            os.replace(tmp, os.path.join(directory, "serving.json"))
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return manifest

    def load_models(self, directory: Optional[str] = None) -> int:
        """Resume: restore every state ``save_models`` wrote (params + updater
        + step — the capability the reference's saveUpdater=true format
        implies but never exercises, SURVEY §5 checkpoint/resume). Returns
        the restored iteration count.

        **Elastic mesh restore:** a directory holding
        ``*_state_shard-K-of-M.zip`` files is a mesh generation written by
        M coordinated workers; the shards are merged and reassembled onto
        THIS experiment regardless of M — a generation written by any mesh
        shape restores bit-exactly onto any other (including M=1 and the
        serve path), because the shard partition is a pure re-grouping of
        the same flat key space."""
        from gan_deeplearning4j_tpu.utils.serializer import ModelSerializer, read_model

        cfg = self.config
        directory = directory or cfg.output_dir
        prefix = os.path.join(directory, cfg.file_prefix)

        def _placed(state):
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                state = jax.device_put(
                    state, NamedSharding(self.mesh, PartitionSpec()))
            # Re-own every restored leaf through a compiled multiply-by-one
            # before any donating step sees it: on CPU the implicit transfer
            # of a checkpoint's numpy array can be zero-copy, so a donated
            # buffer aliases memory the runtime does not own and freeing it
            # corrupts the glibc heap a few allocations later (replicated
            # device_put over virtual host-platform devices carries the same
            # hazard). A real compute op forces fresh executable-owned
            # output allocations — jnp.copy lowers to an elidable alias,
            # which does NOT; x*1 is bit-exact.
            return jax.jit(lambda s: jax.tree_util.tree_map(
                lambda a: a * 1, s))(state)

        def _stored(state, trainer=None):
            # checkpoints written under bf16 storage restore as bf16 already
            # (dtype-tagged); an f32 checkpoint resumed under param_dtype=bf16
            # gets cast on entry, mirroring __init__. Under update sharding
            # the tree-form updater state is re-packed onto THIS mesh's
            # partition — a pure re-grouping, so restores are bit-exact
            # regardless of the writer's mesh shape (or compute mode).
            if self._param_dtype is not None:
                state = self._cast_state(state)
            if (trainer is not None
                    and getattr(trainer, "shard_updates", False)
                    and isinstance(state, TrainState)):
                return trainer.place_state(TrainState(
                    state.params,
                    trainer.plan.pack_state(state.opt_state),
                    state.step,
                ))
            return _placed(state)

        shard_files = sorted(
            n for n in os.listdir(directory)
            if _MESH_SHARD_RE.search(n) and n.startswith(cfg.file_prefix)
        )
        if shard_files:
            return self._load_models_sharded(directory, shard_files, _stored)

        self.dis_state = _stored(
            ModelSerializer.restore_train_state(f"{prefix}_dis_model.zip", self.dis_trainer),
            self.dis_trainer,
        )
        self.gan_state = _stored(
            ModelSerializer.restore_train_state(f"{prefix}_gan_model.zip", self.gan_trainer),
            self.gan_trainer,
        )
        if self.cv is not None:
            self.cv_state = _stored(
                ModelSerializer.restore_train_state(f"{prefix}_CV_model.zip", self.cv_trainer),
                self.cv_trainer,
            )
        _, gen_params, _, _ = read_model(f"{prefix}_gen_model.zip", load_updater=False)
        self.gen_params = _stored(gen_params)
        # the gan graph steps once per loop iteration — use it as the counter
        self.batch_counter = int(self.gan_state.step)
        return self.batch_counter

    @staticmethod
    def _merged_shard_state(directory: str, shard_files: List[str]) -> Dict:
        """Merge a mesh generation's shard files into one flat state dict,
        verifying disjointness, completeness, and a consistent shard_count —
        the model-agnostic half of a sharded restore (the WGAN-GP experiment
        reassembles its own states from the same merge)."""
        from gan_deeplearning4j_tpu.utils.serializer import read_state_shard

        counts = set()
        indices = []
        flat: Dict = {}
        total_keys = None
        for name in shard_files:
            arrays, meta = read_state_shard(os.path.join(directory, name))
            counts.add(int(meta["shard_count"]))
            indices.append(int(meta["shard_index"]))
            total_keys = int(meta["total_keys"])
            overlap = set(arrays) & set(flat)
            if overlap:
                raise ValueError(
                    f"mesh shards overlap on keys {sorted(overlap)[:3]}... "
                    f"— not one consistent generation")
            flat.update(arrays)
        if len(counts) != 1:
            raise ValueError(
                f"mesh shards disagree on shard_count ({sorted(counts)}) — "
                f"files from different generations are mixed")
        want = counts.pop()
        if sorted(indices) != list(range(want)):
            raise ValueError(
                f"mesh generation incomplete: have shards {sorted(indices)} "
                f"of {want} — refusing a partial restore")
        if total_keys is not None and len(flat) != total_keys:
            raise ValueError(
                f"mesh generation torn: merged {len(flat)} keys, writer "
                f"recorded {total_keys}")
        return flat

    def _load_models_sharded(self, directory: str, shard_files: List[str],
                             stored) -> int:
        """Reassemble a mesh generation: merge every shard's flat arrays
        (disjoint by construction, verified here), check the union covers
        the writer's full key count, and rebuild each TrainState onto this
        experiment's live trainers. ``stored`` is the caller's
        cast-and-place closure so sharded and whole-file restores go
        through one placement path."""
        from gan_deeplearning4j_tpu.utils.serializer import _unflatten

        flat = self._merged_shard_state(directory, shard_files)

        def train_state(model: str, trainer) -> TrainState:
            params = _unflatten(flat, f"{model}/params")
            opt_state = _unflatten(flat, f"{model}/updater")
            if not opt_state:
                opt = getattr(trainer.optimizer, "base", trainer.optimizer)
                opt_state = opt.init(params)
            step = jnp.asarray(int(np.asarray(flat[f"{model}/step"])),
                               jnp.int32)
            return TrainState(params, opt_state, step)

        self.dis_state = stored(train_state("dis", self.dis_trainer),
                                self.dis_trainer)
        self.gan_state = stored(train_state("gan", self.gan_trainer),
                                self.gan_trainer)
        if self.cv is not None:
            self.cv_state = stored(train_state("CV", self.cv_trainer),
                                   self.cv_trainer)
        self.gen_params = stored(_unflatten(flat, "gen/params"))
        self.batch_counter = int(self.gan_state.step)
        return self.batch_counter

    # -- the loop (I14) --------------------------------------------------
    def _window_limit(self, have_predictions: bool) -> int:
        """How many iterations the device loop may run before the host must
        intervene. An export after iteration j needs the state AT j, so an
        export index may only be a window's LAST element; per-iteration
        checkpointing (save_models with checkpoint_every=1) forces windows
        of 1, as do the phased trainer and loss_fetch_every=1 (label-noise
        resampling happens inside the scanned body since round 5, so it no
        longer forces per-dispatch stepping). A sparser checkpoint cadence
        (checkpoint_every > 1) only bounds windows at its own boundaries,
        like the export cadences."""
        cfg = self.config
        if (
            not getattr(self, "_supports_device_loop", False)  # phased path
            or (cfg.save_models and cfg.checkpoint_every <= 1)
            or cfg.loss_fetch_every <= 1
            # an epilogue hook observes state after EVERY iteration, so
            # every iteration must be a window boundary
            or getattr(self, "_epilogue_active", False)
        ):
            return 1
        i = self.batch_counter
        w = min(cfg.loss_fetch_every, cfg.num_iterations - i)
        bounds = [cfg.print_every]
        if cfg.save_models:
            bounds.append(cfg.checkpoint_every)
        if have_predictions:
            bounds.append(cfg.save_every)
        for every in bounds:
            r = i % every
            w = min(w, 1 if r == 0 else every - r + 1)
        return max(1, w)

    def run(self, train_iterator, test_iterator=None, eval_callback=None,
            epilogue_callback=None) -> Dict:
        """The training loop — host feeds WINDOWS, the device runs them.

        ``eval_callback(experiment, index)``, when given, fires at every
        ``print_every`` boundary (the manifold-export cadence, where window
        construction guarantees the model state is current) — the hook for
        in-training evaluation such as FID-based best-checkpoint selection
        (``scripts/quality_run.py``). It runs on the host between windows, so
        its cost gates training only at boundaries, never inside a window.

        ``epilogue_callback(experiment, index)``, when given, fires after
        EVERY iteration's epilogue (exports + checkpoint), with windows
        pinned to 1 so the model state is always current at the call; a
        ``False`` return stops the loop cleanly after the current
        iteration — the preemption/supervision entry point (a resilience
        supervisor publishes a store generation here, or drains out on a
        preemption flag without losing the iteration that just finished).

        Up to ``config.loss_fetch_every`` iterations at a time execute as one
        ``lax.scan`` dispatch (``train_iterations``); loss scalars come back
        in one batched read per flush. Two tunnel-scale costs motivate this
        (measured round 3): a per-step device→host read stalls the pipeline
        (~200 ms vs ~1-2 ms of device work per iteration), and per-step
        dispatch adds milliseconds of host latency. Windows shrink
        automatically at export/checkpoint boundaries so observable behavior
        (manifold/prediction exports, per-iteration checkpoints, loss
        history) is identical to the sequential loop; images_per_sec is the
        window average — the honest number under async dispatch."""
        cfg = self.config
        self._epilogue_active = epilogue_callback is not None
        if cfg.prefetch > 0 and not hasattr(train_iterator, "next_window"):
            # device-resident iterators are already in HBM and expose the
            # one-slice window fast path — wrapping them would hide
            # next_window and re-dispatch per batch for nothing
            sharding = getattr(self.dis_trainer, "batch_sharding", lambda: None)()
            train_iterator = DevicePrefetchIterator(
                train_iterator, depth=cfg.prefetch, sharding=sharding
            )
        history: List[Dict[str, float]] = []
        pending: List[tuple] = []  # (start iteration, loss record, images list)
        pending_iters = 0
        window_t0 = time.perf_counter()

        def flush() -> None:
            """One batched device→host read for every pending loss value."""
            nonlocal window_t0, pending_iters
            if not pending:
                return
            keys = list(pending[0][1].keys())
            rows = jnp.concatenate(
                [
                    jnp.stack(
                        [
                            jnp.atleast_1d(jnp.asarray(rec[k], jnp.float32))
                            for k in keys
                        ],
                        axis=1,
                    )
                    for _, rec, _ in pending
                ]
            )
            values = np.asarray(rows)  # the only device→host read
            elapsed = time.perf_counter() - window_t0
            per_iter = elapsed / len(values)
            row = 0
            for start, _, images in pending:
                for k, n_images in enumerate(images):
                    entry = dict(zip(keys, (float(v) for v in values[row])))
                    entry["images_per_sec"] = (
                        n_images / per_iter if per_iter > 0 else 0.0
                    )
                    self.metrics.log(start + k, entry)
                    history.append(entry)
                    row += 1
            pending.clear()
            pending_iters = 0
            window_t0 = time.perf_counter()

        have_predictions = test_iterator is not None and self.cv is not None
        # consumed-but-unprocessed batches (ragged tails, pow2 truncation)
        from collections import deque

        carry: deque = deque()

        def pull():
            if carry:
                return carry.popleft()
            if train_iterator.has_next():
                return train_iterator.next()
            return None

        with device_trace(cfg.profile_dir):
            while (
                carry or train_iterator.has_next()
            ) and self.batch_counter < cfg.num_iterations:
                # -- assemble the window ---------------------------------
                # Window sizes are quantized to powers of two: every
                # distinct K compiles its own scan program (~20-40 s cold on
                # TPU), so free-running sizes — epoch remainders, export
                # distances — would spend more time compiling than training.
                # Pow2 quantization bounds the program count at
                # log2(loss_fetch_every)+1 for the whole run.
                wmax = self._window_limit(have_predictions)
                target = 1 << (wmax.bit_length() - 1)
                window = None
                if target > 1 and not carry and hasattr(train_iterator, "next_window"):
                    # device-resident iterators serve a whole window as ONE
                    # stacked slice — k per-batch pulls would pay k host
                    # dispatches (~1 ms each on a tunneled chip)
                    window = train_iterator.next_window(target)

                # -- train it --------------------------------------------
                if window is not None:
                    wf, wl = window
                    n_window = int(wf.shape[0])
                    images = [int(wf.shape[1])] * n_window
                    with self.timer.phase("train_window"):
                        losses = self.train_iterations(wf, wl)
                else:
                    batches = [pull()]
                    while len(batches) < target:
                        nxt = pull()
                        if nxt is None:
                            break
                        if np.shape(nxt.features) != np.shape(batches[0].features):
                            carry.appendleft(nxt)  # ragged tail: later window
                            break
                        batches.append(nxt)
                    keep = 1 << (len(batches).bit_length() - 1)
                    while len(batches) > keep:  # epoch remainder → next turn
                        carry.appendleft(batches.pop())
                    n_window = len(batches)
                    images = [b.num_examples() for b in batches]
                    if n_window == 1:
                        losses = self.train_iteration(
                            batches[0].features, batches[0].labels
                        )
                    else:
                        with self.timer.phase("train_window"):
                            losses = self.train_iterations(
                                jnp.stack([jnp.asarray(b.features) for b in batches]),
                                None
                                if batches[0].labels is None
                                else jnp.stack([jnp.asarray(b.labels) for b in batches]),
                            )
                pending.append((self.batch_counter, losses, images))
                pending_iters += n_window

                # -- per-iteration epilogue ------------------------------
                # (by _window_limit construction, export indices can only be
                # the window's last element, whose state is current now)
                for _ in range(n_window):
                    index = self.batch_counter + 1
                    at_print = self.batch_counter % cfg.print_every == 0
                    if at_print:
                        with self.timer.phase("export_manifold"):
                            self.export_manifold(index)
                    if have_predictions and self.batch_counter % cfg.save_every == 0:
                        with self.timer.phase("export_predictions"):
                            self.export_predictions(test_iterator, index)
                    if at_print and eval_callback is not None:
                        # close the throughput window BEFORE the callback and
                        # restart it after: the eval hook is instrumentation,
                        # not product behavior — charging its device evals +
                        # host FID math to the window would deflate every
                        # images_per_sec entry sharing a flush group with a
                        # boundary. The manifold/prediction exports stay
                        # INSIDE the window deliberately (both run above,
                        # before this flush, even when print/save boundaries
                        # coincide — ADVICE r3): they are the reference's own
                        # loop work (I15), so the "full run loop" throughput
                        # keeps counting them.
                        flush()
                        with self.timer.phase("eval_callback"):
                            eval_callback(self, index)
                        window_t0 = time.perf_counter()
                    if cfg.save_models and (
                        self.batch_counter % cfg.checkpoint_every == 0
                    ):
                        with self.timer.phase("checkpoint"):
                            self.save_models()
                    logger.info("Completed Batch %d!", self.batch_counter)
                    self.batch_counter += 1
                    # the hook runs with the counter already advanced, so
                    # batch_counter == index == the step count of the state
                    # it observes — a publishing hook labels its checkpoint
                    # with the right step
                    stop = (
                        epilogue_callback is not None
                        and epilogue_callback(self, index) is False
                    )
                    if stop:
                        break
                if pending_iters >= max(1, cfg.loss_fetch_every):
                    flush()
                if stop:
                    break  # epilogue hook asked for a clean early exit
                if not carry and not train_iterator.has_next():
                    train_iterator.reset()  # (:600-602)
        flush()
        if (
            cfg.save_models
            and cfg.checkpoint_every > 1
            and self.batch_counter > 0
            and (self.batch_counter - 1) % cfg.checkpoint_every != 0
        ):
            # final-state checkpoint: with a sparse cadence the last saved
            # checkpoint can trail the end of the run by up to
            # checkpoint_every-1 iterations — resume/publish must see the
            # weights training actually finished with
            with self.timer.phase("checkpoint"):
                self.save_models()
        return {
            "iterations": self.batch_counter,
            "history": history,
            "timings": dict(self.timer.totals),
        }
