"""CLI entry point — ``python -m gan_deeplearning4j_tpu [flags]``.

The reference's ``main`` (dl4jGANComputerVision.java:94-101) echoes argv and
runs the GAN experiment end to end; here the flags actually configure the run
(see ``--help``). Data: reference-format MNIST CSVs under ``--data-dir`` are
used if present, else the deterministic synthetic set is generated there.
"""

from __future__ import annotations

import logging
import os
import re
import sys

from gan_deeplearning4j_tpu.data import (
    CSVRecordReader,
    FileSplit,
    RecordReaderDataSetIterator,
    write_csv,
)
from gan_deeplearning4j_tpu.data.mnist import prepare_mnist
from gan_deeplearning4j_tpu.harness import ExperimentConfig, make_experiment
from gan_deeplearning4j_tpu.runtime import backend_info


def _csv_iterator(path: str, batch: int, label_index: int, num_classes: int):
    reader = CSVRecordReader(0, ",")
    reader.initialize(FileSplit(path))
    return RecordReaderDataSetIterator(reader, batch, label_index, num_classes)


def _prepare_synthetic(config: ExperimentConfig, experiment) -> None:
    """Family-appropriate synthetic CSVs (features…,label) for non-MNIST
    families — MNIST keeps the reference's exact file contract via
    ``prepare_mnist`` (gan.ipynb cell 2)."""
    import numpy as np

    os.makedirs(config.data_dir, exist_ok=True)
    for split, n, seed in (
        ("train", 2 * config.batch_size_train, 0),
        ("test", config.batch_size_pred, 1),
    ):
        feats = experiment.family.synthetic_data(n, experiment.model_cfg, seed)
        labels = (np.arange(n) % config.num_classes).reshape(-1, 1).astype(np.float32)
        path = os.path.join(
            config.data_dir, f"{config.file_prefix}_{split}.csv"
        )
        write_csv(path, np.hstack([feats, labels]), precision=6)


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(levelname)s %(message)s"
    )
    print("Program arguments:", sys.argv[1:] if argv is None else argv)
    config = ExperimentConfig.from_args(argv)
    if not config.use_accelerator:
        # the useGpu=false path (dl4jGANComputerVision.java:92): run on host.
        # Must happen before the backend initializes; jax.config wins over the
        # JAX_PLATFORMS env var on this image.
        import jax

        jax.config.update("jax_platforms", "cpu")
    print("Execution backend:", backend_info())

    experiment = make_experiment(config)

    train_csv = os.path.join(config.data_dir, f"{config.file_prefix}_train.csv")
    test_csv = os.path.join(config.data_dir, f"{config.file_prefix}_test.csv")
    if not (os.path.exists(train_csv) and os.path.exists(test_csv)):
        print(f"No CSVs under {config.data_dir!r}; generating synthetic data there.")
        if config.model_family == "mnist":
            prepare_mnist(config.data_dir, prefix=config.file_prefix)
        else:
            _prepare_synthetic(config, experiment)

    train_it = _csv_iterator(
        train_csv, config.batch_size_train, config.num_features, config.num_classes
    )
    test_it = _csv_iterator(
        test_csv, config.batch_size_pred, config.num_features, config.num_classes
    )
    if config.resume:
        restored = experiment.load_models()
        print(f"Resumed from iteration {restored}")
    result = experiment.run(train_it, test_it)
    print(f"Done: {result['iterations']} iterations")
    print(experiment.timer.report())

    # offline eval — the gan.ipynb cell-6 flow, in-process (accuracy on the
    # latest predictions export + the latent-manifold PNG). Families without
    # a transfer classifier still get the manifold image.
    if result["iterations"] > 0:
        from gan_deeplearning4j_tpu.eval import accuracy_from_csvs, render_manifold

        def latest(pattern: str):
            """Highest-index export matching {prefix}_{pattern}_{N}.csv
            (exports follow print_every/save_every cadences, so the final
            iteration may not have one)."""
            candidates = []
            for name in os.listdir(config.output_dir):
                m = re.fullmatch(
                    re.escape(config.file_prefix) + "_" + pattern + r"_(\d+)\.csv", name
                )
                if m:
                    candidates.append((int(m.group(1)), name))
            return os.path.join(config.output_dir, max(candidates)[1]) if candidates else None

        preds = latest("test_predictions") if experiment.cv is not None else None
        manifold = latest("out")
        if preds:
            acc = accuracy_from_csvs(preds, test_csv, config.num_features)
            print(f"Transfer-classifier accuracy: {acc * 100:.2f}%")
        if manifold:
            png = render_manifold(
                manifold,
                os.path.join(config.output_dir, "DCGAN_Generated_Images.png"),
                grid=config.latent_grid,
                side=config.height,
                channels=config.channels,
            )
            print(f"Manifold image: {png}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
