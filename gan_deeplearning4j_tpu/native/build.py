"""Build the native data-layer library with the system toolchain.

One g++ invocation producing ``_gdt_native.so`` next to this file; rebuilt
automatically when the source is newer than the binary. No pybind11 in this
image — the ABI is plain C, bound with ctypes (csv_loader.py)."""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "src", "csv_loader.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "_gdt_native.so")
_lock = threading.Lock()


def library_path() -> str:
    return _LIB


def needs_build() -> bool:
    if not os.path.exists(_LIB):
        return True
    try:
        return os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
    except OSError:
        return False  # prebuilt .so shipped without src/ — use it as-is


def build(force: bool = False) -> Optional[str]:
    """Compile if needed. Returns the library path, or None if the toolchain
    is unavailable/fails (callers fall back to the numpy path). The compile
    goes to a temp file + atomic rename so concurrent processes never dlopen
    a partially written .so."""
    with _lock:
        if not force and not needs_build():
            return _LIB
        tmp = _LIB + f".tmp.{os.getpid()}"
        cmd = [
            "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            "-o", tmp, _SRC,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, _LIB)
        except (OSError, subprocess.SubprocessError):
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            return None
        return _LIB
