"""ctypes binding for the native CSV parser/writer (src/csv_loader.cpp).

The TPU-framework analog of DataVec's native-backed CSV layer (SURVEY §2.2
D13): parse-to-dense-float32 at memory bandwidth, multithreaded. All entry
points degrade gracefully — ``available()`` is False when the toolchain or
the built library is missing, and callers (data/records.py) fall back to
numpy."""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from gan_deeplearning4j_tpu.native import build as _build

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    path = _build.build()
    if path is None or not os.path.exists(path):
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.gdt_csv_read.restype = ctypes.c_int
        lib.gdt_csv_read.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long),
        ]
        lib.gdt_csv_free.restype = None
        lib.gdt_csv_free.argtypes = [ctypes.POINTER(ctypes.c_float)]
        lib.gdt_csv_write.restype = ctypes.c_int
        lib.gdt_csv_write.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_float),
            ctypes.c_long, ctypes.c_long, ctypes.c_char, ctypes.c_int,
        ]
        lib.gdt_csv_write_f64.restype = ctypes.c_int
        lib.gdt_csv_write_f64.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_double),
            ctypes.c_long, ctypes.c_long, ctypes.c_char, ctypes.c_int,
        ]
    except (OSError, AttributeError):
        # AttributeError: a stale prebuilt .so missing a newer symbol (e.g.
        # gdt_csv_write_f64) — fall back to numpy rather than crash callers
        _load_failed = True
        return None
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


_ERRORS = {
    1: "cannot open/read file",
    2: "ragged rows (inconsistent column counts)",
    3: "parse failure (non-numeric field)",
    4: "empty input",
}


def load_csv(path: str, skip_lines: int = 0, delimiter: str = ",") -> np.ndarray:
    """Parse a numeric CSV into an (N, C) float32 array via the native lib."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native CSV library unavailable")
    data = ctypes.POINTER(ctypes.c_float)()
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    status = lib.gdt_csv_read(
        os.fspath(path).encode(), skip_lines, delimiter.encode()[:1],
        ctypes.byref(data), ctypes.byref(rows), ctypes.byref(cols),
    )
    if status != 0:
        raise ValueError(
            f"native CSV parse of {path!r} failed: {_ERRORS.get(status, status)}"
        )
    try:
        # copy out of the malloc'd buffer into numpy-owned memory
        out = np.ctypeslib.as_array(data, shape=(rows.value, cols.value)).copy()
    finally:
        lib.gdt_csv_free(data)
    return out


def write_csv(path: str, array: np.ndarray, delimiter: str = ",", precision: int = 6) -> str:
    """Write an (N, C) array as CSV (%.{precision}f) via the native lib.
    float64 input formats from float64 (matching the numpy fallback digit
    for digit); everything else goes through float32."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native CSV library unavailable")
    array = np.asarray(array)
    if array.dtype == np.float64:
        arr = np.ascontiguousarray(array)
        fn, ctype = lib.gdt_csv_write_f64, ctypes.c_double
    else:
        arr = np.ascontiguousarray(array.astype(np.float32, copy=False))
        fn, ctype = lib.gdt_csv_write, ctypes.c_float
    if arr.ndim != 2:
        raise ValueError(f"expected a 2-D array, got shape {arr.shape}")
    status = fn(
        os.fspath(path).encode(),
        arr.ctypes.data_as(ctypes.POINTER(ctype)),
        arr.shape[0], arr.shape[1], delimiter.encode()[:1], precision,
    )
    if status != 0:
        raise ValueError(f"native CSV write to {path!r} failed")
    return path
