"""Native (C++) runtime components, bound via ctypes with Python fallbacks.

The reference's framework stack keeps its data layer and op executors in
native code behind the JVM (libnd4j, DataVec — SURVEY §2.2 D2-D5, D13). The
TPU rebuild's compute path is XLA (already native); this package holds the
native pieces *around* the compute path — currently the CSV data layer
(csv_loader) — built on demand with the system toolchain."""

from gan_deeplearning4j_tpu.native import build, csv_loader

__all__ = ["build", "csv_loader"]
