// Native CSV parser/writer — the C++ data-layer component (SURVEY §2.2 D13).
//
// The reference's data layer (DataVec CSVRecordReader) runs inside the JVM on
// top of native IO; this is the TPU-framework analog: a small C ABI library
// that parses numeric CSVs into a dense float32 matrix (and writes them back)
// at memory bandwidth, multithreaded over row chunks. Python binds it with
// ctypes (gan_deeplearning4j_tpu/native/csv_loader.py) and transparently
// falls back to numpy when the shared object is absent.
//
// Error codes: 0 ok, 1 cannot open/read, 2 ragged rows, 3 parse failure,
// 4 empty input.

#include <atomic>
#include <cmath>
#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <locale.h>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Buffer {
  std::string data;
};

bool read_file(const char* path, std::string* out) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  if (size < 0) {
    std::fclose(f);
    return false;
  }
  std::fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(size));
  size_t got = size ? std::fread(&(*out)[0], 1, static_cast<size_t>(size), f) : 0;
  std::fclose(f);
  return got == static_cast<size_t>(size);
}

// Offsets of line starts for non-empty lines (handles \n and \r\n endings).
void line_offsets(const std::string& text, std::vector<size_t>* starts,
                  std::vector<size_t>* ends) {
  size_t pos = 0;
  const size_t n = text.size();
  while (pos < n) {
    size_t eol = text.find('\n', pos);
    size_t end = (eol == std::string::npos) ? n : eol;
    size_t trimmed = end;
    while (trimmed > pos && (text[trimmed - 1] == '\r' || text[trimmed - 1] == ' '))
      --trimmed;
    if (trimmed > pos) {
      starts->push_back(pos);
      ends->push_back(trimmed);
    }
    if (eol == std::string::npos) break;
    pos = eol + 1;
  }
}

long count_fields(const char* p, const char* end, char delim) {
  long fields = 1;
  for (; p < end; ++p)
    if (*p == delim) ++fields;
  return fields;
}

const double kPow10[] = {1e0,  1e1,  1e2,  1e3,  1e4,  1e5,  1e6,  1e7,
                         1e8,  1e9,  1e10, 1e11, 1e12, 1e13, 1e14, 1e15,
                         1e16, 1e17, 1e18};

// Fast decimal parser for the common CSV shapes ("0.27", "-1.5", "666",
// "1e-3"); ~10x strtof, which locks the locale per call. Returns the cursor
// after the number, or nullptr to signal "let strtof try" (covers nan/inf/
// overlong digit runs).
const char* parse_float_fast(const char* p, const char* end, float* out) {
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) neg = (*p++ == '-');
  double val = 0.0;
  int digits = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    val = val * 10.0 + (*p++ - '0');
    ++digits;
  }
  if (p < end && *p == '.') {
    ++p;
    int frac = 0;
    double f = 0.0;
    while (p < end && *p >= '0' && *p <= '9') {
      f = f * 10.0 + (*p++ - '0');
      ++frac;
    }
    if (frac > 18) return nullptr;
    val += f / kPow10[frac];
    digits += frac;
  }
  if (digits == 0 || digits > 18) return nullptr;
  if (p < end && (*p == 'e' || *p == 'E')) {
    ++p;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) eneg = (*p++ == '-');
    long ex = 0;
    int edigits = 0;
    while (p < end && *p >= '0' && *p <= '9') {
      ex = ex * 10 + (*p++ - '0');
      ++edigits;
    }
    if (edigits == 0 || ex > 300) return nullptr;
    const double scale =
        (ex <= 18) ? kPow10[ex] : std::pow(10.0, static_cast<double>(ex));
    val = eneg ? val / scale : val * scale;
  }
  *out = static_cast<float>(neg ? -val : val);
  return p;
}

// Process-lifetime "C" locale so the strtof fallback is deterministic under
// any LC_NUMERIC (a comma-decimal locale would otherwise parse "3.14" as 3).
locale_t c_locale() {
  static locale_t loc = newlocale(LC_ALL_MASK, "C", static_cast<locale_t>(0));
  return loc;
}

// Parse one line of `cols` floats into out; returns false on error.
bool parse_line(const char* p, const char* end, char delim, long cols, float* out) {
  for (long c = 0; c < cols; ++c) {
    while (p < end && *p == ' ') ++p;
    const char* next = parse_float_fast(p, end, &out[c]);
    if (next == nullptr) {  // rare shapes (nan/inf/huge) -> strtof fallback
      // Bounded: copy the field into a NUL-terminated scratch buffer first —
      // strtof on the raw pointer would scan the whole null-terminated file
      // buffer past the logical field end.
      const char* fend = p;
      while (fend < end && *fend != delim) ++fend;
      char scratch[64];
      const size_t len = static_cast<size_t>(fend - p);
      if (len == 0 || len >= sizeof(scratch)) return false;
      std::memcpy(scratch, p, len);
      scratch[len] = '\0';
      char* sn = nullptr;
      errno = 0;
      out[c] = strtof_l(scratch, &sn, c_locale());
      if (sn == scratch) return false;
      next = p + (sn - scratch);
    }
    p = next;
    while (p < end && *p == ' ') ++p;
    if (c + 1 < cols) {
      if (p >= end || *p != delim) return false;
      ++p;
    }
  }
  while (p < end && (*p == ' ' || *p == '\r')) ++p;
  return p == end;  // trailing garbage -> error
}

}  // namespace

extern "C" {

int gdt_csv_read(const char* path, long skip_lines, char delim, float** out_data,
                 long* out_rows, long* out_cols) {
  std::string text;
  if (!read_file(path, &text)) return 1;
  std::vector<size_t> starts, ends;
  line_offsets(text, &starts, &ends);
  if (skip_lines < 0) skip_lines = 0;
  if (static_cast<size_t>(skip_lines) >= starts.size()) return 4;

  const size_t first = static_cast<size_t>(skip_lines);
  const long rows = static_cast<long>(starts.size() - first);
  const long cols =
      count_fields(text.data() + starts[first], text.data() + ends[first], delim);
  float* data = static_cast<float*>(std::malloc(sizeof(float) * rows * cols));
  if (!data) return 1;

  std::atomic<int> status{0};
  long nthreads = static_cast<long>(std::thread::hardware_concurrency());
  if (nthreads < 1) nthreads = 1;
  if (nthreads > rows) nthreads = rows;
  std::vector<std::thread> workers;
  const long chunk = (rows + nthreads - 1) / nthreads;
  for (long t = 0; t < nthreads; ++t) {
    const long r0 = t * chunk;
    const long r1 = (r0 + chunk < rows) ? r0 + chunk : rows;
    if (r0 >= r1) break;
    workers.emplace_back([&, r0, r1]() {
      for (long r = r0; r < r1 && status.load(std::memory_order_relaxed) == 0; ++r) {
        const char* p = text.data() + starts[first + r];
        const char* end = text.data() + ends[first + r];
        if (count_fields(p, end, delim) != cols) {
          status.store(2, std::memory_order_relaxed);
          return;
        }
        if (!parse_line(p, end, delim, cols, data + r * cols)) {
          status.store(3, std::memory_order_relaxed);
          return;
        }
      }
    });
  }
  for (auto& w : workers) w.join();
  if (int s = status.load()) {
    std::free(data);
    return s;
  }
  *out_data = data;
  *out_rows = rows;
  *out_cols = cols;
  return 0;
}

void gdt_csv_free(float* ptr) { std::free(ptr); }

}  // extern "C"

namespace {

// Fixed-precision float -> decimal text, round-half-away-from-zero (printf
// semantics for the values seen here); ~10x snprintf. Falls back to snprintf
// outside the fast range.
inline char* emit_fixed(char* out, double v, int precision) {
  // fast path only when v * 10^precision fits an unsigned long long; NaN,
  // inf, and huge values take the printf path (bounded, length-checked)
  const double mag = (v < 0 ? -v : v) * kPow10[precision];
  if (!(mag < 1.8e19)) {
    char tmp[96];
    int n = std::snprintf(tmp, sizeof(tmp), "%.*f", precision, v);
    if (n < 0) n = 0;
    if (n > static_cast<int>(sizeof(tmp)) - 1) n = sizeof(tmp) - 1;
    std::memcpy(out, tmp, static_cast<size_t>(n));
    return out + n;
  }
  if (v < 0 || (v == 0.0 && std::signbit(v))) {
    *out++ = '-';
    v = -v;
  }
  const double scaled = v * kPow10[precision] + 0.5;
  unsigned long long units = static_cast<unsigned long long>(scaled);
  char digits[32];
  int n = 0;
  unsigned long long ip = units;
  for (int i = 0; i < precision; ++i) {
    digits[n++] = static_cast<char>('0' + ip % 10);
    ip /= 10;
  }
  char frac_sep = precision ? '.' : '\0';
  char idigits[24];
  int ni = 0;
  do {
    idigits[ni++] = static_cast<char>('0' + ip % 10);
    ip /= 10;
  } while (ip);
  while (ni) *out++ = idigits[--ni];
  if (frac_sep) {
    *out++ = frac_sep;
    while (n) *out++ = digits[--n];
  }
  return out;
}

// Shared writer body over the element type: f32 exports come straight from
// device fetches; f64 exists so the native path formats the same digits as
// the numpy fallback for double input (no silent downcast).
template <typename T>
int write_csv_impl(const char* path, const T* data, long rows, long cols,
                   char delim, int precision) {
  std::FILE* f = std::fopen(path, "wb");
  if (!f) return 1;
  if (precision < 0 || precision > 17) precision = 6;
  std::string buf;
  // worst case per field: 95 chars (printf fallback buffer) + delimiter
  const size_t row_cap = static_cast<size_t>(cols) * 96 + 2;
  buf.resize(row_cap * 256);  // flush every 256 rows
  char* cur = &buf[0];
  long pending = 0;
  for (long r = 0; r < rows; ++r) {
    for (long c = 0; c < cols; ++c) {
      if (c) *cur++ = delim;
      cur = emit_fixed(cur, static_cast<double>(data[r * cols + c]), precision);
    }
    *cur++ = '\n';
    if (++pending == 256 || r + 1 == rows) {
      const size_t len = static_cast<size_t>(cur - buf.data());
      if (std::fwrite(buf.data(), 1, len, f) != len) {
        std::fclose(f);
        return 1;
      }
      cur = &buf[0];
      pending = 0;
    }
  }
  return std::fclose(f) == 0 ? 0 : 1;  // flush failure = write failure
}

}  // namespace

extern "C" {

// Write a dense float32 matrix as fixed-precision CSV (the export path,
// reference :550-598, without per-scalar host reads). Returns 0 on success.
int gdt_csv_write(const char* path, const float* data, long rows, long cols,
                  char delim, int precision) {
  return write_csv_impl(path, data, rows, cols, delim, precision);
}

// Same, formatting from float64 (keeps the native writer digit-identical to
// the numpy fallback when callers hand in doubles).
int gdt_csv_write_f64(const char* path, const double* data, long rows,
                      long cols, char delim, int precision) {
  return write_csv_impl(path, data, rows, cols, delim, precision);
}

}  // extern "C"
