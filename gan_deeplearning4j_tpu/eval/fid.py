"""FID harness (BASELINE.md: "DCGAN images/sec/chip …; FID@50k").

Fréchet Inception Distance fits Gaussians to feature activations of real vs
generated samples and measures ||μr−μg||² + Tr(Σr+Σg−2(ΣrΣg)^½). The canonical
feature net is InceptionV3 pool3; this environment has no network egress to
fetch those weights, so the harness ships two extractors:

- ``frozen_feature_fn`` (the DEFAULT for quality tracking): a seeded FROZEN
  random-conv stack, fully determined by (image shape, seed) and independent
  of any model under evaluation — the same inputs score the same features in
  every run, every round, on every backend, so FID numbers are comparable
  over time. Random convolutional features are a standard offline stand-in
  for Inception embeddings (round-2 VERDICT weak #4: tapping the trained
  discriminator made the metric self-referential — the feature space moved
  every run).
- ``graph_feature_fn``: taps any named layer of a framework graph (e.g. the
  trained discriminator's ``dis_dense_layer_6``) — useful for model-space
  diagnostics, NOT for cross-run tracking.

Plug in an Inception extractor for literature-comparable numbers."""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class FeatureStats:
    """Gaussian moments of a feature set: mean (D,) and covariance (D, D)."""

    mean: np.ndarray
    cov: np.ndarray

    @staticmethod
    def from_features(features: np.ndarray) -> "FeatureStats":
        features = np.asarray(features, dtype=np.float64)
        if features.ndim != 2:
            features = features.reshape(features.shape[0], -1)
        if features.shape[0] < 2:
            raise ValueError("need at least 2 samples to fit covariance")
        return FeatureStats(
            mean=features.mean(axis=0),
            cov=np.cov(features, rowvar=False).reshape(
                features.shape[1], features.shape[1]
            ),
        )


def _sqrtm_psd(mat: np.ndarray) -> np.ndarray:
    """Matrix square root of a (near-)PSD symmetric matrix via eigendecomp —
    numerically safer than scipy.linalg.sqrtm for GAN feature covariances."""
    vals, vecs = np.linalg.eigh((mat + mat.T) / 2.0)
    vals = np.clip(vals, 0.0, None)
    return (vecs * np.sqrt(vals)) @ vecs.T


def fid_from_stats(real: FeatureStats, fake: FeatureStats, eps: float = 1e-6) -> float:
    """Fréchet distance between the two Gaussians."""
    diff = real.mean - fake.mean
    # regularize before the product: keeps the sqrt stable when either
    # covariance is rank-deficient (small sample counts). Using
    # sqrt(A)·B·sqrt(A) keeps the argument symmetric PSD; its sqrt has the
    # same trace as sqrtm(A·B) in the textbook formula.
    offset = eps * np.eye(real.cov.shape[0])
    sr = _sqrtm_psd(real.cov + offset)
    covmean = _sqrtm_psd(sr @ (fake.cov + offset) @ sr)
    return float(diff @ diff + np.trace(real.cov + fake.cov - 2.0 * covmean))


# (out_channels, kernel, stride) per stage of the frozen extractor; the
# feature vector concatenates each stage's spatial mean → 32+64+128 = 224 dims
_FROZEN_STAGES = ((32, 5, 2), (64, 5, 2), (128, 3, 2))


def frozen_feature_fn(
    height: int,
    width: int,
    channels: int = 1,
    seed: int = 666,
    batch_size: int = 500,
) -> Callable:
    """Fixed random-conv feature extractor — the stable FID feature space.

    Three seeded He-initialized conv stages (stride 2, leaky-ReLU), each
    contributing its spatial mean activation; features depend ONLY on
    (height, width, channels, seed), never on a trained model. Inputs may be
    flat (N, H·W·C) rows (the harness's CSV layout) or (N, H, W, C) images,
    values in [0, 1].
    """
    import jax
    import jax.numpy as jnp

    keys = jax.random.split(jax.random.PRNGKey(seed), len(_FROZEN_STAGES))
    kernels = []
    c_in = channels
    for key, (c_out, k, stride) in zip(keys, _FROZEN_STAGES):
        fan_in = k * k * c_in
        kernels.append(
            (
                jax.random.normal(key, (k, k, c_in, c_out), jnp.float32)
                * jnp.sqrt(2.0 / fan_in),
                stride,
            )
        )
        c_in = c_out

    def forward(x):
        x = x.reshape(x.shape[0], height, width, channels).astype(jnp.float32)
        x = x * 2.0 - 1.0  # center [0,1] pixels
        pooled = []
        for kernel, stride in kernels:
            # HIGHEST precision: on TPU the default f32 conv runs bf16 MXU
            # passes, which would shift the "fixed" feature space between
            # backends — the exact incomparability this extractor exists to
            # prevent (tests pin values at rtol 2e-4 across platforms)
            x = jax.lax.conv_general_dilated(
                x, kernel, (stride, stride), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                precision=jax.lax.Precision.HIGHEST,
            )
            x = jnp.where(x > 0, x, 0.2 * x)  # leaky ReLU
            pooled.append(x.mean(axis=(1, 2)))
        return jnp.concatenate(pooled, axis=-1)

    fwd = jax.jit(forward)

    def extract(samples: np.ndarray) -> np.ndarray:
        chunks = []
        for i in range(0, len(samples), batch_size):
            chunks.append(np.asarray(fwd(jnp.asarray(samples[i : i + batch_size]))))
        return np.concatenate(chunks, axis=0)

    # the raw jittable (N,·)→(N,224) forward, for callers composing the
    # extractor with other device computations (e.g. generator→features in
    # one dispatch, scripts/quality_run.py's in-training tracker)
    extract.forward = forward
    return extract


def graph_feature_fn(graph, params, layer_name: str, batch_size: int = 500) -> Callable:
    """Feature extractor tapping ``layer_name``'s activation of a framework
    graph (ComputationGraph.feed_forward), batched on device."""
    import jax
    import jax.numpy as jnp

    tap = jax.jit(
        lambda p, x: graph.feed_forward(p, x, train=False)[layer_name]
    )

    def extract(samples: np.ndarray) -> np.ndarray:
        chunks = []
        for i in range(0, len(samples), batch_size):
            out = np.asarray(tap(params, jnp.asarray(samples[i : i + batch_size])))
            chunks.append(out.reshape(out.shape[0], -1))
        return np.concatenate(chunks, axis=0)

    return extract


def fid_score(
    real_samples: np.ndarray,
    fake_samples: np.ndarray,
    feature_fn: Optional[Callable] = None,
) -> float:
    """End-to-end FID: extract features (identity when ``feature_fn`` is None
    — raw-pixel FID, useful for smoke tests), fit stats, measure."""
    extract = feature_fn if feature_fn is not None else (lambda x: np.asarray(x).reshape(len(x), -1))
    return fid_from_stats(
        FeatureStats.from_features(extract(real_samples)),
        FeatureStats.from_features(extract(fake_samples)),
    )
