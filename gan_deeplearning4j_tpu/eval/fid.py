"""FID harness (BASELINE.md: "DCGAN images/sec/chip …; FID@50k").

Fréchet Inception Distance fits Gaussians to feature activations of real vs
generated samples and measures ||μr−μg||² + Tr(Σr+Σg−2(ΣrΣg)^½). The canonical
feature net is InceptionV3 pool3; this environment has no network egress to
fetch those weights, so the harness ships two extractors:

- ``frozen_feature_fn`` (the DEFAULT for quality tracking): a seeded FROZEN
  random-conv stack, fully determined by (image shape, seed) and independent
  of any model under evaluation — the same inputs score the same features in
  every run, every round, on every backend, so FID numbers are comparable
  over time. Random convolutional features are a standard offline stand-in
  for Inception embeddings (round-2 VERDICT weak #4: tapping the trained
  discriminator made the metric self-referential — the feature space moved
  every run).
- ``graph_feature_fn``: taps any named layer of a framework graph (e.g. the
  trained discriminator's ``dis_dense_layer_6``) — useful for model-space
  diagnostics, NOT for cross-run tracking.

Plug in an Inception extractor for literature-comparable numbers."""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class FeatureStats:
    """Gaussian moments of a feature set: mean (D,) and covariance (D, D)."""

    mean: np.ndarray
    cov: np.ndarray

    @staticmethod
    def from_features(features: np.ndarray) -> "FeatureStats":
        features = np.asarray(features, dtype=np.float64)
        if features.ndim != 2:
            features = features.reshape(features.shape[0], -1)
        if features.shape[0] < 2:
            raise ValueError("need at least 2 samples to fit covariance")
        return FeatureStats(
            mean=features.mean(axis=0),
            cov=np.cov(features, rowvar=False).reshape(
                features.shape[1], features.shape[1]
            ),
        )


def _sqrtm_psd(mat: np.ndarray) -> np.ndarray:
    """Matrix square root of a (near-)PSD symmetric matrix via eigendecomp —
    numerically safer than scipy.linalg.sqrtm for GAN feature covariances."""
    vals, vecs = np.linalg.eigh((mat + mat.T) / 2.0)
    vals = np.clip(vals, 0.0, None)
    return (vecs * np.sqrt(vals)) @ vecs.T


def fid_from_stats(real: FeatureStats, fake: FeatureStats, eps: float = 1e-6) -> float:
    """Fréchet distance between the two Gaussians."""
    diff = real.mean - fake.mean
    # regularize before the product: keeps the sqrt stable when either
    # covariance is rank-deficient (small sample counts). Using
    # sqrt(A)·B·sqrt(A) keeps the argument symmetric PSD; its sqrt has the
    # same trace as sqrtm(A·B) in the textbook formula.
    offset = eps * np.eye(real.cov.shape[0])
    sr = _sqrtm_psd(real.cov + offset)
    covmean = _sqrtm_psd(sr @ (fake.cov + offset) @ sr)
    return float(diff @ diff + np.trace(real.cov + fake.cov - 2.0 * covmean))


def _batched(fwd, batch_size: int):
    """Wrap a jitted (N,·)→(N,D) device forward into a chunked host-side
    extractor: one device round trip per ``batch_size`` samples. Shared by
    every feature extractor in this module so chunking fixes land once."""
    import jax.numpy as jnp

    def extract(samples: np.ndarray) -> np.ndarray:
        chunks = []
        for i in range(0, len(samples), batch_size):
            chunks.append(np.asarray(fwd(jnp.asarray(samples[i : i + batch_size]))))
        return np.concatenate(chunks, axis=0)

    return extract


# (out_channels, kernel, stride) per stage of the frozen extractor; the
# feature vector concatenates each stage's spatial mean → 32+64+128 = 224 dims
_FROZEN_STAGES = ((32, 5, 2), (64, 5, 2), (128, 3, 2))


def frozen_feature_fn(
    height: int,
    width: int,
    channels: int = 1,
    seed: int = 666,
    batch_size: int = 500,
) -> Callable:
    """Fixed random-conv feature extractor — the stable FID feature space.

    Three seeded He-initialized conv stages (stride 2, leaky-ReLU), each
    contributing its spatial mean activation; features depend ONLY on
    (height, width, channels, seed), never on a trained model. Inputs may be
    flat (N, H·W·C) rows (the harness's CSV layout) or (N, H, W, C) images,
    values in [0, 1].
    """
    import jax
    import jax.numpy as jnp

    keys = jax.random.split(jax.random.PRNGKey(seed), len(_FROZEN_STAGES))
    kernels = []
    c_in = channels
    for key, (c_out, k, stride) in zip(keys, _FROZEN_STAGES):
        fan_in = k * k * c_in
        kernels.append(
            (
                jax.random.normal(key, (k, k, c_in, c_out), jnp.float32)
                * jnp.sqrt(2.0 / fan_in),
                stride,
            )
        )
        c_in = c_out

    def forward(x):
        x = x.reshape(x.shape[0], height, width, channels).astype(jnp.float32)
        x = x * 2.0 - 1.0  # center [0,1] pixels
        pooled = []
        for kernel, stride in kernels:
            # HIGHEST precision: on TPU the default f32 conv runs bf16 MXU
            # passes, which would shift the "fixed" feature space between
            # backends — the exact incomparability this extractor exists to
            # prevent (tests pin values at rtol 2e-4 across platforms)
            x = jax.lax.conv_general_dilated(
                x, kernel, (stride, stride), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                precision=jax.lax.Precision.HIGHEST,
            )
            x = jnp.where(x > 0, x, 0.2 * x)  # leaky ReLU
            pooled.append(x.mean(axis=(1, 2)))
        return jnp.concatenate(pooled, axis=-1)

    extract = _batched(jax.jit(forward), batch_size)
    # the raw jittable (N,·)→(N,224) forward, for callers composing the
    # extractor with other device computations (e.g. generator→features in
    # one dispatch, scripts/quality_run.py's in-training tracker)
    extract.forward = forward
    return extract


def inception_feature_fn(
    height: int,
    width: int,
    channels: int = 1,
    path: Optional[str] = None,
    batch_size: int = 500,
    seed: int = 666,
) -> Callable:
    """Literature-comparable FID extractor from USER-SUPPLIED weights
    (round-4 VERDICT item 7): loads a feature network from ``path`` or
    ``$INCEPTION_WEIGHTS`` and returns an extractor with the same call
    contract as :func:`frozen_feature_fn`. With no weights available it
    FALLS BACK to the frozen extractor (logged via the returned function's
    ``.source`` attribute: ``"inception:<path>"`` or ``"frozen"``) — this
    environment has no egress, so the canonical InceptionV3 pool3 weights
    cannot be fetched, only mounted.

    Weight format — one ``.npz`` with a ``__schema__`` JSON entry describing
    a small dataflow graph over the remaining arrays (expressive enough for
    InceptionV3's branched topology, not just sequential stacks):

    .. code-block:: python

        {"input":  {"height": 299, "width": 299, "channels": 3,
                    "mean": [...], "std": [...]},      # optional normalize
         "nodes": [{"name": "c1", "op": "conv", "in": "input",
                    "stride": 2, "padding": "VALID", "activation": "relu",
                    "kernel": "c1/kernel", "bias": "c1/bias"},  # HWIO
                   {"name": "b1", "op": "conv", "in": "c1", "stride": 1,
                    "padding": "SAME", "kernel": "b1/kernel"},
                   {"name": "p1", "op": "maxpool", "in": "c1",
                    "size": 3, "stride": 1, "padding": "SAME"},
                   {"name": "b",  "op": "concat", "in": ["b1", "p1"]},
                   {"name": "f",  "op": "global_avgpool", "in": "b"}],
         "output": "f"}

    (``concat`` joins the channel axis, so its inputs must share spatial
    dims — here both branches keep ``c1``'s via stride 1 + SAME.)

    Ops: ``conv`` (+optional bias/relu), ``maxpool``, ``avgpool``
    (zero-padding EXCLUDED from the divisor, matching the TF/pytorch-fid
    ``count_include_pad=False`` semantics published FID numbers assume),
    ``concat``, ``global_avgpool``. Inputs are resized to the schema's
    spatial size (bilinear, matching the standard FID preprocessing
    pipeline) and grayscale is broadcast to the schema's channel count."""
    import json
    import os

    import jax
    import jax.numpy as jnp

    path = path or os.environ.get("INCEPTION_WEIGHTS")
    if not path or not os.path.exists(path):
        fallback = frozen_feature_fn(
            height, width, channels, seed=seed, batch_size=batch_size
        )
        fallback.source = "frozen"
        return fallback

    with np.load(path, allow_pickle=False) as npz:
        schema = json.loads(str(npz["__schema__"]))
        arrays = {k: np.asarray(npz[k]) for k in npz.files if k != "__schema__"}

    spec_in = schema["input"]
    nodes = schema["nodes"]
    out_name = schema["output"]
    mean = jnp.asarray(spec_in.get("mean", [0.0]), jnp.float32)
    std = jnp.asarray(spec_in.get("std", [1.0]), jnp.float32)
    h_in, w_in, c_in = spec_in["height"], spec_in["width"], spec_in["channels"]
    consts = {k: jnp.asarray(v, jnp.float32) for k, v in arrays.items()}

    def forward(x):
        x = x.reshape(x.shape[0], height, width, channels).astype(jnp.float32)
        if channels == 1 and c_in > 1:
            x = jnp.broadcast_to(x, x.shape[:3] + (c_in,))
        if (height, width) != (h_in, w_in):
            x = jax.image.resize(
                x, (x.shape[0], h_in, w_in, x.shape[3]), method="bilinear"
            )
        x = (x - mean) / std
        acts = {"input": x}
        for node in nodes:
            op = node["op"]
            src = node["in"]
            if op == "concat":
                y = jnp.concatenate([acts[s] for s in src], axis=-1)
            else:
                y = acts[src]
                if op == "conv":
                    s = node.get("stride", 1)
                    y = jax.lax.conv_general_dilated(
                        y, consts[node["kernel"]], (s, s),
                        node.get("padding", "SAME"),
                        dimension_numbers=("NHWC", "HWIO", "NHWC"),
                        precision=jax.lax.Precision.HIGHEST,
                    )
                    if node.get("bias"):
                        y = y + consts[node["bias"]]
                    if node.get("activation") == "relu":
                        y = jnp.maximum(y, 0.0)
                elif op in ("maxpool", "avgpool"):
                    k, s = node["size"], node.get("stride", 1)
                    pad = node.get("padding", "VALID")
                    init, fn = (
                        (-jnp.inf, jax.lax.max) if op == "maxpool"
                        else (0.0, jax.lax.add)
                    )
                    pre_spatial = (1,) + y.shape[1:3] + (1,)
                    y = jax.lax.reduce_window(
                        y, init, fn, (1, k, k, 1), (1, s, s, 1), pad
                    )
                    if op == "avgpool":
                        # divide by the number of REAL elements per window
                        # (padding excluded): TF / pytorch-fid use
                        # count_include_pad=False, and published FID numbers
                        # assume it — a plain /k² understates edge windows
                        counts = jax.lax.reduce_window(
                            jnp.ones(pre_spatial, y.dtype), 0.0, jax.lax.add,
                            (1, k, k, 1), (1, s, s, 1), pad,
                        )
                        y = y / counts
                elif op == "global_avgpool":
                    y = y.mean(axis=(1, 2))
                else:
                    raise ValueError(f"unknown op {op!r} in {path}")
            acts[node["name"]] = y
        out = acts[out_name]
        return out.reshape(out.shape[0], -1)

    extract = _batched(jax.jit(forward), batch_size)
    extract.forward = forward
    extract.source = f"inception:{path}"
    return extract


def quick_fid_scorer(
    exp,
    frozen_fn,
    real_stats: FeatureStats,
    num_samples: int = 2048,
    seed: int = 679,
) -> Callable:
    """In-training quick-FID tracker shared by ``scripts/quality_run.py``
    and ``scripts/tune_sweep.py`` (previously two hand-synced copies).

    Returns ``score(experiment, index) -> fid``: generator→frozen-features
    composed as ONE jitted device program over a FIXED z set (paired across
    every eval, so successive scores differ by model state, not sampling
    noise), scored against precomputed ``real_stats``. Appends
    ``[index, fid]`` to ``score.curve``; a repeated call for the SAME index
    returns the cached value instead of re-evaluating — callers can
    unconditionally score the final iteration without duplicating the entry
    when the callback cadence already landed on it."""
    import jax
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.runtime.dtype import compute_dtype_scope

    z_size = exp.model_cfg.z_size
    z = np.random.default_rng(seed).random(
        (num_samples, z_size), dtype=np.float32
    ) * 2.0 - 1.0
    z_dev = jnp.asarray(z)
    gen_features = jax.jit(lambda p, zz: frozen_fn.forward(exp._gen_fwd(p, zz)))
    curve: list = []

    def score(e, index) -> float:
        if curve and curve[-1][0] == index:
            return curve[-1][1]
        with compute_dtype_scope(e._compute_dtype):
            feats = np.asarray(gen_features(e.gen_params, z_dev))
        fid = float(fid_from_stats(real_stats, FeatureStats.from_features(feats)))
        curve.append([index, round(fid, 3)])
        return fid

    score.curve = curve
    return score


def graph_feature_fn(graph, params, layer_name: str, batch_size: int = 500) -> Callable:
    """Feature extractor tapping ``layer_name``'s activation of a framework
    graph (ComputationGraph.feed_forward), batched on device."""
    import jax
    import jax.numpy as jnp

    # params stay a traced ARGUMENT: closing them into the jit would bake
    # the whole parameter pytree into the executable as constants
    tap = jax.jit(
        lambda p, x: graph.feed_forward(p, x, train=False)[layer_name]
        .reshape(x.shape[0], -1)
    )
    return _batched(lambda x: tap(params, x), batch_size)


def fid_score(
    real_samples: np.ndarray,
    fake_samples: np.ndarray,
    feature_fn: Optional[Callable] = None,
) -> float:
    """End-to-end FID: extract features (identity when ``feature_fn`` is None
    — raw-pixel FID, useful for smoke tests), fit stats, measure."""
    extract = feature_fn if feature_fn is not None else (lambda x: np.asarray(x).reshape(len(x), -1))
    return fid_from_stats(
        FeatureStats.from_features(extract(real_samples)),
        FeatureStats.from_features(extract(fake_samples)),
    )
