"""Latent-manifold image rendering — the gan.ipynb cell-6 visualization.

The notebook tiles the 100 decoded z-grid digits into a 280×280 image and
saves ``DCGAN_Generated_Images.png`` via matplotlib. This module reproduces
that artifact with a dependency-free PNG writer (stdlib zlib only), so the
render runs in any environment the framework does."""

from __future__ import annotations

import struct
import zlib

import numpy as np


def tile_images(images: np.ndarray, grid: int) -> np.ndarray:
    """(grid², H, W[, C]) → one (grid·H, grid·W[, C]) mosaic, row-major —
    the notebook's nested paste loop."""
    images = np.asarray(images)
    n, h, w = images.shape[:3]
    if n != grid * grid:
        raise ValueError(f"need {grid * grid} images for a {grid}×{grid} grid, got {n}")
    rest = images.shape[3:]
    out = np.zeros((grid * h, grid * w) + rest, dtype=images.dtype)
    for idx in range(n):
        r, c = divmod(idx, grid)
        out[r * h : (r + 1) * h, c * w : (c + 1) * w] = images[idx]
    return out


def write_png(path: str, image: np.ndarray) -> str:
    """Minimal PNG encoder: float arrays in [0,1] or uint8; (H,W) grayscale,
    (H,W,3) RGB, or (H,W,1)."""
    img = np.asarray(image)
    if img.dtype != np.uint8:
        img = (np.clip(img, 0.0, 1.0) * 255.0 + 0.5).astype(np.uint8)
    if img.ndim == 3 and img.shape[2] == 1:
        img = img[:, :, 0]
    if img.ndim == 2:
        color_type = 0  # grayscale
    elif img.ndim == 3 and img.shape[2] == 3:
        color_type = 2  # RGB
    else:
        raise ValueError(f"unsupported image shape {image.shape}")
    h, w = img.shape[:2]
    raw = b"".join(b"\x00" + img[r].tobytes() for r in range(h))  # filter 0 rows

    def chunk(tag: bytes, data: bytes) -> bytes:
        return (
            struct.pack(">I", len(data))
            + tag
            + data
            + struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF)
        )

    ihdr = struct.pack(">IIBBBBB", w, h, 8, color_type, 0, 0, 0)
    png = (
        b"\x89PNG\r\n\x1a\n"
        + chunk(b"IHDR", ihdr)
        + chunk(b"IDAT", zlib.compress(raw, 9))
        + chunk(b"IEND", b"")
    )
    with open(path, "wb") as fh:
        fh.write(png)
    return path


def render_manifold(
    manifold_csv_or_array, path: str, grid: int = 10, side: int = 28, channels: int = 1
) -> str:
    """Cell 6's ``DCGAN_Generated_Images.png`` flow: read the exported
    ``*_out_N.csv`` (grid² rows × side²·C features) or take the array
    directly, tile, write PNG."""
    if isinstance(manifold_csv_or_array, str):
        flat = np.loadtxt(manifold_csv_or_array, delimiter=",", ndmin=2)
    else:
        flat = np.asarray(manifold_csv_or_array)
    shape = (grid * grid, side, side) if channels == 1 else (grid * grid, side, side, channels)
    return write_png(path, tile_images(flat.reshape(shape), grid))
