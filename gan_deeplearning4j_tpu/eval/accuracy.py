"""Classifier accuracy — the gan.ipynb cell-6 analog.

The reference's acceptance test is offline: pandas reads
``mnist_test_predictions_1.csv``, takes argmax per row, and compares against
the test labels (``idxmax(axis=1) == y_test``). Same contract here, plus an
in-process path that runs the classifier directly."""

from __future__ import annotations

from typing import Optional

import numpy as np


def accuracy_score(pred_probs: np.ndarray, labels: np.ndarray) -> float:
    """mean(argmax(probs) == y) — cell 6's accuracy line. ``labels`` may be
    integer class ids or one-hot rows."""
    pred_probs = np.asarray(pred_probs)
    labels = np.asarray(labels)
    if labels.ndim > 1:
        labels = labels.argmax(axis=1)
    if pred_probs.shape[0] != labels.shape[0]:
        raise ValueError(
            f"{pred_probs.shape[0]} predictions vs {labels.shape[0]} labels"
        )
    return float(np.mean(pred_probs.argmax(axis=1) == labels))


def accuracy_from_csvs(predictions_csv: str, test_csv: str, num_features: int = 784) -> float:
    """The exact offline flow: predictions CSV (N×classes probabilities, as
    written by GanExperiment.export_predictions) against the reference-format
    test CSV whose last column is the integer label."""
    preds = np.loadtxt(predictions_csv, delimiter=",", ndmin=2)
    test = np.loadtxt(test_csv, delimiter=",", ndmin=2)
    labels = test[:, num_features].astype(np.int64)
    return accuracy_score(preds, labels)


def evaluate_classifier(
    graph, params, features: np.ndarray, labels: np.ndarray, batch_size: int = 500
) -> float:
    """In-process accuracy: batched inference (the reference's 500-row
    prediction batches, dl4jGANComputerVision.java:67,576-598) → argmax."""
    import jax
    import jax.numpy as jnp

    fwd = jax.jit(lambda p, x: graph.output(p, x, train=False))
    chunks = []
    for i in range(0, len(features), batch_size):
        chunks.append(np.asarray(fwd(params, jnp.asarray(features[i : i + batch_size]))))
    preds: Optional[np.ndarray] = np.vstack(chunks) if chunks else None
    if preds is None:
        raise ValueError("no features to evaluate")
    return accuracy_score(preds, labels)
