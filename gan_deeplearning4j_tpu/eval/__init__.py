"""Evaluation layer (SURVEY §7.8): the gan.ipynb cell-6 analogs — accuracy on
exported predictions, latent-manifold image rendering — plus the FID harness
BASELINE.md requires (the reference never records a quantitative metric)."""

from gan_deeplearning4j_tpu.eval.accuracy import (
    accuracy_from_csvs,
    accuracy_score,
    evaluate_classifier,
)
from gan_deeplearning4j_tpu.eval.fid import (
    FeatureStats,
    fid_from_stats,
    fid_score,
    frozen_feature_fn,
    graph_feature_fn,
    inception_feature_fn,
)
from gan_deeplearning4j_tpu.eval.images import render_manifold, tile_images, write_png

__all__ = [
    "accuracy_from_csvs",
    "accuracy_score",
    "evaluate_classifier",
    "FeatureStats",
    "fid_from_stats",
    "fid_score",
    "frozen_feature_fn",
    "inception_feature_fn",
    "graph_feature_fn",
    "render_manifold",
    "tile_images",
    "write_png",
]
