"""Dense (fully-connected) op.

The reference's DenseLayer GEMMs run on cuBLAS (SURVEY §3.3 hot loop); here a
single ``dot_general`` that XLA tiles onto the MXU. Inputs/outputs stay in the
storage dtype; the contraction runs in the compute dtype (bfloat16 when mixed
precision is enabled) with float32 accumulation — the TPU-native fast path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gan_deeplearning4j_tpu.runtime.dtype import get_compute_dtype


def dense(x, w, b=None):
    """y = x @ w + b with MXU-friendly dtypes.

    Args:
      x: (batch, in) activations.
      w: (in, out) kernel.
      b: optional (out,) bias.
    """
    out_dtype = x.dtype
    cdt = get_compute_dtype()
    y = jnp.matmul(x.astype(cdt), w.astype(cdt), preferred_element_type=jnp.float32)
    y = y.astype(out_dtype)
    if b is not None:
        y = y + b
    return y


def quant_dense(x, w_q, w_scale, b, act_scale):
    """Dequant-at-matmul int8 dense: float activations in, float out.

    The post-training-quantized serving path (docs/QUANT.md): weights are
    stored int8 with a per-output-channel symmetric scale, activations are
    quantized on entry against a calibrated per-layer scale, the
    contraction runs int8×int8 with an int32 accumulator
    (``preferred_element_type`` — the hardware's integer-MAC path), and
    the single dequant multiply happens once on the accumulator. The wire
    dtype never changes: callers see float rows exactly as with
    :func:`dense`.

    Args:
      x: (batch, in) float activations.
      w_q: (in, out) int8 kernel.
      w_scale: (out,) float per-channel weight scales (w ≈ w_q * w_scale).
      b: optional (out,) float bias (applied after dequant).
      act_scale: python float activation scale (x ≈ x_q * act_scale) —
        static, baked into the compiled executable.
    """
    out_dtype = x.dtype
    x_q = jnp.clip(jnp.round(x * (1.0 / act_scale)), -127.0, 127.0)
    x_q = x_q.astype(jnp.int8)
    acc = jax.lax.dot_general(
        x_q, w_q,
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    y = acc.astype(jnp.float32) * (w_scale.astype(jnp.float32) * act_scale)
    y = y.astype(out_dtype)
    if b is not None:
        y = y + b
    return y
