"""Dense (fully-connected) op.

The reference's DenseLayer GEMMs run on cuBLAS (SURVEY §3.3 hot loop); here a
single ``dot_general`` that XLA tiles onto the MXU. Inputs/outputs stay in the
storage dtype; the contraction runs in the compute dtype (bfloat16 when mixed
precision is enabled) with float32 accumulation — the TPU-native fast path.
"""

from __future__ import annotations

import jax.numpy as jnp

from gan_deeplearning4j_tpu.runtime.dtype import get_compute_dtype


def dense(x, w, b=None):
    """y = x @ w + b with MXU-friendly dtypes.

    Args:
      x: (batch, in) activations.
      w: (in, out) kernel.
      b: optional (out,) bias.
    """
    out_dtype = x.dtype
    cdt = get_compute_dtype()
    y = jnp.matmul(x.astype(cdt), w.astype(cdt), preferred_element_type=jnp.float32)
    y = y.astype(out_dtype)
    if b is not None:
        y = y + b
    return y
