"""Functional op layer — the TPU-native replacement for libnd4j/cuDNN kernels.

Where the reference dispatches Conv2D/BatchNorm/Subsampling to cuDNN and dense
GEMMs to cuBLAS (Java/pom.xml:119-128; SURVEY §2.2 D2-D4), every op here is a
pure function lowered by XLA onto the TPU MXU/VPU. Layout is NHWC (TPU's
preferred conv layout) rather than ND4J's NCHW; the nn layer handles the
boundary reshapes.
"""

from gan_deeplearning4j_tpu.ops import activations, conv, linear, losses, norm, initializers, clipping

__all__ = ["activations", "conv", "linear", "losses", "norm", "initializers", "clipping"]
