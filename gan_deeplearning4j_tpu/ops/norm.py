"""Batch normalization (functional).

Replaces the cuDNN BatchNorm kernel the reference binds
(Java/pom.xml:124-128; layers at dl4jGANComputerVision.java:132-135,186-199).
DL4J semantics reproduced:

- normalizes over all axes except the channel/feature axis (last axis here:
  features for 2-D inputs, channels for NHWC 4-D inputs);
- running mean/var are *named parameters* (``mean``/``var``) updated during the
  training forward pass with DL4J's default decay 0.9
  (running = decay * running + (1-decay) * batch_stat) — the reference copies
  them between graphs by name every iteration
  (dl4jGANComputerVision.java:437-440,498-500,523-527), so they must live in
  the param tree, not hidden module state;
- inference uses the running statistics.

DL4J default eps = 1e-5.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

DEFAULT_EPS = 1e-5
DEFAULT_DECAY = 0.9


def batch_norm_train(
    x, gamma, beta, running_mean, running_var, *, eps: float = DEFAULT_EPS, decay: float = DEFAULT_DECAY
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Training-mode BN: normalize by batch statistics, return updated running
    stats. Reduction axes = all but the last (feature/channel) axis."""
    axes = tuple(range(x.ndim - 1))
    mean = jnp.mean(x, axis=axes)
    # population variance (ddof=0), matching cuDNN/DL4J forward
    var = jnp.var(x, axis=axes)
    inv = jnp.reciprocal(jnp.sqrt(var + eps))
    y = (x - mean) * inv * gamma + beta
    # accumulate in the promoted dtype, store back in the stats' own dtype:
    # params must be dtype-stable through a train step or bf16 param storage
    # would silently upcast the state tree after one iteration
    new_mean = (decay * running_mean + (1.0 - decay) * mean).astype(running_mean.dtype)
    new_var = (decay * running_var + (1.0 - decay) * var).astype(running_var.dtype)
    return y, new_mean, new_var


def batch_norm_inference(x, gamma, beta, running_mean, running_var, *, eps: float = DEFAULT_EPS):
    inv = jnp.reciprocal(jnp.sqrt(running_var + eps))
    return (x - running_mean) * inv * gamma + beta
