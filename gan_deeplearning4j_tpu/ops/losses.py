"""Loss functions.

The reference uses ``LossFunction.XENT`` with sigmoid (discriminator/GAN
output, dl4jGANComputerVision.java:159-162,303-307) and ``MCXENT`` with
softmax (classifier head, :358-362). DL4J's XENT clamps probabilities to
[eps, 1-eps] with eps=1e-5 before the log — reproduced here for parity.
Wasserstein + gradient-penalty losses cover the WGAN-GP config in BASELINE.md
(grad-of-grad flows through XLA natively).

Score convention: mean over batch of the summed per-example loss — DL4J's
``score()`` — so gradients match the reference's scale.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

XENT_CLIP_EPS = 1e-5


def binary_xent(probs, labels, *, eps: float = XENT_CLIP_EPS):
    """XENT on sigmoid outputs (probabilities), DL4J LossBinaryXENT."""
    p = jnp.clip(probs, eps, 1.0 - eps)
    per_example = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p))
    return jnp.mean(jnp.sum(per_example, axis=tuple(range(1, per_example.ndim))))


def categorical_xent(probs, labels, *, eps: float = XENT_CLIP_EPS):
    """MCXENT on softmax outputs (probabilities), DL4J LossMCXENT."""
    p = jnp.clip(probs, eps, 1.0 - eps)
    per_example = -jnp.sum(labels * jnp.log(p), axis=-1)
    return jnp.mean(per_example)


def mse(preds, labels):
    per_example = jnp.sum((preds - labels) ** 2, axis=tuple(range(1, preds.ndim)))
    return jnp.mean(per_example)


def wasserstein(critic_scores, labels):
    """Wasserstein critic loss: labels ∈ {+1 (real), -1 (fake)} —
    minimizes -E[D(real)] + E[D(fake)]."""
    return -jnp.mean(critic_scores * labels)


def gradient_penalty(critic_fn, real, fake, rng, *, target: float = 1.0):
    """WGAN-GP penalty E[(||∇_x D(x̂)||₂ − 1)²] at x̂ = εx + (1−ε)x̃.

    ``critic_fn`` maps a batch to per-example scores. The grad-of-grad this
    needs is plain ``jax.grad`` composition — XLA lowers it natively (the
    BASELINE.md WGAN-GP config's whole point)."""
    eps_shape = (real.shape[0],) + (1,) * (real.ndim - 1)
    epsilon = jax.random.uniform(rng, eps_shape, dtype=real.dtype)
    x_hat = epsilon * real + (1.0 - epsilon) * fake

    def scalar_critic(x):
        return jnp.sum(critic_fn(x))

    grads = jax.grad(scalar_critic)(x_hat)
    norms = jnp.sqrt(jnp.sum(grads**2, axis=tuple(range(1, grads.ndim))) + 1e-12)
    return jnp.mean((norms - target) ** 2)


_REGISTRY = {
    "xent": binary_xent,
    "binary_xent": binary_xent,
    "mcxent": categorical_xent,
    "categorical_xent": categorical_xent,
    "mse": mse,
    "wasserstein": wasserstein,
}


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise KeyError(f"unknown loss {name_or_fn!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]
