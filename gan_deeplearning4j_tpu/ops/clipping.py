"""Gradient normalization.

The reference configures ``GradientNormalization.ClipElementWiseAbsoluteValue``
with threshold 1.0 on every graph (dl4jGANComputerVision.java:124-125 et al.):
each gradient element is clamped to [-t, t] before the updater runs.
Clip-by-global-norm is provided for the wider model families.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def clip_elementwise(grads, threshold: float):
    """Clamp every gradient element to [-threshold, threshold] (DL4J
    ClipElementWiseAbsoluteValue)."""
    t = jnp.asarray(threshold)
    return jax.tree_util.tree_map(lambda g: jnp.clip(g, -t, t), grads)


def clip_by_global_norm(grads, max_norm: float):
    leaves = jax.tree_util.tree_leaves(grads)
    global_norm = jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in leaves))
    scale = jnp.minimum(1.0, max_norm / (global_norm + 1e-12))
    return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype), grads)
