"""Convolution / pooling / upsampling ops (NHWC, TPU layout).

TPU-native replacement for the cuDNN kernels the reference binds
(deeplearning4j-cuda Conv2D/Subsampling, Java/pom.xml:124-128) and the
Upsampling2D layer (dl4jGANComputerVision.java:201-219). XLA's TPU conv
emitter plays cuDNN's role: ``lax.conv_general_dilated`` in NHWC/HWIO maps
straight onto the MXU; pooling is a ``reduce_window``; nearest-neighbor
upsampling is a broadcast-reshape that XLA fuses into the following conv's
input.

Shape semantics match DL4J's ``ConvolutionMode.Truncate`` (the reference's
default): out = floor((in + 2p - k) / s) + 1, which is exactly XLA's explicit
padding + VALID windowing.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from gan_deeplearning4j_tpu.runtime.dtype import get_compute_dtype

IntPair = Union[int, Tuple[int, int], Sequence[int]]


def _pair(v: IntPair) -> Tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    a, b = v
    return (int(a), int(b))


def conv_out_size(in_size: int, kernel: int, stride: int, padding: int) -> int:
    """DL4J Truncate-mode output size: floor((in + 2p - k)/s) + 1."""
    return (in_size + 2 * padding - kernel) // stride + 1


def conv2d(x, w, b=None, *, stride: IntPair = 1, padding: IntPair = 0):
    """2-D convolution, NHWC input, HWIO kernel, explicit symmetric padding.

    Runs the contraction in the compute dtype (bf16 under mixed precision)
    with float32 accumulation via ``preferred_element_type``.
    """
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    out_dtype = x.dtype
    cdt = get_compute_dtype()
    y = lax.conv_general_dilated(
        x.astype(cdt),
        w.astype(cdt),
        window_strides=(sh, sw),
        padding=((ph, ph), (pw, pw)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        # No preferred_element_type under mixed precision: jax 0.9's conv
        # transpose rule rejects bf16 inputs with an f32 preference (the
        # cotangent arrives f32 against a bf16 operand). The MXU still
        # accumulates bf16 convs in f32 internally; only the stored output
        # is bf16, upcast on the next line.
        **({} if cdt != x.dtype else {"preferred_element_type": jnp.float32}),
    )
    y = y.astype(out_dtype)
    if b is not None:
        y = y + b  # (out,) broadcasts over NHW
    return y


def conv2d_transpose(x, w, b=None, *, stride: IntPair = 1, padding: IntPair = 0):
    """Transposed convolution (Deconvolution2D analog for the wider DCGAN
    family; the reference's generator uses upsample+conv instead,
    dl4jGANComputerVision.java:201-219, but DL4J ships Deconvolution2D and the
    CIFAR/CelebA configs in BASELINE.md exercise it).

    Shape: out = (in - 1) * s - 2p + k, the inverse of :func:`conv_out_size`.
    """
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    out_dtype = x.dtype
    cdt = get_compute_dtype()
    kh, kw = w.shape[0], w.shape[1]
    y = lax.conv_transpose(
        x.astype(cdt),
        w.astype(cdt),
        strides=(sh, sw),
        padding=((kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        # see conv2d: omit the f32 preference under mixed precision
        **({} if cdt != x.dtype else {"preferred_element_type": jnp.float32}),
    )
    y = y.astype(out_dtype)
    if b is not None:
        y = y + b
    return y


def max_pool2d(x, *, kernel: IntPair, stride: IntPair, padding: IntPair = 0):
    """Max pooling over NHWC (SubsamplingLayer MAX analog,
    dl4jGANComputerVision.java:139-143,150-154 — kernel 2x2 stride 1)."""
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    neg_inf = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return lax.reduce_window(
        x,
        neg_inf,
        lax.max,
        window_dimensions=(1, kh, kw, 1),
        window_strides=(1, sh, sw, 1),
        padding=((0, 0), (ph, ph), (pw, pw), (0, 0)),
    )


def avg_pool2d(x, *, kernel: IntPair, stride: IntPair, padding: IntPair = 0):
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    summed = lax.reduce_window(
        x,
        jnp.zeros((), x.dtype),
        lax.add,
        window_dimensions=(1, kh, kw, 1),
        window_strides=(1, sh, sw, 1),
        padding=((0, 0), (ph, ph), (pw, pw), (0, 0)),
    )
    if ph == 0 and pw == 0:
        return summed / (kh * kw)
    ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
    counts = lax.reduce_window(
        ones,
        jnp.zeros((), x.dtype),
        lax.add,
        window_dimensions=(1, kh, kw, 1),
        window_strides=(1, sh, sw, 1),
        padding=((0, 0), (ph, ph), (pw, pw), (0, 0)),
    )
    return summed / counts


def upsample2d(x, *, scale: IntPair = 2):
    """Nearest-neighbor upsampling (Upsampling2D analog). Implemented as a
    broadcast+reshape — zero FLOPs; XLA fuses it into the consumer conv."""
    sh, sw = _pair(scale)
    n, h, w, c = x.shape
    y = x[:, :, None, :, None, :]
    y = jnp.broadcast_to(y, (n, h, sh, w, sw, c))
    return y.reshape(n, h * sh, w * sw, c)
