"""Weight initializers.

The reference uses ``WeightInit.XAVIER`` everywhere
(dl4jGANComputerVision.java:127 et al.). DL4J's XAVIER is a *Gaussian*
N(0, 2/(fan_in+fan_out)); we reproduce that as the default and provide the
uniform variant plus He/normal/zeros for the wider layer zoo.

Fan-in/fan-out convention: dense kernels are (in, out); conv kernels are HWIO
(kh, kw, in, out) with receptive-field scaling, matching XLA's native layout.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv HWIO: receptive field * channels
    receptive = math.prod(shape[:-2])
    return shape[-2] * receptive, shape[-1] * receptive


def xavier(key, shape, dtype=jnp.float32):
    """DL4J WeightInit.XAVIER: gaussian with var = 2/(fan_in+fan_out)."""
    fan_in, fan_out = _fans(shape)
    std = math.sqrt(2.0 / (fan_in + fan_out))
    return std * jax.random.normal(key, shape, dtype=dtype)


def xavier_uniform(key, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    limit = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype=dtype, minval=-limit, maxval=limit)


def he_normal(key, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    std = math.sqrt(2.0 / fan_in)
    return std * jax.random.normal(key, shape, dtype=dtype)


def normal(key, shape, dtype=jnp.float32, stddev=0.01):
    return stddev * jax.random.normal(key, shape, dtype=dtype)


def zeros(key, shape, dtype=jnp.float32):
    del key
    return jnp.zeros(shape, dtype=dtype)


def ones(key, shape, dtype=jnp.float32):
    del key
    return jnp.ones(shape, dtype=dtype)


_REGISTRY = {
    "xavier": xavier,
    "xavier_uniform": xavier_uniform,
    "he": he_normal,
    "he_normal": he_normal,
    "normal": normal,
    "zeros": zeros,
    "ones": ones,
}


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise KeyError(f"unknown initializer {name_or_fn!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]
