"""Activations used by the reference: tanh (hidden default), sigmoid (dis/gen
outputs), softmax (classifier), identity (reference:
dl4jGANComputerVision.java:126,159-162,215,303-307,358-362). A few extras
(relu/leaky_relu/elu) round out the zoo for the non-MNIST model families.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def identity(x):
    return x


def tanh(x):
    return jnp.tanh(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def softmax(x):
    return jax.nn.softmax(x, axis=-1)


def relu(x):
    return jax.nn.relu(x)


def leaky_relu(x, negative_slope: float = 0.2):
    return jax.nn.leaky_relu(x, negative_slope)


def elu(x):
    return jax.nn.elu(x)


_REGISTRY = {
    "identity": identity,
    "linear": identity,
    "tanh": tanh,
    "sigmoid": sigmoid,
    "softmax": softmax,
    "relu": relu,
    "leakyrelu": leaky_relu,
    "leaky_relu": leaky_relu,
    "elu": elu,
}


def get(name_or_fn):
    """Resolve an activation by name (case-insensitive) or pass through a callable."""
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _REGISTRY:
        raise KeyError(f"unknown activation {name_or_fn!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]
