"""telemetry/ — the unified observability plane (docs/OBSERVABILITY.md).

One substrate, three parts, consumed by every other plane (training
harness, serving, resilience, the bench scripts):

- :mod:`.trace` — structured spans in a bounded ring buffer with Chrome
  trace-event export (Perfetto-loadable) and correlation ids that survive
  the batcher's cross-thread pipeline and supervisor attempts. Disabled
  by default at zero hot-path cost.
- :mod:`.registry` — process-wide named counters/gauges/histograms with
  labeled series; exported as the JSON ``/metrics`` payload, Prometheus
  text exposition, and BENCH artifact snapshots from the same storage.
- :mod:`.device` — on-demand ``jax.profiler`` captures behind the serving
  API's ``POST /debug/trace`` and the supervisor CLI's SIGUSR2.

Two fleet-scale layers sit on that substrate (docs/OBSERVABILITY.md
"Fleet observability"):

- :mod:`.aggregate` — merge per-process registry snapshots into ONE
  fleet snapshot (counters summed, gauges worker-labeled, histogram
  percentiles recomputed over pooled samples) and per-process span
  traces into ONE Chrome trace; the router's ``GET /metrics?scope=fleet``
  and ``GET /debug/trace``.
- :mod:`.slo` — multi-window availability/latency burn rates over
  router-observed outcomes; empty windows fail closed.
- :mod:`.alerts` — the declarative alerting & anomaly-detection plane
  over those snapshots: rule kinds threshold/absence/burn/anomaly, a
  pending/firing/resolved lifecycle with per-direction hysteresis,
  trace exemplars, and pluggable sinks (docs/OBSERVABILITY.md
  "Alerting").

The package namespace is LAZY (PEP 562) like the project root: importing
it must not import jax — ``registry``/``trace`` are stdlib-only and the
analyzer and bench parent depend on that; only :mod:`.device` touches jax,
and only inside a capture.
"""

# name -> (module to import, attribute to take from it; None = the module)
_LAZY_EXPORTS = {
    "MetricsRegistry": ("gan_deeplearning4j_tpu.telemetry.registry",
                        "MetricsRegistry"),
    "get_registry": ("gan_deeplearning4j_tpu.telemetry.registry",
                     "get_registry"),
    "set_registry": ("gan_deeplearning4j_tpu.telemetry.registry",
                     "set_registry"),
    "percentiles": ("gan_deeplearning4j_tpu.telemetry.registry",
                    "percentiles"),
    "Tracer": ("gan_deeplearning4j_tpu.telemetry.trace", "Tracer"),
    "TRACER": ("gan_deeplearning4j_tpu.telemetry.trace", "TRACER"),
    "get_tracer": ("gan_deeplearning4j_tpu.telemetry.trace", "get_tracer"),
    "new_trace_id": ("gan_deeplearning4j_tpu.telemetry.trace",
                     "new_trace_id"),
    "current_trace_id": ("gan_deeplearning4j_tpu.telemetry.trace",
                         "current_trace_id"),
    "bind_trace_id": ("gan_deeplearning4j_tpu.telemetry.trace",
                      "bind_trace_id"),
    "unbind_trace_id": ("gan_deeplearning4j_tpu.telemetry.trace",
                        "unbind_trace_id"),
    "configure_from_env": ("gan_deeplearning4j_tpu.telemetry.trace",
                           "configure_from_env"),
    "sanitize_trace_id": ("gan_deeplearning4j_tpu.telemetry.trace",
                          "sanitize_trace_id"),
    "merge_snapshots": ("gan_deeplearning4j_tpu.telemetry.aggregate",
                        "merge_snapshots"),
    "snapshot_to_prometheus": ("gan_deeplearning4j_tpu.telemetry.aggregate",
                               "snapshot_to_prometheus"),
    "merge_traces": ("gan_deeplearning4j_tpu.telemetry.aggregate",
                     "merge_traces"),
    "SLOConfig": ("gan_deeplearning4j_tpu.telemetry.slo", "SLOConfig"),
    "SLOTracker": ("gan_deeplearning4j_tpu.telemetry.slo", "SLOTracker"),
    "AlertRule": ("gan_deeplearning4j_tpu.telemetry.alerts", "AlertRule"),
    "AlertManager": ("gan_deeplearning4j_tpu.telemetry.alerts",
                     "AlertManager"),
    "ExemplarStore": ("gan_deeplearning4j_tpu.telemetry.alerts",
                      "ExemplarStore"),
    "WebhookSink": ("gan_deeplearning4j_tpu.telemetry.alerts",
                    "WebhookSink"),
    "log_sink": ("gan_deeplearning4j_tpu.telemetry.alerts", "log_sink"),
    "default_fleet_rules": ("gan_deeplearning4j_tpu.telemetry.alerts",
                            "default_fleet_rules"),
    "default_mux_rules": ("gan_deeplearning4j_tpu.telemetry.alerts",
                          "default_mux_rules"),
    "capture_device_trace": ("gan_deeplearning4j_tpu.telemetry.device",
                             "capture_device_trace"),
    "capture_async": ("gan_deeplearning4j_tpu.telemetry.device",
                      "capture_async"),
    "install_signal_capture": ("gan_deeplearning4j_tpu.telemetry.device",
                               "install_signal_capture"),
    "CaptureBusy": ("gan_deeplearning4j_tpu.telemetry.device",
                    "CaptureBusy"),
}

__all__ = list(_LAZY_EXPORTS)


def __getattr__(name):
    try:
        module_name, attr = _LAZY_EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    module = importlib.import_module(module_name)
    value = module if attr is None else getattr(module, attr)
    globals()[name] = value  # cache: __getattr__ runs once per name
    return value


def __dir__():
    return sorted({*globals(), *_LAZY_EXPORTS})
