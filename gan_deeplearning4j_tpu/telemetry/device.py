"""On-demand device profiling — ``jax.profiler`` captures behind a hook.

The span tracer (:mod:`.trace`) answers *which stage* took the time; the
device profiler answers *what the XLA timeline did inside it* — per-op
device occupancy, HBM traffic, compile stalls. A capture is expensive
(tens of MB, device interference), so it is never ambient: a live process
exposes it as a momentary hook —

- the serving API's ``POST /debug/trace?ms=N`` (service.py), and
- ``SIGUSR2`` on the supervisor worker CLI (resilience/__main__.py) —

each dumping one bounded capture into an artifacts directory and
returning to normal operation. Dumps are TensorBoard-profile format
(``.xplane.pb`` under ``plugins/profile/``; newer jax wheels also emit a
Perfetto trace when asked). ``jax`` is imported lazily so this module —
and the telemetry package with it — stays importable in jax-free
containers.

One capture at a time per process: ``jax.profiler`` rejects nested
captures, so the hook refuses (``CaptureBusy``) instead of crashing the
serving thread that raced a second request in.
"""

from __future__ import annotations

import itertools
import logging
import os
import signal
import threading
import time
from typing import Optional, Tuple

logger = logging.getLogger(__name__)

_capture_lock = threading.Lock()
_capture_ids = itertools.count(1)


class CaptureBusy(RuntimeError):
    """A device capture is already running in this process."""


def _capture_dir(artifacts_dir: str) -> str:
    # the counter keeps two captures started within the same wall-clock
    # second from landing (and overwriting) in one directory
    stamp = time.strftime("%Y%m%d-%H%M%S")
    return os.path.join(
        artifacts_dir,
        f"device-{stamp}-pid{os.getpid()}-{next(_capture_ids)}",
    )


def _capture_locked(out: str, duration_ms: int) -> str:
    """The capture itself. The CALLER holds ``_capture_lock``."""
    import jax

    os.makedirs(out, exist_ok=True)
    try:
        # newer wheels can emit a Perfetto trace next to the xplane dump
        jax.profiler.start_trace(out, create_perfetto_trace=True)
    except TypeError:  # older start_trace signature
        jax.profiler.start_trace(out)
    try:
        time.sleep(duration_ms / 1000.0)
    finally:
        jax.profiler.stop_trace()
    logger.info("device trace captured to %s (%d ms)", out, duration_ms)
    return out


def capture_device_trace(artifacts_dir: str, duration_ms: int = 1000,
                         out: Optional[str] = None) -> str:
    """Capture ``duration_ms`` of device activity into ``out`` (default: a
    fresh stamped directory under ``artifacts_dir``); returns that
    directory. Blocks the calling thread for the capture window PLUS
    profiler start/stop cost — tens of seconds on a cold profiler under a
    sandboxed kernel — so interactive callers use :func:`capture_async`
    (the serving hook answers 202 with the artifact path immediately)."""
    if duration_ms < 1:
        raise ValueError("duration_ms must be >= 1")
    out = out or _capture_dir(artifacts_dir)
    if not _capture_lock.acquire(blocking=False):
        raise CaptureBusy("a device capture is already in progress")
    try:
        return _capture_locked(out, duration_ms)
    finally:
        _capture_lock.release()


def install_signal_capture(artifacts_dir: str,
                           duration_ms: int = 1000,
                           signum: int = signal.SIGUSR2) -> None:
    """SIGUSR2 → one background device capture. The handler only spawns a
    daemon thread (signal handlers must not block for the capture window);
    a signal landing mid-capture is logged and dropped — a stuck operator
    mashing SIGUSR2 must not stack captures."""

    def _worker() -> None:
        try:
            capture_device_trace(artifacts_dir, duration_ms)
        except CaptureBusy:
            logger.warning("SIGUSR2 ignored: a device capture is running")
        except Exception:
            logger.exception("SIGUSR2 device capture failed")

    def _handler(signo, frame) -> None:
        threading.Thread(
            target=_worker, name="device-trace-capture", daemon=True
        ).start()

    signal.signal(signum, _handler)
    logger.info("signal %d captures %d ms device traces into %s",
                signum, duration_ms, artifacts_dir)


def capture_async(artifacts_dir: str, duration_ms: int = 1000
                  ) -> Tuple["threading.Thread", str]:
    """Start a capture on a daemon thread; returns ``(thread, out_dir)``
    so the caller can answer immediately with the path the artifact WILL
    land at (the serving hook's 202 contract). The lock is ACQUIRED here,
    before returning — two racing callers cannot both get a 202 whose
    artifact then silently never lands; the loser gets
    :class:`CaptureBusy` synchronously and the caller can 409. The spawned
    thread inherits lock ownership and releases it when the capture (or
    its failure) finishes."""
    if duration_ms < 1:
        raise ValueError("duration_ms must be >= 1")
    # the output path is composed BEFORE taking the capture lock: a
    # failure here must not strand the lock held with no thread to
    # release it (every later capture would 409 forever)
    out = _capture_dir(artifacts_dir)
    if not _capture_lock.acquire(blocking=False):
        raise CaptureBusy("a device capture is already in progress")
    t = threading.Thread(
        target=_swallow_owned, args=(out, duration_ms),
        name="device-trace-capture", daemon=True,
    )
    t.start()
    return t, out


def _swallow_owned(out: str, duration_ms: int) -> None:
    """Async capture body: lock already held by capture_async."""
    try:
        _capture_locked(out, duration_ms)
    except Exception:
        logger.exception("device capture failed")
    finally:
        _capture_lock.release()


def default_artifacts_dir(base: Optional[str] = None) -> str:
    """Where hook-triggered captures land unless configured:
    ``$GDT_TRACE_DIR``, else ``<base or cwd>/artifacts/device_traces``."""
    env = os.environ.get("GDT_TRACE_DIR")
    if env:
        return env
    return os.path.join(base or os.getcwd(), "artifacts", "device_traces")
