"""SLO burn-rate tracking — the fleet's admission signal.

The router observes every request outcome the fleet produces; this module
turns that stream into the two numbers an operator (or the ROADMAP's
future autoscaler and the rolling-upgrade gate) can actually act on:

- **availability burn rate** — of the requests in a window, the fraction
  that failed (an answered 5xx, which includes the router's honest 503s),
  divided by the error budget ``1 - availability_target``. Burn rate 1.0
  means the fleet is spending budget exactly as fast as the SLO allows;
  14.4 means a 30-day budget dies in ~2 days (the classic page-now
  threshold, scaled to whatever windows are configured here).
- **latency burn rate** — same arithmetic over the latency objective:
  the fraction of answered (non-5xx) requests slower than
  ``latency_threshold_s``, against the budget ``1 - latency_target``.
  5xx answers are excluded so a fast failure cannot flatter the latency
  SLI while the availability one burns.

**Multi-window**: each objective is evaluated over a *fast* window (is it
burning NOW — reacts in seconds, noisy) and a *slow* window (has it been
burning — stable, slow to clear). The standard alerting/admission rule —
act only when BOTH exceed the threshold — is what :meth:`SLOTracker.ok`
implements: the fast window arms quickly, the slow window keeps one
transient blip from flapping the signal.

**Empty windows fail closed**: a window with zero observations has an
*undefined* burn rate, exported as ``NaN`` — and :meth:`SLOTracker.ok`
treats NaN as NOT-ok. An admission gate that cannot see traffic must not
conclude the fleet is healthy; "no data" and "healthy" are different
claims (the drill and the autoscaler both key on this).

Stdlib-only; events live in one bounded deque (drop-oldest beyond
``max_events``, prune-older-than-slow-window on every record).
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from typing import Callable, Optional

from gan_deeplearning4j_tpu.telemetry.registry import get_registry


@dataclasses.dataclass
class SLOConfig:
    """Objectives and windows. Defaults suit a drill-scale fleet; a real
    deployment widens the windows (e.g. 300s/3600s) without touching the
    math."""

    availability_target: float = 0.999   # fraction of requests answered ok
    latency_threshold_s: float = 0.5     # "fast enough" boundary
    latency_target: float = 0.99         # fraction of answers under it
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    max_events: int = 65536

    def validate(self) -> "SLOConfig":
        for name in ("availability_target", "latency_target"):
            v = getattr(self, name)
            if not 0.0 < v < 1.0:
                raise ValueError(f"{name} must be in (0, 1), got {v}")
        if self.latency_threshold_s <= 0:
            raise ValueError("latency_threshold_s must be > 0")
        if not 0 < self.fast_window_s <= self.slow_window_s:
            raise ValueError("need 0 < fast_window_s <= slow_window_s")
        if self.max_events < 1:
            raise ValueError("max_events must be >= 1")
        return self


class SLOTracker:
    """Sliding-window burn rates over a stream of request outcomes.

    ``record(ok, latency_s)`` is the hot path (router, once per routed
    request): one lock, one append. ``clock`` is injectable so the window
    math is testable without wall-clock sleeps."""

    OBJECTIVES = ("availability", "latency")
    WINDOWS = ("fast", "slow")

    def __init__(self, config: Optional[SLOConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 *, metric_prefix: str = "fleet",
                 labels: Optional[dict] = None):
        """``metric_prefix``/``labels`` scope the exported gauges: the
        router's fleet-wide tracker keeps the default
        ``fleet_slo_burn_rate{objective,window}``; the mux plane tracks
        one SLI stream PER VARIANT with ``metric_prefix="mux"`` and
        ``labels={"model": name}``, so every tracker's burn rates land
        as distinct labeled series of one ``mux_slo_*`` family instead
        of N trackers fighting over one unlabeled series
        (docs/MULTIPLEX.md)."""
        self.config = (config or SLOConfig()).validate()
        self._clock = clock
        self._lock = threading.Lock()
        # (t, ok, latency_ok) — latency_ok is None for failed requests
        # (excluded from the latency SLI, see module docstring)
        self._events: deque = deque(maxlen=self.config.max_events)
        self._total = {"requests": 0, "failed": 0, "slow": 0}
        # high-water mark of observed clock readings: event timestamps
        # are clamped monotonic against it (see _now_locked)
        self._clock_hwm: Optional[float] = None
        self._labels = {str(k): str(v)
                        for k, v in sorted((labels or {}).items())}
        extra = tuple(self._labels)
        registry = get_registry()
        burn_family = registry.gauge(
            f"{metric_prefix}_slo_burn_rate",
            "error-budget burn rate per objective and window "
            "(NaN = empty window, fails closed)",
            labelnames=extra + ("objective", "window"))
        self._g_burn = lambda objective, window: burn_family.labels(
            **self._labels, objective=objective, window=window)
        ok_family = registry.gauge(
            f"{metric_prefix}_slo_ok",
            "1 when every objective's fast AND slow burn rates are under "
            "1.0, 0 otherwise (NaN burn = 0 — no data fails closed)",
            labelnames=extra)
        self._g_ok = ok_family.labels(**self._labels) if extra else ok_family

    # -- recording -------------------------------------------------------
    def _now_locked(self) -> float:
        """The clock reading, clamped monotonic (caller holds the lock).
        The default clock is ``time.monotonic``, but the tracker is
        clock-injectable and deployments substitute wall clocks — which
        STEP: NTP slews, VM suspend/resume, leap smears. A backwards
        step would write an out-of-order timestamp into the event deque,
        silently skewing window membership (the prune loop stops at the
        first in-window event, so misordered old events survive behind
        it, and a window evaluated at the stepped-back "now" ages events
        it should still hold). Clamping to the high-water mark keeps the
        deque sorted and every window evaluation consistent; when the
        clock recovers past the mark, real time resumes."""
        now = self._clock()
        if self._clock_hwm is not None and now < self._clock_hwm:
            return self._clock_hwm
        self._clock_hwm = now
        return now

    def record(self, ok: bool, latency_s: Optional[float] = None) -> None:
        """One observed outcome. ``ok`` False = availability failure (an
        answered 5xx / honest 503); ``latency_s`` is the client-visible
        duration, measured only for answered (ok) requests."""
        latency_ok: Optional[bool] = None
        if ok and latency_s is not None:
            latency_ok = latency_s <= self.config.latency_threshold_s
        with self._lock:
            now = self._now_locked()
            self._events.append((now, bool(ok), latency_ok))
            self._total["requests"] += 1
            if not ok:
                self._total["failed"] += 1
            if latency_ok is False:
                self._total["slow"] += 1
            # prune past the slow window so the deque holds only what any
            # window can still read (maxlen already bounds pathology)
            horizon = now - self.config.slow_window_s
            while self._events and self._events[0][0] < horizon:
                self._events.popleft()

    # -- window math -----------------------------------------------------
    def _window_counts(self, window_s: float, now: float) -> dict:
        lo = now - window_s
        total = failed = answered = slow = 0
        for t, ok, latency_ok in self._events:
            if t < lo:
                continue
            total += 1
            if not ok:
                failed += 1
            else:
                answered += 1
                if latency_ok is False:
                    slow += 1
        return {"total": total, "failed": failed,
                "answered": answered, "slow": slow}

    @staticmethod
    def _burn(bad: int, n: int, target: float) -> float:
        if n == 0:
            return float("nan")  # undefined, and ok() fails closed on it
        return (bad / n) / (1.0 - target)

    def burn_rates(self) -> dict:
        """``{objective: {window: burn}}`` — NaN for empty windows."""
        cfg = self.config
        with self._lock:
            # same monotonic clamp as record(): a stepped-back clock must
            # not evaluate windows at a "now" older than recorded events
            now = self._now_locked()
            counts = {
                "fast": self._window_counts(cfg.fast_window_s, now),
                "slow": self._window_counts(cfg.slow_window_s, now),
            }
        out: dict = {"availability": {}, "latency": {}}
        for window, c in counts.items():
            out["availability"][window] = self._burn(
                c["failed"], c["total"], cfg.availability_target)
            out["latency"][window] = self._burn(
                c["slow"], c["answered"], cfg.latency_target)
        return out

    def ok(self, threshold: float = 1.0) -> bool:
        """The admission signal: True only when EVERY objective's fast AND
        slow burn rates are strictly under ``threshold``. NaN (empty
        window) is not under anything — no data fails closed."""
        for rates in self.burn_rates().values():
            for burn in rates.values():
                if math.isnan(burn) or burn >= threshold:
                    return False
        return True

    def snapshot(self) -> dict:
        """The ``/healthz`` block — objectives, burn rates, lifetime
        counts, and the boolean signal. Also refreshes the registry
        gauges so a Prometheus scrape racing a healthz read sees the same
        numbers."""
        rates = self.burn_rates()
        for objective, windows in rates.items():
            for window, burn in windows.items():
                self._g_burn(objective, window).set(burn)
        # recompute from the rates already in hand (ok() would re-read
        # the clock and could disagree with the exported rates)
        signal = all(
            not (math.isnan(b) or b >= 1.0)
            for windows in rates.values() for b in windows.values()
        )
        self._g_ok.set(1.0 if signal else 0.0)
        with self._lock:
            totals = dict(self._total)
        cfg = self.config
        return {
            "objectives": {
                "availability_target": cfg.availability_target,
                "latency_threshold_s": cfg.latency_threshold_s,
                "latency_target": cfg.latency_target,
            },
            "windows_s": {"fast": cfg.fast_window_s,
                          "slow": cfg.slow_window_s},
            # JSON has no NaN: an empty window exports as null here (the
            # gauges keep the NaN; both read as "undefined, not healthy")
            "burn_rates": {
                objective: {
                    window: (None if math.isnan(b) else b)
                    for window, b in windows.items()
                }
                for objective, windows in rates.items()
            },
            "totals": totals,
            "ok": signal,
        }
