"""Process-wide metrics registry — one definition for every number the
repo reports.

Before this module each plane observed itself differently: the batcher
kept bespoke counter dicts, ``StageStats``/``PhaseTimer`` kept their own
sample deques, the resilience drill re-derived publish costs from event
lists, and the three agreed only by convention. Here every counter, gauge,
and histogram is a named *family* in one registry; a family fans out into
labeled *series* (``family.labels(kind="sample")``) that the hot paths
resolve ONCE at construction and then update lock-cheap — no dict lookups,
no allocation per update. The registry exports two ways from the same
storage: :meth:`MetricsRegistry.snapshot` (the JSON ``/metrics`` payload
and BENCH artifacts) and :meth:`MetricsRegistry.to_prometheus` (text
exposition for scrapers), so a bench file and a live scrape can never
disagree about what a metric means (the TensorFlow-system paper's point:
shared instrumentation is what turns claims into measurements).

Stdlib-only on purpose: the registry must import (and serve) in the
analyzer's jax-free container and in bench.py's parent process.

Threading: every series update takes the series' own lock — counter
increments from the batcher's worker and completer threads must never
lose updates (``x += 1`` on a plain attribute is interleavable at the
bytecode level). Family/series *creation* takes the registry lock.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


def percentiles(values: Iterable[float], qs: Sequence[float] = (50, 95, 99)
                ) -> Dict[str, float]:
    """Nearest-rank percentiles of ``values`` as ``{"p50": ..., ...}``
    (empty dict for no samples). THE percentile definition — PhaseTimer,
    StageStats, the serving latency metrics, and serve_bench all route
    through this one function so BENCH artifacts and /metrics agree."""
    data = sorted(float(v) for v in values)
    if not data:
        return {}
    out = {}
    for q in qs:
        rank = max(1, min(len(data), math.ceil(q / 100.0 * len(data))))
        out[f"p{q:g}"] = data[rank - 1]
    return out


def _check_labels(labelnames: Sequence[str], kv: dict) -> Tuple:
    if set(kv) != set(labelnames):
        raise ValueError(
            f"expected labels {tuple(labelnames)}, got {tuple(sorted(kv))}"
        )
    return tuple(kv[name] for name in labelnames)


class Counter:
    """Monotonic counter series. ``inc`` only goes up — rates and totals."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time value series (queue depth, generation number)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Distribution series: count + sum + a bounded deque of recent raw
    samples. Keeping raw samples (not fixed buckets) preserves the repo's
    nearest-rank p50/p95/p99 contract exactly — the same numbers land in
    the JSON ``/metrics`` payload, the Prometheus summary exposition, and
    BENCH artifacts, because they come from this one deque."""

    __slots__ = ("_lock", "count", "total", "samples")

    def __init__(self, max_samples: int = 65536):
        self._lock = threading.Lock()
        self.count = 0
        self.total = 0.0
        self.samples: deque = deque(maxlen=max_samples)

    def observe(self, value: float) -> None:
        with self._lock:
            self.count += 1
            self.total += value
            self.samples.append(value)

    def percentiles(self, qs: Sequence[float] = (50, 95, 99)
                    ) -> Dict[str, float]:
        with self._lock:
            data = tuple(self.samples)
        return percentiles(data, qs)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One named metric: a set of series keyed by label values."""

    def __init__(self, name: str, kind: str, help: str,
                 labelnames: Sequence[str], **series_kw):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self._series_kw = series_kw
        self._lock = threading.Lock()
        self._series: Dict[Tuple, object] = {}

    def labels(self, **kv):
        """The series for one label combination — resolve once, keep the
        handle, update it directly on the hot path."""
        key = _check_labels(self.labelnames, kv)
        series = self._series.get(key)
        if series is None:
            with self._lock:
                series = self._series.get(key)
                if series is None:
                    series = _KINDS[self.kind](**self._series_kw)
                    self._series[key] = series
        return series

    # label-less families act as their own single series
    def _solo(self):
        return self.labels()

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    def set(self, value: float) -> None:
        self._solo().set(value)

    def observe(self, value: float) -> None:
        self._solo().observe(value)

    def remove(self, **kv) -> bool:
        """Drop one labeled series. Gauges describe facts about things
        that can stop existing (a retired fleet member): without removal
        the series would linger at its last value forever and read as a
        live fact to every scrape and alert rule."""
        key = _check_labels(self.labelnames, kv)
        with self._lock:
            return self._series.pop(key, None) is not None

    def series(self) -> List[Tuple[Dict[str, str], object]]:
        with self._lock:
            return [
                (dict(zip(self.labelnames, key)), s)
                for key, s in sorted(self._series.items())
            ]


def _prom_name(name: str) -> str:
    out = [c if (c.isalnum() or c in "_:") else "_" for c in name]
    if out and out[0].isdigit():
        out.insert(0, "_")
    return "".join(out)


def _prom_label_value(value) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _prom_labels(labels: Dict[str, str], extra: Optional[dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        f'{_prom_name(k)}="{_prom_label_value(v)}"'
        for k, v in merged.items()
    )
    return "{" + inner + "}"


def _fmt(v: float) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"  # Prometheus text form (an SLO burn rate with an
        # empty window exports as NaN, not as a crash in int())
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class MetricsRegistry:
    """Get-or-create families; one instance is the process-wide default
    (:func:`get_registry`). Re-requesting a family with the same name,
    kind, and labelnames returns the existing one — the serving engine,
    batcher, harness, and store can all declare their metrics idempotently
    — while a conflicting redeclaration raises instead of silently forking
    the definition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, name: str, kind: str, help: str,
                labelnames: Sequence[str], **series_kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if (fam.kind != kind or fam.labelnames != tuple(labelnames)
                        or fam._series_kw != series_kw):
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.kind} "
                        f"with labels {fam.labelnames} and "
                        f"{fam._series_kw or 'no series options'}, "
                        f"re-requested as {kind} with labels "
                        f"{tuple(labelnames)} and {series_kw or 'none'}"
                    )
                return fam
            fam = _Family(name, kind, help, labelnames, **series_kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  max_samples: int = 65536) -> _Family:
        return self._family(name, "histogram", help, labelnames,
                            max_samples=max_samples)

    # -- introspection / export -------------------------------------------
    def families(self) -> List[_Family]:
        with self._lock:
            return [f for _, f in sorted(self._families.items())]

    def series_count(self) -> int:
        """Total live series across families — the overhead smoke asserts
        this does not move while the telemetry-off serve path runs (no
        allocation on the hot path)."""
        return sum(len(f.series()) for f in self.families())

    def snapshot(self, include_samples: bool = False) -> dict:
        """JSON-ready view: the payload embedded in ``/metrics`` and in
        BENCH artifacts (``serve_bench --record`` / ``resilience_drill``).

        ``include_samples`` additionally exports each histogram's raw
        sample deque — the form the fleet aggregator needs so merged
        percentiles keep the nearest-rank contract (percentiles cannot be
        merged from quantiles; they CAN be recomputed from the union of
        samples — :mod:`.aggregate`)."""
        out: dict = {}
        for fam in self.families():
            series = []
            for labels, s in fam.series():
                if fam.kind == "histogram":
                    entry = {
                        "labels": labels, "count": s.count, "sum": s.total,
                        **s.percentiles(),
                    }
                    if include_samples:
                        with s._lock:
                            entry["samples"] = list(s.samples)
                    series.append(entry)
                else:
                    series.append({"labels": labels, "value": s.value})
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "series": series}
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4). Histograms export as
        summaries — quantile series straight off the same sample deque the
        JSON payload reads, plus ``_sum``/``_count``."""
        lines: List[str] = []
        for fam in self.families():
            name = _prom_name(fam.name)
            if fam.help:
                lines.append(f"# HELP {name} {fam.help}")
            kind = "summary" if fam.kind == "histogram" else fam.kind
            lines.append(f"# TYPE {name} {kind}")
            for labels, s in fam.series():
                if fam.kind == "histogram":
                    ps = s.percentiles((50, 95, 99))
                    for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                        if key in ps:
                            lines.append(
                                f"{name}"
                                f"{_prom_labels(labels, {'quantile': q})} "
                                f"{_fmt(ps[key])}"
                            )
                    lines.append(
                        f"{name}_sum{_prom_labels(labels)} {_fmt(s.total)}")
                    lines.append(
                        f"{name}_count{_prom_labels(labels)} {s.count}")
                else:
                    lines.append(
                        f"{name}{_prom_labels(labels)} {_fmt(s.value)}")
        return "\n".join(lines) + "\n"


_default = MetricsRegistry()
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry every subsystem registers into."""
    return _default


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-wide registry; returns the previous one. Test
    isolation hook (tests/conftest.py installs a fresh registry per test
    so per-instance assertions never see another test's series)."""
    global _default
    with _default_lock:
        previous = _default
        _default = registry
    return previous
