"""Structured tracing — spans in a bounded ring buffer, Chrome trace export.

Answers the question none of the per-plane stats can: *where did this
request (or training step) spend its time, across module boundaries*. A
span is one named interval; spans carry small ``args`` dicts (generation
numbers, flush ids, correlation ids) and land in a process-wide ring
buffer whose export is Chrome trace-event JSON — loadable in Perfetto /
``chrome://tracing`` directly, and foldable into occupancy tables by
``scripts/trace_report.py``.

Disabled is the default and the contract: with tracing off the serve fast
path must not allocate or record anything. Every hot-path call site guards
on ``TRACER.enabled`` (one attribute read) before touching timestamps or
args, and ``span()`` returns a shared no-op context manager — the same
object every time, so even the disabled path creates no garbage.

Correlation across threads and processes:

- ``new_trace_id()`` mints process-unique ids; ``bind_trace_id`` /
  ``current_trace_id`` carry one through a thread via ``contextvars``.
  The batcher's pipeline crosses threads (submit → worker → completer),
  where contextvars do not follow — there the id rides the request object
  itself and every stage stamps it into its span args, which is the
  property the trace tests pin.
- Timestamps are wall-epoch microseconds (``perf_counter`` deltas pinned
  to an epoch captured at import), so traces from two processes on one
  host — a training supervisor and the serving replica consuming its
  generations — merge into a single coherent timeline by concatenating
  their event lists.

Async stages (a flush dispatched by one thread and finalized by another)
use Chrome async events (``ph: "b"``/``"e"``) keyed by a flush id;
same-thread intervals use complete events (``ph: "X"``).
"""

from __future__ import annotations

import contextvars
import itertools
import json
import os
import re
import threading
import time
from collections import deque
from typing import Dict, Optional

#: pins perf_counter deltas to the wall clock ONCE so every event in this
#: process (and any sibling process on the host) shares one timeline
_EPOCH = time.time() - time.perf_counter()

#: cached: os.getpid() is a real syscall (~12 µs under gVisor-style
#: sandboxes) and the pid cannot change under us — a fresh interpreter
#: (including multiprocessing spawn) re-imports this module
_PID = os.getpid()

_trace_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "gdt_trace_id", default=None
)

_ids = itertools.count(1)


def new_trace_id() -> str:
    """Process-unique correlation id (pid-prefixed so ids from merged
    multi-process traces never collide)."""
    return f"{_PID:x}-{next(_ids):x}"


#: the X-Trace-Id header contract (docs/OBSERVABILITY.md): short, shell-
#: and log-safe. Anything else from a client is ignored, not echoed — a
#: header is attacker-controlled input and these ids land verbatim in
#: traces, logs, and span args.
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9._:\-]{1,128}$")


def sanitize_trace_id(value) -> Optional[str]:
    """A client/peer-supplied trace id, validated — or None. The router
    and the serving HTTP handler adopt a propagated id only through this
    gate; an invalid one falls back to minting."""
    if not isinstance(value, str):
        return None
    value = value.strip()
    return value if _TRACE_ID_RE.match(value) else None


def current_trace_id() -> Optional[str]:
    return _trace_ctx.get()


def bind_trace_id(trace_id: Optional[str]):
    """Bind the current thread's correlation id; returns a token for
    ``unbind_trace_id``."""
    return _trace_ctx.set(trace_id)


def unbind_trace_id(token) -> None:
    _trace_ctx.reset(token)


class _NoopSpan:
    """The disabled-path span: one shared instance, nothing allocated."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    """An enabled span: records a complete ("X") event on exit."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: Optional[dict]):
        self._tracer = tracer
        self._name = name
        self._args = args
        self._t0 = time.perf_counter()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._tracer.complete(
            self._name, self._t0, time.perf_counter(), self._args
        )
        return False


class Tracer:
    """Bounded-ring-buffer span recorder. ``capacity`` bounds memory: the
    buffer keeps the newest events and silently drops the oldest (a
    long-lived server must never grow without bound because someone left
    tracing on); ``dropped`` counts what fell off."""

    def __init__(self, capacity: int = 65536, enabled: bool = False):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._recorded = 0
        self.enabled = bool(enabled)

    # -- lifecycle ---------------------------------------------------------
    def enable(self, capacity: Optional[int] = None) -> None:
        with self._lock:
            if capacity is not None and capacity != self._events.maxlen:
                self._events = deque(self._events, maxlen=capacity)
            self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._recorded = 0

    @property
    def capacity(self) -> int:
        return self._events.maxlen

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._recorded - len(self._events))

    def __len__(self) -> int:
        return len(self._events)

    # -- recording ---------------------------------------------------------
    def _record(self, name: str, ph: str, t_s: float, dur_s: Optional[float],
                args: Optional[dict], span_id: Optional[str]) -> None:
        event = {
            "name": name,
            "ph": ph,
            "ts": (t_s + _EPOCH) * 1e6,
            "pid": _PID,
            "tid": threading.get_ident() & 0x7FFFFFFF,
        }
        if dur_s is not None:
            event["dur"] = dur_s * 1e6
        if span_id is not None:
            event["id"] = span_id
            event["cat"] = "flight"
        trace_id = _trace_ctx.get()
        if args or trace_id:
            merged = dict(args) if args else {}
            if trace_id and "trace_id" not in merged:
                merged["trace_id"] = trace_id
            event["args"] = merged
        with self._lock:
            self._events.append(event)
            self._recorded += 1

    def span(self, name: str, **args):
        """Context manager timing one interval. Hot paths should guard on
        ``tracer.enabled`` before building kwargs; this method's own
        disabled path returns the shared no-op span."""
        if not self.enabled:
            return _NOOP_SPAN
        return _Span(self, name, args or None)

    def complete(self, name: str, t0_s: float, t1_s: float,
                 args: Optional[dict] = None) -> None:
        """Record an already-measured interval (perf_counter seconds) —
        the zero-overhead form for code that takes its own timestamps."""
        if not self.enabled:
            return
        self._record(name, "X", t0_s, max(0.0, t1_s - t0_s), args, None)

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        self._record(name, "i", time.perf_counter(), None, args, None)

    def async_begin(self, name: str, span_id: str,
                    args: Optional[dict] = None) -> None:
        """Open an interval that a DIFFERENT thread will close (the
        batcher's dispatched flush). ``span_id`` pairs begin with end."""
        if not self.enabled:
            return
        self._record(name, "b", time.perf_counter(), None, args, span_id)

    def async_end(self, name: str, span_id: str,
                  args: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        self._record(name, "e", time.perf_counter(), None, args, span_id)

    # -- export ------------------------------------------------------------
    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def chrome_trace(self, metadata: Optional[dict] = None) -> dict:
        """The Chrome trace-event JSON object (Perfetto-loadable)."""
        body = {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
        }
        meta = {"dropped_events": self.dropped}
        if metadata:
            meta.update(metadata)
        body["metadata"] = meta
        return body

    def dump(self, path: str, metadata: Optional[dict] = None) -> str:
        """Write the Chrome trace JSON to ``path`` (dirs created)."""
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        with open(path, "w") as fh:
            json.dump(self.chrome_trace(metadata), fh)
            fh.write("\n")
        return path


#: the process-wide tracer every subsystem records into. Disabled by
#: default; CLIs enable it behind --telemetry / GDT_TELEMETRY=trace.
TRACER = Tracer()


def get_tracer() -> Tracer:
    return TRACER


def configure_from_env(env: Optional[Dict[str, str]] = None) -> bool:
    """Enable the process tracer when ``GDT_TELEMETRY`` contains ``trace``
    (comma-separated feature list). Returns whether tracing is enabled."""
    value = (env or os.environ).get("GDT_TELEMETRY", "")
    if "trace" in [part.strip() for part in value.split(",")]:
        TRACER.enable()
    return TRACER.enabled
