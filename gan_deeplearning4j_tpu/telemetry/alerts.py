"""Fleet alerting & anomaly detection — the plane that tells a human.

PR 6 gave the repo measurements, PR 11 merged them fleet-wide, PR 12/13
closed control loops over them — but every consumer so far is a machine.
This module is the missing third leg of the observability plane
(tracing, metrics, **alerting**): a declarative rule engine evaluated
over the SAME snapshot-shaped payload ``GET /metrics?scope=fleet``
serves, with a full alert lifecycle, hysteresis, trace exemplars, and
pluggable delivery (docs/OBSERVABILITY.md "Alerting").

**Rule kinds** (:class:`AlertRule.kind`):

- ``threshold`` — a gauge value (or a counter's per-second rate with
  ``rate=True``) compared against a bound. One alert *instance* per
  matching labeled series, so ``fleet_member_routable < 1`` fans out
  into one ``worker_down{worker=...}`` per member.
- ``absence`` — the expected series is missing from the snapshot. The
  silent failure mode: a subsystem that stops reporting looks exactly
  like a subsystem with nothing to report, unless absence itself alarms.
- ``burn`` — the multi-window SLO semantics of :mod:`.slo`, read back
  off the exported ``*_slo_burn_rate{...,window}`` gauges: an objective
  breaches only when BOTH its fast and slow windows are at/over the
  threshold; NaN (empty window) qualifies nothing. Series group by
  their non-window labels, so one rule over ``mux_slo_burn_rate``
  yields one instance per model (the mux plane's per-model scoping).
- ``anomaly`` — a rolling median+MAD robust z-score over a histogram
  percentile (or a gauge), catching the drift no static threshold
  names: p99 latency creeping from 8 ms to 80 ms is invisible to a
  500 ms bound and obvious to a baseline. MAD (not stddev) so the
  baseline survives its own outliers; breached observations are NOT
  absorbed into the baseline (an incident must not normalize itself).

**Fail-closed three-valued evaluation**: every evaluation yields breach,
clear, or *undefined* (NaN value, empty window, not enough baseline
points, series temporarily unscraped). Undefined can move an alert to
``pending`` — "cannot prove healthy" — but never to ``firing``, and it
never RESOLVES a firing alert either: no data is not evidence in either
direction (the same stance :mod:`.slo` and the autoscaler take).

**Lifecycle with per-direction hysteresis**::

    inactive -> pending(for_ticks) -> firing
    firing   -> resolved(keep_firing_ticks) -> inactive

Entering ``firing`` takes ``for_ticks`` consecutive breaches; leaving it
takes ``keep_firing_ticks`` consecutive clears; ``resolved`` stays
visible for ``resolved_hold_ticks`` so a dashboard shows what just
happened. A flapping signal therefore costs at most one transition per
full hysteresis window — it cannot page-storm. ``arm_on_first_clear``
holds a rule's breaches until the series has been healthy once (a
booting fleet is not a down fleet).

**Exemplars**: a firing alert captures up to ``exemplar_k`` recent
entries from its :class:`ExemplarStore` category — the trace ids (and
labels) of concrete requests that crossed the bad threshold, recorded by
the router on failed attempts, 5xx answers, and slow answers. An alert
is then one click from evidence: the ids link straight into the merged
``GET /debug/trace`` chain.

**Surfaces**: ``GET /alerts`` (JSON, and ``?format=prom`` rendering the
Prometheus-convention ``ALERTS{alertname,severity,state}`` series),
an ``alerts`` block in ``/healthz``, ``fleet_alerts_total
{alertname,state}`` transition counters in the process registry, a
bounded JSON incident ring, and pluggable sinks — :func:`log_sink`
(structured log line) and :class:`WebhookSink` (bounded-timeout,
bounded-retry POST from its own thread, never the evaluation path).

**Cost contract**: the evaluator reads snapshots it is handed — it owns
no scrape and adds no per-worker fan-out (the router ticks it from the
health loop it already runs). A process that never constructs an
:class:`AlertManager` allocates zero new metric series — the PR 6
telemetry-off contract.

Stdlib-only, like the rest of the metrics plane.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import threading
import time
import urllib.request
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from gan_deeplearning4j_tpu.telemetry.registry import get_registry

logger = logging.getLogger(__name__)

#: lifecycle states, in escalation order
STATES = ("inactive", "pending", "firing", "resolved")

KINDS = ("threshold", "absence", "burn", "anomaly")

_OPS = {
    ">": lambda v, b: v > b,
    ">=": lambda v, b: v >= b,
    "<": lambda v, b: v < b,
    "<=": lambda v, b: v <= b,
}

#: the scale factor making the MAD a consistent estimator of the
#: standard deviation under normality — the conventional robust-z form
_MAD_K = 0.6745


def _to_float(value) -> float:
    """Snapshot values as floats; ``None`` (a JSON-sanitized NaN) and
    anything non-numeric read as NaN — undefined, never a crash."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return float("nan")
    return float(value)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


@dataclasses.dataclass
class AlertRule:
    """One declarative rule (module docstring for the kinds)."""

    name: str
    kind: str
    #: metric family the rule reads (every kind reads exactly one; the
    #: jaxlint JG023 rule cross-checks literal names against the
    #: families the tree actually creates)
    metric: str = ""
    #: label filter: only series carrying these labels participate
    labels: dict = dataclasses.field(default_factory=dict)
    severity: str = "page"  # "page" | "warn"
    description: str = ""
    # -- threshold ----------------------------------------------------
    op: str = ">"
    bound: float = float("nan")
    #: compare the per-second counter rate instead of the raw value
    rate: bool = False
    # -- burn ---------------------------------------------------------
    objective: str = "availability"
    burn_threshold: float = 1.0
    # -- anomaly ------------------------------------------------------
    #: histogram percentile key ("p50"/"p95"/"p99"); None = gauge value
    field: Optional[str] = "p99"
    window: int = 120        # rolling baseline observations kept
    min_points: int = 16     # baseline size below which eval is undefined
    z_max: float = 8.0       # robust z bound
    direction: str = "above"  # "above" | "below" | "both"
    #: MAD floor as a fraction of |median|: a near-constant baseline has
    #: MAD ~0, which would turn ordinary jitter into an infinite z —
    #: the floor means a breach needs a shift of at least
    #: ~z_max * mad_floor_frac / 0.6745 relative to the baseline
    mad_floor_frac: float = 0.05
    #: absolute MAD floor, for series whose healthy median is ~0 (queue
    #: depths, pressure): with median 0 the relative floor vanishes and
    #: a blip of 1 would z to infinity — the absolute floor states the
    #: smallest deviation worth a standard unit
    mad_floor_abs: float = 0.0
    # -- lifecycle ----------------------------------------------------
    for_ticks: int = 2
    keep_firing_ticks: int = 3
    resolved_hold_ticks: int = 8
    #: hold breaches until the series has evaluated clear once — a
    #: booting worker is not a down worker
    arm_on_first_clear: bool = False
    # -- evidence -----------------------------------------------------
    exemplar_category: Optional[str] = None
    exemplar_k: int = 4
    #: optional enrichment hook: instance labels -> extra annotations,
    #: called at the pending transition (the router maps a worker id to
    #: its pid here). Excluded from serialization.
    annotate: Optional[Callable[[dict], dict]] = None

    def validate(self) -> "AlertRule":
        if not self.name:
            raise ValueError("rule needs a name")
        if self.kind not in KINDS:
            raise ValueError(f"{self.name}: unknown kind {self.kind!r} "
                             f"(want one of {KINDS})")
        if not self.metric:
            raise ValueError(f"{self.name}: needs a metric family name")
        if self.kind == "threshold":
            if self.op not in _OPS:
                raise ValueError(f"{self.name}: unknown op {self.op!r}")
            if math.isnan(self.bound):
                raise ValueError(f"{self.name}: threshold needs a bound")
        if self.kind == "anomaly":
            if self.direction not in ("above", "below", "both"):
                raise ValueError(
                    f"{self.name}: direction {self.direction!r}")
            if self.window < self.min_points or self.min_points < 2:
                raise ValueError(
                    f"{self.name}: need window >= min_points >= 2")
            if self.z_max <= 0:
                raise ValueError(f"{self.name}: z_max must be > 0")
            if self.mad_floor_frac < 0 or self.mad_floor_abs < 0:
                raise ValueError(
                    f"{self.name}: mad floors must be >= 0")
        if self.for_ticks < 1 or self.keep_firing_ticks < 1:
            raise ValueError(
                f"{self.name}: for_ticks and keep_firing_ticks must be "
                f">= 1 (the hysteresis)")
        if self.resolved_hold_ticks < 0:
            raise ValueError(f"{self.name}: resolved_hold_ticks >= 0")
        if self.severity not in ("page", "warn"):
            raise ValueError(f"{self.name}: severity {self.severity!r}")
        return self

    def describe(self) -> dict:
        body = {
            "name": self.name, "kind": self.kind, "metric": self.metric,
            "labels": dict(self.labels), "severity": self.severity,
            "for_ticks": self.for_ticks,
            "keep_firing_ticks": self.keep_firing_ticks,
        }
        if self.kind == "threshold":
            body.update(op=self.op, bound=self.bound, rate=self.rate)
        elif self.kind == "burn":
            body.update(objective=self.objective,
                        burn_threshold=self.burn_threshold)
        elif self.kind == "anomaly":
            body.update(field=self.field, window=self.window,
                        min_points=self.min_points, z_max=self.z_max,
                        direction=self.direction)
        if self.description:
            body["description"] = self.description
        return body


class ExemplarStore:
    """Bounded per-category ring of bad-request evidence. ``record`` is
    hot-path adjacent (the router calls it on failures/slow answers) —
    one lock, one append; everything is dropped-oldest bounded."""

    def __init__(self, per_category: int = 128,
                 wall_clock: Callable[[], float] = time.time):
        self._per_category = per_category
        self._wall = wall_clock
        self._lock = threading.Lock()
        self._categories: Dict[str, deque] = {}

    def record(self, category: str, trace_id: Optional[str],
               **labels) -> None:
        entry = {"trace_id": trace_id, "t": self._wall(),
                 **{k: v for k, v in labels.items() if v is not None}}
        with self._lock:
            ring = self._categories.get(category)
            if ring is None:
                ring = self._categories[category] = deque(
                    maxlen=self._per_category)
            ring.append(entry)

    def recent(self, category: str, k: int = 4,
               match: Optional[dict] = None) -> List[dict]:
        """Newest-first entries of ``category`` whose labels carry every
        ``match`` pair (compared as strings — instance labels are)."""
        with self._lock:
            entries = list(self._categories.get(category, ()))
        out = []
        for entry in reversed(entries):
            if match and any(str(entry.get(mk)) != str(mv)
                             for mk, mv in match.items()):
                continue
            out.append(dict(entry))
            if len(out) >= k:
                break
        return out

    def snapshot(self) -> dict:
        with self._lock:
            return {cat: list(ring)
                    for cat, ring in sorted(self._categories.items())}


# -- sinks -------------------------------------------------------------------

def log_sink(record: dict) -> None:
    """Structured one-line delivery: greppable, machine-parseable, and
    present even when no webhook is configured."""
    level = (logging.WARNING if record.get("to") in ("pending", "firing")
             else logging.INFO)
    logger.log(level, "ALERT %s", json.dumps(record, sort_keys=True,
                                             default=str))


class WebhookSink:
    """POST each transition to ``url`` as JSON — from a daemon thread
    over a bounded drop-oldest queue, with a bounded timeout and bounded
    retries, so a dead receiver can neither stall alert evaluation nor
    accumulate unbounded state (jaxlint JG017 polices the timeout)."""

    def __init__(self, url: str, *, timeout: float = 2.0, retries: int = 2,
                 backoff_s: float = 0.5, max_queue: int = 64):
        if timeout <= 0 or retries < 0 or backoff_s < 0:
            raise ValueError("need timeout > 0, retries >= 0, backoff >= 0")
        self.url = url
        self.timeout = timeout
        self.retries = retries
        self.backoff_s = backoff_s
        self.sent = 0
        self.failed = 0
        self._queue: deque = deque(maxlen=max_queue)
        self._event = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="alert-webhook", daemon=True)
        self._thread.start()

    def __call__(self, record: dict) -> None:
        self._queue.append(record)
        self._event.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._event.wait(0.5)
            self._event.clear()
            while True:
                try:
                    record = self._queue.popleft()
                except IndexError:
                    break
                self._deliver(record)

    def _deliver(self, record: dict) -> None:
        body = json.dumps(record, default=str).encode()
        for attempt in range(self.retries + 1):
            try:
                req = urllib.request.Request(
                    self.url, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=self.timeout):
                    self.sent += 1
                    return
            except Exception:
                # OSError is the common case, but a malformed URL
                # (ValueError) or a garbage status line (HTTPException)
                # must not kill the delivery thread — a dead thread
                # silently drops every FUTURE page while evaluation
                # keeps running
                if attempt < self.retries:
                    self._stop.wait(self.backoff_s * (2 ** attempt))
        self.failed += 1

    def close(self, timeout: float = 2.0) -> None:
        self._stop.set()
        self._event.set()
        self._thread.join(timeout)


# -- snapshot reading --------------------------------------------------------

def _matching_series(snapshot: dict, metric: str, match: dict) -> list:
    fam = snapshot.get(metric)
    if not isinstance(fam, dict):
        return []
    series = fam.get("series")
    if not isinstance(series, list):
        return []
    out = []
    for s in series:
        if not isinstance(s, dict):
            continue
        labels = s.get("labels") or {}
        if all(str(labels.get(k)) == str(v) for k, v in match.items()):
            out.append(s)
    return out


class _InstanceState:
    """Lifecycle state of one (rule, labeled series) alert instance."""

    __slots__ = ("labels", "state", "since_wall", "pending", "clears",
                 "hold", "armed", "value", "annotations", "exemplars",
                 "unobserved", "baseline", "last_counter")

    def __init__(self, labels: dict):
        self.labels = dict(labels)
        self.state = "inactive"
        self.since_wall: Optional[float] = None
        self.pending = 0        # consecutive breaches toward firing
        self.clears = 0         # consecutive clears toward resolved
        self.hold = 0           # resolved-visibility countdown
        self.armed = False
        self.value: float = float("nan")
        self.annotations: dict = {}
        self.exemplars: List[dict] = []
        self.unobserved = 0
        self.baseline: Optional[deque] = None   # anomaly rolling window
        self.last_counter: Optional[Tuple[float, float]] = None  # (v, t)


class AlertManager:
    """The evaluator: rules in, transitions out (module docstring).

    ``evaluate(snapshot)`` is the tick — the router drives it from the
    health loop it already runs, handing it the same snapshot-shaped
    dict ``GET /metrics?scope=fleet`` is built from. ``clock`` feeds the
    rate rules (monotonic), ``wall_clock`` stamps incidents (the trace
    overlay in ``scripts/trace_report.py --alerts`` joins them to the
    wall-epoch span timeline)."""

    def __init__(self, rules: List[AlertRule], *,
                 clock: Callable[[], float] = time.monotonic,
                 wall_clock: Callable[[], float] = time.time,
                 exemplars: Optional[ExemplarStore] = None,
                 sinks: Tuple[Callable[[dict], None], ...] = (),
                 max_incidents: int = 256):
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in {sorted(names)}")
        self.rules = [r.validate() for r in rules]
        self._clock = clock
        self._wall = wall_clock
        self.exemplars = exemplars or ExemplarStore(wall_clock=wall_clock)
        self.sinks = tuple(sinks)
        self._lock = threading.Lock()
        self._states: Dict[str, Dict[tuple, _InstanceState]] = {
            r.name: {} for r in self.rules}
        self.incidents: deque = deque(maxlen=max_incidents)
        self._ticks = 0
        self._c_transitions = get_registry().counter(
            "fleet_alerts_total",
            "alert lifecycle transitions by alertname and entered state",
            labelnames=("alertname", "state"))

    # -- evaluation ------------------------------------------------------
    def evaluate(self, snapshot: dict) -> List[dict]:
        """One tick over a snapshot-shaped dict; returns the transition
        records emitted (also appended to the incident ring, counted in
        ``fleet_alerts_total``, and delivered to every sink)."""
        now = self._clock()
        transitions: List[dict] = []
        with self._lock:
            self._ticks += 1
            for rule in self.rules:
                states = self._states[rule.name]
                observed = self._observe(rule, snapshot, states, now)
                for key, (labels, value, verdict) in observed.items():
                    st = states.get(key)
                    if st is None:
                        st = states[key] = _InstanceState(labels)
                    st.unobserved = 0
                    st.value = value
                    self._step(rule, st, verdict, transitions)
                # series that vanished from the snapshot: undefined — a
                # firing alert holds briefly, then resolves (the series
                # being GONE is no longer evidence of an ongoing breach:
                # a retired worker must not page forever); idle states GC
                for key in list(states):
                    if key in observed:
                        continue
                    st = states[key]
                    st.unobserved += 1
                    if (st.state == "firing"
                            and st.unobserved >= rule.keep_firing_ticks):
                        self._transition(rule, st, "resolved", transitions,
                                         reason="series gone")
                    elif st.state == "resolved":
                        st.hold += 1
                        if st.hold >= rule.resolved_hold_ticks:
                            self._transition(rule, st, "inactive",
                                             transitions)
                    elif (st.state in ("inactive", "pending")
                          and st.unobserved >= 64):
                        del states[key]
        for record in transitions:
            for sink in self.sinks:
                try:
                    sink(record)
                except Exception:  # a sink bug must not kill evaluation
                    logger.exception("alert sink failed")
        return transitions

    # -- per-kind observation: key -> (labels, value, verdict) -----------
    def _observe(self, rule: AlertRule, snapshot: dict, states, now
                 ) -> Dict[tuple, tuple]:
        if rule.kind == "absence":
            present = bool(_matching_series(snapshot, rule.metric,
                                            rule.labels))
            # one instance, keyed by the rule's own filter; missing IS
            # the breach
            return {_label_key(rule.labels):
                    (dict(rule.labels), 0.0 if present else float("nan"),
                     not present)}
        if rule.kind == "burn":
            return self._observe_burn(rule, snapshot)
        out: Dict[tuple, tuple] = {}
        for s in _matching_series(snapshot, rule.metric, rule.labels):
            labels = dict(s.get("labels") or {})
            key = _label_key(labels)
            st = states.setdefault(key, _InstanceState(labels))
            if rule.kind == "threshold":
                value, verdict = self._eval_threshold(rule, s, st, now)
            else:  # anomaly
                value, verdict = self._eval_anomaly(rule, s, st)
            out[key] = (labels, value, verdict)
        return out

    def _eval_threshold(self, rule: AlertRule, series: dict,
                        st: _InstanceState, now: float):
        value = _to_float(series.get("value"))
        if rule.rate:
            if st.last_counter is None:
                rate = float("nan")  # no previous point yet
            else:
                prev_v, prev_t = st.last_counter
                dt = now - prev_t
                dv = value - prev_v
                # a counter that went DOWN restarted; the interval is
                # undefined, not negative traffic
                rate = (dv / dt) if (dt > 0 and dv >= 0) else float("nan")
            if not math.isnan(value):
                st.last_counter = (value, now)
            value = rate
        if math.isnan(value):
            return value, None
        return value, _OPS[rule.op](value, rule.bound)

    def _eval_anomaly(self, rule: AlertRule, series: dict,
                      st: _InstanceState):
        value = _to_float(series.get(rule.field)
                          if rule.field else series.get("value"))
        if st.baseline is None:
            st.baseline = deque(maxlen=rule.window)
        if math.isnan(value):
            return value, None  # undefined; baseline untouched
        verdict: Optional[bool] = None
        if len(st.baseline) >= rule.min_points:
            data = sorted(st.baseline)
            median = data[len(data) // 2]
            mad = sorted(abs(x - median) for x in data)[len(data) // 2]
            # MAD floors: a near-flat baseline must not turn ordinary
            # jitter into an infinite z (mad_floor_* docstrings)
            mad = max(mad, abs(median) * rule.mad_floor_frac,
                      rule.mad_floor_abs, 1e-9)
            z = _MAD_K * (value - median) / mad
            if rule.direction == "above":
                verdict = z > rule.z_max
            elif rule.direction == "below":
                verdict = -z > rule.z_max
            else:
                verdict = abs(z) > rule.z_max
        if verdict is not True:
            # breached observations never join the baseline — an
            # incident must not normalize itself into the new normal
            st.baseline.append(value)
        return value, verdict

    def _observe_burn(self, rule: AlertRule, snapshot: dict
                      ) -> Dict[tuple, tuple]:
        """Group the burn-rate gauge's series by their non-window labels
        (one instance per model/tracker), require the rule's objective,
        and breach only when BOTH windows are at/over the threshold —
        the :mod:`.slo` multi-window semantics, read back off the
        exported gauges. Any NaN or missing window is undefined."""
        match = {**rule.labels, "objective": rule.objective}
        groups: Dict[tuple, Dict[str, float]] = {}
        group_labels: Dict[tuple, dict] = {}
        for s in _matching_series(snapshot, rule.metric, match):
            labels = dict(s.get("labels") or {})
            window = labels.pop("window", None)
            if window not in ("fast", "slow"):
                continue
            key = _label_key(labels)
            groups.setdefault(key, {})[window] = _to_float(s.get("value"))
            group_labels[key] = labels
        out: Dict[tuple, tuple] = {}
        for key, windows in groups.items():
            fast = windows.get("fast", float("nan"))
            slow = windows.get("slow", float("nan"))
            value = max(fast, slow) if not (
                math.isnan(fast) or math.isnan(slow)) else float("nan")
            if math.isnan(value):
                verdict: Optional[bool] = None
            else:
                verdict = (fast >= rule.burn_threshold
                           and slow >= rule.burn_threshold)
            out[key] = (group_labels[key], value, verdict)
        return out

    # -- the lifecycle state machine -------------------------------------
    def _step(self, rule: AlertRule, st: _InstanceState,
              verdict: Optional[bool], transitions: List[dict]) -> None:
        if verdict is False:
            st.armed = True
        elif rule.arm_on_first_clear and not st.armed:
            # breaches (and no-data) before the first healthy evaluation
            # are boot noise, not regressions
            verdict = None
        if verdict is None:
            # fail closed: no data may move inactive to pending ("cannot
            # prove healthy"), but it never advances toward firing and
            # never resolves a firing alert — including indirectly: a
            # data gap RESETS the clear streak, or two non-consecutive
            # clears separated by a blind spot (a scrape wedging during
            # the very incident being alerted on) would resolve a live
            # breach. Unarmed arm_on_first_clear instances stay
            # inactive — boot grace is what arming is for.
            if st.state == "firing":
                st.clears = 0
            elif (st.state == "inactive"
                    and (st.armed or not rule.arm_on_first_clear)):
                self._transition(rule, st, "pending", transitions,
                                 reason="no data")
            elif st.state == "resolved":
                st.hold += 1
                if st.hold >= rule.resolved_hold_ticks:
                    self._transition(rule, st, "inactive", transitions)
            return
        if verdict:
            if st.state in ("inactive", "resolved"):
                self._transition(rule, st, "pending", transitions)
                st.pending = 1
            elif st.state == "pending":
                st.pending += 1
            else:  # firing: fresh evidence re-arms the resolve hysteresis
                st.clears = 0
                return
            if st.pending >= rule.for_ticks:
                self._transition(rule, st, "firing", transitions)
            return
        # verdict is False — clear
        if st.state == "pending":
            self._transition(rule, st, "inactive", transitions)
        elif st.state == "firing":
            st.clears += 1
            if st.clears >= rule.keep_firing_ticks:
                self._transition(rule, st, "resolved", transitions)
        elif st.state == "resolved":
            st.hold += 1
            if st.hold >= rule.resolved_hold_ticks:
                self._transition(rule, st, "inactive", transitions)

    def _transition(self, rule: AlertRule, st: _InstanceState, to: str,
                    transitions: List[dict], reason: str = "") -> None:
        prev = st.state
        st.state = to
        st.since_wall = self._wall()
        if to == "pending":
            st.pending = 0
            st.clears = 0
            st.exemplars = []
            if rule.annotate is not None:
                try:
                    st.annotations = dict(rule.annotate(st.labels) or {})
                except Exception:
                    logger.exception("annotate hook failed for %s",
                                     rule.name)
        elif to == "firing":
            st.clears = 0
            if rule.exemplar_category:
                st.exemplars = self.exemplars.recent(
                    rule.exemplar_category, k=rule.exemplar_k,
                    match={k: v for k, v in st.labels.items()
                           if k in ("worker", "model")})
        elif to == "resolved":
            st.hold = 0
        elif to == "inactive":
            st.pending = st.clears = st.hold = 0
        record = {
            "t": st.since_wall,
            "alert": rule.name,
            "severity": rule.severity,
            "labels": dict(st.labels),
            "from": prev,
            "to": to,
            "value": None if math.isnan(st.value) else st.value,
        }
        if reason:
            record["reason"] = reason
        if st.annotations:
            record["annotations"] = dict(st.annotations)
        if to == "firing" and st.exemplars:
            record["exemplars"] = list(st.exemplars)
        self.incidents.append(record)
        transitions.append(record)
        self._c_transitions.labels(alertname=rule.name, state=to).inc()

    # -- surfaces --------------------------------------------------------
    def active(self) -> List[dict]:
        """Every non-inactive alert instance (the ``/alerts`` payload's
        core), firing first."""
        out: List[dict] = []
        with self._lock:
            for rule in self.rules:
                for st in self._states[rule.name].values():
                    if st.state == "inactive":
                        continue
                    entry = {
                        "alert": rule.name,
                        "severity": rule.severity,
                        "state": st.state,
                        "labels": dict(st.labels),
                        "value": (None if math.isnan(st.value)
                                  else st.value),
                        "since": st.since_wall,
                    }
                    if st.annotations:
                        entry["annotations"] = dict(st.annotations)
                    if st.exemplars:
                        entry["exemplars"] = list(st.exemplars)
                    out.append(entry)
        order = {"firing": 0, "pending": 1, "resolved": 2}
        out.sort(key=lambda e: (order.get(e["state"], 3), e["alert"]))
        return out

    @staticmethod
    def _count(entries: List[dict]) -> Dict[str, int]:
        counts = {state: 0 for state in STATES[1:]}
        for entry in entries:
            counts[entry["state"]] += 1
        return counts

    def counts(self) -> Dict[str, int]:
        return self._count(self.active())

    def snapshot(self) -> dict:
        """The ``GET /alerts`` JSON payload. ``/alerts`` is polled
        continuously (dashboards, the drill's monitor), so the instance
        walk happens once and the counts derive from it."""
        with self._lock:
            ticks = self._ticks
            incidents = list(self.incidents)
        entries = self.active()
        return {
            "rules": [r.describe() for r in self.rules],
            "alerts": entries,
            "counts": self._count(entries),
            "ticks": ticks,
            "incidents": incidents,
        }

    def health_block(self) -> dict:
        """The compact ``/healthz`` block: what is firing, right now."""
        active = self.active()
        firing = [e for e in active if e["state"] == "firing"]
        return {
            "ok": not firing,
            "firing": [{"alert": e["alert"], "labels": e["labels"],
                        "severity": e["severity"]} for e in firing],
            "pending": sum(1 for e in active if e["state"] == "pending"),
            "rules": len(self.rules),
        }

    def to_prometheus(self) -> str:
        """``?format=prom``: the Prometheus alerting convention — one
        ``ALERTS{alertname,severity,state}`` series per pending/firing
        instance, value 1 (transition counters live in the registry's
        own exposition as ``fleet_alerts_total``)."""
        lines = ["# TYPE ALERTS gauge"]
        for entry in self.active():
            if entry["state"] not in ("pending", "firing"):
                continue
            labels = {"alertname": entry["alert"],
                      "severity": entry["severity"],
                      "state": entry["state"], **entry["labels"]}
            inner = ",".join(f'{k}="{v}"' for k, v in labels.items())
            lines.append("ALERTS{" + inner + "} 1")
        return "\n".join(lines) + "\n"


# -- default rule packs ------------------------------------------------------

def default_fleet_rules(*, probe_interval_s: float = 0.25,
                        scrape_stale_after_s: float = 10.0,
                        latency_drift_floor_s: float = 0.05,
                        annotate_member: Optional[Callable] = None
                        ) -> List[AlertRule]:
    """The rule pack covering the surfaces the fleet already exports
    (docs/OBSERVABILITY.md "Alerting" walks each one). Tick cadence is
    the router health loop's ``probe_interval``; the ``for_ticks``
    defaults below convert roughly into seconds through it.
    ``latency_drift_floor_s`` is the anomaly rule's MAD floor — the
    smallest p99 wiggle worth a standard unit; a p99 shift of roughly
    ``z_max / 0.6745`` floors over the baseline pages (~0.6 s at the
    defaults; an operator serving a fast fleet lowers it)."""
    ticks = lambda seconds: max(2, int(round(seconds / probe_interval_s)))  # noqa: E731
    return [
        AlertRule(
            name="worker_down", kind="threshold",
            metric="fleet_member_routable", op="<", bound=1.0,
            severity="page", for_ticks=ticks(0.6),
            keep_firing_ticks=ticks(0.6),
            arm_on_first_clear=True,
            exemplar_category="worker_failure",
            annotate=annotate_member,
            description="a member that once served is no longer routable "
                        "(ejected, draining, or dead)"),
        AlertRule(
            name="scrape_stale", kind="threshold",
            metric="fleet_member_scrape_age_seconds",
            op=">", bound=scrape_stale_after_s,
            severity="warn", for_ticks=ticks(0.6),
            keep_firing_ticks=ticks(0.6),
            arm_on_first_clear=True,
            annotate=annotate_member,
            description="a member's /metrics has not answered — wedged "
                        "observability is invisible failure"),
        AlertRule(
            name="slo_availability_burn", kind="burn",
            metric="fleet_slo_burn_rate", objective="availability",
            burn_threshold=1.0, severity="page",
            for_ticks=ticks(0.75), keep_firing_ticks=ticks(1.0),
            description="availability error budget burning on BOTH "
                        "windows (telemetry/slo.py semantics)"),
        AlertRule(
            name="slo_latency_burn", kind="burn",
            metric="fleet_slo_burn_rate", objective="latency",
            burn_threshold=1.0, severity="warn",
            for_ticks=ticks(0.75), keep_firing_ticks=ticks(1.0),
            description="latency error budget burning on BOTH windows"),
        AlertRule(
            name="brownout_latched", kind="threshold",
            metric="fleet_brownout", op=">=", bound=1.0,
            severity="warn", for_ticks=ticks(5.0),
            keep_firing_ticks=ticks(1.0),
            description="brownout admission control engaged and staying "
                        "engaged — capacity is exhausted, not blipped"),
        AlertRule(
            name="spawn_failures_climbing", kind="threshold",
            metric="fleet_spawn_failures_total", rate=True,
            op=">", bound=0.0, severity="page",
            for_ticks=ticks(0.75), keep_firing_ticks=ticks(1.5),
            description="workers dying before ever becoming routable — "
                        "the relaunch backoff ladder is climbing"),
        AlertRule(
            name="latency_anomaly", kind="anomaly",
            metric="fleet_request_latency_seconds", field="p99",
            window=240, min_points=20, z_max=8.0, direction="above",
            mad_floor_abs=latency_drift_floor_s,
            severity="page", for_ticks=ticks(0.75),
            keep_firing_ticks=ticks(1.0),
            exemplar_category="latency",
            description="p99 latency drifted far above its own rolling "
                        "baseline (median+MAD robust z) — the regression "
                        "no static threshold names"),
        AlertRule(
            name="queue_pressure_anomaly", kind="anomaly",
            metric="fleet_pressure", field=None,
            window=240, min_points=20, z_max=8.0, direction="above",
            mad_floor_abs=1.0,  # a healthy-idle median of 0 must not make
            # one queued request an infinite z
            severity="warn", for_ticks=ticks(0.75),
            keep_firing_ticks=ticks(1.0),
            description="queue+in-flight per routable worker far above "
                        "its rolling baseline"),
    ]


def default_mux_rules() -> List[AlertRule]:
    """Per-model scoping for a mux worker (docs/MULTIPLEX.md): the burn
    and queue rules read the per-model labeled families, so ONE rule
    fans out into one alert instance per variant."""
    return [
        AlertRule(
            name="model_slo_burn", kind="burn",
            metric="mux_slo_burn_rate", objective="availability",
            burn_threshold=1.0, severity="page",
            for_ticks=3, keep_firing_ticks=4,
            description="one variant's availability budget burning on "
                        "both windows (per-model SLI stream)"),
        AlertRule(
            name="model_queue_anomaly", kind="anomaly",
            metric="mux_queue_depth", field=None,
            window=240, min_points=20, z_max=8.0, direction="above",
            mad_floor_abs=1.0,
            severity="warn", for_ticks=3, keep_firing_ticks=4,
            description="one variant's queue depth far above its own "
                        "rolling baseline"),
    ]
