"""Fleet metrics aggregation — merge per-process registry snapshots.

The fleet runs N serving workers plus a router, each with its own
process-wide :class:`~.registry.MetricsRegistry`. Until this module the
"fleet view" was N separate ``/metrics`` endpoints a human had to sum in
their head. :func:`merge_snapshots` folds the per-worker snapshots
(``registry.snapshot(include_samples=True)``) into ONE snapshot-shaped
dict the router serves at ``GET /metrics?scope=fleet``:

- **counters** are summed across members per label combination — a
  monotonic total is additive, and the merge is pure arithmetic over
  already-atomic per-process values, so the fleet total is exactly the
  sum of what each member reported (no sampling, no loss);
- **gauges** are NOT summed — a queue depth of 3 on one worker and 0 on
  another is two facts, not a 3. Every gauge series gains a ``worker``
  label naming its member, so the fleet payload keeps each fact;
- **histograms** merge count + sum + the raw sample deques, and the
  p50/p95/p99 quoted for the merged series are recomputed by the SAME
  nearest-rank :func:`~.registry.percentiles` over the union of samples —
  the one way the repo's percentile contract can hold fleet-wide
  (quantiles of quantiles are not quantiles; quantiles of the pooled
  samples are).

Partial failure degrades, never crashes: a member whose scrape failed is
listed in the ``_fleet.gaps`` metadata AND as a labeled
``fleet_member_up{worker=...} 0`` gauge series, so a dashboard shows the
hole instead of silently under-counting.

Stdlib-only, like the rest of the metrics plane — the router process
never imports jax.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, Optional

from gan_deeplearning4j_tpu.telemetry.registry import (
    _fmt,
    _prom_labels,
    _prom_name,
    percentiles,
)

#: reserved top-level key carrying merge metadata (members, gaps,
#: conflicts) — not a metric family; the Prometheus renderer skips it
FLEET_META_KEY = "_fleet"

#: synthetic per-member liveness family injected by the merge: 1 for every
#: member whose snapshot landed, 0 for every gap
MEMBER_UP = "fleet_member_up"


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


def merge_snapshots(parts: Dict[str, dict],
                    gaps: Iterable[str] = (),
                    member_labels: Optional[Dict[str, dict]] = None
                    ) -> dict:
    """Merge member snapshots into one fleet snapshot.

    ``parts`` maps member id (worker id, ``"router"``) to that process's
    ``registry.snapshot(include_samples=True)`` payload; ``gaps`` names
    members whose scrape failed. Malformed families or members are
    recorded under ``_fleet.conflicts`` and skipped — an aggregation
    endpoint must degrade to a labeled partial view, never 500.

    ``member_labels`` maps a member id to extra labels stamped on EVERY
    series that member contributes — the model/generation dimension
    (docs/MULTIPLEX.md): two workers serving different generations emit
    identical ``serve_requests_total{kind,status}`` series, and without
    a distinguishing label the merge would sum them into one number,
    collapsing the per-model story. The router passes each worker's
    scraped ``generation`` here, so the merged counters keep one series
    per (labels × generation). Pass-through only fills labels a series
    does not already carry — a worker's own per-model labels (the mux
    plane's ``model=...``) always win."""
    families: dict = {}
    conflicts: list = []
    member_labels = member_labels or {}
    # accumulators: family -> label_key -> merged state
    for member in sorted(parts):
        snapshot = parts[member]
        extra = member_labels.get(member) or {}
        if not isinstance(snapshot, dict):
            conflicts.append(f"{member}: snapshot is not an object")
            continue
        for name in sorted(snapshot):
            fam = snapshot[name]
            if not (isinstance(fam, dict) and isinstance(
                    fam.get("series"), list) and "type" in fam):
                conflicts.append(f"{member}: family {name!r} malformed")
                continue
            kind = fam["type"]
            merged = families.setdefault(name, {
                "type": kind, "help": fam.get("help", ""), "series": {},
            })
            if merged["type"] != kind:
                conflicts.append(
                    f"{member}: family {name!r} is {kind}, fleet has "
                    f"{merged['type']} — member series skipped")
                continue
            for s in fam["series"]:
                if not isinstance(s, dict):
                    continue
                labels = dict(s.get("labels") or {})
                for k, v in extra.items():
                    # member-level dimension (generation/model): fill,
                    # never override a label the series already carries
                    labels.setdefault(str(k), str(v))
                if kind == "gauge":
                    # one fact per member: label, don't sum. setdefault —
                    # a series that already names the member it describes
                    # (the router's per-worker fleet_member_* gauges)
                    # keeps its own worker label; overriding it with the
                    # CONTRIBUTING member would relabel every fact as a
                    # fact about the router process
                    labels.setdefault("worker", member)
                key = _label_key(labels)
                slot = merged["series"].get(key)
                if slot is None:
                    slot = merged["series"][key] = {
                        "labels": labels, "count": 0, "sum": 0.0,
                        "samples": [], "value": 0.0,
                    }
                if kind == "histogram":
                    slot["count"] += int(s.get("count", 0))
                    slot["sum"] += float(s.get("sum", 0.0))
                    samples = s.get("samples")
                    if isinstance(samples, list):
                        slot["samples"].extend(
                            float(v) for v in samples)
                else:
                    slot["value"] += float(s.get("value", 0.0))

    out: dict = {}
    for name in sorted(families):
        fam = families[name]
        series = []
        for _, slot in sorted(fam["series"].items()):
            if fam["type"] == "histogram":
                entry = {"labels": slot["labels"], "count": slot["count"],
                         "sum": slot["sum"]}
                # the nearest-rank contract, fleet-wide: recompute from the
                # pooled samples (members that snapshot without samples
                # contribute count/sum only — percentiles then describe
                # the sampled subset, still nearest-rank)
                entry.update(percentiles(slot["samples"]))
                series.append(entry)
            else:
                series.append({"labels": slot["labels"],
                               "value": slot["value"]})
        out[name] = {"type": fam["type"], "help": fam["help"],
                     "series": series}

    gaps = sorted(set(gaps))
    members = sorted(parts)
    out[MEMBER_UP] = {
        "type": "gauge",
        "help": "1 when the member's registry scrape landed in this "
                "fleet snapshot, 0 when it failed (labeled gap)",
        "series": (
            [{"labels": {"worker": m}, "value": 1.0} for m in members]
            + [{"labels": {"worker": g}, "value": 0.0} for g in gaps]
        ),
    }
    out[FLEET_META_KEY] = {
        "members": members,
        "gaps": gaps,
        "conflicts": conflicts,
    }
    return out


def json_sanitize(obj):
    """Deep copy with non-finite floats replaced by None. JSON has no
    NaN/Infinity: a gauge holding NaN (the SLO burn rates' empty-window
    value) must reach the JSON fleet surface as ``null``, or strict
    parsers (jq, JS, Go) reject the whole payload — Python's own
    ``json.loads`` accepting ``NaN`` is the trap. The Prometheus path
    renders the SAME snapshot through ``_fmt``, which emits the text
    forms ``NaN``/``+Inf`` instead."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: json_sanitize(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [json_sanitize(v) for v in obj]
    return obj


def snapshot_to_prometheus(snapshot: dict) -> str:
    """Prometheus text exposition (0.0.4) of a snapshot-shaped dict — the
    merged fleet snapshot or any single ``registry.snapshot()`` payload.
    Histograms export as summaries off the same p50/p95/p99 the JSON
    quotes, so the two fleet surfaces can never disagree."""
    lines: list = []
    for name in sorted(k for k in snapshot if k != FLEET_META_KEY):
        fam = snapshot[name]
        if not (isinstance(fam, dict) and isinstance(
                fam.get("series"), list)):
            continue
        prom = _prom_name(name)
        if fam.get("help"):
            lines.append(f"# HELP {prom} {fam['help']}")
        kind = fam.get("type", "gauge")
        lines.append(
            f"# TYPE {prom} {'summary' if kind == 'histogram' else kind}")
        for s in fam["series"]:
            labels = s.get("labels") or {}
            if kind == "histogram":
                for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                    if key in s:
                        lines.append(
                            f"{prom}{_prom_labels(labels, {'quantile': q})} "
                            f"{_fmt(s[key])}")
                lines.append(
                    f"{prom}_sum{_prom_labels(labels)} "
                    f"{_fmt(s.get('sum', 0.0))}")
                lines.append(
                    f"{prom}_count{_prom_labels(labels)} "
                    f"{int(s.get('count', 0))}")
            else:
                lines.append(
                    f"{prom}{_prom_labels(labels)} "
                    f"{_fmt(s.get('value', 0.0))}")
    return "\n".join(lines) + "\n"


def merge_traces(docs: Dict[str, Optional[dict]],
                 metadata: Optional[dict] = None) -> dict:
    """Concatenate Chrome trace documents into ONE trace. Valid by
    construction: every process's tracer pins timestamps to the wall
    epoch and stamps its own pid, so merged events share a timeline and
    render as distinct process tracks (docs/OBSERVABILITY.md). ``docs``
    maps member id to its ``/debug/spans`` payload (None = scrape
    failure, recorded as a gap)."""
    events: list = []
    sources: dict = {}
    gaps: list = []
    for member in sorted(docs):
        doc = docs[member]
        member_events = (doc or {}).get("traceEvents")
        if not isinstance(member_events, list):
            gaps.append(member)
            continue
        sources[member] = len(member_events)
        events.extend(member_events)
    meta = {"sources": sources, "gaps": gaps}
    if metadata:
        meta.update(metadata)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": meta}
