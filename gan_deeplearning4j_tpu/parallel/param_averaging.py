"""Synchronous parameter averaging over the device mesh (SURVEY §2.2 D16).

The reference's distributed trainer is DL4J's
``ParameterAveragingTrainingMaster`` (dl4jGANComputerVision.java:325-330):
broadcast params to workers → each worker fits ``batchSizePerWorker``-sized
minibatches locally → every ``averagingFrequency`` minibatches, average
params *and updater state* arithmetically across workers (the map-reduce
formula in gan.ipynb cell 3). Spark ships serialized DataSets and params
between JVMs; here the workers are mesh shards and the averaging is a
``lax.pmean`` over ICI inside one compiled program — no driver, no
serialization, no temp files (the ``deleteTempFiles`` chore at :620
disappears by construction).

Semantics note (SURVEY §7 hard parts): averaging params every k steps is
NOT equivalent to per-step gradient all-reduce — workers' params diverge
for k local RmsProp steps before the mean. Both modes exist here:
per-step gradient sync is :class:`~gan_deeplearning4j_tpu.parallel.trainer.
GraphTrainer` on a mesh; this class is the faithful k-step averaging.

Update-sharding note (parallel/update_sharding.py): cross-replica
weight-update sharding does NOT apply to this trainer, by construction —
between averaging boundaries every worker holds deliberately DIVERGENT
local updater state (that divergence is the algorithm), so there is no
replicated, redundantly-applied update to shard. The config layer rejects
``update_sharding=True`` with ``distributed='param_averaging'``; only the
per-step ``pmean`` path has the redundancy the optimization removes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

# modern jax exports shard_map at the top level; 0.4.x kept it under
# jax.experimental — accept both so older-wheel CPU containers (CI) import
# the same code path the TPU rig runs on the new wheel
try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - depends on installed jax wheel
    from jax.experimental.shard_map import shard_map as _shard_map

from gan_deeplearning4j_tpu.optim.optimizer import GraphOptimizer
from gan_deeplearning4j_tpu.parallel.trainer import TrainState, make_train_state


if hasattr(jax.lax, "pcast"):
    def _to_varying(x, axis_name: str):
        """Mark a replicated value as worker-varying for shard_map's
        replication checker (a type-system cast — runtime no-op)."""
        return jax.lax.pcast(x, axis_name, to="varying")

    _SHARD_MAP_COMPAT: Dict[str, Any] = {}
else:  # pragma: no cover - older wheel (no pcast): can't annotate the
    # replicated->varying carry transition, so disable the rep checker
    # instead; the compiled math is identical either way
    def _to_varying(x, axis_name: str):
        return x

    _SHARD_MAP_COMPAT = {"check_rep": False}


def _average_tree(tree, axis_name: str):
    """Arithmetic mean across workers. Integer leaves (e.g. Adam's step
    counter) are identical on every worker by construction — pmax keeps the
    value while marking it replicated."""

    def avg(x):
        if jnp.issubdtype(x.dtype, jnp.integer):
            return jax.lax.pmax(x, axis_name)
        return jax.lax.pmean(x, axis_name)

    return jax.tree_util.tree_map(avg, tree)


class ParameterAveragingTrainer:
    """DL4J ``ParameterAveragingTrainingMaster`` + ``SparkComputationGraph``
    as one shard_map'd XLA program per averaging round.

    One *round* = every worker runs ``averaging_frequency`` local optimizer
    steps on its own ``batch_size_per_worker``-sized minibatches (params
    diverging, exactly like Spark executors), then the mean of params and
    updater state is taken over the mesh ``data`` axis.
    """

    def __init__(
        self,
        graph,
        mesh: jax.sharding.Mesh,
        batch_size_per_worker: int = 200,
        averaging_frequency: int = 10,
        data_axis: str = "data",
    ):
        if averaging_frequency < 1:
            raise ValueError("averaging_frequency must be >= 1")
        if batch_size_per_worker < 1:
            raise ValueError("batch_size_per_worker must be >= 1")
        self.graph = graph
        self.optimizer = GraphOptimizer(graph)
        self.mesh = mesh
        self.data_axis = data_axis
        self.batch_size_per_worker = int(batch_size_per_worker)
        self.averaging_frequency = int(averaging_frequency)
        self.num_workers = int(mesh.shape[data_axis])
        self._round_fns: Dict[int, Any] = {}
        self._rounds_fns: Dict[Tuple[int, int, int], Any] = {}

    # -- sizing -------------------------------------------------------------
    @property
    def round_examples(self) -> int:
        """Rows consumed per full round: workers × frequency × local batch."""
        return self.num_workers * self.averaging_frequency * self.batch_size_per_worker

    def init_state(self, seed: Optional[int] = None, params: Optional[Dict] = None) -> TrainState:
        return make_train_state(self.graph, self.optimizer, self.mesh, seed, params)

    # -- the round ----------------------------------------------------------
    def _build_round(self, freq: int, b: int):
        axis = self.data_axis

        def local_fit(state: TrainState, feats, labels, rng):
            """One worker's local fit: ``freq`` sequential optimizer steps on
            its shard — the executor-side ``ComputationGraph.fit`` of §3.3."""
            feats = feats.reshape((freq, b) + feats.shape[1:])
            labels = labels.reshape((freq, b) + labels.shape[1:])
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis))

            def body(carry, minibatch):
                params, opt_state = carry
                mb_feats, mb_labels, mb_rng = minibatch

                def loss_fn(p):
                    loss, (_, new_p) = self.graph.loss(
                        p, mb_feats, mb_labels, train=True, rng=mb_rng
                    )
                    return loss, new_p

                (loss, new_params), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
                params, opt_state = self.optimizer.step(new_params, grads, opt_state)
                return (params, opt_state), loss

            keys = jax.random.split(rng, freq)
            # the replicated broadcast params become worker-varying once they
            # absorb sharded-data gradients; mark the carry as such up front
            carry0 = jax.tree_util.tree_map(
                lambda x: _to_varying(x, axis),
                (state.params, state.opt_state),
            )
            (params, opt_state), losses = jax.lax.scan(body, carry0, (feats, labels, keys))
            # the averaging step — the whole distributed algorithm is here
            params = _average_tree(params, axis)
            opt_state = _average_tree(opt_state, axis)
            return (
                TrainState(params, opt_state, state.step + freq),
                jax.lax.pmean(losses, axis),
            )

        mapped = _shard_map(
            local_fit,
            mesh=self.mesh,
            in_specs=(P(), P(axis), P(axis), P()),
            out_specs=(P(), P()),
            **_SHARD_MAP_COMPAT,
        )
        return jax.jit(mapped, donate_argnums=(0,))

    def _build_rounds(self, k: int, freq: int, b: int):
        """K whole averaging rounds in ONE dispatch (round-4 VERDICT item 5):
        an outer ``lax.scan`` over rounds wraps the inner per-round scan of
        local steps, all inside one shard_map program — the averaging-mode
        analog of ``GanExperiment.train_iterations``. Per-round dispatch
        latency (milliseconds of host→TPU round trip each) previously made
        the faithful mode the only unscanned hot path."""
        axis = self.data_axis

        def local_rounds(state: TrainState, feats, labels, rng):
            # local shapes after shard_map: (k, freq*b, …) per worker
            feats = feats.reshape((k, freq, b) + feats.shape[2:])
            labels = labels.reshape((k, freq, b) + labels.shape[2:])
            # mirror fit()'s caller-side chain: rng_i = split(rng)[1] per
            # round, so K scanned rounds consume the EXACT key sequence K
            # sequential fit_round calls would (tested bit-identical)
            round_keys = []
            for _ in range(k):
                rng, sub = jax.random.split(rng)
                round_keys.append(sub)
            round_keys = jnp.stack(round_keys)

            def step_body(carry, minibatch):
                params, opt_state = carry
                mb_feats, mb_labels, mb_rng = minibatch

                def loss_fn(p):
                    loss, (_, new_p) = self.graph.loss(
                        p, mb_feats, mb_labels, train=True, rng=mb_rng
                    )
                    return loss, new_p

                (loss, new_params), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params)
                params, opt_state = self.optimizer.step(new_params, grads, opt_state)
                return (params, opt_state), loss

            def round_body(carry, xs):
                f, l, key = xs
                keys = jax.random.split(
                    jax.random.fold_in(key, jax.lax.axis_index(axis)), freq
                )
                carry, losses = jax.lax.scan(step_body, carry, (f, l, keys))
                params = _average_tree(carry[0], axis)
                opt_state = _average_tree(carry[1], axis)
                # the averaged values are replicated in VALUE, but the outer
                # scan needs a rep-type-stable carry — keep it varying
                carry = jax.tree_util.tree_map(
                    lambda x: _to_varying(x, axis),
                    (params, opt_state),
                )
                return carry, jax.lax.pmean(losses, axis)

            carry0 = jax.tree_util.tree_map(
                lambda x: _to_varying(x, axis),
                (state.params, state.opt_state),
            )
            (params, opt_state), losses = jax.lax.scan(
                round_body, carry0, (feats, labels, round_keys)
            )
            # every round ends averaged, so the final carry is replicated in
            # value — re-mark it so the P() out_spec's replication holds
            params = _average_tree(params, axis)
            opt_state = _average_tree(opt_state, axis)
            return (
                TrainState(params, opt_state, state.step + k * freq),
                losses,  # (k, freq) per-local-step means
            )

        mapped = _shard_map(
            local_rounds,
            mesh=self.mesh,
            in_specs=(P(), P(None, axis), P(None, axis), P()),
            out_specs=(P(), P()),
            **_SHARD_MAP_COMPAT,
        )
        return jax.jit(mapped, donate_argnums=(0,))

    def fit_rounds(
        self,
        state: TrainState,
        features,
        labels,
        rng=None,
        freq: Optional[int] = None,
        batch_size: Optional[int] = None,
    ) -> Tuple[TrainState, jnp.ndarray]:
        """K averaging rounds in ONE device dispatch. ``features``/``labels``
        are (K, workers × freq × b, …), each round's rows worker-major like
        :meth:`fit_round`. Bit-identical to K sequential ``fit_round`` calls
        chained through ``rng, sub = split(rng)`` (the chain :meth:`fit`
        uses). Returns (state, (K, freq) losses)."""
        freq = self.averaging_frequency if freq is None else freq
        b = self.batch_size_per_worker if batch_size is None else batch_size
        k = int(features.shape[0])
        expected = self.num_workers * freq * b
        if features.shape[1] != expected or labels.shape[1] != expected:
            raise ValueError(
                f"each round expects {expected} rows "
                f"({self.num_workers} workers × {freq} × {b}), got "
                f"features {features.shape[1]} / labels {labels.shape[1]}"
            )
        if rng is None:
            rng = jax.random.PRNGKey(int(state.step))
        if (k, freq, b) not in self._rounds_fns:
            self._rounds_fns[(k, freq, b)] = self._build_rounds(k, freq, b)
        return self._rounds_fns[(k, freq, b)](state, features, labels, rng)

    def fit_round(
        self,
        state: TrainState,
        features,
        labels,
        rng=None,
        freq: Optional[int] = None,
        batch_size: Optional[int] = None,
    ) -> Tuple[TrainState, jnp.ndarray]:
        """Run one averaging round on ``workers × freq × batch`` rows laid out
        worker-major on axis 0. Returns (state, per-local-step mean losses).
        ``batch_size`` overrides the per-worker batch for tail rounds."""
        freq = self.averaging_frequency if freq is None else freq
        b = self.batch_size_per_worker if batch_size is None else batch_size
        expected = self.num_workers * freq * b
        if features.shape[0] != expected or labels.shape[0] != expected:
            raise ValueError(
                f"round expects {expected} rows "
                f"({self.num_workers} workers × {freq} × {b}), "
                f"got features {features.shape[0]} / labels {labels.shape[0]}"
            )
        if rng is None:
            rng = jax.random.PRNGKey(int(state.step))
        if (freq, b) not in self._round_fns:
            self._round_fns[(freq, b)] = self._build_round(freq, b)
        return self._round_fns[(freq, b)](state, features, labels, rng)

    @staticmethod
    def _worker_major(arr: np.ndarray, freq: int, workers: int, b: int) -> np.ndarray:
        """Regroup a row-major stream into worker-major (worker, freq, b)
        order so each mesh shard sees a contiguous run of minibatches."""
        used = freq * workers * b
        return (
            arr[:used]
            .reshape((freq, workers, b) + arr.shape[1:])
            .swapaxes(0, 1)
            .reshape((used,) + arr.shape[1:])
        )

    def fit(
        self, state: TrainState, iterator, rng=None
    ) -> Tuple[TrainState, List[float]]:
        """Consume a DataSetIterator in averaging rounds (the
        ``sparkGraph.fit(rdd)`` surface). Full rounds run at exactly
        ``averaging_frequency``; leftovers run as one tail round at reduced
        frequency and/or reduced per-worker batch. A final ragged tail is
        padded by cycling its own rows so every example trains — no data is
        silently dropped (DL4J likewise trains uneven worker splits)."""
        losses: List[float] = []
        if rng is None:
            rng = jax.random.PRNGKey(int(state.step))
        rows = self.num_workers * self.batch_size_per_worker
        # chunk lists, concatenated only when a round's worth has accumulated
        buf_f: List[np.ndarray] = []
        buf_l: List[np.ndarray] = []
        buffered = 0

        def run_round(state, rng, feats, labs, freq, b):
            f = self._worker_major(feats, freq, self.num_workers, b)
            l = self._worker_major(labs, freq, self.num_workers, b)
            rng, sub = jax.random.split(rng)
            state, round_losses = self.fit_round(
                state, jnp.asarray(f), jnp.asarray(l), sub, freq, b
            )
            losses.extend(float(x) for x in round_losses)
            return state, rng

        def drain_full(state, rng):
            """Run every buffered FULL round. Multiple rounds go through
            fit_rounds as ONE scanned dispatch (round-4 device loop); its
            internal key chain is exactly the split(rng)-per-round sequence
            used here, so the caller advances rng by k splits to stay
            aligned with the sequential path (bit-compatible either way)."""
            nonlocal buf_f, buf_l, buffered
            feats = np.concatenate(buf_f, axis=0) if len(buf_f) > 1 else buf_f[0]
            labs = np.concatenate(buf_l, axis=0) if len(buf_l) > 1 else buf_l[0]
            k = feats.shape[0] // self.round_examples
            if k > 1:
                used = k * self.round_examples
                freq, b = self.averaging_frequency, self.batch_size_per_worker

                def regroup(arr: np.ndarray) -> np.ndarray:
                    # vectorized per-round worker-major regroup — one pass,
                    # no k temporaries (same layout as _worker_major applied
                    # to each round slice then stacked)
                    return (
                        arr[:used]
                        .reshape((k, freq, self.num_workers, b) + arr.shape[1:])
                        .swapaxes(1, 2)
                        .reshape((k, self.round_examples) + arr.shape[1:])
                    )

                state, round_losses = self.fit_rounds(
                    state, jnp.asarray(regroup(feats)), jnp.asarray(regroup(labs)), rng
                )
                losses.extend(float(x) for x in np.asarray(round_losses).ravel())
                for _ in range(k):  # keep the caller's chain aligned
                    rng, _ = jax.random.split(rng)
                feats, labs = feats[used:], labs[used:]
            elif k == 1:
                state, rng = run_round(
                    state, rng, feats, labs,
                    self.averaging_frequency, self.batch_size_per_worker,
                )
                feats, labs = (
                    feats[self.round_examples:], labs[self.round_examples:]
                )
            buf_f = [feats] if feats.shape[0] else []
            buf_l = [labs] if labs.shape[0] else []
            buffered = feats.shape[0]
            return state, rng

        while iterator.has_next():
            batch = iterator.next()
            buf_f.append(np.asarray(batch.features))
            buf_l.append(np.asarray(batch.labels))
            buffered += batch.num_examples()
            if buffered >= self.round_examples:
                state, rng = drain_full(state, rng)

        if buffered > 0:
            feats = np.concatenate(buf_f, axis=0) if len(buf_f) > 1 else buf_f[0]
            labs = np.concatenate(buf_l, axis=0) if len(buf_l) > 1 else buf_l[0]
            n = feats.shape[0]
            # shorter-frequency tail at the standard per-worker batch
            freq = n // rows
            if freq >= 1:
                used = freq * rows
                state, rng = run_round(
                    state, rng, feats, labs, freq, self.batch_size_per_worker
                )
                feats, labs, n = feats[used:], labs[used:], n - used
            # Ragged tail: shrink the per-worker batch and pad by cycling
            # rows. Weighting note: the < num_workers padded rows are trained
            # twice at full weight in this final partial round — a bounded
            # skew analogous to DL4J's uneven worker splits (the reference's
            # TrainingMaster repartitions without per-row weighting either).
            # Masking inside the scanned program would buy exactness at the
            # cost of a second compiled round shape; with duplication bounded
            # by num_workers-1 rows out of >= num_workers, the skew is < one
            # worker-batch in 10^3 at reference scale — documented, not
            # corrected.
            if n > 0:
                b = max(1, -(-n // self.num_workers))  # ceil
                need = self.num_workers * b
                if need > n:
                    idx = np.arange(need) % n
                    feats, labs = feats[idx], labs[idx]
                state, rng = run_round(state, rng, feats, labs, 1, b)
        return state, losses
