"""Cross-replica weight-update sharding (ROADMAP mesh-scale compute half).

Implements "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (PAPERS.md) for the data-parallel trainer: instead
of every mesh shard redundantly applying the identical full optimizer
update after the gradient all-reduce, the flat param/updater key space is
partitioned across the ``data`` axis, each shard applies the update only
for the keys it owns, and the updated params are all-gathered back to
replicated. Gradients then only need to be *reduce-scattered* (each shard
needs the summed gradient for its keys alone), and the resident updater
state drops to ~1/N per device — PROFILE.md puts trainable state at
13-20% of HBM traffic, so this is both an HBM and a step-time lever.

Two design anchors:

- **The partition is the checkpoint partition.** Ownership of a key is
  :func:`gan_deeplearning4j_tpu.utils.serializer.shard_assignment`
  evaluated on the sorted global flat key namespace — the deterministic
  size-balanced partition the mesh checkpoint plane's
  ``serializer.shard_keys`` writes shard files with (both sides derive it
  from the same flat state, so N processes agree without communicating).
  A compute shard therefore owns the same updater keys as the checkpoint
  shard of the same index: shard files map 1:1 onto compute shards with
  **no format change** (restore merges shards regardless of membership,
  so pre-existing round-robin generations keep restoring), and elastic
  reshard-on-restore stays a pure re-grouping in both directions.
- **The sharding is expressed, not hand-rolled.** Owned keys are packed
  into one ``(num_shards, width)`` row matrix per updater-spec group,
  placed with ``NamedSharding(mesh, P(data))`` so row *k* lives on shard
  *k*. The update math runs on the rows under that constraint; XLA's SPMD
  partitioner then materializes the comms — the replicated->rows
  transition after the gradient reduction is each shard slicing its own
  row (the reduce-scatter seam the paper's XLA pass targets), and the
  rows->replicated transition on the new params is the all-gather. This is
  the annotation-driven formulation of the paper, which is itself an XLA
  pass, not a hand-written collective schedule.

Exactness contract (docs/RESILIENCE.md, update-sharding section):
packing is reshape/slice/concat/pad and the in-tree updaters are
elementwise, so with ``exact_grads`` (default) pinning the backward
replicated, GRADS AND UPDATER STATE are proven digest-exact against the
replicated :class:`~gan_deeplearning4j_tpu.optim.optimizer.GraphOptimizer`
path at mesh 1/2/4 on forced host devices. Params track within a few
ulps per step: XLA selects divide/rsqrt and fma forms for the delta per
program shape, a codegen variance no annotation controls — and GAN
dynamics amplify any ulp chaotically across iterations, so cross-MODE
experiment parity is tolerance-based (tested at one fused iteration).
Within-mode determinism and the supervisor's bit-exact RESUME contract
are untouched (resume compares a program against itself). Checkpoint
pack/unpack round-trips are bit-exact in both directions at any
mesh-size pair. ``exact_grads=False`` additionally lets GSPMD shard the
backward itself (partial-grad sub-contractions + reduce-scatter — the
paper's full comms win) at the price of reassociated grad reductions —
the mode to measure on chip.

Multi-field updater state (Adam's m/v/t) is owned as a unit by the owner
of the param's FIRST state key in sorted order; scalar fields (Adam's t)
are stored broadcast per element so every update stays elementwise.
Single-field updaters (RmsProp — the reference's only optimizer) and
stateless ones map 1:1 onto the checkpoint key partition exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from gan_deeplearning4j_tpu.optim.optimizer import GraphOptimizer


@dataclasses.dataclass(frozen=True)
class _Slot:
    """One piece of a trainable param leaf in the packed row layout.

    Small leaves are one whole-leaf piece owned by the checkpoint
    partition's shard (``start``/``stop`` span the leaf). Leaves bigger
    than the group's split threshold are element-split into one piece per
    shard — a single 6.4M-element dense kernel is 59% of the reference
    model's updater bytes, so whole-leaf ownership alone could never
    approach the 1/N residency target."""

    key: str                 # flat param key: <model>/params/<layer>/<pname>
    layer: str
    pname: str
    shape: Tuple[int, ...]
    start: int               # element range [start, stop) of the flat leaf
    stop: int
    row: int                 # owning shard index
    offset: int              # start position within (row, group)
    split: bool              # True when the leaf is element-split
    state_keys: Tuple[str, ...]  # flat updater keys, sorted (may be empty)

    @property
    def size(self) -> int:
        return self.stop - self.start


@dataclasses.dataclass
class _Group:
    """All slots sharing one (updater spec, param dtype): one packed row
    matrix for params/grads and one per state field."""

    spec: Any                # the UpdaterSpec (frozen dataclass, hashable)
    dtype: Any               # param/grad dtype of every slot in the group
    fields: Tuple[str, ...]  # state field names, sorted ("cache"; "m","v","t")
    field_dtypes: Dict[str, Any]
    scalar_fields: frozenset  # fields whose tree form is 0-d (Adam's t)
    slots: List[_Slot] = dataclasses.field(default_factory=list)
    rows: List[List[_Slot]] = dataclasses.field(default_factory=list)
    width: int = 0


def flat_model_keys(model_name: str, params: Dict,
                    optimizer: GraphOptimizer) -> Dict[str, int]:
    """The flat checkpoint key namespace ONE model contributes to
    ``GanExperiment._flat_state()`` as a key -> element-count mapping
    (the partition input): every param leaf, every updater state leaf,
    and the step counter — derived from shapes alone (eval_shape), no
    state materialized."""
    from gan_deeplearning4j_tpu.utils.serializer import (
        _element_count,
        _flatten,
    )

    keys: Dict[str, Any] = {}
    _flatten(f"{model_name}/params", params, keys)
    _flatten(f"{model_name}/updater", optimizer.state_structs(params), keys)
    keys[f"{model_name}/step"] = None
    return {k: _element_count(v) for k, v in keys.items()}


class UpdateShardingPlan:
    """The deterministic partition + packed layout for one model's
    trainable state over the mesh ``data_axis``.

    ``global_keys`` maps every flat key the partition is taken over to
    its element count (the experiment passes its ``_flat_state()``
    namespace so ownership matches the mesh checkpoint shards — both
    sides evaluate :func:`serializer.shard_assignment` on the same
    input); ``None`` derives it from this model alone — the
    standalone-trainer degenerate case, identical to a single-model
    experiment's namespace.
    """

    def __init__(self, graph, optimizer: GraphOptimizer, params: Dict,
                 mesh, data_axis: str = "data", model_name: str = "model",
                 global_keys: Optional[Dict[str, int]] = None,
                 exact_grads: bool = True):
        del graph  # the optimizer carries everything layout needs
        from gan_deeplearning4j_tpu.utils.serializer import shard_assignment

        self.mesh = mesh
        self.data_axis = data_axis
        self.model_name = model_name
        self.num_shards = int(mesh.shape[data_axis])
        self.base = optimizer
        # exact_grads=True pins the gradient tree REPLICATED before the
        # rows are sliced out: the backward then compiles exactly like
        # the replicated baseline's (full grads on every shard — the
        # all-reduce it already pays), and since everything downstream is
        # elementwise on the same bytes, sharded updates are bit-exact
        # against the baseline. False lets GSPMD propagate the row
        # sharding INTO the backward (partial-grad sub-contractions +
        # reduce-scatter — the paper's full comms win), at the price of
        # reassociated reductions: ~1 ulp per step, which GAN dynamics
        # amplify — the documented-tolerance mode for chip measurement.
        self.exact_grads = exact_grads
        if global_keys is None:
            global_keys = flat_model_keys(model_name, params, optimizer)
        assign = shard_assignment(dict(global_keys), self.num_shards)

        structs = optimizer.state_structs(params)
        self._groups: Dict[str, _Group] = {}
        self._slots: List[_Slot] = []
        for layer in sorted(params):
            spec = optimizer.updaters.get(layer)
            if spec is None:
                continue
            for pname in sorted(params[layer]):
                if not optimizer.trainable(layer, pname):
                    continue
                leaf = params[layer][pname]
                shape = tuple(jnp.shape(leaf))
                dtype = jnp.asarray(leaf).dtype if not hasattr(leaf, "dtype") \
                    else leaf.dtype
                field_structs = structs.get(layer, {}).get(pname, {})
                fields = tuple(sorted(field_structs))
                state_keys = tuple(
                    f"{model_name}/updater/{layer}/{pname}/{f}" for f in fields
                )
                anchor = state_keys[0] if state_keys \
                    else f"{model_name}/params/{layer}/{pname}"
                if anchor not in assign:
                    raise ValueError(
                        f"update-sharding anchor key {anchor!r} is missing "
                        f"from the global flat key list — the partition "
                        f"would disagree with the checkpoint plane")
                slot = _Slot(
                    key=f"{model_name}/params/{layer}/{pname}",
                    layer=layer, pname=pname, shape=shape,
                    start=0, stop=max(1, int(jnp.size(leaf))),
                    row=assign[anchor],
                    offset=-1,  # assigned per group below
                    split=False,
                    state_keys=state_keys,
                )
                gid = f"{spec.kind}|{repr(spec)}|{jnp.dtype(dtype).name}"
                group = self._groups.get(gid)
                if group is None:
                    group = _Group(
                        spec=spec, dtype=jnp.dtype(dtype), fields=fields,
                        field_dtypes={
                            f: jnp.dtype(field_structs[f].dtype)
                            for f in fields
                        },
                        scalar_fields=frozenset(
                            f for f in fields
                            if len(field_structs[f].shape) == 0
                        ),
                    )
                    self._groups[gid] = group
                group.slots.append(slot)

        # Element-split oversized leaves: a leaf above the group's split
        # threshold becomes one contiguous piece per shard. Whole-leaf
        # ownership keeps the 1:1 checkpoint mapping for everything
        # below the threshold; splitting is what bounds the widest row
        # (per-device residency) at ~group_total/N + threshold.
        n = self.num_shards
        for group in self._groups.values():
            total = sum(s.size for s in group.slots)
            threshold = max(1024, -(-total // (4 * n)))
            pieces: List[_Slot] = []
            for slot in group.slots:
                if n > 1 and slot.size > threshold:
                    chunk = -(-slot.size // n)  # ceil
                    for j in range(n):
                        lo, hi = j * chunk, min((j + 1) * chunk, slot.size)
                        if lo >= hi:
                            continue
                        pieces.append(dataclasses.replace(
                            slot, start=lo, stop=hi, row=j, split=True))
                else:
                    pieces.append(slot)
            group.slots = pieces

        # row layout: per group, the pieces owned by each shard in sorted
        # (key, start) order, offsets cumulative, rows padded to the
        # widest shard
        for group in self._groups.values():
            rows: List[List[_Slot]] = [[] for _ in range(self.num_shards)]
            for slot in sorted(group.slots, key=lambda s: (s.key, s.start)):
                row = rows[slot.row]
                offset = sum(s.size for s in row)
                row.append(dataclasses.replace(slot, offset=offset))
            group.rows = rows
            group.slots = [s for row in rows for s in row]
            group.width = max(
                1, max(sum(s.size for s in row) for row in rows))
            self._slots.extend(group.slots)
        self._gids = sorted(self._groups)

    # -- shardings ---------------------------------------------------------
    def rows_sharding(self) -> NamedSharding:
        """Row *k* of every packed matrix lives on shard *k* of the data
        axis — the placement JG013/JG018 police (the axis name is the
        plan's, never a copy-pasted literal)."""
        return NamedSharding(self.mesh, PartitionSpec(self.data_axis))

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def state_shardings(self) -> "PackedOptState":
        rows = self.rows_sharding()
        return PackedOptState(
            {gid: {f: rows for f in self._groups[gid].fields}
             for gid in self._gids},
            self,
        )

    # -- partition introspection ------------------------------------------
    def updater_keys_for_shard(self, shard: int) -> List[str]:
        """Flat updater state keys WHOLLY resident on compute shard
        ``shard`` — the set the 1:1 checkpoint-mapping tests compare
        against ``serializer.shard_keys`` (element-split keys span every
        shard and are listed by :meth:`element_split_state_keys`)."""
        out = []
        for gid in self._gids:
            for slot in self._groups[gid].rows[shard]:
                if not slot.split:
                    out.extend(slot.state_keys)
        return sorted(out)

    def element_split_state_keys(self) -> List[str]:
        """Updater keys whose leaves are element-split across every shard
        (each shard holds one contiguous slice) — the leaves too big for
        whole-leaf balance; their checkpoint bytes are written merged by
        whichever worker the key partition assigns them to."""
        return sorted({k for s in self._slots if s.split
                       for k in s.state_keys})

    def describe(self) -> Dict:
        """Layout summary for bench records: shard counts, per-group
        widths, split keys, and the padding overhead of the row layout."""
        groups = {}
        for gid in self._gids:
            g = self._groups[gid]
            used = [sum(s.size for s in row) for row in g.rows]
            groups[gid] = {
                "kind": g.spec.kind,
                "fields": list(g.fields),
                "width": g.width,
                "rows_used": used,
                "split_keys": sorted({s.key for s in g.slots if s.split}),
                "padding_fraction": (
                    1.0 - (sum(used) / float(g.width * self.num_shards))
                ),
            }
        return {
            "model": self.model_name,
            "num_shards": self.num_shards,
            "data_axis": self.data_axis,
            "exact_grads": self.exact_grads,
            "groups": groups,
        }

    def _pieces_by_key(self, group: _Group) -> Dict[str, List[_Slot]]:
        by_key: Dict[str, List[_Slot]] = {}
        for slot in group.slots:
            by_key.setdefault(slot.key, []).append(slot)
        return {k: sorted(v, key=lambda s: s.start)
                for k, v in by_key.items()}

    # -- packing -----------------------------------------------------------
    def _pack_rows(self, group: _Group, leaf_of: Callable[[_Slot], Any],
                   dtype) -> jnp.ndarray:
        """(num_shards, width) row matrix: row k = the flattened leaf
        pieces shard k owns, in sorted (key, start) order, zero-padded to
        the group width. ``leaf_of`` returns the FULL leaf (or a scalar);
        piece slicing happens here. Pure reshape/slice/concat/pad —
        exact, and cheap enough for XLA to fuse away."""
        rows = []
        for row_slots in group.rows:
            parts = []
            for slot in row_slots:
                leaf = jnp.asarray(leaf_of(slot), dtype)
                if leaf.ndim == 0:
                    # scalar state (Adam's t): stored broadcast per element
                    # so the update stays elementwise
                    parts.append(jnp.broadcast_to(leaf, (slot.size,)))
                else:
                    parts.append(leaf.reshape(-1)[slot.start:slot.stop])
            used = sum(s.size for s in row_slots)
            if parts:
                row = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
                if used < group.width:
                    row = jnp.pad(row, (0, group.width - used))
            else:
                row = jnp.zeros((group.width,), dtype)
            rows.append(row)
        return jnp.stack(rows)

    def init_packed(self, params: Dict) -> "PackedOptState":
        """Fresh packed state straight from the optim layer's shard-slice
        init (:meth:`UpdaterSpec.init_state_packed`) — bit-identical
        values to packing the replicated tree init, without ever
        materializing the full replicated state tree."""
        per_key: Dict[str, Dict[str, Any]] = {}
        for gid in self._gids:
            group = self._groups[gid]
            for slot in group.slots:
                if slot.key not in per_key:
                    flat = jnp.asarray(
                        params[slot.layer][slot.pname],
                        group.dtype).reshape(-1)
                    per_key[slot.key] = group.spec.init_state_packed(flat)
        groups = {}
        for gid in self._gids:
            group = self._groups[gid]
            groups[gid] = {
                field: self._pack_rows(
                    group,
                    lambda s, f=field: per_key[s.key][f],
                    group.field_dtypes[field],
                )
                for field in group.fields
            }
        return PackedOptState(groups, self)

    def pack_state(self, opt_state: Dict) -> "PackedOptState":
        """Tree-form updater state -> packed rows. The inverse of
        :meth:`unpack_state` up to zero padding; packing a tree and
        unpacking it back is bit-exact (elastic-restore property)."""
        groups = {}
        for gid in self._gids:
            group = self._groups[gid]
            groups[gid] = {
                field: self._pack_rows(
                    group,
                    lambda s, f=field: opt_state[s.layer][s.pname][f],
                    group.field_dtypes[field],
                )
                for field in group.fields
            }
        return PackedOptState(groups, self)

    def unpack_state(self, packed: "PackedOptState") -> Dict:
        """Packed rows -> the tree form GraphOptimizer.init produces —
        what checkpoints serialize (no format change) and digests are
        taken over."""
        state: Dict = {}
        for gid in self._gids:
            group = self._groups[gid]
            for pieces in self._pieces_by_key(group).values():
                first = pieces[0]
                entry = {}
                for field in group.fields:
                    rows = packed.groups[gid][field]
                    if field in group.scalar_fields:
                        entry[field] = rows[first.row, first.offset]
                    else:
                        segs = [rows[p.row, p.offset:p.offset + p.size]
                                for p in pieces]
                        flat = segs[0] if len(segs) == 1 \
                            else jnp.concatenate(segs)
                        entry[field] = flat.reshape(first.shape)
                state.setdefault(first.layer, {})[first.pname] = entry
        # stateless updaters still own an (empty) entry in the tree form
        for slot in self._slots:
            state.setdefault(slot.layer, {}).setdefault(slot.pname, {})
        return state

    # -- the sharded update -----------------------------------------------
    def apply_update(self, params: Dict, grads: Dict,
                     packed: "PackedOptState",
                     lr_scale=None) -> Tuple[Dict, "PackedOptState"]:
        """The sharded replacement for ``GraphOptimizer.step``: clip (same
        math, replicated), reduce-scatter the gradients into owned rows,
        update locally with the sharded state, all-gather the params.

        Must run inside jit on the plan's mesh (the sharding constraints
        are the whole point). Per-element math is GraphOptimizer.step's
        exactly — every in-tree updater is elementwise."""
        base = self.base
        grads = base.clip_grads(grads)
        rows_spec = self.rows_sharding()
        constrain = jax.lax.with_sharding_constraint
        if self.exact_grads:
            rep = self.replicated_sharding()
            grads = jax.tree_util.tree_map(
                lambda g: constrain(g, rep), grads)
        new_params = {layer: dict(v) for layer, v in params.items()}
        new_groups: Dict[str, Dict[str, Any]] = {}
        upd_by_gid: Dict[str, Any] = {}
        for gid in self._gids:
            group = self._groups[gid]
            # the replicated->rows transition on summed grads is each
            # shard slicing its own row: the reduce-scatter seam
            g_rows = constrain(
                self._pack_rows(
                    group, lambda s: grads[s.layer][s.pname], group.dtype),
                rows_spec)
            p_rows = constrain(
                self._pack_rows(
                    group, lambda s: params[s.layer][s.pname], group.dtype),
                rows_spec)
            state = {f: constrain(packed.groups[gid][f], rows_spec)
                     for f in group.fields}
            delta, new_state = group.spec.apply(state, g_rows, p_rows)
            if lr_scale is not None:
                # cast like GraphOptimizer.step: an f32 scale on a bf16
                # delta would silently promote params out of bf16 storage
                delta = delta * jnp.asarray(lr_scale, delta.dtype)
            upd_by_gid[gid] = p_rows - delta
            new_groups[gid] = {
                f: constrain(new_state[f], rows_spec) for f in group.fields
            }

        # THE param all-gather — exactly ONE collective per dtype per
        # optimizer step: the groups' updated row matrices are
        # concatenated along the width axis before the replicate
        # constraint, and every leaf slice afterwards is device-local.
        # Lesser shapes measured slower on the CPU container (collectives
        # are sync points across device threads sharing two cores): one
        # gather per LEAF ~1.4x step time, one per GROUP still ~1.2x.
        by_dtype: Dict[Any, List[str]] = {}
        for gid in self._gids:
            by_dtype.setdefault(self._groups[gid].dtype, []).append(gid)
        for dtype, gids in by_dtype.items():
            cat = upd_by_gid[gids[0]] if len(gids) == 1 \
                else jnp.concatenate([upd_by_gid[g] for g in gids], axis=1)
            cat = constrain(cat, self.replicated_sharding())
            col = 0
            for gid in gids:
                group = self._groups[gid]
                upd_full = cat[:, col:col + group.width]
                col += group.width
                for pieces in self._pieces_by_key(group).values():
                    first = pieces[0]
                    segs = [upd_full[p.row, p.offset:p.offset + p.size]
                            for p in pieces]
                    flat = segs[0] if len(segs) == 1 \
                        else jnp.concatenate(segs)
                    new_params[first.layer][first.pname] = flat.reshape(
                        first.shape)
        # non-trainable leaves (BN running stats) were already replicated
        # and pass through from the forward's new_params untouched; the
        # jit out_shardings pin the whole tree replicated
        return new_params, PackedOptState(new_groups, self)


@jax.tree_util.register_pytree_node_class
class PackedOptState:
    """The packed sharded updater state: ``{group id: {field: (N, width)
    rows}}`` with the plan as static aux data (identity-hashed, so jit
    caches per plan — one plan per trainer by construction)."""

    def __init__(self, groups: Dict[str, Dict[str, Any]],
                 plan: UpdateShardingPlan):
        self.groups = groups
        self.plan = plan

    def tree_flatten(self):
        return (self.groups,), self.plan

    @classmethod
    def tree_unflatten(cls, plan, children):
        return cls(children[0], plan)

    def __repr__(self) -> str:
        return (f"PackedOptState(model={self.plan.model_name!r}, "
                f"shards={self.plan.num_shards}, "
                f"groups={sorted(self.groups)})")


class ShardedGraphOptimizer:
    """Drop-in for :class:`GraphOptimizer` whose state is the packed
    sharded layout. ``init``/``step`` keep the base signatures so the
    fused iteration body and the scan device loop run unchanged; ``base``
    is the wrapped replicated optimizer (serialization and elastic
    restore re-init through it — tree form is the checkpoint contract)."""

    def __init__(self, plan: UpdateShardingPlan):
        self.plan = plan
        self.base = plan.base

    def trainable(self, layer: str, pname: str) -> bool:
        return self.base.trainable(layer, pname)

    @property
    def updaters(self):
        return self.base.updaters

    def init(self, params: Dict) -> PackedOptState:
        """Packed state with the SAME values the replicated init produces
        (shard-slice init per slot, then pack), so fresh sharded and
        replicated runs start from identical bytes."""
        return self.plan.init_packed(params)

    def step(self, params: Dict, grads: Dict, opt_state: PackedOptState,
             lr_scale=None) -> Tuple[Dict, PackedOptState]:
        return self.plan.apply_update(params, grads, opt_state,
                                      lr_scale=lr_scale)
