"""Distributed training — the TPU-native replacement for the reference's
Spark layer (SURVEY §2.2 D15-D16, §2.3, §2.4).

The reference scales with a Spark driver shipping serialized DataSets to
JVM workers and averaging parameters through the driver
(`SparkComputationGraph` + `ParameterAveragingTrainingMaster`,
dl4jGANComputerVision.java:317-333). Here the "cluster" is a
``jax.sharding.Mesh`` of TPU chips on ICI and the communication backend is
XLA collectives:

- :class:`GraphTrainer` — jitted train step; given a mesh, the batch is
  sharded over the ``data`` axis and params are replicated, so XLA inserts
  the gradient/batch-stat all-reduces over ICI automatically (per-step
  gradient synchronization — the averaging_frequency→1 limit).
- :class:`ParameterAveragingTrainer` — explicit ``shard_map`` reproduction of
  the reference's sync parameter averaging: each mesh shard fits
  ``averaging_frequency`` minibatches locally (divergent local params),
  then params *and updater state* are arithmetically averaged with
  ``lax.pmean`` (the map-reduce of gan.ipynb cell 3).
"""

from gan_deeplearning4j_tpu.parallel.trainer import GraphTrainer, TrainState
from gan_deeplearning4j_tpu.parallel.param_averaging import ParameterAveragingTrainer

__all__ = ["GraphTrainer", "TrainState", "ParameterAveragingTrainer"]
