"""Distributed training — the TPU-native replacement for the reference's
Spark layer (SURVEY §2.2 D15-D16, §2.3, §2.4).

The reference scales with a Spark driver shipping serialized DataSets to
JVM workers and averaging parameters through the driver
(`SparkComputationGraph` + `ParameterAveragingTrainingMaster`,
dl4jGANComputerVision.java:317-333). Here the "cluster" is a
``jax.sharding.Mesh`` of TPU chips on ICI and the communication backend is
XLA collectives:

- :class:`GraphTrainer` — jitted train step; given a mesh, the batch is
  sharded over the ``data`` axis and params are replicated, so XLA inserts
  the gradient/batch-stat all-reduces over ICI automatically (per-step
  gradient synchronization — the averaging_frequency→1 limit).
- :class:`ParameterAveragingTrainer` — explicit ``shard_map`` reproduction of
  the reference's sync parameter averaging: each mesh shard fits
  ``averaging_frequency`` minibatches locally (divergent local params),
  then params *and updater state* are arithmetically averaged with
  ``lax.pmean`` (the map-reduce of gan.ipynb cell 3).
- :mod:`~gan_deeplearning4j_tpu.parallel.update_sharding` — cross-replica
  weight-update sharding for :class:`GraphTrainer` (``shard_updates=``):
  reduce-scatter grads, apply the optimizer update only for the keys each
  shard owns (updater state resident at ~1/N per device), all-gather the
  params. The key partition is the mesh checkpoint plane's round-robin,
  so checkpoint shard files map 1:1 onto compute shards.
"""

from gan_deeplearning4j_tpu.parallel.trainer import GraphTrainer, TrainState
from gan_deeplearning4j_tpu.parallel.param_averaging import ParameterAveragingTrainer
from gan_deeplearning4j_tpu.parallel.update_sharding import (
    PackedOptState,
    ShardedGraphOptimizer,
    UpdateShardingPlan,
)

__all__ = [
    "GraphTrainer",
    "TrainState",
    "ParameterAveragingTrainer",
    "PackedOptState",
    "ShardedGraphOptimizer",
    "UpdateShardingPlan",
]
