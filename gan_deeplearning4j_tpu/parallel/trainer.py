"""GraphTrainer — the jitted fit loop (SURVEY §2.2 C/D15, §7.6).

Replaces ``SparkComputationGraph.fit(JavaRDD<DataSet>)``
(dl4jGANComputerVision.java:426,471,545) with a single compiled XLA program
per step: forward → loss (+L2) → backward → per-layer updater. On a mesh,
the batch is sharded over the ``data`` axis while params/optimizer state are
replicated; XLA then inserts ``all-reduce`` over ICI for every cross-batch
reduction — the gradient mean *and* BatchNorm's batch statistics (sync-BN),
with no hand-written collectives. Buffers are donated so params update
in-place in HBM (the workspace/buffer-donation analog of D19).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from gan_deeplearning4j_tpu.optim.optimizer import GraphOptimizer


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class TrainState:
    """Params + per-layer updater state + step counter, as one pytree.

    This is the unit the reference serializes per iteration
    (``ModelSerializer.writeModel(…, saveUpdater=true)``,
    dl4jGANComputerVision.java:605-619) and what the parameter-averaging
    master broadcasts/averages.
    """

    params: Dict
    opt_state: Dict
    step: jnp.ndarray

    def tree_flatten(self):
        return (self.params, self.opt_state, self.step), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)


def make_train_state(
    graph, optimizer: GraphOptimizer, mesh=None, seed=None, params=None
) -> TrainState:
    """Fresh TrainState (step 0), replicated over the mesh when given —
    shared by all trainer front ends."""
    if params is None:
        params = graph.init(seed)
    state = TrainState(
        params=params,
        opt_state=optimizer.init(params),
        step=jnp.zeros((), jnp.int32),
    )
    if mesh is not None:
        state = jax.device_put(state, NamedSharding(mesh, P()))
    return state


def state_shardings(state: TrainState, plan) -> TrainState:
    """The placement pytree for a TrainState under update sharding:
    params/step replicated, the packed updater rows split over the data
    axis (row *k* on shard *k*). Used both for ``device_put`` placement
    and as jit in/out shardings."""
    rep = plan.replicated_sharding()
    return TrainState(
        jax.tree_util.tree_map(lambda _: rep, state.params),
        plan.state_shardings(),
        rep,
    )


class GraphTrainer:
    """Single-chip or data-parallel trainer for one ComputationGraph.

    With ``mesh=None`` the step jits for whatever device jax defaults to
    (one TPU chip). With a mesh, ``data_axis`` names the batch-sharded axis;
    parameters stay replicated, which is the right layout at this model
    scale (all-reduce of grads rides ICI; no parameter sharding needed —
    SURVEY §2.3 leaves the ``model`` axis open but unused, as the reference
    has no tensor parallelism).
    """

    def __init__(
        self,
        graph,
        mesh: Optional[jax.sharding.Mesh] = None,
        data_axis: str = "data",
        donate: bool = True,
        shard_updates: bool = False,
        model_name: str = "model",
        global_state_keys=None,
    ):
        self.graph = graph
        self.optimizer = GraphOptimizer(graph)
        self.mesh = mesh
        self.data_axis = data_axis
        self._donate = donate
        if shard_updates and mesh is None:
            raise ValueError("shard_updates requires a mesh — there is no "
                             "data axis to shard the update over")
        self.shard_updates = shard_updates
        self.model_name = model_name
        self._global_state_keys = global_state_keys
        self.plan = None
        # the sharded step's shardings need a plan, and the plan needs
        # param shapes — defer the jit build to the first train_step
        self._step_fn = None if shard_updates else self._build_step(donate)
        self._eval_fn = None

    # -- state --------------------------------------------------------------
    def init_state(self, seed: Optional[int] = None, params: Optional[Dict] = None) -> TrainState:
        if not self.shard_updates:
            return make_train_state(self.graph, self.optimizer, self.mesh, seed, params)
        if params is None:
            params = self.graph.init(seed)
        self._ensure_plan(params)
        return self.place_state(TrainState(
            params=params,
            opt_state=self.optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        ))

    # -- update sharding -----------------------------------------------------
    def _ensure_plan(self, params: Dict) -> None:
        if self.plan is not None:
            return
        from gan_deeplearning4j_tpu.parallel.update_sharding import (
            UpdateShardingPlan,
        )

        self.enable_update_sharding(UpdateShardingPlan(
            self.graph, self.optimizer, params, self.mesh,
            data_axis=self.data_axis, model_name=self.model_name,
            global_keys=self._global_state_keys,
        ))

    def enable_update_sharding(self, plan) -> None:
        """Install an :class:`UpdateShardingPlan` (the experiment passes
        one built over its full multi-model key namespace; standalone use
        derives a single-model plan lazily). Swaps the optimizer for the
        sharded drop-in and invalidates the compiled step."""
        from gan_deeplearning4j_tpu.parallel.update_sharding import (
            ShardedGraphOptimizer,
        )

        if isinstance(self.optimizer, ShardedGraphOptimizer):
            self.optimizer = self.optimizer.base
        self.plan = plan
        self.optimizer = ShardedGraphOptimizer(plan)
        self.shard_updates = True
        self._step_fn = None

    def place_state(self, state: TrainState) -> TrainState:
        """Place a (tree-params, packed-updater) state: params/step
        replicated, packed rows over the data axis."""
        return jax.device_put(state, state_shardings(state, self.plan))

    def _replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self) -> Optional[NamedSharding]:
        """Sharding for incoming batches (leading/batch dim over the data
        axis) — hand this to DevicePrefetchIterator so batches land
        pre-sharded."""
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P(self.data_axis))

    # -- the step ------------------------------------------------------------
    def _loss_fn(self, params, features, labels, rng):
        loss, (_, new_params) = self.graph.loss(
            params, features, labels, train=True, rng=rng
        )
        return loss, new_params

    def _build_step(self, donate: bool, state: Optional[TrainState] = None):
        def step(state: TrainState, features, labels, rng) -> Tuple[TrainState, jnp.ndarray]:
            # Distinct per-step randomness by construction: the step counter
            # is folded into whatever key the caller supplied, so a caller
            # passing a fixed key (train_step's default) still gives
            # dropout-style layers a fresh mask every optimizer step
            # (round-2 VERDICT weak #5).
            rng = jax.random.fold_in(rng, state.step)
            (loss, new_params), grads = jax.value_and_grad(
                self._loss_fn, has_aux=True
            )(state.params, features, labels, rng)
            # new_params carries BN running-stat updates from the forward
            # pass; the optimizer never touches "state"-role params.
            params, opt_state = self.optimizer.step(new_params, grads, state.opt_state)
            return TrainState(params, opt_state, state.step + 1), loss

        kwargs: Dict[str, Any] = {}
        if donate:
            kwargs["donate_argnums"] = (0,)
        if self.mesh is not None:
            rep = self._replicated()
            data = NamedSharding(self.mesh, P(self.data_axis))
            if self.shard_updates and state is not None:
                st = state_shardings(state, self.plan)
                kwargs["in_shardings"] = (st, data, data, rep)
                kwargs["out_shardings"] = (st, rep)
            else:
                kwargs["in_shardings"] = (rep, data, data, rep)
                kwargs["out_shardings"] = (rep, rep)
        return jax.jit(step, **kwargs)

    def train_step(self, state: TrainState, features, labels, rng=None) -> Tuple[TrainState, jnp.ndarray]:
        """One optimizer step. ``rng`` feeds dropout-style layers (unused by
        the reference topologies); the jitted step folds ``state.step`` into
        it, so the default base key still yields per-step masks."""
        if rng is None:
            rng = jax.random.PRNGKey(0)
        if self._step_fn is None:  # sharded mode: shardings need the state
            self._step_fn = self._build_step(self._donate, state)
        return self._step_fn(state, features, labels, rng)

    # -- fit ----------------------------------------------------------------
    def fit(
        self,
        state: TrainState,
        iterator,
        num_batches: Optional[int] = None,
        rng=None,
    ) -> Tuple[TrainState, List[float]]:
        """Consume a DataSetIterator (DL4J ``fit(iterator)``). Returns the
        new state and per-batch losses (host floats, fetched at the end)."""
        losses = []
        seen = 0
        if rng is None:
            rng = jax.random.PRNGKey(int(state.step))
        while iterator.has_next() and (num_batches is None or seen < num_batches):
            batch = iterator.next()
            rng, sub = jax.random.split(rng)
            state, loss = self.train_step(state, batch.features, batch.labels, sub)
            losses.append(loss)
            seen += 1
        return state, [float(l) for l in losses]

    # -- inference ----------------------------------------------------------
    def output(self, state: TrainState, features):
        """Jitted inference forward (DL4J ``graph.output``)."""
        if self._eval_fn is None:
            kwargs = {}
            if self.mesh is not None:
                kwargs["in_shardings"] = (
                    self._replicated(),
                    NamedSharding(self.mesh, P(self.data_axis)),
                )
            self._eval_fn = jax.jit(
                lambda params, x: self.graph.output(params, x, train=False), **kwargs
            )
        return self._eval_fn(state.params, features)
