"""jaxlint phase 1½ — the lifecycle index (paired-resource summaries).

Every hardening round in this repo's history hand-caught the same bug
shape: a paired operation whose second half can be skipped on an exception
path — the engine's replica in-flight ledger needed a release-exactly-once
fix (PR 4), the router's retry token needed a refund when no routable
worker remained (PR 8), the device-capture lock needed explicit ownership
handoff to its worker thread (PR 6). The class is mechanical, and with
83 acquire/release-shaped call pairs across the tree it is exactly what an
analyzer should police. This module discovers **paired-resource
protocols** and summarizes, per function, which resources are opened, on
which control-flow paths they are guaranteed closed, and where ownership
is handed to another thread or callback so the closing obligation
transfers. Rules JG027–JG029 consume the summaries.

Protocols come from three sources:

- a **seeded pair table** — lock ``acquire``/``release``, trace
  ``async_begin``/``async_end``, engine ``dispatch``/``finalize``, token
  ``take``/``refund``, ``register``/``unregister``. A seeded open is only
  tracked when its close-half name appears somewhere in the same module
  (``atexit.register`` in a module that never unregisters is a
  fire-and-forget API, not half of a protocol);
- **inferred project-local pairs** — a class whose methods are textual
  duals (``open_stream``/``close_stream``, ``checkout``/``checkin`` — the
  first ``_``-segment swapped through :data:`DUAL_SEGMENTS`) and both
  touch a common ``self`` attribute defines a protocol; use sites are only
  tracked where the receiver's class is statically resolvable (a local or
  ``self`` attribute assigned ``Cls(...)``), so ``thread.start()`` never
  reads as an un-stopped resource;
- **in-flight counters** — ``self.<attr> += n`` paired with
  ``self.<attr> -= n`` in the *same function* of a class that uses both
  halves; the increment opens a reservation the decrement must release on
  every path (the PR 4 ledger bug). Cross-method counter halves are the
  normal dispatch/finalize ledger and are not modeled.

Per open event the forward path analysis classifies the outcome:

- ``closed`` — a matching close (same receiver) dominates every path out
  of the open's scope: same-statement pairing, ``try``/``finally`` whose
  finally closes, or a close on every branch. A close reached only after
  a *raise-capable* statement (one containing a call) records an
  exception-path hazard — the JG027 shape;
- ``transferred`` — the receiver or the open's bound token is returned,
  raised, stored into ``self``/a container, or passed to another call:
  the closing obligation moved with it. ``threading.Thread(target=...)``
  and callback-registration calls additionally record a :class:`Handoff`
  with the resolved receiver function, and whether that function contains
  the close (JG029's input). A ``self.<attr>`` (or module-global) open
  whose close-half lives in a *different* method of the same class
  (module) is likewise a transfer — the instance holds the resource
  between its ``start``/``stop``-shaped halves;
- ``leak`` — an early ``return``/``raise``/``continue`` escape, a
  fall-through off the end of the function, or a loop boundary crossed
  with the resource open.

Everything is statically visible facts only. Known approximations
(documented once here, referenced by the rules): ``try`` bodies are
combined with their handlers branch-wise, not edge-exact (an exception
mid-try that a handler swallows without closing can slip through); a
close reached only through an unresolvable helper call is invisible (the
generic token-transfer rule usually covers it); ``with`` context managers
are balanced by construction and never count as opens.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from gan_deeplearning4j_tpu.analysis import _common

#: (open, close) method-name pairs tracked wherever the close-half name
#: appears in the same module
SEEDED_PAIRS: Tuple[Tuple[str, str], ...] = (
    ("acquire", "release"),
    ("async_begin", "async_end"),
    ("dispatch", "finalize"),
    ("take", "refund"),
    ("register", "unregister"),
)

#: first-``_``-segment duals used to infer project-local pairs from class
#: method names (both methods must touch a common ``self`` attribute)
DUAL_SEGMENTS: Dict[str, str] = {
    "open": "close", "start": "stop", "begin": "end", "enter": "exit",
    "attach": "detach", "connect": "disconnect", "checkout": "checkin",
    "borrow": "restore", "reserve": "unreserve", "lease": "unlease",
}

_SEEDED_OPEN = {o: c for o, c in SEEDED_PAIRS}
_SEEDED_CLOSE = {c: o for o, c in SEEDED_PAIRS}


@dataclasses.dataclass(frozen=True)
class PairProtocol:
    """One open/close discipline. ``kind`` is "seeded", "inferred" or
    "counter"; inferred pairs carry the defining class' canonical name."""

    open: str
    close: str
    kind: str
    cls: Optional[str] = None


@dataclasses.dataclass
class Handoff:
    """The open resource handed to a thread target / registered callback."""

    target: str                 # surface name of the receiver function
    node: ast.AST
    resolved: bool              # the receiver's body was found and scanned
    target_closes: bool         # ... and it contains the closing call


@dataclasses.dataclass
class OpenEvent:
    """One tracked open, with the outcome of the forward path analysis."""

    pair: PairProtocol
    recv: str                   # receiver text ("self._lock", "tok")
    node: ast.AST
    method: str
    outcome: str                # "closed" | "transferred" | "leak"
    leak_kind: Optional[str] = None   # "exception-path" | "early-exit" |
    #                                   "fall-through" | "loop-carried"
    hazard_node: Optional[ast.AST] = None  # the raising / escaping stmt
    transfer_kind: Optional[str] = None    # "returned"|"stored"|"argument"|
    #                                        "handoff"|"cross-method"
    handoff: Optional[Handoff] = None


@dataclasses.dataclass
class BalanceIssue:
    """A JG028 shape found by the block-linear balance pass."""

    pair: PairProtocol
    recv: str
    kind: str                   # "double-close" | "close-without-open" |
    #                             "loop-carried-release"
    node: ast.AST
    method: str
    prior: Optional[ast.AST] = None  # the earlier close / the open outside


@dataclasses.dataclass
class FunctionLifecycle:
    """Per-function slice handed to the rules."""

    name: str                   # qualname ("Cls.m" or "fn")
    node: ast.AST
    opens: List[OpenEvent] = dataclasses.field(default_factory=list)
    issues: List[BalanceIssue] = dataclasses.field(default_factory=list)


class LifecycleIndex:
    """Lazy per-path cache of :class:`FunctionLifecycle` summaries, built
    from the project index's parsed modules on first use by a rule, so
    runs that exclude JG027–JG029 pay nothing for it."""

    def __init__(self, project) -> None:
        self._project = project
        self._cache: Dict[str, List[FunctionLifecycle]] = {}
        self._inferred: Optional[Dict[str, List[PairProtocol]]] = None

    def functions(self, path: str) -> List[FunctionLifecycle]:
        if path not in self._cache:
            info = self._project.by_path.get(path)
            self._cache[path] = (
                [] if info is None
                else _build_module(info.srcmod, self._project,
                                   self.inferred_pairs()))
        return self._cache[path]

    def inferred_pairs(self) -> Dict[str, List[PairProtocol]]:
        """Canonical class name -> inferred protocols, discovered once
        over every indexed module (cross-module use sites resolve through
        the importing module's absolutized imports)."""
        if self._inferred is None:
            self._inferred = {}
            for info in self._project.modules.values():
                for cls in ast.walk(info.srcmod.tree):
                    if not isinstance(cls, ast.ClassDef):
                        continue
                    for proto in _infer_class_pairs(cls):
                        canon = f"{info.name}.{cls.name}"
                        proto = dataclasses.replace(proto, cls=canon)
                        self._inferred.setdefault(canon, []).append(proto)
        return self._inferred

    def stats(self) -> dict:
        """Index-wide totals (the campaign preflight snapshot): protocols
        discovered, opens analyzed, and how each open resolved."""
        counts = {"files": 0, "functions": 0, "opens": 0,
                  "closed": 0, "transferred": 0, "leaked": 0,
                  "handoffs": 0, "handoffs_resolved": 0,
                  "balance_issues": 0,
                  "pairs_seeded": len(SEEDED_PAIRS),
                  "pairs_inferred": sum(
                      len(v) for v in self.inferred_pairs().values())}
        for path in sorted(self._project.by_path):
            fls = self.functions(path)
            counts["files"] += 1
            counts["functions"] += len(fls)
            for fl in fls:
                counts["opens"] += len(fl.opens)
                counts["balance_issues"] += len(fl.issues)
                for ev in fl.opens:
                    key = {"closed": "closed",
                           "transferred": "transferred",
                           "leak": "leaked"}[ev.outcome]
                    counts[key] += 1
                    if ev.handoff is not None:
                        counts["handoffs"] += 1
                        if ev.handoff.resolved:
                            counts["handoffs_resolved"] += 1
        return counts


def build(project) -> LifecycleIndex:
    return LifecycleIndex(project)


# -- protocol discovery -----------------------------------------------------

def _dual_name(name: str) -> Optional[str]:
    """``open_stream`` -> ``close_stream`` via the first-segment dual
    table, else None."""
    head, sep, rest = name.partition("_")
    dual = DUAL_SEGMENTS.get(head)
    if dual is None:
        return None
    return f"{dual}{sep}{rest}"


def _self_attrs_touched(fn) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(fn):
        if (isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name)
                and n.value.id == "self"):
            out.add(n.attr)
    return out


def _infer_class_pairs(cls: ast.ClassDef) -> List[PairProtocol]:
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    out = []
    for name, fn in sorted(methods.items()):
        if name in _SEEDED_OPEN:
            continue  # seeded pairs already track these names everywhere
        dual = _dual_name(name)
        if dual is None or dual not in methods:
            continue
        if _self_attrs_touched(fn) & _self_attrs_touched(methods[dual]):
            out.append(PairProtocol(open=name, close=dual, kind="inferred"))
    return out


def _module_attr_names(tree: ast.AST) -> Set[str]:
    """Every attribute name called anywhere in the module — the gate for
    seeded pairs (open tracked only when the close-half is in play)."""
    out: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            out.add(n.func.attr)
    return out


def _counter_attrs(cls: ast.ClassDef) -> Set[str]:
    """``self`` attributes the class both ``+=``s and ``-=``s — the
    in-flight-ledger shape."""
    plus: Set[str] = set()
    minus: Set[str] = set()
    for n in ast.walk(cls):
        if (isinstance(n, ast.AugAssign)
                and isinstance(n.target, ast.Attribute)
                and isinstance(n.target.value, ast.Name)
                and n.target.value.id == "self"):
            if isinstance(n.op, ast.Add):
                plus.add(n.target.attr)
            elif isinstance(n.op, ast.Sub):
                minus.add(n.target.attr)
    return plus & minus


# -- receiver typing (inferred-pair use sites) ------------------------------

class _TypeEnv:
    """Receiver text -> canonical class name, from ``x = Cls(...)`` local
    assignments and ``self.attr = Cls(...)`` in the enclosing class."""

    def __init__(self, project, mod) -> None:
        self._project = project
        self._mod = mod
        self._info = project.by_path.get(mod.path)
        self.types: Dict[str, str] = {}

    def canonical_class(self, ctor: ast.AST) -> Optional[str]:
        resolved = self._mod.resolve(ctor)
        if resolved is None or self._info is None:
            return None
        canon = self._project._canonical_call(self._info, resolved)
        return canon

    def learn(self, target_text: str, value: ast.AST) -> None:
        if isinstance(value, ast.Call):
            canon = self.canonical_class(value.func)
            if canon is not None:
                self.types[target_text] = canon


def _recv_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse handles all exprs
        return "<expr>"


# -- per-module construction ------------------------------------------------

def _build_module(mod, project, inferred: Dict[str, List[PairProtocol]]):
    out: List[FunctionLifecycle] = []
    module_attrs = _module_attr_names(mod.tree)
    # module-level functions
    for n in mod.tree.body:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(_analyze_function(
                mod, project, inferred, module_attrs, n, qualprefix="",
                counter_attrs=frozenset(), scope_body=mod.tree.body))
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        counters = frozenset(_counter_attrs(cls))
        for n in cls.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(_analyze_function(
                    mod, project, inferred, module_attrs, n,
                    qualprefix=cls.name + ".", counter_attrs=counters,
                    scope_body=cls.body))
    return out


def _closes_in_tree(tree: ast.AST, recv: str, close: str,
                    counter: bool = False) -> bool:
    """Does ``tree`` contain ``<recv>.<close>()`` (or ``<recv> -= ...``
    for counters)? ``self.``-qualified receivers match across methods."""
    for n in ast.walk(tree):
        if counter:
            if (isinstance(n, ast.AugAssign) and isinstance(n.op, ast.Sub)
                    and _recv_text(n.target) == recv):
                return True
        elif (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == close
                and _recv_text(n.func.value) == recv):
            return True
    return False


class _FnAnalysis:
    """The forward path analysis for one function body."""

    # scan outcomes
    CLOSED, TRANSFER, LEAK, FALL, BREAK = range(5)

    def __init__(self, mod, project, inferred, module_attrs, fn,
                 qualname, counter_attrs, scope_body):
        self.mod = mod
        self.project = project
        self.inferred = inferred
        self.module_attrs = module_attrs
        self.fn = fn
        self.qualname = qualname
        self.counter_attrs = counter_attrs
        self.scope_body = scope_body  # class body / module body (transfer
        #                               downgrade + handoff resolution)
        self.env = _TypeEnv(project, mod)
        self.result = FunctionLifecycle(name=qualname, node=fn)
        # seed the type env from the enclosing class' __init__ so
        # ``self.pool = StreamPool()`` types later ``self.pool.open_*``
        for stmt in scope_body:
            if (isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and stmt.name == "__init__"):
                for n in ast.walk(stmt):
                    if (isinstance(n, ast.Assign) and len(n.targets) == 1
                            and isinstance(n.targets[0], ast.Attribute)):
                        self.env.learn(_recv_text(n.targets[0]), n.value)

    # -- open/close matching ------------------------------------------------
    def _match_open(self, call: ast.Call) -> Optional[Tuple[PairProtocol, str]]:
        if not isinstance(call.func, ast.Attribute):
            return None
        name = call.func.attr
        recv = _recv_text(call.func.value)
        close = _SEEDED_OPEN.get(name)
        if close is not None and close in self.module_attrs:
            return PairProtocol(open=name, close=close, kind="seeded"), recv
        cls = self.env.types.get(recv)
        if cls is not None:
            for proto in self.inferred.get(cls, ()):
                if proto.open == name:
                    return proto, recv
        return None

    def _is_close_call(self, node: ast.AST, pair: PairProtocol,
                       recv: str) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == pair.close
                and _recv_text(node.func.value) == recv)

    def _stmt_closes(self, stmt: ast.stmt, pair: PairProtocol,
                     recv: str) -> bool:
        if pair.kind == "counter":
            return any(
                isinstance(n, ast.AugAssign) and isinstance(n.op, ast.Sub)
                and _recv_text(n.target) == recv
                for n in _common.walk_excluding_defs(stmt))
        return any(self._is_close_call(n, pair, recv)
                   for n in _common.walk_excluding_defs(stmt))

    def _block_closes(self, stmts, pair, recv) -> bool:
        return any(self._stmt_closes(s, pair, recv) for s in stmts)

    # -- transfer / handoff -------------------------------------------------
    def _token_names(self, stmt: ast.stmt, call: ast.Call,
                     pair: PairProtocol, recv: str) -> Set[str]:
        """Names that carry the closing obligation: the open's bound
        result, the receiver's base name, and — for ``async_begin`` — the
        span-id argument (the token the matching ``async_end`` needs)."""
        names: Set[str] = set()
        base = _common.base_name(call.func.value) if isinstance(
            call.func, ast.Attribute) else None
        if base is not None and base != "self":
            names.add(base)
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        if pair.open == "async_begin" and len(call.args) >= 2:
            b = _common.base_name(call.args[1])
            if b is not None:
                names.add(b)
        return names

    def _handoff_target(self, call: ast.Call) -> Optional[ast.AST]:
        """The receiver-function expression of a thread spawn or callback
        registration, else None."""
        resolved = self.mod.resolve(call.func)
        if resolved in ("threading.Thread", "threading.Timer"):
            for kw in call.keywords:
                if kw.arg in ("target", "function"):
                    return kw.value
            if resolved == "threading.Thread" and call.args:
                return call.args[0]
            if resolved == "threading.Timer" and len(call.args) >= 2:
                return call.args[1]
        if isinstance(call.func, ast.Attribute) and call.func.attr in (
                "add_done_callback", "register_callback", "on_complete",
                "submit"):
            if call.args:
                return call.args[0]
        return None

    def _resolve_callable_body(self, expr: ast.AST) -> Optional[ast.AST]:
        """AST body of a handoff receiver: a same-class ``self._m``, a
        module function, or a project-indexed import."""
        attr = None
        if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            attr = expr.attr
            for stmt in self.scope_body:
                if (isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and stmt.name == attr):
                    return stmt
            return None
        summary = self.project.resolve_function(self.mod, expr)
        if summary is not None:
            return summary.node
        return None

    def _make_handoff(self, call: ast.Call, pair: PairProtocol,
                      recv: str) -> Optional[Handoff]:
        target_expr = self._handoff_target(call)
        if target_expr is None:
            return None
        body = self._resolve_callable_body(target_expr)
        target_name = _recv_text(target_expr)
        if body is None:
            return Handoff(target=target_name, node=call,
                           resolved=False, target_closes=False)
        closes = _closes_in_tree(body, recv, pair.close,
                                 counter=(pair.kind == "counter"))
        return Handoff(target=target_name, node=call, resolved=True,
                       target_closes=closes)

    def _stmt_transfers(self, stmt: ast.stmt, tokens: Set[str],
                        pair: PairProtocol, recv: str):
        """(kind, handoff) when ``stmt`` moves the closing obligation,
        else None. Handoffs are checked first so JG029 sees them even when
        the generic argument rule would also match."""
        for n in _common.walk_excluding_defs(stmt):
            if isinstance(n, ast.Call):
                h = self._make_handoff(n, pair, recv)
                if h is not None:
                    hand_args = {a for arg in n.args
                                 for a in [_common.base_name(arg)] if a}
                    hand_args |= {a for kw in n.keywords
                                  for a in [_common.base_name(kw.value)] if a}
                    recv_base = recv.split(".")[0].split("[")[0]
                    if (tokens & hand_args
                            or h.target_closes
                            or (h.resolved and recv_base in ("self",))):
                        return "handoff", h
        if not tokens:
            return None
        for n in _common.walk_excluding_defs(stmt):
            if isinstance(n, (ast.Return, ast.Raise)):
                val = n.value if isinstance(n, ast.Return) else (
                    n.exc if n.exc is not None else None)
                if val is not None and tokens & _common.loaded_names(val):
                    return "returned", None
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        if tokens & _common.loaded_names(n.value):
                            return "stored", None
            if isinstance(n, ast.Call):
                if self._is_close_call(n, pair, recv):
                    continue
                arg_names = set()
                for arg in n.args:
                    b = _common.base_name(arg)
                    if b:
                        arg_names.add(b)
                    arg_names |= _common.loaded_names(arg)
                for kw in n.keywords:
                    arg_names |= _common.loaded_names(kw.value)
                if tokens & arg_names:
                    return "argument", None
        return None

    @staticmethod
    def _stmt_raises(stmt: ast.stmt) -> Optional[ast.AST]:
        """The first call inside ``stmt`` (nested defs excluded) — the
        statically visible "this statement can raise" marker."""
        for n in _common.walk_excluding_defs(stmt):
            if isinstance(n, ast.Call):
                return n
        return None

    # -- the forward scan ---------------------------------------------------
    def _scan_block(self, stmts, start, st) -> int:
        """Scan ``stmts[start:]`` with shared state ``st`` (dict carrying
        raising/hazard/partial-close info). Returns a scan outcome."""
        for stmt in stmts[start:]:
            out = self._scan_stmt(stmt, st)
            if out != self.FALL:
                return out
        return self.FALL

    def _scan_stmt(self, stmt, st) -> int:
        pair, recv, tokens = st["pair"], st["recv"], st["tokens"]
        # compound statements dispatch FIRST: a close buried in one arm of
        # an if/try or inside a loop body is not "this statement closes" —
        # the branch logic owns partial-close, loop-carried, and finally
        # semantics
        if isinstance(stmt, ast.If):
            return self._scan_if(stmt, st)
        if isinstance(stmt, ast.Try):
            return self._scan_try(stmt, st)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if self._stmt_raises(ast.Expr(item.context_expr)) is not None:
                    st.setdefault("raising", item.context_expr)
            return self._scan_block(stmt.body, 0, st)
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            return self._scan_loop(stmt, st)
        if self._stmt_closes(stmt, pair, recv):
            if st.get("partial") is not None:
                self.result.issues.append(BalanceIssue(
                    pair=pair, recv=recv, kind="double-close", node=stmt,
                    method=self.qualname, prior=st["partial"]))
            st["closed_at"] = stmt
            return self.CLOSED
        tr = self._stmt_transfers(stmt, tokens, pair, recv)
        if tr is not None:
            st["transfer"], st["handoff"] = tr
            return self.TRANSFER
        if isinstance(stmt, (ast.Return, ast.Raise)):
            st["leak"] = ("early-exit", stmt)
            return self.LEAK
        if isinstance(stmt, ast.Continue):
            st["leak"] = ("loop-carried", stmt)
            return self.LEAK
        if isinstance(stmt, ast.Break):
            return self.BREAK
        r = self._stmt_raises(stmt)
        if r is not None:
            st.setdefault("raising", r)
        return self.FALL

    def _branch(self, stmts, st) -> Tuple[int, dict]:
        sub = {"pair": st["pair"], "recv": st["recv"],
               "tokens": st["tokens"]}
        if "raising" in st:
            sub["raising"] = st["raising"]
        out = self._scan_block(stmts, 0, sub)
        return out, sub

    @staticmethod
    def _block_departs(stmts) -> bool:
        """The block's last statement leaves the enclosing scope — a
        ``close(); return`` branch is DONE with the resource, so a close
        on the surviving path is not a double-close."""
        return bool(stmts) and isinstance(
            stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))

    def _scan_if(self, stmt: ast.If, st) -> int:
        r = self._stmt_raises(ast.Expr(stmt.test))
        if r is not None:
            st.setdefault("raising", r)
        results = [(self._branch(stmt.body, st), stmt.body),
                   (self._branch(stmt.orelse, st), stmt.orelse)]
        for (out, sub), _stmts in results:
            if out == self.LEAK:
                st["leak"] = sub["leak"]
                return self.LEAK
        outs = [out for (out, _), _stmts in results]
        if all(o in (self.CLOSED, self.TRANSFER, self.BREAK) for o in outs):
            # BREAK branches jump past the loop; the close after the loop
            # still runs for them only if it is outside — approximated as
            # closed-with-the-others (the balance pass owns loop shapes)
            for (out, sub), _stmts in results:
                if out == self.CLOSED:
                    st["closed_at"] = sub.get("closed_at")
                    return self.CLOSED
            st["transfer"] = next(
                sub.get("transfer") for (out, sub), _stmts in results
                if out == self.TRANSFER)
            st["handoff"] = next(
                (sub.get("handoff") for (out, sub), _stmts in results
                 if out == self.TRANSFER), None)
            return self.TRANSFER
        for (out, sub), stmts in results:
            if out == self.CLOSED and not self._block_departs(stmts):
                # closed on one path, open on the other: remember — a
                # later close is a double-close on this path (JG028); an
                # end-of-function without one is a partial leak (JG027).
                # A branch that closes then EXITS already left the scope
                # and constrains nothing downstream.
                st["partial"] = sub.get("closed_at")
            if out == self.BREAK:
                return self.BREAK
            if "raising" in sub:
                st.setdefault("raising", sub["raising"])
        return self.FALL

    def _scan_try(self, stmt: ast.Try, st) -> int:
        pair, recv = st["pair"], st["recv"]
        if self._block_closes(stmt.finalbody, pair, recv):
            # finally closes: every path through the try is covered; a
            # hazard only exists in the gap BEFORE the try
            st["closed_at"] = stmt
            return self.CLOSED
        results = [(self._branch(stmt.body + stmt.orelse, st),
                    stmt.body + stmt.orelse)]
        for handler in stmt.handlers:
            results.append((self._branch(handler.body, st), handler.body))
        for (out, sub), _stmts in results:
            if out == self.LEAK:
                st["leak"] = sub["leak"]
                return self.LEAK
        outs = [out for (out, _), _stmts in results]
        if all(o in (self.CLOSED, self.TRANSFER) for o in outs):
            for (out, sub), _stmts in results:
                if out == self.CLOSED:
                    st["closed_at"] = sub.get("closed_at")
                    return self.CLOSED
            st["transfer"] = results[0][0][1].get("transfer") or "argument"
            st["handoff"] = results[0][0][1].get("handoff")
            return self.TRANSFER
        for (out, sub), stmts in results:
            if out == self.CLOSED and not self._block_departs(stmts):
                st["partial"] = sub.get("closed_at")
            if "raising" in sub:
                st.setdefault("raising", sub["raising"])
        if self._block_closes(stmt.finalbody, pair, recv):
            return self.CLOSED  # pragma: no cover - handled above
        out = self._scan_block(stmt.finalbody, 0, st)
        if out != self.FALL:
            return out
        return self.FALL

    def _scan_loop(self, stmt, st) -> int:
        pair, recv = st["pair"], st["recv"]
        if self._block_closes(stmt.body, pair, recv):
            # close inside a loop body for a resource opened outside it:
            # released 0 times if the body never runs, N times if it
            # iterates — the loop-carried-release shape (JG028)
            close_node = next(
                s for s in stmt.body if self._stmt_closes(s, pair, recv))
            self.result.issues.append(BalanceIssue(
                pair=pair, recv=recv, kind="loop-carried-release",
                node=close_node, method=self.qualname, prior=st["node"]))
            st["closed_at"] = close_node
            return self.CLOSED
        out, sub = self._branch(stmt.body, st)
        if out == self.TRANSFER:
            st["transfer"] = sub.get("transfer")
            st["handoff"] = sub.get("handoff")
            return self.TRANSFER
        if out == self.LEAK and sub["leak"][0] != "loop-carried":
            st["leak"] = sub["leak"]
            return self.LEAK
        if "raising" in sub:
            st.setdefault("raising", sub["raising"])
        return self.FALL

    # -- driving ------------------------------------------------------------
    def analyze(self) -> FunctionLifecycle:
        self._walk_block(self.fn.body, stack=[])
        self._balance_pass(self.fn.body, state={}, in_loop=False)
        return self.result

    def _enclosing_finally_closes(self, stack, pair, recv) -> bool:
        for stmts, idx, kind, node in stack:
            if (isinstance(node, ast.Try)
                    and self._block_closes(node.finalbody, pair, recv)):
                return True
        return False

    def _open_in_stmt(self, stmt):
        """(call, effective_position) for a tracked open in ``stmt``:
        ``"after"`` for ``if not x.acquire(...): <exit>`` conditional
        acquires (the open survives only past the guard), ``"here"``
        otherwise. Opens in other condition shapes are not tracked."""
        if isinstance(stmt, ast.If):
            test = stmt.test
            if (isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not)
                    and isinstance(test.operand, ast.Call)):
                m = self._match_open(test.operand)
                if m is not None and stmt.body and isinstance(
                        stmt.body[-1], (ast.Return, ast.Raise, ast.Continue)):
                    return test.operand, m, "after"
            return None
        if isinstance(stmt, (ast.Expr, ast.Assign)):
            val = stmt.value
            if isinstance(val, ast.Call):
                m = self._match_open(val)
                if m is not None:
                    # open consumed by its close in the same expression
                    # (``finalize(dispatch(...))``) is balanced inline
                    return val, m, "here"
        if (isinstance(stmt, ast.AugAssign) and isinstance(stmt.op, ast.Add)
                and isinstance(stmt.target, ast.Attribute)
                and isinstance(stmt.target.value, ast.Name)
                and stmt.target.value.id == "self"
                and stmt.target.attr in self.counter_attrs):
            pair = PairProtocol(open="+=", close="-=", kind="counter")
            return stmt, (pair, _recv_text(stmt.target)), "here"
        return None

    def _record_open(self, call, pair, recv, stmt, stack, effective_idx):
        # inline-balanced: the close call wraps the open in one statement
        if pair.kind != "counter" and any(
                self._is_close_call(n, pair, recv)
                for n in _common.walk_excluding_defs(stmt)):
            self.result.opens.append(OpenEvent(
                pair=pair, recv=recv, node=call, method=self.qualname,
                outcome="closed"))
            return
        if self._enclosing_finally_closes(stack, pair, recv):
            self.result.opens.append(OpenEvent(
                pair=pair, recv=recv, node=call, method=self.qualname,
                outcome="closed"))
            return
        tokens = (self._token_names(stmt, call, pair, recv)
                  if isinstance(call, ast.Call) else set())
        st = {"pair": pair, "recv": recv, "tokens": tokens, "node": call}
        out = self.FALL
        # innermost-out: scan the rest of each enclosing block
        for level in range(len(stack) - 1, -1, -1):
            stmts, idx, kind, node = stack[level]
            start = idx + 1 if level == len(stack) - 1 else idx + 1
            out = self._scan_block(stmts, start, st)
            if out == self.BREAK:
                # jump past the innermost enclosing loop
                while level > 0 and kind != "loop":
                    level -= 1
                    stmts, idx, kind, node = stack[level]
                out = self.FALL
                continue
            if out != self.FALL:
                break
            if kind == "loop":
                # fell off a loop body with the resource open: the next
                # iteration re-opens without closing
                st["leak"] = ("loop-carried", node)
                out = self.LEAK
                break
        ev = OpenEvent(pair=pair, recv=recv, node=call,
                       method=self.qualname, outcome="closed")
        if out == self.CLOSED:
            if "raising" in st:
                ev.outcome = "leak"
                ev.leak_kind = "exception-path"
                ev.hazard_node = st["raising"]
        elif out == self.TRANSFER:
            ev.outcome = "transferred"
            ev.transfer_kind = st.get("transfer")
            ev.handoff = st.get("handoff")
            if "raising" in st:
                # a raise-capable gap BEFORE the ownership moved: the
                # handoff never happens on the exception path
                ev.outcome = "leak"
                ev.leak_kind = "exception-path"
                ev.hazard_node = st["raising"]
        else:  # LEAK or fall-through
            kind_, node_ = st.get("leak", ("fall-through", call))
            if self._cross_scope_close(pair, recv):
                ev.outcome = "transferred"
                ev.transfer_kind = "cross-method"
            else:
                ev.outcome = "leak"
                ev.leak_kind = kind_
                ev.hazard_node = node_
        if st.get("partial") is not None and ev.outcome == "leak":
            ev.leak_kind = ev.leak_kind or "fall-through"
        self.result.opens.append(ev)

    def _cross_scope_close(self, pair: PairProtocol, recv: str) -> bool:
        """Close-half for ``recv`` in a *different* function of the same
        class/module scope — the instance-holds-the-resource idiom
        (``start``/``stop``): the obligation transfers to the peer."""
        if not (recv.startswith("self.") or "." not in recv):
            return False
        if self._stmt_closes_anywhere(self.fn, pair, recv):
            return False  # close in THIS function: protocol is local
        for stmt in self.scope_body:
            if (isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and stmt is not self.fn
                    and self._stmt_closes_anywhere(stmt, pair, recv)):
                return True
        return False

    def _stmt_closes_anywhere(self, tree, pair, recv) -> bool:
        return _closes_in_tree(tree, recv, pair.close,
                               counter=(pair.kind == "counter"))

    def _walk_block(self, stmts, stack) -> None:
        for i, stmt in enumerate(stmts):
            found = self._open_in_stmt(stmt)
            if found is not None:
                call, (pair, recv), pos = found
                frame = stack + [(stmts, i, "body", stmt)]
                self._record_open(call, pair, recv, stmt, frame, i)
            # learn local constructor types in source order
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                self.env.learn(_recv_text(stmt.targets[0]), stmt.value)
            for child_stmts, kind in self._child_blocks(stmt):
                self._walk_block(
                    child_stmts, stack + [(stmts, i, kind, stmt)])

    @staticmethod
    def _child_blocks(stmt):
        if isinstance(stmt, ast.If):
            yield stmt.body, "body"
            yield stmt.orelse, "body"
        elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            yield stmt.body, "loop"
            yield stmt.orelse, "body"
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            yield stmt.body, "body"
        elif isinstance(stmt, ast.Try):
            yield stmt.body, "try"
            for h in stmt.handlers:
                yield h.body, "body"
            yield stmt.orelse, "body"
            yield stmt.finalbody, "body"

    # -- the block-linear balance pass (JG028) ------------------------------
    def _balance_pass(self, stmts, state, in_loop) -> None:
        """Per-receiver open/closed state machine over straight-line
        blocks: a close in the CLOSED state is a double-close; a close in
        a state only opened by SOME preceding branch is a
        close-without-open. State resets to unknown at control joins the
        machine cannot follow."""
        for stmt in stmts:
            if isinstance(stmt, (ast.Expr, ast.Assign)) and isinstance(
                    getattr(stmt, "value", None), ast.Call):
                call = stmt.value
                m = self._match_open(call)
                if m is not None:
                    state[m[1] + "|" + m[0].close] = ("open", call)
                elif isinstance(call.func, ast.Attribute):
                    name = call.func.attr
                    recv = _recv_text(call.func.value)
                    opened = _SEEDED_CLOSE.get(name)
                    key = recv + "|" + name
                    if opened is not None and name in self.module_attrs:
                        pair = PairProtocol(open=opened, close=name,
                                            kind="seeded")
                        prev = state.get(key)
                        if prev is not None and prev[0] == "closed":
                            self.result.issues.append(BalanceIssue(
                                pair=pair, recv=recv, kind="double-close",
                                node=call, method=self.qualname,
                                prior=prev[1]))
                        elif prev is not None and prev[0] == "maybe":
                            self.result.issues.append(BalanceIssue(
                                pair=pair, recv=recv,
                                kind="close-without-open", node=call,
                                method=self.qualname, prior=prev[1]))
                        if prev is not None:
                            state[key] = ("closed", call)
            elif isinstance(stmt, ast.If):
                # a branch that opens without closing leaves the receiver
                # maybe-open at the join
                pre = dict(state)
                self._balance_pass(stmt.body, state, in_loop)
                other = dict(pre)
                self._balance_pass(stmt.orelse, other, in_loop)
                branch_exits = bool(stmt.body) and isinstance(
                    stmt.body[-1], (ast.Return, ast.Raise,
                                    ast.Continue, ast.Break))
                for key in set(state) | set(other):
                    a, b = state.get(key), other.get(key)
                    if branch_exits:
                        state[key] = b if b is not None else None
                        if state[key] is None:
                            state.pop(key, None)
                    elif a != b:
                        if a is not None and a[0] == "open" and (
                                b is None or b[0] != "open"):
                            state[key] = ("maybe", a[1])
                        elif b is not None and b[0] == "open" and (
                                a is None or a[0] != "open"):
                            state[key] = ("maybe", b[1])
                        else:
                            state.pop(key, None)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self._balance_pass(stmt.body, dict(state), True)
                state.clear()
            elif isinstance(stmt, ast.Try):
                self._balance_pass(stmt.body, state, in_loop)
                for h in stmt.handlers:
                    self._balance_pass(h.body, dict(state), in_loop)
                self._balance_pass(stmt.orelse, state, in_loop)
                self._balance_pass(stmt.finalbody, state, in_loop)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._balance_pass(stmt.body, state, in_loop)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested scopes analyzed separately
            elif isinstance(stmt, (ast.Return, ast.Raise)):
                state.clear()


def _analyze_function(mod, project, inferred, module_attrs, fn, qualprefix,
                      counter_attrs, scope_body) -> FunctionLifecycle:
    return _FnAnalysis(mod, project, inferred, module_attrs, fn,
                       qualprefix + fn.name, counter_attrs,
                       scope_body).analyze()
