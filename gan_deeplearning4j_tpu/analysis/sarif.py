"""SARIF 2.1.0 output for jaxlint.

SARIF (Static Analysis Results Interchange Format) is the lingua franca CI
annotators speak — GitHub code scanning, VS Code's SARIF viewer, Gerrit
checks all ingest it directly, so ``--format sarif`` makes the gate's
findings appear inline on changed lines with zero glue code.

Mapping choices:

- ``active`` findings are ``level: error`` (they fail the gate);
  ``baselined`` ones are included as ``level: note`` with a
  ``suppressions`` entry (state ``accepted``, the human justification as
  the text) so reviewers see the debt without the gate re-flagging it;
  engine warnings ride along as tool-level notifications.
- the content-based fingerprint goes into ``partialFingerprints`` under
  ``jaxlint/v1`` — the same stability contract the baseline uses (survives
  line drift, invalidated by edits to the offending line), which is
  exactly what SARIF asks of a partial fingerprint.
- columns are converted to SARIF's 1-based convention.
"""

from __future__ import annotations

_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
           "Schemata/sarif-schema-2.1.0.json")


def _result(finding, level: str, justification=None) -> dict:
    out = {
        "ruleId": finding.code,
        "level": level,
        "message": {"text": finding.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {
                    "uri": finding.path.replace("\\", "/"),
                },
                "region": {
                    "startLine": max(finding.line, 1),
                    "startColumn": finding.col + 1,
                },
            },
        }],
        "partialFingerprints": {"jaxlint/v1": finding.fingerprint},
    }
    if justification is not None:
        out["suppressions"] = [{
            "kind": "external",
            "status": "accepted",
            "justification": justification,
        }]
    return out


def to_sarif(report, rules, baseline_entries=None) -> dict:
    """One SARIF run for a :class:`~.engine.Report`."""
    by_fp = {e.get("fingerprint"): e for e in (baseline_entries or [])}
    results = [_result(f, "error") for f in report.active]
    for f in report.baselined:
        entry = by_fp.get(f.fingerprint, {})
        results.append(_result(
            f, "note", justification=entry.get("justification", "baselined")))
    return {
        "$schema": _SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "jaxlint",
                    "informationUri": "docs/STATIC_ANALYSIS.md",
                    "rules": [
                        {
                            "id": r.code,
                            "name": r.name,
                            "shortDescription": {"text": r.summary},
                        }
                        for r in rules
                    ],
                },
            },
            "invocations": [{
                "executionSuccessful": report.gate_ok,
                "toolExecutionNotifications": [
                    {"level": "warning", "message": {"text": w}}
                    for w in report.warnings
                ],
            }],
            "results": results,
        }],
    }
