"""JG026 — blocking call while holding a lock in a threaded class.

The silent latency/deadlock hazard in health-loop-shaped code: a probe,
sleep, join, or subprocess executed inside ``with self._lock:`` stalls
every thread contending for that lock for the full duration of the block —
on the serve path that is the batcher's submit thread, on the route path
the request handlers. Worse than latency: if the blocked operation itself
waits on work that needs the lock (joining the worker thread that is
parked on ``with self._lock``), the class deadlocks. JG017 bounds the
network wait; this rule says even a *bounded* wait does not belong under
a lock other threads turn around on.

The model (phase-1 concurrency index): in any class that spawns threads
(``Thread(target=...)``, ``Timer``, ``run`` override) or serves HTTP
handler methods — the statically visible serve/route-path classes — a
known blocking call executed with ≥1 lock held is flagged. The blocking
set is JG017's network calls plus ``time.sleep``, thread/process
``.join`` (disambiguated from ``str.join`` by argument shape),
``subprocess``/``os`` spawn-and-wait entry points, and device sync
(``jax.block_until_ready`` / ``.block_until_ready()``). One resolved
same-class call hop is followed: ``with self._lock: self._probe()``
where ``_probe`` calls ``urlopen`` is flagged at the call site.

Not flagged: blocking calls with no lock held (the correct idiom —
snapshot under the lock, block outside it); classes with no threads
(single-threaded blocking is just I/O); ``Condition.wait``/``wait_for``
(they *release* the lock while waiting — that is the point of a CV);
``str.join``. Known false negatives: blocking reached through more than
one call hop or through cross-class calls; ``.acquire()`` held regions.
"""

from __future__ import annotations


class BlockingCallUnderLock:
    code = "JG026"
    name = "blocking-call-under-lock"
    summary = ("network/sleep/join/subprocess/device-sync call executed "
               "while holding a lock other threads contend for")
    skip_tests = True

    def check(self, mod):
        if mod.project is None:
            return
        for cc in mod.project.concurrency.classes(mod.path):
            if not cc.entry_points:
                continue
            for name, mc in sorted(cc.methods.items()):
                for b in mc.blocking:
                    # lexically-held locks only: a block held purely via
                    # propagated call-site guards (caller_held) is charged
                    # at the call site by the hop loop below — reporting
                    # it here too would double-count one defect
                    if not b.held:
                        continue
                    held = b.held | mc.caller_held
                    yield self._finding(mod, cc, b.method, b.label,
                                        sorted(held), b.node)
                for call in mc.self_calls:
                    if not (call.held or mc.caller_held):
                        continue
                    callee = cc.methods.get(call.callee)
                    if callee is None:
                        continue
                    held = sorted(call.held | mc.caller_held)
                    for b in callee.blocking:
                        yield self._finding(
                            mod, cc, call.method, b.label, held,
                            call.node, via=call.callee)
                        break  # one finding per call site is enough

    def _finding(self, mod, cc, method, label, held, node, via=None):
        locks = ", ".join(f"`{h}`" for h in held)
        through = f" (via `self.{via}()`)" if via else ""
        return mod.finding(
            self.code,
            f"`{method}` calls blocking `{label}`{through} while holding "
            f"{locks} — `{cc.name}` runs threads that contend for the "
            f"lock, so every one of them stalls for the full wait (and "
            f"deadlocks if the awaited work needs the lock); snapshot "
            f"state under the lock and block outside it",
            node,
        ), node
