"""JG006 — donation safety.

``donate_argnums`` hands an argument's buffers to XLA for reuse; after the
call the donated array is INVALID, and touching it raises (on TPU) or
silently reads garbage (some backends/versions). Every jitted step in this
repo donates its train states — the idiom is safe only because call sites
rebind: ``state, loss = step(state, ...)``. This rule mechanically checks
that shape: a donated argument must not be READ after the donating call
unless the call's own assignment rebinds it.

Donating callables are resolved three ways (matching this repo's idioms):

1. ``f = jax.jit(fn, donate_argnums=(0,))`` — direct local binding;
2. ``return jax.jit(fn, **kwargs)`` where ``kwargs`` is a dict literal in
   the same function containing ``donate_argnums`` — the ``_build_*``
   pattern of ``harness/experiment.py`` and ``models/wgan_gp.py``;
3. ``self.attr = self._build_x()`` inside a class whose ``_build_x`` is a
   (2)-style builder — calls through ``self.attr(...)`` in any method of
   that class are then checked.

Only Name / ``self.x`` attribute arguments are tracked (a freshly
constructed expression cannot be used-after-donate by name). A donated name
read later in the same function — or anywhere in the same loop body when
the call sits in a loop without rebinding — is a finding.

Donation through ``functools.partial`` / import indirection is JG010
(``donation_flow``), which shares :func:`scan_use_after_donate` below —
same call-site semantics, different discovery.
"""

from __future__ import annotations

import ast
from typing import Optional

from gan_deeplearning4j_tpu.analysis import _common
from gan_deeplearning4j_tpu.analysis.project import jit_donate_argnums


def _jit_call(node: ast.AST, mod) -> Optional[ast.Call]:
    if (isinstance(node, ast.Call)
            and mod.resolve(node.func) in _common.JIT_WRAPPERS):
        return node
    return None


def _arg_key(node: ast.AST) -> Optional[str]:
    """Trackable identity of a donated argument: a bare name or a
    ``self.x``-style attribute chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and _common.base_name(node):
        return ast.unparse(node)
    return None


# -- the shared use-after-donate scanner (JG006 + JG010) --------------------

def _attr_targets(stmt) -> set:
    out = set()
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, ast.Attribute):
                key = _arg_key(node)
                if key:
                    out.add(key)
    return out


def _attr_binds(node) -> set:
    out = set()
    for s in ast.walk(node):
        if isinstance(s, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            out |= _attr_targets(s)
    return out


def _stmt_containing(scope, call):
    best = None
    for stmt in ast.walk(scope):
        if not isinstance(stmt, ast.stmt):
            continue
        if (stmt.lineno <= call.lineno
                and (stmt.end_lineno or stmt.lineno) >= (call.end_lineno
                                                         or call.lineno)):
            if best is None or stmt.lineno >= best.lineno:
                best = stmt
    return best


def _enclosing_loop(scope, call):
    """(loop_node, names_rebound_per_iteration) for the innermost
    for/while loop or comprehension containing the call, else
    (None, set()). Comprehension generator targets count as per-
    iteration binds; everything else in a comprehension cannot rebind,
    which is exactly why donating inside one is always wrong."""
    best, binds = None, set()
    for loop in _common.iter_loops(scope):
        if (loop.lineno <= call.lineno
                and (loop.end_lineno or loop.lineno) >= call.lineno
                and any(n is call for n in ast.walk(loop))):
            best, binds = loop, _common.bound_names(loop) | _attr_binds(loop)
    for comp in ast.walk(scope):
        if not isinstance(comp, (ast.ListComp, ast.SetComp, ast.DictComp,
                                 ast.GeneratorExp)):
            continue
        if any(n is call for n in ast.walk(comp)):
            targets = set()
            for gen in comp.generators:
                _common._target_names(gen.target, targets)
            best, binds = comp, targets
    return best, binds


def _later_use(scope, call, akey, is_attr):
    """First read of ``akey`` after the donating call, ignoring reads
    that happen after an intervening rebind."""
    call_end = call.end_lineno or call.lineno
    rebind_lines = []
    for n in ast.walk(scope):
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            keys = (_attr_targets(n) if is_attr
                    else _common.assignment_targets(n))
            if akey in keys and n.lineno > call_end:
                rebind_lines.append(n.lineno)
    next_rebind = min(rebind_lines) if rebind_lines else float("inf")

    for n in ast.walk(scope):
        if is_attr:
            if (isinstance(n, ast.Attribute)
                    and isinstance(n.ctx, ast.Load)
                    and _arg_key(n) == akey
                    and call_end < n.lineno <= next_rebind):
                return n
        else:
            if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                    and n.id == akey and call_end < n.lineno <= next_rebind):
                return n
    return None


def scan_use_after_donate(scope, donators: dict, mod, code: str):
    """Yield ``(finding, node)`` for every use-after-donate in ``scope``.
    ``donators`` maps callable identity (bare name or ``self.attr``) to its
    donated argnums; ``code`` is the rule code to report under (JG006 for
    same-module discovery, JG010 for partial/import indirection)."""
    calls = []  # (call, [(donated_pos, arg_key, arg_node)])
    for n in ast.walk(scope):
        if not isinstance(n, ast.Call):
            continue
        fkey = _arg_key(n.func)
        if fkey not in donators:
            continue
        donated = []
        for pos in donators[fkey]:
            if pos < len(n.args):
                akey = _arg_key(n.args[pos])
                if akey:
                    donated.append((pos, akey, n.args[pos]))
        if donated:
            calls.append((n, donated))

    for call, donated in calls:
        stmt = _stmt_containing(scope, call)
        rebound = _common.assignment_targets(stmt) if stmt else set()
        rebound_attrs = _attr_targets(stmt) if stmt else set()
        loop, loop_binds = _enclosing_loop(scope, call)
        for pos, akey, anode in donated:
            is_attr = "." in akey
            if (akey in rebound_attrs) if is_attr else (akey in rebound):
                continue  # state = step(state, ...) — the safe idiom
            if loop is not None and akey not in loop_binds:
                # the donating call re-reads the name next iteration:
                # the loop itself is the use-after-donate
                f = mod.finding(
                    code,
                    f"`{akey}` is donated (donate_argnums position "
                    f"{pos}) to `{_arg_key(call.func)}` inside a loop "
                    f"without being rebound — the next iteration "
                    f"passes an already-donated buffer; rebind the "
                    f"result over `{akey}` or drop the donation",
                    call,
                )
                yield f, call
                break
            use = _later_use(scope, call, akey, is_attr)
            if use is not None:
                f = mod.finding(
                    code,
                    f"`{akey}` is donated (donate_argnums position "
                    f"{pos}) to `{_arg_key(call.func)}` but read again "
                    f"at line {use.lineno} — a donated buffer is "
                    f"invalid after the call; rebind the result or "
                    f"drop the donation",
                    call,
                )
                yield f, call
                break


class DonationSafety:
    code = "JG006"
    name = "donation-safety"
    summary = "argument read after being donated to a jitted call"

    def check(self, mod):
        donators = self._collect_donators(mod)
        if not donators:
            return
        for scope in _common.iter_scopes(mod.tree):
            if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield from scan_use_after_donate(scope, donators, mod, self.code)

    # -- donator discovery --------------------------------------------------
    def _collect_donators(self, mod) -> dict:
        """Maps callable identity -> donated argnums. Identities are plain
        names (``"f"``) or ``"self.attr"`` strings (class-scoped; name
        collisions across classes are accepted imprecision)."""
        donators: dict = {}
        # (1) direct: name = jax.jit(..., donate_argnums=...)
        for scope in _common.iter_scopes(mod.tree):
            body = getattr(scope, "body", None) or []
            for n in ast.walk(scope):
                if (isinstance(n, ast.Assign) and len(n.targets) == 1):
                    jc = _jit_call(n.value, mod)
                    if jc is None:
                        continue
                    nums = jit_donate_argnums(jc, body, mod.resolve)
                    if not nums:
                        continue
                    key = _arg_key(n.targets[0])
                    if key:
                        donators[key] = nums
        # (2) builder methods: return jax.jit(f, **kwargs-with-donate)
        builder_nums: dict = {}
        for n in ast.walk(mod.tree):
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for ret in ast.walk(n):
                if isinstance(ret, ast.Return) and ret.value is not None:
                    jc = _jit_call(ret.value, mod)
                    if jc is not None:
                        nums = jit_donate_argnums(jc, n.body, mod.resolve)
                        if nums:
                            builder_nums[n.name] = nums
        # (3) self.attr = self._build_x()
        for n in ast.walk(mod.tree):
            if (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Attribute)
                    and isinstance(n.value, ast.Call)
                    and isinstance(n.value.func, ast.Attribute)
                    and n.value.func.attr in builder_nums):
                key = _arg_key(n.targets[0])
                if key:
                    donators[key] = builder_nums[n.value.func.attr]
        return donators
