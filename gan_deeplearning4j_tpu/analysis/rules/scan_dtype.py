"""JG008 — Python-float literal on a loop-carry path.

``lax.scan``/``fori_loop``/``while_loop`` require the carry's dtype to be
invariant across iterations, and this repo runs its hot bodies under a
swappable compute dtype (``runtime/dtype.py`` — bf16 on the MXU path). A
bare Python float literal in carry arithmetic is resolved against whatever
dtype the carry happens to have at trace time:

- under a low-precision compute scope the literal silently ROUNDS to the
  carry dtype — ``0.999`` is 0.99609375 in bf16, a 3e-3 relative error that
  compounds per iteration (over a 128-step scan window, ``0.999**128`` ≈
  0.88 but ``0.99609**128`` ≈ 0.61: the decay schedule the literal was
  meant to encode is simply a different schedule);
- with strongly-typed scalars in the mix (``np.float64(...)``, x64 mode)
  the promotion goes the other way and the carry dtype drifts upward, which
  ``lax.scan`` rejects at trace time with a carry-mismatch error — the
  lucky outcome.

The rule flags float literals that participate in BinOp arithmetic on the
carry path of a loop-combinator body: inside the returned carry expression,
or in the value of an assignment whose target (transitively) feeds it.
Bodies are resolved through name indirection — a lambda, a local ``def``,
or (via the project index) a function imported from another module; the
finding lands in the file that owns the body.

True negatives: literals whose dtype is pinned — inside a call carrying a
``dtype=`` kwarg, an ``.astype(...)``, or a ``jnp.float32``-style cast —
integer literals (exact in every float dtype within range), comparisons,
and literals on non-carry values (per-step outputs do not compound).
"""

from __future__ import annotations

import ast

from gan_deeplearning4j_tpu.analysis import _common

_LOOP_COMBINATORS = {
    "jax.lax.scan": "scan",
    "jax.lax.fori_loop": "fori",
    "jax.lax.while_loop": "while",
}

#: calls that pin a literal's dtype (beyond any call with a dtype= kwarg)
_CAST_CALLS = {
    "jax.numpy.float32", "jax.numpy.float16", "jax.numpy.bfloat16",
    "jax.numpy.float64", "jax.numpy.asarray", "jax.numpy.array",
    "numpy.float32", "numpy.float64", "numpy.asarray", "numpy.array",
}


def _body_arg(call: ast.Call, kind: str):
    """The body-function expression of a loop-combinator call."""
    if kind == "scan":
        pos, kw_name = 0, "f"
    elif kind == "fori":
        pos, kw_name = 2, "body_fun"
    else:  # while
        pos, kw_name = 1, "body_fun"
    if len(call.args) > pos:
        return call.args[pos]
    for kw in call.keywords:
        if kw.arg == kw_name:
            return kw.value
    return None


def _fn_params(fn) -> list:
    a = fn.args
    return [p.arg for p in (a.posonlyargs + a.args)]


def _carry_param(fn, kind: str):
    params = _fn_params(fn)
    idx = 1 if kind == "fori" else 0
    return params[idx] if len(params) > idx else None


def _carry_exprs(fn, kind: str) -> list:
    """The expressions whose value becomes next iteration's carry."""
    if isinstance(fn, ast.Lambda):
        vals = [fn.body]
    else:
        vals = [
            r.value
            for r in _common.walk_excluding_defs(fn.body)
            if isinstance(r, ast.Return) and r.value is not None
        ]
    if kind != "scan":
        return vals  # fori/while bodies return the carry itself
    out = []
    for v in vals:
        out.append(v.elts[0] if isinstance(v, ast.Tuple) and v.elts else v)
    return out


def _exempt_literals(fn, resolve) -> set:
    """ids of float Constants whose dtype is pinned by an enclosing call."""
    exempt = set()
    for n in ast.walk(fn):
        if not isinstance(n, ast.Call):
            continue
        pinned = (
            any(kw.arg == "dtype" for kw in n.keywords)
            or (isinstance(n.func, ast.Attribute) and n.func.attr == "astype")
            or resolve(n.func) in _CAST_CALLS
        )
        if pinned:
            for c in ast.walk(n):
                if isinstance(c, ast.Constant) and isinstance(c.value, float):
                    exempt.add(id(c))
    return exempt


def _float_operands(binop: ast.BinOp):
    for side in (binop.left, binop.right):
        node = side
        while isinstance(node, ast.UnaryOp):
            node = node.operand
        if isinstance(node, ast.Constant) and isinstance(node.value, float):
            yield node


class ScanCarryDtypeDrift:
    code = "JG008"
    name = "scan-carry-dtype-drift"
    summary = ("bare Python float literal in loop-carry arithmetic — "
               "rounds to the compute dtype and compounds per iteration")

    def check(self, mod):
        for call in ast.walk(mod.tree):
            if not isinstance(call, ast.Call):
                continue
            kind = _LOOP_COMBINATORS.get(mod.resolve(call.func))
            if kind is None:
                continue
            body = _body_arg(call, kind)
            if body is None:
                continue
            fn, owner = self._resolve_body(body, mod)
            if fn is None:
                continue
            yield from self._check_body(fn, kind, owner)

    def _resolve_body(self, body, mod):
        """(function node, owning SourceModule) — lambda inline, a def in
        this module, or an imported function through the project index."""
        if isinstance(body, ast.Lambda):
            return body, mod
        if isinstance(body, ast.Name):
            for n in ast.walk(mod.tree):
                if (isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and n.name == body.id):
                    return n, mod
        if mod.project is not None:
            summary = mod.project.resolve_function(mod, body)
            if summary is not None and summary.node is not None:
                info = mod.project.modules.get(summary.module)
                owner = info.srcmod if info else None
                if owner is not None:
                    return summary.node, owner
        return None, None

    def _check_body(self, fn, kind, mod):
        carry = _carry_param(fn, kind)
        if carry is None:
            return
        carry_exprs = _carry_exprs(fn, kind)
        if not carry_exprs:
            return
        body_root = [fn.body] if isinstance(fn, ast.Lambda) else fn.body
        # names that (transitively) feed the returned carry
        assigns = [
            (s, _common.assignment_targets(s), s.value)
            for s in _common.walk_excluding_defs(body_root)
            if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign))
            and getattr(s, "value", None) is not None
        ]
        feeding = set()
        for expr in carry_exprs:
            feeding |= _common.loaded_names(expr)
        changed = True
        while changed:
            changed = False
            for _, targets, value in assigns:
                if targets & feeding:
                    loaded = _common.loaded_names(value)
                    if not loaded <= feeding:
                        feeding |= loaded
                        changed = True
        exempt = _exempt_literals(fn, mod.resolve)
        roots = list(carry_exprs) + [
            value for _, targets, value in assigns if targets & feeding
        ]
        reported = set()
        for root in roots:
            for n in ast.walk(root):
                if not isinstance(n, ast.BinOp):
                    continue
                for lit in _float_operands(n):
                    if id(lit) in exempt or id(lit) in reported:
                        continue
                    reported.add(id(lit))
                    f = mod.finding(
                        self.code,
                        f"float literal `{lit.value}` in arithmetic on the "
                        f"{kind}-loop carry path (carry `{carry}`) — the "
                        f"literal is resolved against the carry's compute "
                        f"dtype at trace time (0.999 is ~0.9961 in bf16) "
                        f"and the rounding compounds every iteration; pin "
                        f"it: jnp.asarray({lit.value}, dtype=...) or do "
                        f"this arithmetic in f32 and cast back",
                        lit,
                    )
                    yield f, n
