"""JG009 — host callback inside a timed region.

``io_callback``/``pure_callback``/``jax.debug.print``/``jax.debug.callback``
suspend device execution and round-trip through the host every time they
run. Inside a *timed region* — a loop that reads a wall clock, or the span
between two clock reads — that round-trip is billed to the measurement: on
the tunneled axon platform a single host hop costs ~70 ms (PROFILE.md
round 3), an order of magnitude above the per-step times bench.py exists
to resolve. The bench architecture's whole design rule is "nothing crosses
the host boundary inside the window except the final fence"; a callback
hidden two calls deep breaks it invisibly.

Cross-module: the callback rarely sits in the timed loop itself — it sits
in a jitted step the loop calls, often defined a module away. Phase 1's
project index records which functions perform host callbacks directly and
the rule consults the TRANSITIVE closure over the intra-project call graph,
so ``timed(step)`` is flagged when ``step -> _log_losses -> io_callback``.

True negatives: callbacks outside any timed region (debugging
instrumentation in un-timed paths is fine), fences (``np.asarray``,
``block_until_ready`` — those are the protocol, JG002 owns their
correctness), and clock reads themselves.
"""

from __future__ import annotations

import ast

from gan_deeplearning4j_tpu.analysis import _common


def _clock_lines(nodes, mod):
    return sorted(
        n.lineno
        for n in _common.walk_excluding_defs(nodes)
        if isinstance(n, ast.Call) and mod.resolve(n.func) in _common.CLOCK_CALLS
    )


class CallbackInTimedRegion:
    code = "JG009"
    name = "callback-in-timed-region"
    summary = ("io_callback/pure_callback reached from a timed region — "
               "the measurement includes host round-trips")

    def check(self, mod):
        reported = set()
        # region 1: any loop that reads a clock
        for loop in _common.iter_loops(mod.tree):
            if _clock_lines(loop, mod):
                yield from self._scan_region(
                    loop, mod, reported, where="timed loop")
        # region 2: the straight-line span between the first and last clock
        # read of a function body (the `t0 = clock(); work; t1 = clock()`
        # shape) — nested defs excluded, loops already covered above
        for scope in _common.iter_scopes(mod.tree):
            body = getattr(scope, "body", None)
            if not body:
                continue
            lines = _clock_lines(body, mod)
            if len(lines) < 2:
                continue
            lo, hi = lines[0], lines[-1]
            span = [
                n for n in _common.walk_excluding_defs(body)
                if isinstance(n, ast.Call)
                and lo <= getattr(n, "lineno", 0) <= hi
            ]
            yield from self._scan_calls(
                span, mod, reported, where="timed span")

    def _scan_region(self, region, mod, reported, where):
        calls = [
            n for n in _common.walk_excluding_defs(region)
            if isinstance(n, ast.Call)
        ]
        yield from self._scan_calls(calls, mod, reported, where)

    def _scan_calls(self, calls, mod, reported, where):
        for call in calls:
            if id(call) in reported:
                continue
            resolved = mod.resolve(call.func)
            if resolved in _common.HOST_CALLBACKS:
                reported.add(id(call))
                f = mod.finding(
                    self.code,
                    f"`{resolved}` inside a {where} — every invocation "
                    f"suspends the device and round-trips through the host "
                    f"(~70 ms through the tunnel), so the measurement times "
                    f"the callback, not the compute; move it outside the "
                    f"timed region",
                    call,
                )
                yield f, call
                continue
            if mod.project is None or resolved in _common.CLOCK_CALLS:
                continue
            summary = mod.project.resolve_function(mod, call.func)
            if summary is not None and mod.project.callback_tainted(summary):
                reported.add(id(call))
                f = mod.finding(
                    self.code,
                    f"`{ast.unparse(call.func)}` is called inside a {where} "
                    f"and `{summary.fq}` performs a host callback "
                    f"(io_callback/pure_callback/jax.debug.*), directly or "
                    f"through its callees — the measurement includes host "
                    f"round-trips; strip the callback or time a "
                    f"callback-free variant",
                    call,
                )
                yield f, call
