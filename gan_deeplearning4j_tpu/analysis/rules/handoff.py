"""JG029 — resource handed to a thread/callback that never closes it.

The subtle third act of the pair family: the opening function does
everything right locally — it opens the resource and hands it to a
worker thread or completion callback, transferring the closing
obligation — but the *receiver* never closes it. Locally both functions
look fine (the opener transferred, the receiver just uses what it was
given); the leak only exists in the pairing. The device-capture plane
hit exactly this before PR 6 made ``_swallow_owned`` release the capture
lock in its ``finally``.

The model (phase-1½ lifecycle index + project call summaries): an open
whose outcome is a handoff — the receiver or its token passed into
``threading.Thread(target=...)`` / ``Timer`` / ``add_done_callback`` /
callback registration — where the receiver function *resolves* through
the project index (same-class ``self._m``, module function, or imported
function) and its body does **not** contain the closing call on the
same receiver. An unresolvable target stays a silent transfer: the
analyzer only indicts code it can actually read.

Not flagged: handoffs whose resolved receiver closes (the correct
ownership-transfer idiom — flagging it would punish the fix); handoffs
of resources the *spawning* function also closes on every path (the
thread only borrows it); unresolvable targets (lambdas wrapping foreign
calls, ``functools.partial`` chains, cross-process queues). Known false
negatives: a receiver that closes only via its own helper call; a
receiver resolved through more than one re-export hop.
"""

from __future__ import annotations


class HandoffWithoutTransfer:
    code = "JG029"
    name = "handoff-without-transfer"
    summary = ("resource opened then passed to Thread(target=...)/callback "
               "whose resolved body never makes the closing call")
    skip_tests = True

    def check(self, mod):
        if mod.project is None:
            return
        for fl in mod.project.lifecycle.functions(mod.path):
            for ev in fl.opens:
                h = ev.handoff
                if (ev.outcome != "transferred" or h is None
                        or not h.resolved or h.target_closes):
                    continue
                yield mod.finding(
                    self.code,
                    f"`{fl.name}` opens `{ev.recv}.{ev.pair.open}(...)` "
                    f"and hands it to `{h.target}`, but that receiver "
                    f"never calls `{ev.recv}.{ev.pair.close}()` — the "
                    f"closing obligation was transferred to code that "
                    f"doesn't discharge it; close it in the receiver's "
                    f"`finally` (or keep ownership here and close after "
                    f"the handoff completes)",
                    h.node,
                ), h.node
