"""JG017 — blocking network call without an explicit timeout.

The fleet plane (fleet/router, fleet/health, fleet/manager, the deploy
watcher's HTTP paths, the drills) is built on stdlib blocking I/O —
``urllib.request.urlopen``, ``http.client.HTTPConnection``,
``socket.create_connection``. Each of these blocks FOREVER by default: a
SIGSTOPped worker, a half-open TCP connection, or a dropped tunnel turns
the caller into a second hung process — the exact failure the router
exists to contain. The fleet drill proved it the hard way: one probe
without a timeout and the health loop wedges behind the very worker it
was supposed to eject, so ejection never happens and every request hangs.

The rule: a call to a known blocking network entry point must bound its
wait — an explicit ``timeout=`` keyword, or a positional argument in the
callable's documented timeout slot (``urlopen(url, data, 5.0)``,
``create_connection(addr, 5.0)``). A bare ``socket.socket()`` is not
flagged (bind/listen shapes are legitimate); resolution goes through the
import map, so aliased imports are still caught and a project-local
``urlopen`` helper is not.

True negatives: any of the calls with ``timeout=`` (or the positional
slot filled), non-network callables, and test modules (``skip_tests`` —
tests pin their own harness timeouts).
"""

from __future__ import annotations

import ast

from gan_deeplearning4j_tpu.analysis import _common

#: blocking network callables -> index of their positional timeout slot
NETWORK_CALLS = {
    "urllib.request.urlopen": 2,          # url, data, timeout
    "http.client.HTTPConnection": 2,      # host, port, timeout
    "http.client.HTTPSConnection": 2,
    "socket.create_connection": 1,        # address, timeout
}


class UnboundedNetworkCall:
    code = "JG017"
    name = "unbounded-network-call"
    summary = ("blocking network call without an explicit timeout — a dead "
               "peer hangs the caller forever")
    skip_tests = True

    def check(self, mod):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = _common.resolve_call(node, mod.imports)
            slot = NETWORK_CALLS.get(resolved)
            if slot is None:
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            if len(node.args) > slot:
                continue  # timeout passed positionally
            yield mod.finding(
                self.code,
                f"`{resolved}` blocks forever without a timeout — a hung "
                f"or half-open peer wedges this thread (and anything "
                f"waiting on it); pass an explicit `timeout=`",
                node,
            ), node
