"""JG025 — lock-order inversion (potential deadlock).

With PRs 11–15 every plane holds its own lock, and several hold two (the
fleet manager's ``_lock``/``_cycle_lock``/``_supervise_lock``, the mux
service's registry + splitter pair). Two threads that take the same two
locks in opposite orders deadlock the first time their critical sections
overlap — a hazard no drill reproduces reliably, because the window is a
few instructions wide. The classic static check: build the
lock-acquisition graph (edge A→B when B is acquired while A is held) and
flag cycles.

The model (phase-1 concurrency index): every ``with <lock>:`` acquisition
contributes edges from each lock already held (lexical nesting), plus one
resolved same-class call hop — ``with self._a: self._helper()`` where
``_helper`` does ``with self._b:`` contributes A→B at the call site. Lock
identities are class-qualified for ``self`` locks (``Manager._lock``),
source text for module-level and foreign locks (``_capture_lock``,
``registry.lock``) — and **unified across classes** through the index's
project-wide union-find: a lock injected via a constructor
(``Worker(lock=self._lock)`` forwarded into ``self._lk``) or planted by
attribute assignment (``worker._lk = self._lock``) is ONE canonical lock,
and the acquisition graph is project-wide, so an inversion split between
two planes (manager nests A→B, the worker it built around the same A
nests B→A) is found even though neither module alone contains a cycle.
Each cycle is reported exactly once, in the module owning its closing
edge (first in sorted path/line order — deterministic across runs).

Not flagged: re-acquiring the same canonical lock (RLock re-entrancy,
Condition-over-lock aliasing, and a shared injected lock held on both
sides of a call are not inversions); consistent global orderings (A→B
twice is one edge). Known false negatives: sharing routes other than
constructor injection/attribute assignment (a lock fished out of a
registry dict); ``.acquire()``/``.release()`` held regions outside
``with`` (the lifecycle index pairs those, but they carry no held-set).
"""

from __future__ import annotations

from typing import Dict, List, Optional


def _short(key: tuple) -> str:
    """Display form of a canonical (module, short_id) lock key."""
    return key[1]


class LockOrderInversion:
    code = "JG025"
    name = "lock-order-inversion"
    summary = ("two locks acquired in opposite orders on different paths — "
               "a potential deadlock")
    skip_tests = True

    def check(self, mod):
        if mod.project is None:
            return
        index = mod.project.concurrency
        edges = index.global_lock_edges()

        adj: Dict[tuple, List[tuple]] = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
        for a in adj:
            adj[a].sort()

        # walk edges in deterministic (path, line, edge) order; the first
        # edge that closes each cycle owns the finding, and only the
        # module that owns it reports — one finding per cycle, stable
        # regardless of which module the runner visits first
        seen_cycles = set()
        for (a, b) in sorted(
                edges,
                key=lambda e: (edges[e][0], edges[e][1].lineno, e)):
            path = self._path(adj, b, a)
            if path is None:
                continue
            cycle = [a] + path  # a -> b -> ... -> a
            key = frozenset(cycle)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            epath, node, where = edges[(a, b)]
            if epath != mod.path:
                continue
            hops = []
            for i in range(len(cycle) - 1):
                e = edges.get((cycle[i], cycle[i + 1]))
                loc = (f"{e[0]}:{e[1].lineno} in {e[2]}"
                       if e else "resolved hop")
                hops.append(
                    f"`{_short(cycle[i])}` -> `{_short(cycle[i + 1])}` "
                    f"({loc})")
            names = ' -> '.join(f'`{_short(c)}`' for c in cycle)
            yield mod.finding(
                self.code,
                f"lock-order inversion: taking `{_short(b)}` while "
                f"holding `{_short(a)}` (in {where}) closes the cycle "
                f"{names} — two threads entering these regions "
                f"concurrently can deadlock; pick one global acquisition "
                f"order [{'; '.join(hops)}]",
                node,
            ), node

    @staticmethod
    def _path(adj, start, goal) -> Optional[List[tuple]]:
        """Deterministic DFS path start -> ... -> goal, as a node list
        ending at goal (start included first), else None."""
        stack = [(start, [start])]
        visited = set()
        while stack:
            cur, path = stack.pop()
            if cur == goal:
                return path
            if cur in visited:
                continue
            visited.add(cur)
            for nxt in reversed(adj.get(cur, [])):
                if nxt not in visited:
                    stack.append((nxt, path + [nxt]))
        return None
