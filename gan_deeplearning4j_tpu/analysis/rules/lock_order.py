"""JG025 — lock-order inversion (potential deadlock).

With PRs 11–15 every plane holds its own lock, and several hold two (the
fleet manager's ``_lock``/``_cycle_lock``/``_supervise_lock``, the mux
service's registry + splitter pair). Two threads that take the same two
locks in opposite orders deadlock the first time their critical sections
overlap — a hazard no drill reproduces reliably, because the window is a
few instructions wide. The classic static check: build the
lock-acquisition graph (edge A→B when B is acquired while A is held) and
flag cycles.

The model (phase-1 concurrency index): per module, every ``with <lock>:``
acquisition contributes edges from each lock already held (lexical
nesting), plus one resolved same-class call hop — ``with self._a:
self._helper()`` where ``_helper`` does ``with self._b:`` contributes
A→B at the call site. Lock identities are class-qualified for ``self``
locks (``Manager._lock``), source text for module-level and foreign locks
(``_capture_lock``, ``registry.lock``); condition variables constructed
over a lock alias to that lock. A cycle in the per-module graph is
reported once, at the edge that closes it, naming the full cycle and
where each edge was taken.

Not flagged: re-acquiring the same canonical lock (RLock re-entrancy and
Condition-over-lock aliasing are not inversions); consistent global
orderings (A→B twice is one edge); acquisition sequences in different
modules (documented false negative: cross-plane inversions need lock ids
that unify across classes, which static ``self`` analysis cannot give —
the drills own that). ``.acquire()``/``.release()`` outside ``with`` is
likewise invisible.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class LockOrderInversion:
    code = "JG025"
    name = "lock-order-inversion"
    summary = ("two locks acquired in opposite orders on different paths — "
               "a potential deadlock")
    skip_tests = True

    def check(self, mod):
        if mod.project is None:
            return
        # edge (A, B) -> first (node, method) that took B while holding A
        edges: Dict[Tuple[str, str], tuple] = {}

        def add_edge(held, lock, node, where):
            for h in held:
                if h != lock and (h, lock) not in edges:
                    edges[(h, lock)] = (node, where)

        for cc in mod.project.concurrency.classes(mod.path):
            for mc in cc.methods.values():
                for acq in mc.acquisitions:
                    add_edge(acq.held_before, acq.lock, acq.node,
                             f"{cc.name}.{mc.name}")
                for call in mc.self_calls:
                    if not call.held:
                        continue
                    callee = cc.methods.get(call.callee)
                    if callee is None:
                        continue
                    # one call hop: locks the callee acquires are taken
                    # while the caller's held set is still held
                    for acq in callee.acquisitions:
                        add_edge(call.held, acq.lock, call.node,
                                 f"{cc.name}.{mc.name} -> {call.callee}")

        adj: Dict[str, List[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
        for a in adj:
            adj[a].sort()

        seen_cycles = set()
        for (a, b) in sorted(
                edges, key=lambda e: (edges[e][0].lineno, e)):
            path = self._path(adj, b, a)
            if path is None:
                continue
            cycle = [a] + path  # a -> b -> ... -> a
            key = frozenset(cycle)
            if key in seen_cycles:
                continue
            seen_cycles.add(key)
            node, where = edges[(a, b)]
            hops = []
            for i in range(len(cycle) - 1):
                e = edges.get((cycle[i], cycle[i + 1]))
                loc = (f"{mod.path}:{e[0].lineno} in {e[1]}"
                       if e else "resolved hop")
                hops.append(
                    f"`{cycle[i]}` -> `{cycle[i + 1]}` ({loc})")
            yield mod.finding(
                self.code,
                f"lock-order inversion: taking `{b}` while holding `{a}` "
                f"(in {where}) closes the cycle "
                f"{' -> '.join(f'`{c}`' for c in cycle)} — two threads "
                f"entering these regions concurrently can deadlock; pick "
                f"one global acquisition order [{'; '.join(hops)}]",
                node,
            ), node

    @staticmethod
    def _path(adj, start: str, goal: str) -> Optional[List[str]]:
        """Deterministic DFS path start -> ... -> goal, as a node list
        ending at goal (start included first), else None."""
        stack = [(start, [start])]
        visited = set()
        while stack:
            cur, path = stack.pop()
            if cur == goal:
                return path
            if cur in visited:
                continue
            visited.add(cur)
            for nxt in reversed(adj.get(cur, [])):
                if nxt not in visited:
                    stack.append((nxt, path + [nxt]))
        return None
