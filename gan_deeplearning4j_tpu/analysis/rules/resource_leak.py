"""JG027 — paired resource leaked on an exception or early-exit path.

The bug class every hardening round has hand-caught at least once: a
paired operation (lock ``acquire``/``release``, trace span
``async_begin``/``async_end``, engine ``dispatch``/``finalize``, token
``take``/``refund``, in-flight counter ``+=``/``-=``, a project-local
``open_x``/``close_x`` dual) whose closing half is skipped when a
statement between the two raises, or when an early ``return``/``raise``/
``continue`` leaves the scope, or when control simply falls off the end
of the function. The engine's replica ledger (PR 4), the router's retry
refund (PR 8), and the device-capture lock (PR 6) were all this shape.

The model (phase-1½ lifecycle index): every tracked open is classified
``closed`` (a matching same-receiver close dominates every exit —
``try/finally``, close on every branch, same-statement pairing),
``transferred`` (the receiver or bound token is returned, raised, stored
into ``self``/a container, passed to another call or thread — the
closing obligation moved with it; a ``self`` resource whose close-half
lives in a sibling method is the ``start``/``stop`` instance-holds-it
idiom and also transfers), or ``leak``. Leaks are flagged with the
escaping statement: the raise-capable call in the unprotected gap, the
early exit, the loop boundary, or the function end.

Not flagged: ``with``-statement acquisition (balanced by construction);
seeded opens in modules that never name the close-half (``atexit
.register`` is fire-and-forget, not half a protocol); cross-method
counters (the dispatch/finalize ledger is ownership-by-design). Known
false negatives (see :mod:`..lifecycle`): closes reached only through
unresolvable helper calls; handlers that swallow a mid-``try`` exception
without closing.
"""

from __future__ import annotations


class LeakedPairedResource:
    code = "JG027"
    name = "leaked-paired-resource"
    summary = ("paired open (acquire/begin/dispatch/take/+=) reachable by "
               "an exception or early-exit path with no guaranteed close "
               "and no ownership transfer")
    skip_tests = True

    _KINDS = {
        "exception-path": ("an exception between the open and the close "
                           "skips the close"),
        "early-exit": "an early exit leaves the scope with it open",
        "loop-carried": ("the loop re-enters and re-opens without the "
                         "close running"),
        "fall-through": "control falls off the end with it still open",
    }

    def check(self, mod):
        if mod.project is None:
            return
        for fl in mod.project.lifecycle.functions(mod.path):
            for ev in fl.opens:
                if ev.outcome != "leak":
                    continue
                why = self._KINDS.get(ev.leak_kind,
                                      self._KINDS["fall-through"])
                opener = ("`self.%s += ...`" % ev.recv.split(".")[-1]
                          if ev.pair.kind == "counter"
                          else f"`{ev.recv}.{ev.pair.open}(...)`")
                closer = (f"`{ev.recv} -= ...`" if ev.pair.kind == "counter"
                          else f"`{ev.recv}.{ev.pair.close}()`")
                yield mod.finding(
                    self.code,
                    f"`{fl.name}` opens {opener} but {why}: {closer} is "
                    f"not guaranteed on every path and ownership never "
                    f"transfers — close it in a `finally` (or hand it off "
                    f"explicitly) so the {ev.pair.kind} pair balances",
                    ev.node,
                ), ev.node
