"""JG018 — sharded-state-spec-mismatch: updater state placed with a
NamedSharding spec that disagrees with its paired params' spec.

The update-sharding design ("Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training", PAPERS.md) rests on one invariant: a
parameter and the optimizer/updater slots that step it live on the SAME
partition of the mesh. Break it — params replicated (``PartitionSpec()``)
while RmsProp caches shard over ``'data'``, or specs copy-pasted between
the trainer and serving meshes — and one of two silent failures follows:
jax inserts a reshard (all-gather or scatter of the full updater state)
into EVERY training step, erasing exactly the HBM/step-time win update
sharding exists for, or the first donated-buffer update hits a
sharding-mismatch error minutes into a run on an exclusively-held chip.
The mesh checkpoint plane (resilience/mesh.py) makes the same assumption
on the restore side: shard manifests are resolved against the live spec,
so a train-time mismatch becomes a restore-time surprise.

The rule fires only on statically-certain evidence, in one scope:

1. a value is *recognizably* params or updater state — its expression (or
   the name it is assigned to) is an identifier containing ``param``, vs
   one containing ``opt_state``/``updater``/``opt_states`` (the repo's
   naming convention, enforced by the trainer API: ``TrainState.params`` /
   ``TrainState.opt_state``);
2. it is placed via ``jax.device_put(x, NamedSharding(mesh, spec))`` or
   ``jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))``
   with a LITERAL ``PartitionSpec`` (string/None/tuple-of-string entries);
3. both roles are placed against the SAME mesh variable, every param
   placement in the scope agrees on one spec, and an updater placement
   uses a different one.

Non-literal specs, unrecognized names, different mesh variables, and
scopes where the param placements already disagree among themselves are
silence, not a guess. Test modules are exempt (``skip_tests`` — parity
tests build deliberately mismatched placements).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from gan_deeplearning4j_tpu.analysis import _common
from gan_deeplearning4j_tpu.analysis.rules.mesh_axes import _scope_walk

_PLACERS = {
    "jax.device_put",
    "jax.lax.with_sharding_constraint",
    "jax.experimental.pjit.with_sharding_constraint",
}
_NAMED_SHARDING = {"jax.sharding.NamedSharding"}
_PSPEC = {"jax.sharding.PartitionSpec"}

_UPDATER_TOKENS = ("opt_state", "opt_states", "updater")
_PARAM_TOKEN = "param"


def _identifier(node: ast.AST) -> Optional[str]:
    """The terminal identifier of a Name or dotted Attribute — the thing
    role classification keys on (``self.opt_state`` -> ``opt_state``)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _role(name: Optional[str]) -> Optional[str]:
    if not name:
        return None
    lowered = name.lower()
    if any(tok in lowered for tok in _UPDATER_TOKENS):
        return "updater"
    if _PARAM_TOKEN in lowered and "spec" not in lowered \
            and "sharding" not in lowered:
        return "param"
    return None


def _literal_spec(call: ast.Call) -> Optional[Tuple]:
    """Normalize a ``PartitionSpec(...)`` call with fully literal entries
    to a comparable tuple; None when any entry is non-literal."""
    out: List = []
    for arg in call.args:
        if isinstance(arg, ast.Constant) and (
                isinstance(arg.value, str) or arg.value is None):
            out.append(arg.value)
        elif isinstance(arg, (ast.Tuple, ast.List)):
            entry = []
            for elt in arg.elts:
                if not (isinstance(elt, ast.Constant)
                        and isinstance(elt.value, str)):
                    return None
                entry.append(elt.value)
            out.append(tuple(entry))
        else:
            return None
    if call.keywords:
        return None
    return tuple(out)


def _spec_repr(spec: Tuple) -> str:
    inner = ", ".join(repr(e) if not isinstance(e, tuple)
                      else "(" + ", ".join(repr(x) for x in e) + ")"
                      for e in spec)
    return f"PartitionSpec({inner})"


class ShardedStateSpecMismatch:
    code = "JG018"
    name = "sharded-state-spec-mismatch"
    summary = ("updater/optimizer state sharded with a spec that disagrees "
               "with its paired params")
    skip_tests = True

    def _placements(self, mod, scope):
        """(role, mesh_name, spec, node) for every statically-certain
        placement in the scope's own statements. ``_scope_walk`` yields
        every node, so placer calls are processed where they are MET (once
        each); a first pass maps a call assigned whole to a single Name —
        ``opt_state = jax.device_put(optimizer.init(p), ...)`` — to that
        name, the role fallback when the placed expression is anonymous."""
        assigned_name: Dict[int, str] = {}
        placer_calls: List[ast.Call] = []
        for node in _scope_walk(scope):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                assigned_name[id(node.value)] = node.targets[0].id
            if isinstance(node, ast.Call) \
                    and mod.resolve(node.func) in _PLACERS:
                placer_calls.append(node)
        out = []
        for call in placer_calls:
            if not call.args:
                continue
            value = call.args[0]
            sharding = None
            if len(call.args) >= 2:
                sharding = call.args[1]
            for kw in call.keywords:
                if kw.arg in ("device", "shardings", "sharding"):
                    sharding = kw.value
            if not (isinstance(sharding, ast.Call)
                    and mod.resolve(sharding.func) in _NAMED_SHARDING
                    and sharding.args
                    and isinstance(sharding.args[0], ast.Name)):
                continue
            mesh_name = sharding.args[0].id
            spec_call = sharding.args[1] if len(sharding.args) >= 2 \
                else None
            if not (isinstance(spec_call, ast.Call)
                    and mod.resolve(spec_call.func) in _PSPEC):
                continue
            spec = _literal_spec(spec_call)
            if spec is None:
                continue
            role = _role(_identifier(value))
            if role is None:
                role = _role(assigned_name.get(id(call)))
            if role is None:
                continue
            out.append((role, mesh_name, spec, call))
        return out

    def check(self, mod):
        for scope in _common.iter_scopes(mod.tree):
            placements = self._placements(mod, scope)
            by_mesh: Dict[str, List] = {}
            for role, mesh_name, spec, node in placements:
                by_mesh.setdefault(mesh_name, []).append((role, spec, node))
            for mesh_name, group in by_mesh.items():
                param_specs = {spec for role, spec, _ in group
                               if role == "param"}
                if len(param_specs) != 1:
                    continue  # no param anchor, or params already disagree
                param_spec = next(iter(param_specs))
                for role, spec, node in group:
                    if role == "updater" and spec != param_spec:
                        yield mod.finding(
                            self.code,
                            f"updater state is placed on mesh "
                            f"{mesh_name!r} with {_spec_repr(spec)} but its "
                            f"paired params use {_spec_repr(param_spec)} — "
                            f"every optimizer step will reshard the full "
                            f"updater state (or fail at first use on "
                            f"chip); shard updater slots with the same "
                            f"spec as the params they step",
                            node,
                        ), node
