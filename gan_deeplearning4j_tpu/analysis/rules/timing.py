"""JG002 — stale-fence timing.

On the tunneled axon platform a dispatch returns immediately and even
``block_until_ready`` can return before execution finishes, so a timed loop
must fence on a device->host read of a value produced by THE CALL BEING
TIMED. Fencing on anything older measures dispatch latency, not execution:
the round-5 ``scripts/mfu_ceiling.py`` harness timed every call against the
*warmup* output and reported numbers whose error was unbounded.

Two patterns:

1. in-loop stale fence — a for/while loop that reads a wall clock
   (``time.perf_counter`` etc.) AND contains a fence call
   (``np.asarray(...)``, ``jax.block_until_ready``/``device_get``,
   ``.block_until_ready()``, ``.item()``) none of whose argument names is
   bound inside the loop: the fenced value cannot be this iteration's
   output. (The bench's chunk loops are clean: the fence reads ``losses``,
   rebound every iteration.)

2. stale sync callback — a ZERO-argument lambda whose body fences a name
   bound in the enclosing function, passed to a call alongside another
   callable argument (the ``_timed_calls(fn, sync)`` shape). A sync
   callback that takes no parameter can never see the timed call's fresh
   output; the fix is ``sync(fn())`` with ``lambda out: np.asarray(...)``.
   This is the exact ``mfu_ceiling.py:164`` bug.
"""

from __future__ import annotations

import ast

from gan_deeplearning4j_tpu.analysis import _common

_CLOCKS = _common.CLOCK_CALLS
_FENCE_CALLS = {
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
    "jax.block_until_ready", "jax.device_get",
}
_FENCE_METHODS = {"block_until_ready", "item"}


def _fence_read_names(call: ast.Call, mod):
    """Names whose values a fence call forces to host, or None if ``call``
    is not a fence."""
    resolved = mod.resolve(call.func)
    if resolved in _FENCE_CALLS:
        names = set()
        for arg in call.args:
            names |= _common.loaded_names(arg)
        return names
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in _FENCE_METHODS and not call.args):
        return _common.loaded_names(call.func.value)
    return None


class StaleFenceTiming:
    code = "JG002"
    name = "stale-fence-timing"
    summary = ("timed loop syncs on a value bound outside the loop — "
               "measures dispatch, not execution")

    def check(self, mod):
        yield from self._check_loops(mod)
        yield from self._check_sync_callbacks(mod)

    # -- pattern 1: in-loop stale fence ------------------------------------
    def _check_loops(self, mod):
        for loop in _common.iter_loops(mod.tree):
            has_clock = any(
                isinstance(n, ast.Call) and mod.resolve(n.func) in _CLOCKS
                for n in _common.walk_excluding_defs(loop)
            )
            if not has_clock:
                continue
            loop_bound = _common.bound_names(loop)
            for n in _common.walk_excluding_defs(loop):
                if not isinstance(n, ast.Call):
                    continue
                read = _fence_read_names(n, mod)
                if read and not (read & loop_bound):
                    f = mod.finding(
                        self.code,
                        f"timed loop fences on "
                        f"`{ast.unparse(n)[:60]}` but none of "
                        f"{sorted(read - {'next', 'iter'})} is assigned in "
                        f"the loop — the fence waits on a stale value, not "
                        f"this iteration's output",
                        n,
                    )
                    yield f, n

    # -- pattern 2: zero-arg stale sync callback ---------------------------
    def _check_sync_callbacks(self, mod):
        # enclosing-scope bindings, innermost function wins
        for scope in _common.iter_scopes(mod.tree):
            body = getattr(scope, "body", None)
            if body is None:
                continue
            scope_bound = _common.bound_names(scope)
            for n in ast.walk(scope):
                if not isinstance(n, ast.Call) or len(n.args) < 2:
                    continue
                lambdas = [a for a in n.args if isinstance(a, ast.Lambda)]
                if len(lambdas) < 2 and not (
                    lambdas and any(
                        not isinstance(a, ast.Lambda)
                        and isinstance(a, (ast.Name, ast.Attribute))
                        for a in n.args
                    )
                ):
                    continue
                for lam in lambdas:
                    if lam.args.args or lam.args.posonlyargs or lam.args.kwonlyargs:
                        continue  # takes a parameter: can receive the output
                    for inner in ast.walk(lam.body):
                        if not isinstance(inner, ast.Call):
                            continue
                        read = _fence_read_names(inner, mod)
                        if read and (read & scope_bound):
                            f = mod.finding(
                                self.code,
                                f"zero-argument sync callback fences "
                                f"`{ast.unparse(inner)[:60]}` from the "
                                f"enclosing scope — it can never see the "
                                f"timed call's own output; pass the result "
                                f"through the callback's parameter",
                                lam,
                            )
                            yield f, lam
                            break
