"""JG012 — dead ``out_shardings`` on donated buffers.

Donation lets XLA alias an input buffer into an output — but ONLY when an
output exists with the same sharding (and shape) as the donated input. A
``jax.jit`` call that donates an argument while declaring ``out_shardings``
in which the donated input's sharding never appears has quietly disabled
the donation: XLA frees the input, reshards into a fresh allocation, and
the HBM saving the donation was written for is gone. Nothing fails — jax
at most logs a "donated buffer was not usable" warning that scrolls past —
so peak memory is silently ~2× what the code claims. This is the
production flavor of the hazard: the sharding ladder gets edited (an
output resharded to ``data`` for a downstream consumer) and the donation
on the companion input becomes dead weight.

The rule fires when a jit/pmap call has statically-resolvable
``donate_argnums``, ``in_shardings`` AND ``out_shardings`` (literal tuples
— including the ``(rep,) * 4 + (data,) * 4`` repetition idiom and the
``kwargs``-dict builder idiom of ``harness/experiment.py``) and some
donated position's in-sharding expression matches NO out-sharding
expression. Comparison is syntactic (unparsed expression text): two
spellings of the same sharding are accepted imprecision on the safe side
(no finding), and unresolvable specs are skipped entirely.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from gan_deeplearning4j_tpu.analysis import _common
from gan_deeplearning4j_tpu.analysis.project import jit_donate_argnums


def _elems(node: ast.AST) -> Optional[Tuple[str, List[str]]]:
    """Static element list of a shardings spec expression.

    Returns ``("tuple", [unparsed element, ...])`` for tuple-shaped specs —
    literal tuples, ``(x,) * k`` repetition, and ``+`` concatenation — or
    ``("single", [unparsed])`` for a lone sharding jit broadcasts to every
    leaf; None when the shape cannot be resolved statically."""
    if isinstance(node, (ast.Tuple, ast.List)):
        return "tuple", [ast.unparse(e) for e in node.elts]
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left, right = _elems(node.left), _elems(node.right)
        if left and right and left[0] == right[0] == "tuple":
            return "tuple", left[1] + right[1]
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        base, count = node.left, node.right
        if isinstance(base, ast.Constant):
            base, count = count, base  # 4 * (rep,)
        inner = _elems(base)
        if (inner and inner[0] == "tuple"
                and isinstance(count, ast.Constant)
                and isinstance(count.value, int)):
            return "tuple", inner[1] * count.value
        return None
    if isinstance(node, (ast.Name, ast.Attribute, ast.Call)):
        return "single", [ast.unparse(node)]
    return None


def _spec_node(call: ast.Call, scope_body, key: str) -> Optional[ast.AST]:
    """The expression bound to ``key`` for this jit call: a direct kwarg,
    a ``**kwargs`` dict-literal entry, or a single ``kwargs[key] = ...``
    subscript assignment in the same scope (the conditional-sharding
    builder idiom). Ambiguous (multiply-assigned) keys resolve to None."""
    for kw in call.keywords:
        if kw.arg == key:
            return kw.value
    for kw in call.keywords:
        if kw.arg is None and isinstance(kw.value, ast.Name) and scope_body:
            kwname = kw.value.id
            found: List[ast.AST] = []
            for stmt in scope_body:
                for n in ast.walk(stmt):
                    if (isinstance(n, ast.Assign) and len(n.targets) == 1):
                        t = n.targets[0]
                        if (isinstance(t, ast.Name) and t.id == kwname
                                and isinstance(n.value, ast.Dict)):
                            for k, v in zip(n.value.keys, n.value.values):
                                if (isinstance(k, ast.Constant)
                                        and k.value == key):
                                    found.append(v)
                        elif (isinstance(t, ast.Subscript)
                              and isinstance(t.value, ast.Name)
                              and t.value.id == kwname
                              and isinstance(t.slice, ast.Constant)
                              and t.slice.value == key):
                            found.append(n.value)
            if len(found) == 1:
                return found[0]
            return None
    return None


class DeadDonatedOutSharding:
    code = "JG012"
    name = "dead-donated-out-sharding"
    summary = "out_shardings never matches a donated input — donation is dead"

    def check(self, mod):
        # scopes nest (the module walk revisits function bodies with the
        # wrong body for kwargs resolution) — analyze every scope and let
        # the engine's (code, path, line, col) dedup keep the first finding
        for scope in _common.iter_scopes(mod.tree):
            body = getattr(scope, "body", None) or []
            for node in ast.walk(scope):
                if not (isinstance(node, ast.Call)
                        and mod.resolve(node.func) in _common.JIT_WRAPPERS):
                    continue
                nums = jit_donate_argnums(node, body, mod.resolve)
                if not nums:
                    continue
                in_spec = _spec_node(node, body, "in_shardings")
                out_spec = _spec_node(node, body, "out_shardings")
                if in_spec is None or out_spec is None:
                    continue
                ins = _elems(in_spec)
                outs = _elems(out_spec)
                if ins is None or outs is None:
                    continue
                out_set = set(outs[1])
                for pos in nums:
                    if ins[0] == "single":
                        elem = ins[1][0]
                    elif pos < len(ins[1]):
                        elem = ins[1][pos]
                    else:
                        continue
                    if elem not in out_set:
                        yield mod.finding(
                            self.code,
                            f"argument {pos} is donated but its in-sharding "
                            f"`{elem}` matches no entry of out_shardings — "
                            f"XLA cannot alias the donated buffer into any "
                            f"output, so the donation is dead (the buffer "
                            f"is freed and a fresh allocation resharded "
                            f"into); make an output sharding match or drop "
                            f"the donation for this argument",
                            node,
                        ), node
                        break
